# Top-level developer entry points.
#
#   make lint             # distlr-lint: wire parity, concurrency,
#                         # config/docs parity, metrics doc, protocol
#                         # model checking (jax-free)
#   make lint-docs        # regenerate docs/CONFIG.md + docs/METRICS.md
#   make verify-protocol  # KV-protocol model checking to closure:
#                         # exhaustive interleaving search + mutant
#                         # rediscovery (counterexample schedules
#                         # printed) + fixture trace conformance
#   make sanitizers       # build the native TSan/ASan/UBSan matrix
#   make sanitizer-smoke  # fast TSan-client + TSan-server e2e
#                         # (delegates to benchmarks/Makefile)
#
# The lint passes are tier-1-enforced through tests/test_analysis.py
# (the protocol pass through tests/test_protocol_model.py); these
# targets are the same runners for hands/CI hooks.  See
# docs/ANALYSIS.md for pass semantics and the suppression policy.

PY ?= python

lint:
	$(PY) -m distlr_tpu.analysis

lint-docs:
	$(PY) -m distlr_tpu.analysis --write-docs

verify-protocol:
	$(PY) -m distlr_tpu.analysis.protocol

verify-protocol-full:
	$(PY) -m distlr_tpu.analysis.protocol --full

sanitizers:
	$(MAKE) -C distlr_tpu/ps/native sanitizers

sanitizer-smoke:
	$(MAKE) -C benchmarks sanitizer-smoke

.PHONY: lint lint-docs verify-protocol verify-protocol-full sanitizers \
	sanitizer-smoke
