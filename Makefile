# Top-level developer entry points.
#
#   make lint             # distlr-lint: wire parity, concurrency,
#                         # config/docs parity, metrics doc, protocol
#                         # model checking (jax-free)
#   make lint-docs        # regenerate docs/CONFIG.md + docs/METRICS.md
#   make verify-protocol  # KV-protocol model checking to closure:
#                         # exhaustive interleaving search + mutant
#                         # rediscovery (counterexample schedules
#                         # printed) + fixture trace conformance
#   make verify-sched     # schedcheck: the REAL fleet classes under
#                         # controlled interleavings — fast-tier DFS
#                         # + fuzz per scenario + both historical-race
#                         # mutants rediscovered as replayable
#                         # schedules
#   make verify-sched-full# deep tier (higher preemption bound / run
#                         # budgets; the pytest `slow` twin)
#   make verify-fleetsim  # fleetsim: thousand-rank discrete-event
#                         # scenarios driving the real autopilot /
#                         # router / reshard / SLO policies — pinned
#                         # digests + all three policy-bug mutants
#   make verify-fleetsim-full # + the multi-seed fuzz sweep per
#                         # scenario (the pytest `slow` twin)
#   make sanitizers       # build the native TSan/ASan/UBSan matrix
#   make sanitizer-smoke  # fast TSan-client + TSan-server e2e
#                         # (delegates to benchmarks/Makefile)
#
# The lint passes are tier-1-enforced through tests/test_analysis.py
# (the protocol pass through tests/test_protocol_model.py); these
# targets are the same runners for hands/CI hooks.  See
# docs/ANALYSIS.md for pass semantics and the suppression policy.

PY ?= python

lint:
	$(PY) -m distlr_tpu.analysis

lint-docs:
	$(PY) -m distlr_tpu.analysis --write-docs

verify-protocol:
	$(PY) -m distlr_tpu.analysis.protocol

verify-protocol-full:
	$(PY) -m distlr_tpu.analysis.protocol --full

verify-sched:
	$(PY) -m distlr_tpu.analysis.schedcheck
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_schedcheck.py \
	  -m 'not slow' -q -p no:cacheprovider

verify-sched-full:
	$(PY) -m distlr_tpu.analysis.schedcheck --full --fuzz 200

verify-fleetsim:
	$(PY) -m distlr_tpu.analysis.fleetsim
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_fleetsim.py \
	  -m 'not slow' -q -p no:cacheprovider

verify-fleetsim-full:
	$(PY) -m distlr_tpu.analysis.fleetsim --full

sanitizers:
	$(MAKE) -C distlr_tpu/ps/native sanitizers

sanitizer-smoke:
	$(MAKE) -C benchmarks sanitizer-smoke

.PHONY: lint lint-docs verify-protocol verify-protocol-full \
	verify-sched verify-sched-full verify-fleetsim \
	verify-fleetsim-full sanitizers sanitizer-smoke
