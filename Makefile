# Top-level developer entry points.
#
#   make lint             # distlr-lint: wire parity, concurrency,
#                         # config/docs parity, metrics doc (jax-free)
#   make lint-docs        # regenerate docs/CONFIG.md + docs/METRICS.md
#   make sanitizers       # build the native TSan/ASan/UBSan matrix
#   make sanitizer-smoke  # fast TSan-client + TSan-server e2e
#                         # (delegates to benchmarks/Makefile)
#
# The lint passes are tier-1-enforced through tests/test_analysis.py;
# this target is the same runner for hands/CI hooks.  See
# docs/ANALYSIS.md for pass semantics and the suppression policy.

PY ?= python

lint:
	$(PY) -m distlr_tpu.analysis

lint-docs:
	$(PY) -m distlr_tpu.analysis --write-docs

sanitizers:
	$(MAKE) -C distlr_tpu/ps/native sanitizers

sanitizer-smoke:
	$(MAKE) -C benchmarks sanitizer-smoke

.PHONY: lint lint-docs sanitizers sanitizer-smoke
