"""THE import point for every thread primitive the fleet creates.

``distlr_tpu/sync`` is to concurrency what :mod:`distlr_tpu.ps.wire`
is to the wire protocol: the one module production code goes through
instead of hand-reaching for the stdlib, so the instrumented and the
native builds share a single code path.  In the default PASSTHROUGH
state every name below *is* the stdlib object (``sync.Lock is
threading.Lock``) — creating a lock through the facade costs exactly
one module-attribute lookup, nothing else, and behavior is
byte-identical to importing :mod:`threading` directly
(regression-pinned in ``tests/test_schedcheck.py``).

When schedcheck (:mod:`distlr_tpu.analysis.schedcheck`) installs
itself, the same names resolve to yield-point-instrumented twins and a
virtual clock, so the REAL production classes — the batcher, the
joiner, the router, the reloader, the membership coordinator, the
chaos proxy — run single-stream under a controlled, replayable
interleaving.  Twins are handed out only to threads the scheduler
manages; an unrelated background thread calling ``sync.Lock()``
mid-install still gets a real stdlib lock, so installs are safe in a
process with live passthrough users.

Checked twin: :mod:`distlr_tpu.analysis.schedcheck.runtime` holds the
instrumented implementations and asserts (per scenario, via the
concurrency lint's shared-state registry) that every lock the lint
knows about on a class under test actually resolved through this
facade — a module that silently reverts to raw ``threading`` fails
schedcheck before it can un-instrument its own races.

Deliberately import-light (stdlib only): the serving and control
planes stay jax-free and cheap to import.
"""

from __future__ import annotations

import queue as _queue
import threading as _threading
import time as _time

# -- passthrough bindings (the production defaults) ---------------------
# Each name is the stdlib object itself, not a wrapper: passthrough must
# be zero-overhead and byte-identical.  schedcheck's install() swaps
# these module attributes for twins and uninstall() restores them.

Lock = _threading.Lock
RLock = _threading.RLock
Condition = _threading.Condition
Event = _threading.Event
Semaphore = _threading.Semaphore
BoundedSemaphore = _threading.BoundedSemaphore
Thread = _threading.Thread
Queue = _queue.Queue

#: queue exception types are shared between passthrough and twins, so
#: ``except sync.Empty`` works identically under both builds
Empty = _queue.Empty
Full = _queue.Full

#: the clock the adopted modules read where timing feeds a scheduling
#: decision (wait deadlines, backoff arithmetic, rate limits).  Under
#: schedcheck these become the VIRTUAL clock — time advances only when
#: every managed task is blocked, which is what makes timed waits
#: deterministic instead of schedule noise.
monotonic = _time.monotonic
wall = _time.time
sleep = _time.sleep

#: every swappable name, in one place (install/uninstall + tests)
SWAPPABLE = (
    "Lock", "RLock", "Condition", "Event", "Semaphore",
    "BoundedSemaphore", "Thread", "Queue", "monotonic", "wall", "sleep",
)

_PASSTHROUGH = {name: globals()[name] for name in SWAPPABLE}
_installed_by: object | None = None


def install(twins: dict, *, owner: object) -> None:
    """Swap the facade onto instrumented twins (schedcheck only).

    ``twins`` maps names from :data:`SWAPPABLE` to replacement
    callables; unnamed entries keep their passthrough binding.
    Refuses to double-install — two schedcheck runtimes in one process
    would corrupt each other's schedules.
    """
    global _installed_by
    if _installed_by is not None:
        raise RuntimeError(
            "distlr_tpu.sync is already instrumented — one schedcheck "
            "runtime at a time")
    unknown = sorted(set(twins) - set(SWAPPABLE))
    if unknown:
        raise ValueError(f"unknown sync names {unknown}; "
                         f"swappable: {SWAPPABLE}")
    for name, fn in twins.items():
        globals()[name] = fn
    _installed_by = owner


def uninstall(*, owner: object) -> None:
    """Restore the passthrough bindings (idempotent per owner)."""
    global _installed_by
    if _installed_by is None:
        return
    if _installed_by is not owner:
        raise RuntimeError("sync.uninstall by a non-owner")
    for name, obj in _PASSTHROUGH.items():
        globals()[name] = obj
    _installed_by = None


def instrumented() -> bool:
    return _installed_by is not None
