"""Linear model family as pure-functional JAX: dense binary LR, multinomial
softmax regression, and sparse (CSR) binary LR.

Replaces the reference's ``distlr::LR`` (``src/lr.cc`` / ``include/lr.h``)
whose hot loop is an O(B*D^2) scalar nest (``src/lr.cc:35-41``: it
re-computes the full dot product w.x inside the per-feature loop and copies
the feature vector per access).  Here each step is two MXU matmuls —
``X @ w`` and ``X^T @ residual`` — O(B*D), bfloat16 on the MXU with float32
accumulation.

Every model exposes the same pure-function surface:

* ``init(config) -> params``          (reference-RNG or He-style init)
* ``loss(params, batch, cfg) -> scalar``  (mean logloss + L2)
* ``grad(params, batch, cfg) -> params-like``  (closed form, quirk-gated)
* ``predict(params, X) -> labels``
* ``accuracy(params, batch) -> scalar``

``batch`` is ``(X, y, mask)`` with a boolean mask for padded rows (static
shapes; see :mod:`distlr_tpu.data.iterator`).  Gradients are closed-form
rather than ``jax.grad`` of the loss so the reference's exact formula
``(sigma(Xw) - y)^T X / B + C*w/B`` (``src/lr.cc:38-40``, quirk Q4) can be
reproduced bit-for-bit in compat mode; a ``jax.grad`` path is kept in tests
as the oracle for the "correct" mode.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from distlr_tpu.config import Config
from distlr_tpu.utils.reference_rng import reference_init_weights


# Longest int8 x int8 contraction whose worst case (every product
# +/-127*127, same sign) still fits int32: floor((2^31-1) / 127^2).
_INT8_ACC_MAX = (2**31 - 1) // (127 * 127)


# Chunks below this are useless on the MXU (every k divides by 1, so a
# floor is what actually forces awkward lengths onto the convert path).
_INT8_MIN_CHUNK = 1024

# Each chunk is an unrolled dot_general in the traced step; divisor-poor
# dims (e.g. k = 1024 * 131^2 -> best divisor 4*131^2, 256 chunks) would
# blow up HLO size and compile time, so past this many chunks the
# convert path wins.
_INT8_MAX_CHUNKS = 32


def _int8_chunk_len(k: int) -> int | None:
    """Largest divisor of ``k`` that keeps a worst-case int8 x int8
    contraction inside int32.  ``None`` — caller must take the convert
    path — when no divisor of useful size exists OR the resulting chunk
    count would exceed ``_INT8_MAX_CHUNKS`` unrolled dots.  Trace-time
    only (static shapes)."""
    if k <= _INT8_ACC_MAX:
        return k
    best = None
    for d in range(1, int(k**0.5) + 1):
        if k % d:
            continue
        for c in (d, k // d):
            if c <= _INT8_ACC_MAX and (best is None or c > best):
                best = c
    if best is None or best < _INT8_MIN_CHUNK or k // best > _INT8_MAX_CHUNKS:
        return None
    return best


def _int8_contract(a, b, a_axis: int) -> jnp.ndarray:
    """Overflow-safe ``a . b`` over ``a``'s axis ``a_axis`` and ``b``'s
    leading axis, both int8, on the MXU -> float32 (unscaled).

    A single int32 accumulation wraps once the contraction length
    exceeds ``_INT8_ACC_MAX`` (~133k) in the worst case — reachable for
    the backward at ``batch_size=-1`` on a big shard, and for the
    forward at north-star D.  The contraction is therefore split into
    the largest dividing chunks that cannot wrap: one plain dot_general
    per chunk over a contraction-axis slice, accumulated in float32
    (chunk partials are < 2^31, so the f32 rounding there is ~1e-9
    relative — far below the int8 quantization noise).  The unrolled
    slice-per-chunk form matters: expressing the same split as a single
    reshape + c-batched dot_general measured 55k samples/s on the
    D=1M step vs ~165k for both the unrolled form and the (unsafe)
    unchunked dot — the batched dot forces a bad layout on the (B, D)
    operand, while column slices keep each chunk a plain MXU matmul
    (benchmarks/exp_int8_chunk.py, on-chip).  When the length is
    awkward — no divisor <= the bound, or only divisors small enough
    that the unroll would exceed ``_INT8_MAX_CHUNKS`` dots — the
    bfloat16-convert formulation is used instead: slower, never wrong.
    """
    k = a.shape[a_axis]
    a_axis = a_axis % a.ndim
    n_c = _int8_chunk_len(k)
    if n_c == k:
        out = jax.lax.dot_general(
            a, b, (((a_axis,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
        return out.astype(jnp.float32)
    if n_c is None:  # no safe chunking: correct-but-slower convert path
        out = jax.lax.dot_general(
            a.astype(jnp.bfloat16), b.astype(jnp.bfloat16),
            (((a_axis,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return out
    acc = None
    for i in range(k // n_c):
        a_i = jax.lax.slice_in_dim(a, i * n_c, (i + 1) * n_c, axis=a_axis)
        b_i = jax.lax.slice_in_dim(b, i * n_c, (i + 1) * n_c, axis=0)
        p = jax.lax.dot_general(
            a_i, b_i, (((a_axis,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        ).astype(jnp.float32)
        acc = p if acc is None else acc + p
    return acc


def quantize_sym(x, max_abs):
    """Symmetric int8 quantization on the grid defined by ``max_abs``:
    ``(q int8, scale)`` with ``x ~ q * scale``.  The ONE definition of
    the int8_dot grid — the single-device paths and the feature-sharded
    steps (which compute ``max_abs`` with a pmax) must quantize
    identically for their bit-for-bit weight-grid parity to hold."""
    scale = jnp.maximum(max_abs, 1e-8) * (1.0 / 127.0)
    q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    return q, scale


def _masked_mean(values, mask):
    denom = jnp.maximum(jnp.sum(mask), 1)
    return jnp.sum(values * mask) / denom


def _l2_grad(w, cfg: Config, batch_n):
    # Q4 gate: reference divides the L2 term by the batch size
    # (src/lr.cc:40); "correct" applies C*w un-scaled.
    term = cfg.l2_c * w
    return term / batch_n if cfg.l2_scale_by_batch else term


@dataclasses.dataclass(frozen=True)
class BinaryLR:
    """Dense binary logistic regression: params = w of shape (D,)."""

    num_features: int
    # MXU-friendly matmul dtype; set "float32" for bit-level parity runs.
    compute_dtype: str = "bfloat16"
    # Dequantization scale for reduced-precision feature storage
    # (cfg.feature_dtype="int8": X is stored as round(X/scale) and the
    # true logit is (Xq @ w) * scale).  Static so XLA folds the convert
    # into the matmul read; applied to the (B,)/(D,) RESULT vectors, not
    # the (B, D) matrix.  1.0 = features are already real-valued.
    feature_scale: float = 1.0
    # Native int8 x int8 -> int32 MXU contraction (cfg.feature_dtype=
    # "int8_dot").  The plain int8 storage path converts the whole (B, D)
    # tile to bfloat16 before the dot — a VPU-bound convert wall measured
    # at ~151-165k samples/s at D=1M (benchmarks/ROOFLINE.md,
    # exp_int8_dot.py).  This path instead quantizes the SMALL per-step
    # operands — w over D for the forward, the residual over B for the
    # backward — with dynamic symmetric scales and feeds both dots int8
    # operands end to end (~170k measured, 1.55x bf16).  Requires X to
    # be int8 (the trainer's feature quantization guarantees it).
    int8_dot: bool = False

    @property
    def param_shape(self) -> tuple[int, ...]:
        return (self.num_features,)

    def init(self, cfg: Config) -> jnp.ndarray:
        if cfg.reference_rng_init:
            # Q2 parity: srand(seed); rand()/RAND_MAX per weight.
            # Reference default seed is 0 (lr.h:10), not RANDOM_SEED.
            return jnp.asarray(reference_init_weights(self.num_features, 0))
        key = jax.random.PRNGKey(cfg.random_seed)
        return jax.random.uniform(key, (self.num_features,), dtype=jnp.float32)

    def logits(self, w, X):
        if self.int8_dot:
            wq, s_w = quantize_sym(w, jnp.max(jnp.abs(w)))
            z = _int8_contract(X, wq, X.ndim - 1)
            return z * (s_w * self.feature_scale)
        cdt = jnp.dtype(self.compute_dtype)
        z = jnp.dot(
            X.astype(cdt),
            w.astype(cdt),
            preferred_element_type=jnp.float32,
        )
        return z * self.feature_scale if self.feature_scale != 1.0 else z

    def loss(self, w, batch, cfg: Config):
        X, y, mask = batch
        z = self.logits(w, X)
        # logloss via softplus for stability: log(1+e^z) - y*z
        ll = jax.nn.softplus(z) - y.astype(jnp.float32) * z
        reg = 0.5 * cfg.l2_c * jnp.sum(w * w)
        if cfg.l2_scale_by_batch:
            reg = reg / jnp.maximum(jnp.sum(mask), 1)
        return _masked_mean(ll, mask) + reg

    def grad(self, w, batch, cfg: Config):
        X, y, mask = batch
        z = self.logits(w, X)
        resid = (jax.nn.sigmoid(z) - y.astype(jnp.float32)) * mask
        n = jnp.maximum(jnp.sum(mask), 1).astype(jnp.float32)
        if self.int8_dot:
            # Residuals live in (-1, 1): a dynamic symmetric scale keeps
            # full int8 resolution on whatever range this batch actually
            # spans (near convergence |r| shrinks, and a fixed scale
            # would quantize everything to 0).
            rq, s_r = quantize_sym(resid, jnp.max(jnp.abs(resid)))
            g = _int8_contract(rq, X, 0) * (s_r * self.feature_scale) / n
            return g + _l2_grad(w, cfg, n)
        cdt = jnp.dtype(self.compute_dtype)
        g = (
            jnp.dot(
                resid.astype(cdt),
                X.astype(cdt),
                preferred_element_type=jnp.float32,
            )
            / n
        )
        if self.feature_scale != 1.0:
            g = g * self.feature_scale
        return g + _l2_grad(w, cfg, n)

    def predict(self, w, X):
        # Reference decision rule: z > 0 (src/lr.cc:100-106).
        return (self.logits(w, X) > 0).astype(jnp.int32)

    def proba(self, w, X):
        """P(y=1) per row — the serving-side output (a CTR system ships
        the probability, not the thresholded label; the reference has no
        serving tier at all)."""
        return jax.nn.sigmoid(self.logits(w, X))

    def accuracy(self, w, batch):
        X, y, mask = batch
        correct = (self.predict(w, X) == y).astype(jnp.float32)
        return _masked_mean(correct, mask)

    def logloss(self, w, batch):
        """Mean test logloss WITHOUT the L2 term — the driver's parity
        metric (BASELINE.json epochs-to-logloss), which regularization
        must not contaminate."""
        X, y, mask = batch
        z = self.logits(w, X)
        ll = jax.nn.softplus(z) - y.astype(jnp.float32) * z
        return _masked_mean(ll, mask)


@dataclasses.dataclass(frozen=True)
class SoftmaxRegression:
    """Multinomial softmax regression: params = W of shape (D, K)."""

    num_features: int
    num_classes: int
    compute_dtype: str = "bfloat16"
    feature_scale: float = 1.0  # see BinaryLR.feature_scale
    int8_dot: bool = False      # see BinaryLR.int8_dot — same formulation,
    #                             W (D, K) quantized on one global grid

    @property
    def param_shape(self) -> tuple[int, ...]:
        return (self.num_features, self.num_classes)

    def init(self, cfg: Config) -> jnp.ndarray:
        shape = (self.num_features, self.num_classes)
        if cfg.reference_rng_init:
            flat = reference_init_weights(self.num_features * self.num_classes, 0)
            return jnp.asarray(flat.reshape(shape))
        key = jax.random.PRNGKey(cfg.random_seed)
        return jax.random.uniform(key, shape, dtype=jnp.float32)

    def logits(self, W, X):
        if self.int8_dot:
            Wq, s_w = quantize_sym(W, jnp.max(jnp.abs(W)))
            z = _int8_contract(X, Wq, X.ndim - 1)  # (B, K)
            return z * (s_w * self.feature_scale)
        cdt = jnp.dtype(self.compute_dtype)
        z = jnp.dot(
            X.astype(cdt),
            W.astype(cdt),
            preferred_element_type=jnp.float32,
        )
        return z * self.feature_scale if self.feature_scale != 1.0 else z

    def loss(self, W, batch, cfg: Config):
        X, y, mask = batch
        z = self.logits(W, X)
        ll = -jax.nn.log_softmax(z)[jnp.arange(z.shape[0]), y]
        reg = 0.5 * cfg.l2_c * jnp.sum(W * W)
        if cfg.l2_scale_by_batch:
            reg = reg / jnp.maximum(jnp.sum(mask), 1)
        return _masked_mean(ll, mask) + reg

    def grad(self, W, batch, cfg: Config):
        X, y, mask = batch
        z = self.logits(W, X)
        p = jax.nn.softmax(z)
        onehot = jax.nn.one_hot(y, self.num_classes, dtype=jnp.float32)
        resid = (p - onehot) * mask[:, None]
        n = jnp.maximum(jnp.sum(mask), 1).astype(jnp.float32)
        if self.int8_dot:
            rq, s_r = quantize_sym(resid, jnp.max(jnp.abs(resid)))
            g = _int8_contract(X, rq, 0) * (s_r * self.feature_scale) / n
            return g + _l2_grad(W, cfg, n)
        cdt = jnp.dtype(self.compute_dtype)
        g = (
            jnp.dot(
                X.astype(cdt).T,
                resid.astype(cdt),
                preferred_element_type=jnp.float32,
            )
            / n
        )
        if self.feature_scale != 1.0:
            g = g * self.feature_scale
        return g + _l2_grad(W, cfg, n)

    def predict(self, W, X):
        return jnp.argmax(self.logits(W, X), axis=-1).astype(jnp.int32)

    def proba(self, W, X):
        """(B, K) class probabilities (see BinaryLR.proba)."""
        return jax.nn.softmax(self.logits(W, X), axis=-1)

    def accuracy(self, W, batch):
        X, y, mask = batch
        correct = (self.predict(W, X) == y).astype(jnp.float32)
        return _masked_mean(correct, mask)

    def logloss(self, W, batch):
        """Mean multiclass test logloss, no L2 (see BinaryLR.logloss)."""
        X, y, mask = batch
        z = self.logits(W, X)
        ll = -jax.nn.log_softmax(z)[jnp.arange(z.shape[0]), y]
        return _masked_mean(ll, mask)


@dataclasses.dataclass(frozen=True)
class SparseBinaryLR:
    """Binary LR over padded-COO sparse batches (one-hot / CTR style).

    A batch is ``(cols, vals, y, mask)`` where ``cols``/``vals`` are
    ``(B, NNZ_MAX)`` padded per-row index/value arrays (pad col = 0,
    pad val = 0).  The forward is a gather-dot; the gradient scatter is a
    ``segment_sum`` over the flattened column ids — the TPU-friendly
    sparse formulation (no dynamic shapes).
    """

    num_features: int

    @property
    def param_shape(self) -> tuple[int, ...]:
        return (self.num_features,)

    def init(self, cfg: Config) -> jnp.ndarray:
        if cfg.reference_rng_init:
            return jnp.asarray(reference_init_weights(self.num_features, 0))
        # Zeros, NOT the dense models' uniform-[0,1) reference mirror: with
        # F active features a positive-mean init biases every logit to
        # ~F/2, and at CTR scale each weight is touched too rarely for SGD
        # to unwind that (uniform init at D=1e5 sits at chance accuracy
        # for tens of epochs).  The reference has no sparse model to be
        # compatible with.
        return jnp.zeros(self.num_features, jnp.float32)

    def logits(self, w, cols, vals):
        return jnp.sum(w[cols] * vals, axis=-1)

    def loss(self, w, batch, cfg: Config):
        cols, vals, y, mask = batch
        z = self.logits(w, cols, vals)
        ll = jax.nn.softplus(z) - y.astype(jnp.float32) * z
        reg = 0.5 * cfg.l2_c * jnp.sum(w * w)
        if cfg.l2_scale_by_batch:
            reg = reg / jnp.maximum(jnp.sum(mask), 1)
        return _masked_mean(ll, mask) + reg

    def grad(self, w, batch, cfg: Config):
        cols, vals, y, mask = batch
        z = self.logits(w, cols, vals)
        resid = (jax.nn.sigmoid(z) - y.astype(jnp.float32)) * mask
        n = jnp.maximum(jnp.sum(mask), 1).astype(jnp.float32)
        contrib = (resid[:, None] * vals).reshape(-1)
        flat_cols = cols.reshape(-1)
        g = jax.ops.segment_sum(contrib, flat_cols, num_segments=self.num_features) / n
        return g + _l2_grad(w, cfg, n)

    def predict(self, w, cols, vals):
        return (self.logits(w, cols, vals) > 0).astype(jnp.int32)

    def proba(self, w, cols, vals):
        """P(y=1) per row (see BinaryLR.proba)."""
        return jax.nn.sigmoid(self.logits(w, cols, vals))

    def accuracy(self, w, batch):
        cols, vals, y, mask = batch
        correct = (self.predict(w, cols, vals) == y).astype(jnp.float32)
        return _masked_mean(correct, mask)

    def logloss(self, w, batch):
        """Mean test logloss, no L2 (see BinaryLR.logloss)."""
        cols, vals, y, mask = batch
        z = self.logits(w, cols, vals)
        ll = jax.nn.softplus(z) - y.astype(jnp.float32) * z
        return _masked_mean(ll, mask)


@dataclasses.dataclass(frozen=True)
class SparseSoftmaxRegression:
    """Multinomial softmax over padded-COO sparse batches: params W of
    shape ``(D, K)``.

    The multiclass member of the CTR encoding family (the reference is
    binary-only — ``src/lr.cc``; BASELINE.json config 5's softmax family
    extended to the sparse path, completing the model-family x encoding
    matrix).  A batch is ``(cols, vals, y, mask)`` like
    :class:`SparseBinaryLR`, with integer class labels.  The forward
    gathers one K-wide class-weight ROW per active feature — the same
    row-gather access pattern the blocked path exploits, so TPU gather
    cost is per-feature, not per-(feature, class) — and the gradient is
    one ``segment_sum`` of per-feature outer contributions
    ``vals[:, :, None] * resid[:, None, :]`` over the flattened column
    ids.  In keyed PS mode the (D, K) rows travel as ``vals_per_key=K``
    frames (one u64 feature id per K floats).
    """

    num_features: int
    num_classes: int

    @property
    def param_shape(self) -> tuple[int, ...]:
        return (self.num_features, self.num_classes)

    def init(self, cfg: Config) -> jnp.ndarray:
        shape = (self.num_features, self.num_classes)
        if cfg.reference_rng_init:
            flat = reference_init_weights(
                self.num_features * self.num_classes, 0)
            return jnp.asarray(flat.reshape(shape))
        # zeros for the same reason as SparseBinaryLR.init: at CTR scale
        # a positive-mean init biases every logit and SGD touches each
        # row too rarely to unwind it
        return jnp.zeros(shape, jnp.float32)

    def logits(self, W, cols, vals):
        # (B, F, K) gathered rows, weighted per-feature, summed over F
        return jnp.sum(W[cols] * vals[..., None], axis=-2)

    def loss(self, W, batch, cfg: Config):
        cols, vals, y, mask = batch
        z = self.logits(W, cols, vals)
        ll = -jax.nn.log_softmax(z)[jnp.arange(z.shape[0]), y]
        reg = 0.5 * cfg.l2_c * jnp.sum(W * W)
        if cfg.l2_scale_by_batch:
            reg = reg / jnp.maximum(jnp.sum(mask), 1)
        return _masked_mean(ll, mask) + reg

    def grad(self, W, batch, cfg: Config):
        cols, vals, y, mask = batch
        z = self.logits(W, cols, vals)
        p = jax.nn.softmax(z)
        onehot = jax.nn.one_hot(y, self.num_classes, dtype=jnp.float32)
        resid = (p - onehot) * mask[:, None]                   # (B, K)
        n = jnp.maximum(jnp.sum(mask), 1).astype(jnp.float32)
        contrib = (vals[..., None] * resid[:, None, :]).reshape(
            -1, self.num_classes)                              # (B*F, K)
        g = jax.ops.segment_sum(
            contrib, cols.reshape(-1), num_segments=self.num_features) / n
        return g + _l2_grad(W, cfg, n)

    def predict(self, W, cols, vals):
        return jnp.argmax(self.logits(W, cols, vals), axis=-1).astype(jnp.int32)

    def proba(self, W, cols, vals):
        """(B, K) class probabilities (see BinaryLR.proba)."""
        return jax.nn.softmax(self.logits(W, cols, vals), axis=-1)

    def accuracy(self, W, batch):
        cols, vals, y, mask = batch
        correct = (self.predict(W, cols, vals) == y).astype(jnp.float32)
        return _masked_mean(correct, mask)

    def logloss(self, W, batch):
        """Mean test cross-entropy, no L2 (see BinaryLR.logloss)."""
        cols, vals, y, mask = batch
        z = self.logits(W, cols, vals)
        ll = -jax.nn.log_softmax(z)[jnp.arange(z.shape[0]), y]
        return _masked_mean(ll, mask)


@dataclasses.dataclass(frozen=True)
class BlockedSparseLR:
    """Binary LR over row-aligned block batches (the row-blocked CTR
    path — see :func:`distlr_tpu.data.hashing.hash_group_blocks`).

    Params are a ``(num_blocks, block_size)`` table.  A batch is
    ``(blocks, lane_vals, y, mask)`` with ``blocks`` of shape (B, G) and
    ``lane_vals`` of shape (B, G, R): each sample gathers G contiguous
    R-wide rows instead of G*R scalars, which amortizes the TPU gather
    unit's per-index cost (benchmarks/ROOFLINE.md: 3.4x the bytes/s of
    scalar gathers); the gradient scatter is a ``segment_sum`` of R-wide
    rows, blocked the same way.  Logit = sum over groups of
    ``T[block_g] . lane_vals_g`` — with lane_vals the one-hot/raw values
    of the group's member fields, this is per-(conjunction, field)
    logistic regression.
    """

    num_blocks: int
    block_size: int = 8

    @property
    def param_shape(self) -> tuple[int, ...]:
        return (self.num_blocks, self.block_size)

    def init(self, cfg: Config) -> jnp.ndarray:
        # Zeros for the same reason SparseBinaryLR uses them: untrained
        # rows (unseen conjunctions) must contribute nothing, not noise.
        return jnp.zeros((self.num_blocks, self.block_size), jnp.float32)

    def logits(self, t, blocks, lane_vals):
        return jnp.sum(t[blocks] * lane_vals, axis=(-1, -2))

    def loss(self, t, batch, cfg: Config):
        blocks, lane_vals, y, mask = batch
        z = self.logits(t, blocks, lane_vals)
        ll = jax.nn.softplus(z) - y.astype(jnp.float32) * z
        reg = 0.5 * cfg.l2_c * jnp.sum(t * t)
        if cfg.l2_scale_by_batch:
            reg = reg / jnp.maximum(jnp.sum(mask), 1)
        return _masked_mean(ll, mask) + reg

    def grad(self, t, batch, cfg: Config):
        blocks, lane_vals, y, mask = batch
        z = self.logits(t, blocks, lane_vals)
        resid = (jax.nn.sigmoid(z) - y.astype(jnp.float32)) * mask
        n = jnp.maximum(jnp.sum(mask), 1).astype(jnp.float32)
        # Row-blocked scatter: (B*G, R) row contributions summed per block.
        contrib = (resid[:, None, None] * lane_vals).reshape(-1, self.block_size)
        g = jax.ops.segment_sum(
            contrib, blocks.reshape(-1), num_segments=self.num_blocks
        ) / n
        return g + _l2_grad(t, cfg, n)

    def predict(self, t, blocks, lane_vals):
        return (self.logits(t, blocks, lane_vals) > 0).astype(jnp.int32)

    def proba(self, t, blocks, lane_vals):
        """P(y=1) per row (see BinaryLR.proba)."""
        return jax.nn.sigmoid(self.logits(t, blocks, lane_vals))

    def accuracy(self, t, batch):
        blocks, lane_vals, y, mask = batch
        correct = (self.predict(t, blocks, lane_vals) == y).astype(jnp.float32)
        return _masked_mean(correct, mask)

    def logloss(self, t, batch):
        """Mean test logloss, no L2 (see BinaryLR.logloss)."""
        blocks, lane_vals, y, mask = batch
        z = self.logits(t, blocks, lane_vals)
        ll = jax.nn.softplus(z) - y.astype(jnp.float32) * z
        return _masked_mean(ll, mask)


def get_model(cfg: Config):
    if cfg.model == "binary_lr":
        return BinaryLR(cfg.num_feature_dim, compute_dtype=cfg.compute_dtype,
                        int8_dot=cfg.feature_dtype == "int8_dot")
    if cfg.model == "softmax":
        return SoftmaxRegression(cfg.num_feature_dim, cfg.num_classes,
                                 compute_dtype=cfg.compute_dtype,
                                 int8_dot=cfg.feature_dtype == "int8_dot")
    if cfg.model == "sparse_lr":
        return SparseBinaryLR(cfg.num_feature_dim)
    if cfg.model == "sparse_softmax":
        return SparseSoftmaxRegression(cfg.num_feature_dim, cfg.num_classes)
    if cfg.model == "blocked_lr":
        if cfg.block_size == 0:
            raise ValueError(
                "block_size=0 (auto) must be resolved before building a "
                "model — see data.hashing.resolve_auto_block_size (the "
                "launch CLI does this for --block-size auto)"
            )
        if cfg.num_feature_dim % cfg.block_size:
            raise ValueError(
                f"num_feature_dim ({cfg.num_feature_dim}) must be a multiple "
                f"of block_size ({cfg.block_size}) for blocked_lr"
            )
        return BlockedSparseLR(cfg.num_feature_dim // cfg.block_size, cfg.block_size)
    raise ValueError(f"unknown model {cfg.model!r}")
