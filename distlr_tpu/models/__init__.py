from distlr_tpu.models.linear import (  # noqa: F401
    BinaryLR,
    SoftmaxRegression,
    SparseBinaryLR,
    get_model,
)
