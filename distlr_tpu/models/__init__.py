from distlr_tpu.models.linear import (  # noqa: F401
    BinaryLR,
    BlockedSparseLR,
    SoftmaxRegression,
    SparseBinaryLR,
    SparseSoftmaxRegression,
    get_model,
)
