"""ctypes binding for the native libsvm parser.

Loaded opportunistically by :mod:`distlr_tpu.data.libsvm`; any import or
build failure falls back to the pure-Python tokenizer.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "native")
_SO = os.path.join(_DIR, "libdistlr_libsvm.so")
_lock = threading.Lock()
_lib = None


def _load():
    global _lib
    if _lib is None:
        with _lock:
            if _lib is None:
                if not os.path.exists(_SO):
                    proc = subprocess.run(
                        ["make", "-C", _DIR], capture_output=True, text=True
                    )
                    if proc.returncode != 0:
                        raise RuntimeError(f"libsvm native build failed: {proc.stderr}")
                lib = ctypes.CDLL(_SO)
                lib.libsvm_count.restype = ctypes.c_int
                lib.libsvm_count.argtypes = [
                    ctypes.c_char_p, ctypes.c_int64,
                    ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
                ]
                lib.libsvm_parse.restype = ctypes.c_int64
                lib.libsvm_parse.argtypes = [
                    ctypes.c_char_p, ctypes.c_int64, ctypes.c_int,
                    ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ]
                _lib = lib
    return _lib


def parse_libsvm_bytes(data: bytes, multiclass: bool):
    """Returns ``(labels i32, row_ptr i64, cols i32, vals f32)``."""
    lib = _load()
    n = len(data)
    n_rows = ctypes.c_int64()
    n_nnz = ctypes.c_int64()
    lib.libsvm_count(data, n, ctypes.byref(n_rows), ctypes.byref(n_nnz))
    labels = np.empty(n_rows.value, dtype=np.int32)
    row_ptr = np.empty(n_rows.value + 1, dtype=np.int64)
    cols = np.empty(n_nnz.value, dtype=np.int32)
    vals = np.empty(n_nnz.value, dtype=np.float32)
    parsed = lib.libsvm_parse(
        data, n, int(multiclass),
        labels.ctypes.data_as(ctypes.c_void_p),
        row_ptr.ctypes.data_as(ctypes.c_void_p),
        cols.ctypes.data_as(ctypes.c_void_p),
        vals.ctypes.data_as(ctypes.c_void_p),
    )
    if parsed != n_rows.value:
        raise ValueError(f"malformed libsvm input (parsed {parsed} of {n_rows.value} rows)")
    return labels, row_ptr, cols, vals
