from distlr_tpu.data.libsvm import parse_libsvm_file, parse_libsvm_lines, write_libsvm  # noqa: F401
from distlr_tpu.data.iterator import BlockedDataIter, DataIter, SparseDataIter  # noqa: F401
from distlr_tpu.data.synthetic import make_synthetic_dataset, write_synthetic_shards  # noqa: F401
from distlr_tpu.data.sharding import shard_libsvm_file, prepare_data_dir  # noqa: F401
from distlr_tpu.data.hashing import (  # noqa: F401
    HashedFeatureEncoder,
    csr_to_padded_coo,
    csr_to_raw_ids,
    encode_blocked,
    hash_buckets,
    make_ctr_dataset,
    read_ctr_meta,
    read_raw_ctr_file,
    resolve_auto_block_size,
    split_field_groups,
    suggest_block_size,
    suggest_blocking,
    write_ctr_shards,
    write_raw_ctr_shards,
)
