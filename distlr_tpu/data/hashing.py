"""Feature hashing (the "hashing trick") for CTR-scale workloads.

The reference caps out at dense feature vectors whose dimension is fixed by
``NUM_FEATURE_DIM`` (``examples/local.sh:14``) — its north-star scaling
path, per BASELINE.json configs 3-4 (Criteo hashed-to-dense 1M features,
Avazu sparse one-hot), needs categorical features of unbounded vocabulary
hashed into a fixed bucket space.  This module provides:

* a vectorized 64-bit mixer (splitmix64) — deterministic, seed-parameterized,
  numpy-only, no Python-object hashing (``hash()`` is salted per process);
* CSR -> hashed padded-COO / hashed dense conversion, feeding either the
  ``SparseBinaryLR`` segment_sum path or the dense MXU path;
* a deterministic synthetic CTR generator (fields x vocab -> one active
  value per field) with ground-truth weights *in bucket space*, so
  convergence tests can assert signal recovery after hashing collisions;
* a reference-layout shard writer (one-hot libsvm rows over bucket ids),
  so the whole existing libsvm pipeline (native parser, sharding,
  trainer) runs unchanged on hashed CTR data.

Sign hashing (Weinberger et al.'s +/-1 trick) is supported to de-bias
collision noise: ``val = sign(h') * raw_val``.
"""

from __future__ import annotations

import dataclasses
import os

import numpy as np

__all__ = [
    "splitmix64",
    "hash_buckets",
    "hash_group_blocks",
    "default_field_groups",
    "split_field_groups",
    "encode_blocked",
    "suggest_block_size",
    "suggest_blocking",
    "resolve_auto_block_size",
    "HashedFeatureEncoder",
    "csr_to_padded_coo",
    "make_ctr_dataset",
    "write_ctr_shards",
    "write_raw_ctr_shards",
    "read_raw_ctr_file",
    "read_ctr_meta",
]

_U64 = np.uint64


def splitmix64(x: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 finalizer: uint64 array -> uint64 array.

    Full-avalanche integer mixer (each input bit flips ~half the output
    bits) — the standard seed-expander of the xoshiro family.
    """
    x = x.astype(_U64, copy=True)
    with np.errstate(over="ignore"):
        x += _U64(0x9E3779B97F4A7C15)
        z = x
        z = (z ^ (z >> _U64(30))) * _U64(0xBF58476D1CE4E5B9)
        z = (z ^ (z >> _U64(27))) * _U64(0x94D049BB133111EB)
        z = z ^ (z >> _U64(31))
    return z


def hash_buckets(ids: np.ndarray, num_buckets: int, *, seed: int = 0, field_ids=None):
    """Hash integer feature ids into ``[0, num_buckets)``.

    ``field_ids`` (same shape or broadcastable) namespaces ids per
    categorical field so value 7 in field 0 and value 7 in field 1 land in
    independent buckets.  Returns ``(buckets, signs)`` where ``signs`` is
    the +/-1 sign-hash (float32) derived from an independent bit of the
    same mix.
    """
    h = np.asarray(ids, dtype=np.int64).astype(_U64)
    if field_ids is not None:
        with np.errstate(over="ignore"):
            h = h + splitmix64(np.asarray(field_ids, dtype=np.int64).astype(_U64) + _U64(0x51))
    with np.errstate(over="ignore"):
        h = splitmix64(h + splitmix64(np.full_like(h, _U64(seed))))
    buckets = (h % _U64(num_buckets)).astype(np.int64)
    # bit 63 is independent of the modulus for num_buckets << 2^63
    signs = np.where((h >> _U64(63)).astype(bool), np.float32(1.0), np.float32(-1.0))
    return buckets, signs


def hash_group_blocks(raw_ids, field_groups, num_blocks: int, *, seed: int = 0,
                      raw_vals=None):
    """Row-aligned ("blocked") hashing: field groups -> block-row ids.

    TPU gathers amortize their per-index cost over contiguous elements
    (benchmarks/ROOFLINE.md: rows-of-8 move 3.4x the bytes/s of scalar
    gathers), but that only pays off if the fetched lanes are all used —
    which requires co-locating several of a sample's features in ONE
    table row.  Per-field buckets cannot co-locate (each field's value
    picks an independent bucket), so this scheme hashes a GROUP of R
    fields jointly: the group's value tuple selects the block row, and
    lane j holds the learned weight of member field j under that
    conjunction.  One R-wide row gather then replaces R scalar gathers.

    The statistical trade (documented, opt-in): weights are per
    (conjunction, field) instead of per field — rows are trained only
    when their exact value tuple recurs, so group LOW-CARDINALITY fields
    (tuple space small enough to recur in training data) and keep
    high-cardinality fields on the scalar `hash_buckets` path.

    Args:
      raw_ids: (N, F) integer categorical values.
      field_groups: sequence of equal-length field-index tuples; use -1
        to pad a short group (its lane contributes value 0).
      num_blocks: table rows; total params = num_blocks * R.
      raw_vals: optional (N, F) float values (default one-hot 1.0).

    Returns ``(blocks, lane_vals)``: (N, G) int64 block ids and
    (N, G, R) float32 per-lane values.
    """
    raw_ids = np.asarray(raw_ids, dtype=np.int64)
    groups = np.asarray(field_groups, dtype=np.int64)
    if groups.ndim != 2:
        raise ValueError("field_groups must be a (G, R) array of field indices")
    n, _ = raw_ids.shape
    g_count, r = groups.shape
    pad = groups < 0
    safe = np.where(pad, 0, groups)
    vals_f = (np.ones_like(raw_ids, dtype=np.float32) if raw_vals is None
              else np.asarray(raw_vals, dtype=np.float32))
    member_ids = raw_ids[:, safe.reshape(-1)].reshape(n, g_count, r)
    lane_vals = vals_f[:, safe.reshape(-1)].reshape(n, g_count, r).copy()
    lane_vals[:, pad] = 0.0

    # Conjunction key: fold member (field, value) mixes in lane order so
    # the tuple (not the multiset) is keyed; padded lanes fold a constant.
    key = np.full((n, g_count), _U64(seed), dtype=_U64)
    with np.errstate(over="ignore"):
        key = splitmix64(key)
        for j in range(r):
            fj = np.where(pad[:, j], _U64(0xD1F), safe[:, j].astype(_U64))
            vj = np.where(pad[None, :, j], _U64(0), member_ids[:, :, j].astype(_U64))
            key = splitmix64(key ^ splitmix64(vj + splitmix64(fj + _U64(0x9E))))
    blocks = (key % _U64(num_blocks)).astype(np.int64)
    return blocks, lane_vals


def default_field_groups(num_fields: int, block_size: int) -> np.ndarray:
    """Consecutive grouping: fields 0..F-1 chunked into ceil(F/R) groups
    of R, the last padded with -1.

    The grouping is a statistical knob (co-hashed fields share a
    conjunction key — see :func:`hash_group_blocks`); consecutive chunks
    are the neutral default when no field-cardinality information exists.
    """
    g_count = -(-num_fields // block_size)
    groups = np.full((g_count, block_size), -1, dtype=np.int64)
    flat = groups.reshape(-1)
    flat[:num_fields] = np.arange(num_fields)
    return groups


def split_field_groups(num_fields: int, block_size: int,
                       num_groups: int = 0) -> np.ndarray:
    """Field grouping with an explicit group count.

    ``num_groups=0`` (the default everywhere) keeps the historical
    :func:`default_field_groups` layout — consecutive R-sized chunks —
    so existing data hashes identically.  ``num_groups == ceil(F/R)``
    returns that SAME default layout (one canonical grouping per
    (F, R, G) triple — the advisor's normalization of G to 0 and an
    explicit ``--block-groups ceil(F/R)`` must hash identically, or a
    model trained one way and evaluated the other silently scores
    garbage).  Larger ``num_groups=G`` splits the fields into G
    near-equal consecutive groups, each padded to R lanes: the
    intermediate groupings between ceil(F/R) chunks and one all-fields
    conjunction.  Measured motivation (r5 operating-point
    sweep, ``benchmarks/FRONTIER_TPU.json``): on low-cardinality i.i.d.
    fields the single-group R=32 layout loses ~28pt (21-field tuples
    never recur) while the SAME R at G=3 holds within 0.3pt of scalar
    hashing — extra groups trade one extra row gather per sample for
    tuple spaces small enough to recur.
    """
    g_min = -(-num_fields // block_size)
    if num_groups in (0, None) or num_groups == g_min:
        return default_field_groups(num_fields, block_size)
    g = int(num_groups)
    if g < g_min or g > num_fields:
        raise ValueError(
            f"num_groups={g} outside [{g_min}, {num_fields}] for "
            f"{num_fields} fields at block_size={block_size} (each group "
            f"holds at most {block_size} fields, at least 1)"
        )
    groups = np.full((g, block_size), -1, dtype=np.int64)
    bounds = np.linspace(0, num_fields, g + 1).astype(int)
    for i in range(g):
        m = bounds[i + 1] - bounds[i]
        groups[i, :m] = np.arange(bounds[i], bounds[i + 1])
    return groups


def suggest_block_size(raw_ids, num_buckets: int,
                       candidates: tuple[int, ...] = (32, 16, 8),
                       *,
                       min_recurrence: float = 32.0,
                       max_row_load: float = 0.5,
                       max_row_load_single: float = 0.1) -> int:
    """Data-driven block-size advisor: the largest candidate R whose
    conjunction groups would actually TRAIN on this data, else 1
    (scalar hashing).

    Row-blocked hashing (:func:`hash_group_blocks`) keys table rows per
    (field-group, value-tuple), so it only learns where tuples recur
    and rows don't collide.  The measured frontier
    (``bench_configs.py`` ``blocked_frontier``, on-chip): at 512
    distinct tuples recurring ~96x, R=16 holds accuracy within 0.4pt
    of scalar hashing at 3.4x its throughput, while R=32 loses ~9pt
    because 512 tuples into D/32 rows is load factor 1 (birthday
    collisions) — and on high-cardinality i.i.d. fields every R fails
    (tuples never recur).  This function checks exactly those two
    failure modes on a sample of real rows:

      recurrence  min over groups of  N / distinct(group tuples)
                  must be >= ``min_recurrence`` (rows are trained per
                  tuple; each needs enough label observations)
      collision   total distinct tuples / (D/R table rows), discounted
      exposure    by the group count G, must be <= ``max_row_load``
                  when G >= 2, and <= ``max_row_load_single`` when the
                  candidate puts ALL fields in one group.  A colliding
                  row averages unrelated conjunctions, but with G >= 2
                  the other groups' rows partially compensate, so
                  corruption scales well below 1/G; at G=1 the row IS
                  the whole logit and there is no redundancy to absorb
                  it.  Measured anchors (equal-param frontier + r5
                  operating-point sweep, correlated-tuples regime):
                  G=2 at row load 1.0 held within 0.4pt, while G=1
                  lost 9.5pt at load 1.0, still lost 3.8pt at load
                  0.25, and only reached parity (+0.2pt) at load
                  0.016 — hence the much stricter single-group bound.

    Recurrence is necessary, not sufficient: purely additive signal
    with no field interactions can still favor scalar hashing by a
    point or two (the low-cardinality i.i.d. row of the frontier held
    R=8 at -2.3pt despite 192x recurrence), so treat the suggestion as
    a starting point and validate with eval metrics.  Pass a
    representative sample (1e5 rows is plenty — both statistics
    concentrate); N below is the sample size, so thresholds are
    computed against the sample, not the full dataset.
    """
    raw_ids = np.asarray(raw_ids, dtype=np.int64)
    n, num_fields = raw_ids.shape
    if n == 0:
        raise ValueError(
            "suggest_block_size needs a non-empty sample of raw rows"
        )
    for r in sorted(candidates, reverse=True):
        groups = default_field_groups(num_fields, r)
        if _grouping_passes(n, _distinct_group_tuples(raw_ids, groups),
                            num_buckets, r, min_recurrence, max_row_load,
                            max_row_load_single):
            return r
    return 1


def _distinct_group_tuples(raw_ids, groups) -> list[int]:
    """Distinct value-tuple count per group (the advisor's raw stat)."""
    return [len(np.unique(raw_ids[:, g[g >= 0]], axis=0)) for g in groups]


def _grouping_passes(n: int, distinct: list[int], num_buckets: int, r: int,
                     min_recurrence: float, max_row_load: float,
                     max_row_load_single: float) -> bool:
    """The advisor's two statistical gates, evaluated on an explicit
    grouping's distinct-tuple counts (shared by
    :func:`suggest_block_size` and :func:`suggest_blocking` so the
    measured thresholds live once)."""
    recurrence = n / max(distinct)
    load = sum(distinct) / max(num_buckets // r, 1)
    load_ok = (load <= max_row_load_single if len(distinct) == 1
               else load / len(distinct) <= max_row_load)
    return recurrence >= min_recurrence and load_ok


def suggest_blocking(raw_ids, num_buckets: int,
                     r_candidates: tuple[int, ...] = (32, 16, 8),
                     *,
                     num_groups: int = 0,
                     max_groups: int = 4,
                     min_recurrence: float = 32.0,
                     max_row_load: float = 0.5,
                     max_row_load_single: float = 0.1) -> tuple[int, int]:
    """Joint (block_size, block_groups) advisor: the cheapest layout
    whose conjunction groups would actually train, else ``(1, 0)``
    (scalar hashing).

    Generalizes :func:`suggest_block_size` over explicit group counts
    (:func:`split_field_groups`): candidates are ordered by gather cost
    — fewest groups first (each group is one row gather per sample,
    the dominant cost on the measured gather-bound step), then smallest
    fitting R (fewer lanes fetched).  Each candidate is gated by the
    same recurrence/row-load thresholds as :func:`suggest_block_size`,
    evaluated on the grouping ACTUALLY trained — this is what lets the
    advisor find e.g. (R=8, 3 default groups) on low-cardinality
    i.i.d. fields where every single-group layout fails, or step down
    to more groups when a wide single group would collide.

    ``num_groups > 0`` pins the user's group count and only searches R
    (the ``--block-size auto --block-groups G`` path).  ``max_groups``
    bounds the EXTRA groups the unpinned search will spend; the default
    ceil(F/R) chunking of every candidate R is always searched
    regardless, so wide-field data never loses a layout the plain
    :func:`suggest_block_size` would have tried.  The returned group
    count is normalized to 0 when it equals the default ceil(F/R)
    chunking, keeping resolved configs canonical.
    """
    raw_ids = np.asarray(raw_ids, dtype=np.int64)
    n, num_fields = raw_ids.shape
    if n == 0:
        raise ValueError("suggest_blocking needs a non-empty sample of raw rows")
    rs = sorted(r_candidates)
    if num_groups:
        g_values = [int(num_groups)]
    else:
        # 1..max_groups bounds the EXTRA gathers auto may spend, but the
        # default ceil(F/R) chunking of every candidate R must always be
        # searched — otherwise wide-field data (F > max_groups * min R)
        # would silently lose layouts the plain R advisor always tried
        g_values = sorted(
            set(range(1, min(max_groups, num_fields) + 1))
            | {-(-num_fields // r) for r in rs}
        )
    # distinct-tuple counts depend only on group MEMBERSHIP, which many
    # (r, g) candidates share — memoize so the np.unique sorts (the
    # advisor's entire cost on a 100k-row sample) run once per layout
    # key must include the shape: a (2, 8) and a (1, 16) grouping over
    # fields 0..15 serialize to identical bytes
    memo: dict[tuple, list[int]] = {}

    def distinct_of(groups) -> list[int]:
        key = (groups.shape, groups.tobytes())
        if key not in memo:
            memo[key] = _distinct_group_tuples(raw_ids, groups)
        return memo[key]

    any_feasible = False
    for g in g_values:
        for r in rs:
            if r * g < num_fields or g > num_fields:
                continue  # G groups of <= R lanes cannot hold every field
            any_feasible = True
            groups = split_field_groups(num_fields, r, g)
            if _grouping_passes(n, distinct_of(groups), num_buckets, r,
                                min_recurrence, max_row_load,
                                max_row_load_single):
                return r, (0 if g == -(-num_fields // r) else g)
    if num_groups and not any_feasible:
        # A pinned G that no candidate R can realize is a config error,
        # not a data statistic — raise like split_field_groups would,
        # instead of silently training scalar with a misleading log.
        raise ValueError(
            f"block_groups={int(num_groups)} is infeasible for "
            f"{num_fields} fields with block-size candidates {tuple(rs)} "
            f"(need ceil(fields/G) <= R and G <= fields)"
        )
    return 1, 0


def resolve_auto_block_size(data_dir: str, ctr_fields: int, num_buckets: int,
                            *, sample_rows: int = 100_000,
                            num_groups: int = 0) -> tuple[int, int]:
    """Resolve ``block_size=0`` ("auto") for a raw-CTR data dir: run
    :func:`suggest_blocking` on a sample of the first train shard and
    return ``(block_size, block_groups)`` (``block_groups`` 0 = default
    ceil(F/R) chunking; ``(1, 0)`` = scalar fallback).  ``num_groups``
    pins an explicit ``--block-groups`` so the advisor validates the
    grouping actually trained.  Requires raw shards on disk — auto
    cannot work on pre-encoded or injected data (the raw categorical
    ids are gone by then)."""
    from distlr_tpu.data.sharding import part_name  # noqa: PLC0415

    path = os.path.join(data_dir, "train", part_name(0))
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"block_size=0 (auto) needs raw-CTR shards to sample; no "
            f"{path} — pass an explicit --block-size instead"
        )
    num_fields = resolve_ctr_fields(data_dir, ctr_fields)
    # Representative sample: stride line reads across the WHOLE shard
    # (row count estimated from file size) instead of taking the head —
    # time-/user-ordered CTR logs cluster identical tuples, so a head
    # sample over-counts recurrence and can green-light exactly the
    # too-wide R the advisor exists to reject.  Striding parses only
    # ~sample_rows rows regardless of shard size.
    import itertools  # noqa: PLC0415

    with open(path, "rb") as f:
        probe = list(itertools.islice(f, 200))
    if not probe:
        raise ValueError(
            f"{path} is empty; cannot sample for block_size auto"
        )
    avg_line = sum(len(ln) for ln in probe) / len(probe)
    approx_rows = max(1, int(os.path.getsize(path) / avg_line))
    # CEIL division: a floor stride of 1 on a shard just over
    # sample_rows would keep only the head — the bias this whole path
    # exists to avoid; ceil guarantees the kept lines span the file.
    stride = max(1, -(-approx_rows // sample_rows))
    raw_ids, _ = read_raw_ctr_file(path, num_fields,
                                   max_rows=sample_rows, stride=stride)
    # only Rs that divide the table (get_model requires it; 1M-style
    # power-of-two bucket counts keep every candidate)
    candidates = tuple(r for r in (32, 16, 8) if num_buckets % r == 0)
    return suggest_blocking(raw_ids, num_buckets, candidates,
                            num_groups=num_groups)


def encode_blocked(raw_ids, num_blocks: int, block_size: int, *, seed: int = 0,
                   raw_vals=None, field_groups=None, num_groups: int = 0):
    """Raw ``(N, F)`` categorical ids -> ``BlockedSparseLR`` batch leaves
    ``(blocks, lane_vals)``.

    The one load-time call sites use; keeps the train/test splits of a
    run hashing identically as long as they share ``seed``, shape, and
    grouping.  ``num_groups=0`` keeps the default consecutive chunking;
    ``num_groups=G`` selects the near-equal G-way split
    (:func:`split_field_groups` — ``cfg.block_groups`` end to end).
    Returns ``(blocks (N, G) int32, lane_vals (N, G, R) float32)``.
    """
    raw_ids = np.asarray(raw_ids, dtype=np.int64)
    if field_groups is None:
        field_groups = split_field_groups(raw_ids.shape[1], block_size,
                                          num_groups)
    blocks, lane_vals = hash_group_blocks(
        raw_ids, field_groups, num_blocks, seed=seed, raw_vals=raw_vals
    )
    return blocks.astype(np.int32), lane_vals


@dataclasses.dataclass(frozen=True)
class HashedFeatureEncoder:
    """Stateless encoder from raw (field, id, value) features to a fixed
    ``num_buckets``-dimensional space.

    The TPU-native successor of the reference's fixed ``NUM_FEATURE_DIM``
    contract (``src/main.cc:130-131``): instead of requiring the data to
    already live in ``[0, D)``, any 64-bit id space is folded into
    ``[0, num_buckets)`` deterministically.
    """

    num_buckets: int
    seed: int = 0
    signed: bool = False

    def encode_coo(self, field_ids, raw_ids, raw_vals=None):
        """(..., F) raw ids -> (cols, vals) in bucket space, same shape."""
        cols, signs = hash_buckets(
            raw_ids, self.num_buckets, seed=self.seed, field_ids=field_ids
        )
        vals = np.ones(cols.shape, np.float32) if raw_vals is None else np.asarray(
            raw_vals, np.float32
        )
        if self.signed:
            vals = vals * signs
        return cols, vals

    def encode_dense(self, field_ids, raw_ids, raw_vals=None):
        """(B, F) raw ids -> dense (B, num_buckets) float32 (scatter-add)."""
        cols, vals = self.encode_coo(field_ids, raw_ids, raw_vals)
        B = cols.shape[0]
        X = np.zeros((B, self.num_buckets), np.float32)
        rows = np.repeat(np.arange(B), cols.shape[1])
        np.add.at(X, (rows, cols.reshape(-1)), vals.reshape(-1))
        return X

    def encode_csr(self, row_ptr, cols, vals):
        """Rehash CSR column ids (no field namespacing) into bucket space;
        returns CSR with the same row_ptr."""
        new_cols, signs = hash_buckets(cols, self.num_buckets, seed=self.seed)
        new_vals = np.asarray(vals, np.float32)
        if self.signed:
            new_vals = new_vals * signs
        return row_ptr, new_cols, new_vals


def csr_to_padded_coo(row_ptr, cols, vals, *, nnz_max: int | None = None):
    """CSR arrays -> static-shape padded COO ``(cols, vals)`` of shape
    ``(B, nnz_max)`` (pad col = 0, pad val = 0) — the ``SparseBinaryLR``
    batch layout (static shapes; XLA compiles one program per NNZ_MAX).

    Rows longer than ``nnz_max`` are truncated (keeping the first entries);
    callers wanting losslessness pass ``nnz_max=None`` (= longest row).
    """
    row_ptr = np.asarray(row_ptr)
    n = len(row_ptr) - 1
    lengths = np.diff(row_ptr)
    if nnz_max is None:
        nnz_max = int(lengths.max()) if n else 0
    nnz_max = max(int(nnz_max), 1)
    out_cols = np.zeros((n, nnz_max), np.int32)
    out_vals = np.zeros((n, nnz_max), np.float32)
    # vectorized gather: entry (i, j) reads CSR slot row_ptr[i] + j while
    # j < min(len_i, nnz_max) (startup-path hot loop for CTR-scale shards)
    j = np.arange(nnz_max)[None, :]
    valid = j < np.minimum(lengths, nnz_max)[:, None]
    src = row_ptr[:-1, None] + j
    out_cols[valid] = cols[src[valid]]
    out_vals[valid] = vals[src[valid]]
    return out_cols, out_vals


def make_ctr_dataset(
    num_samples: int,
    num_fields: int,
    vocab_size: int,
    num_buckets: int,
    *,
    seed: int = 0,
    signed: bool = False,
    noise: float = 0.0,
    num_distinct_tuples: int | None = None,
    center_logits: bool = False,
):
    """Deterministic synthetic CTR data: ``num_fields`` categorical fields,
    each drawing one value from ``vocab_size``, labels from a logistic
    model over the *hashed* one-hot encoding.

    Ground truth lives in bucket space (``w_true`` shape
    ``(num_buckets,)``), so the learnable signal survives hash collisions
    by construction and convergence tests can assert recovery.

    ``num_distinct_tuples`` models correlated fields (real CTR fields are
    rarely independent — e.g. one device model fixes many of them): rows
    are drawn uniformly from a fixed table of that many distinct (F,)
    value tuples, so every tuple recurs ~N/T times regardless of
    ``vocab_size``.  This is the recurrence regime the row-blocked
    hashing path (:func:`hash_group_blocks`) needs; ``None`` keeps the
    fields i.i.d. (tuples essentially never recur at realistic vocab).

    ``center_logits`` subtracts the mean logit before sampling labels.
    At low vocab the handful of occupied buckets gives the logit a
    random O(1) mean offset, which can push the class marginal to 90%+
    and let a majority-class predictor fake high accuracy; centering
    keeps the base rate near 0.5 so accuracy comparisons measure signal.

    Returns ``(raw_ids, cols, vals, y, w_true)`` where ``raw_ids`` is the
    ``(N, F)`` categorical draw, ``(cols, vals)`` its ``(N, F)`` hashed
    padded-COO encoding, and ``y`` in {0,1}.
    """
    rng = np.random.default_rng(seed)
    if num_distinct_tuples is not None:
        table = rng.integers(
            0, vocab_size, size=(num_distinct_tuples, num_fields))
        raw_ids = table[rng.integers(0, num_distinct_tuples, size=num_samples)]
    else:
        raw_ids = rng.integers(0, vocab_size, size=(num_samples, num_fields))
    field_ids = np.broadcast_to(np.arange(num_fields), raw_ids.shape)
    enc = HashedFeatureEncoder(num_buckets, seed=seed, signed=signed)
    cols, vals = enc.encode_coo(field_ids, raw_ids)
    w_true = (rng.standard_normal(num_buckets) * (3.0 / np.sqrt(num_fields))).astype(
        np.float32
    )
    logits = np.sum(w_true[cols] * vals, axis=-1)
    if center_logits:
        logits = logits - logits.mean()
    if noise > 0.0:
        logits += noise * rng.standard_normal(num_samples)
    p = 1.0 / (1.0 + np.exp(-logits))
    y = (rng.random(num_samples) < p).astype(np.int32)
    return raw_ids, cols.astype(np.int32), vals, y, w_true


def write_ctr_shards(
    data_dir: str,
    num_samples: int,
    num_fields: int,
    vocab_size: int,
    num_buckets: int,
    num_parts: int,
    *,
    seed: int = 0,
    test_fraction: float = 0.2,
) -> dict:
    """Write hashed one-hot CTR data as reference-layout libsvm shards
    (``train/part-001..``, ``test/part-001``, ``models/``), rows being
    ``label idx:1 idx:1 ...`` over 1-based bucket ids — byte-compatible
    with the reference's data contract (``include/data_iter.h:19-34``) at
    ``NUM_FEATURE_DIM = num_buckets``."""
    from distlr_tpu.data.sharding import part_name  # noqa: PLC0415

    _, cols, vals, y, w_true = make_ctr_dataset(
        num_samples, num_fields, vocab_size, num_buckets, seed=seed
    )
    n_test = int(num_samples * test_fraction)
    os.makedirs(os.path.join(data_dir, "train"), exist_ok=True)
    os.makedirs(os.path.join(data_dir, "test"), exist_ok=True)
    os.makedirs(os.path.join(data_dir, "models"), exist_ok=True)

    def _write(path, c, v, labels):
        with open(path, "w") as f:
            for i in range(len(labels)):
                toks = [str(2 * int(labels[i]) - 1)]  # +/-1 labels like a9a
                # merge intra-row hash collisions (sum values per bucket) —
                # libsvm indices must be unique & ascending, and the dense
                # parse path assigns rather than accumulates duplicates
                uniq, inv = np.unique(c[i], return_inverse=True)
                summed = np.zeros(len(uniq), np.float32)
                np.add.at(summed, inv, v[i])
                toks += [
                    f"{int(uc) + 1}:{sv:g}" for uc, sv in zip(uniq, summed) if sv != 0
                ]
                f.write(" ".join(toks) + "\n")

    ctr, cte = cols[n_test:], cols[:n_test]
    vtr, vte = vals[n_test:], vals[:n_test]
    ytr, yte = y[n_test:], y[:n_test]
    parts = []
    for i in range(num_parts):
        sl = slice(i * len(ytr) // num_parts, (i + 1) * len(ytr) // num_parts)
        path = os.path.join(data_dir, "train", part_name(i))
        _write(path, ctr[sl], vtr[sl], ytr[sl])
        parts.append(path)
    test_path = os.path.join(data_dir, "test", part_name(0))
    _write(test_path, cte, vte, yte)
    w_path = os.path.join(data_dir, "w_true.npy")
    np.save(w_path, w_true)
    return {"train_parts": parts, "test_path": test_path, "w_true_path": w_path}


_CTR_META = "ctr_meta.json"


def read_ctr_meta(data_dir: str) -> dict | None:
    """The raw-CTR manifest written by :func:`write_raw_ctr_shards`
    (None when the dir holds plain libsvm / hashed shards instead)."""
    import json  # noqa: PLC0415

    path = os.path.join(data_dir, _CTR_META)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def make_uniform_blocked_batch(rng, n: int, num_fields: int,
                               num_blocks: int, block_size: int):
    """Uniform-random one-hot blocked batch ``(blocks, lane_vals)`` for
    benchmarks/tests: ``ceil(F/R)`` groups with the last group's padded
    lanes zeroed — the layout ``default_field_groups`` +
    ``hash_group_blocks`` produce for one-hot data, without the hashing
    (bench workloads want uniform row access, not a data distribution)."""
    g_count = -(-num_fields // block_size)
    blocks = rng.integers(0, num_blocks, size=(n, g_count)).astype(np.int32)
    lane_vals = np.ones((n, g_count, block_size), np.float32)
    pad = g_count * block_size - num_fields
    if pad:
        lane_vals[:, -1, block_size - pad:] = 0.0
    return blocks, lane_vals


def resolve_ctr_fields(data_dir: str, ctr_fields: int) -> int:
    """The raw field count for blocked loading: from the data dir's
    manifest, or from an explicit ``cfg.ctr_fields`` when there is no
    manifest.  When BOTH exist they must agree — a conflict raises here
    (config error) rather than surfacing later as a per-row parse
    failure."""
    meta = read_ctr_meta(data_dir)
    if ctr_fields:
        if meta is not None and int(meta["num_fields"]) != int(ctr_fields):
            # Surface the config-vs-manifest conflict here, where both
            # sources are visible — not later as a baffling per-row
            # "row has N fields, expected M" parse error.
            raise ValueError(
                f"cfg.ctr_fields={int(ctr_fields)} conflicts with "
                f"{os.path.join(data_dir, _CTR_META)} num_fields="
                f"{int(meta['num_fields'])} — drop ctr_fields to trust the "
                "manifest, or regenerate the shards"
            )
        return int(ctr_fields)
    if meta is None:
        raise FileNotFoundError(
            f"{data_dir} has no {_CTR_META} manifest and cfg.ctr_fields is 0 "
            "— blocked_lr needs the raw field count (write shards with "
            "write_raw_ctr_shards / `launch gen-data --ctr-fields F "
            "--ctr-raw`, or set ctr_fields)"
        )
    return int(meta["num_fields"])


def write_raw_ctr_shards(
    data_dir: str,
    num_samples: int,
    num_fields: int,
    vocab_size: int,
    num_parts: int,
    *,
    seed: int = 0,
    test_fraction: float = 0.2,
    num_distinct_tuples: int | None = None,
) -> dict:
    """Write RAW categorical CTR shards: reference-layout parts whose rows
    are ``±1 field:id ...`` with 1-based field numbers and the raw
    categorical id as the "value".

    Unlike :func:`write_ctr_shards` (which bakes scalar bucket hashing
    into the bytes on disk), this format is **hash-scheme agnostic**: the
    same shard trains the scalar one-hot path (`hash_buckets` at load
    time) or the row-blocked path (`hash_group_blocks`) — the hashing is
    a load-time choice, exactly like the encoder split the roofline study
    calls for (benchmarks/ROOFLINE.md, row-blocked section).  Labels come
    from the same hashed-ground-truth logistic model as
    :func:`make_ctr_dataset`, so signal recovery stays assertable.

    A ``ctr_meta.json`` manifest records ``num_fields``/``vocab``/``seed``
    so loaders need no side-channel configuration.  Raw ids ride the
    libsvm float value slot; float32 is exact below 2**24, enforced here.
    """
    import json  # noqa: PLC0415

    from distlr_tpu.data.sharding import part_name  # noqa: PLC0415

    if vocab_size >= 1 << 24:
        raise ValueError(
            f"vocab_size {vocab_size} exceeds float32's exact-integer range "
            "(2^24); raw ids would corrupt in the libsvm value slot"
        )
    # num_distinct_tuples models correlated fields (see make_ctr_dataset)
    # — the tuple-recurrent regime the blocked path needs to learn
    raw_ids, _, _, y, w_true = make_ctr_dataset(
        num_samples, num_fields, vocab_size, max(num_fields * 64, 1024),
        seed=seed, num_distinct_tuples=num_distinct_tuples,
    )
    n_test = int(num_samples * test_fraction)
    os.makedirs(os.path.join(data_dir, "train"), exist_ok=True)
    os.makedirs(os.path.join(data_dir, "test"), exist_ok=True)
    os.makedirs(os.path.join(data_dir, "models"), exist_ok=True)

    def _write(path, ids, labels):
        with open(path, "w") as f:
            for i in range(len(labels)):
                toks = [str(2 * int(labels[i]) - 1)]
                toks += [f"{j + 1}:{int(ids[i, j])}" for j in range(num_fields)]
                f.write(" ".join(toks) + "\n")

    itr, ite = raw_ids[n_test:], raw_ids[:n_test]
    ytr, yte = y[n_test:], y[:n_test]
    parts = []
    for i in range(num_parts):
        sl = slice(i * len(ytr) // num_parts, (i + 1) * len(ytr) // num_parts)
        path = os.path.join(data_dir, "train", part_name(i))
        _write(path, itr[sl], ytr[sl])
        parts.append(path)
    test_path = os.path.join(data_dir, "test", part_name(0))
    _write(test_path, ite, yte)
    meta = {
        "format": "raw_ctr",
        "num_fields": num_fields,
        "vocab_size": vocab_size,
        "seed": seed,
        # provenance only: loaders never read this (the block-size
        # advisor measures recurrence empirically), but a human auditing
        # a data dir should see whether rows were drawn from a fixed
        # tuple table (correlated fields) or i.i.d.
        "num_distinct_tuples": num_distinct_tuples,
    }
    with open(os.path.join(data_dir, _CTR_META), "w") as f:
        json.dump(meta, f)
    w_path = os.path.join(data_dir, "w_true.npy")
    np.save(w_path, w_true)
    return {"train_parts": parts, "test_path": test_path,
            "w_true_path": w_path, "meta": meta}


def csr_to_raw_ids(row_ptr, cols, vals, num_fields: int, *,
                   origin: str = "input") -> np.ndarray:
    """Validated CSR -> raw ``(N, F) int64`` id matrix — THE raw-CTR row
    assembly, shared by the shard reader and the serving front-end so
    training and serving parse (and REJECT) identically.

    ``cols`` give the 0-based field slot, in any order; ``vals`` are the
    raw categorical ids riding the libsvm value slot.  Rejects: a row
    with a missing/extra field, a field number outside ``1..F``, a
    negative / fractional / >= 2^24 id (the float32 value slot has
    already corrupted larger ids), and a duplicated field number (which
    passes the length check but leaves its partner slot unwritten).
    ``origin`` names the source (file path, "request") in errors.
    """
    row_ptr = np.asarray(row_ptr)
    cols = np.asarray(cols)
    vals = np.asarray(vals)
    n = len(row_ptr) - 1
    lengths = np.diff(row_ptr)
    if n and not (lengths == num_fields).all():
        bad = int(np.argmax(lengths != num_fields))
        raise ValueError(
            f"{origin}: row {bad} has {int(lengths[bad])} fields, expected "
            f"{num_fields} (raw-CTR rows carry every field)"
        )
    if n and ((cols < 0).any() or (cols >= num_fields).any()):
        bad = int(cols[(cols < 0) | (cols >= num_fields)][0]) + 1
        raise ValueError(
            f"{origin}: field number {bad} outside 1..{num_fields}"
        )
    if (vals < 0).any():
        raise ValueError(f"{origin}: raw-CTR ids must be non-negative")
    if (vals != np.floor(vals)).any():
        raise ValueError(
            f"{origin}: raw-CTR ids must be integers (found fractional value)"
        )
    if (vals >= float(1 << 24)).any():
        # Mirror write_raw_ctr_shards' bound: an id >= 2^24 has already
        # been rounded in the float32 value slot, so casting it to int64
        # would yield a silently-corrupted id, not the one on disk.
        raise ValueError(
            f"{origin}: raw-CTR id exceeds float32's exact-integer range "
            "(2^24); the id was already corrupted when it was encoded"
        )
    raw_ids = np.full((n, num_fields), -1, np.int64)
    raw_ids[np.repeat(np.arange(n), num_fields), cols] = vals.astype(np.int64)
    if (raw_ids < 0).any():
        bad = int(np.argmax((raw_ids < 0).any(axis=1)))
        raise ValueError(
            f"{origin}: row {bad} repeats a field number (every field must "
            "appear exactly once)"
        )
    return raw_ids


def read_raw_ctr_file(path: str, num_fields: int, *,
                      max_rows: int | None = None, stride: int = 1):
    """Parse one raw-CTR shard -> ``(raw_ids (N, F) int64, y (N,) int32)``.

    Rides the existing libsvm parser (native fast path included): field
    numbers arrive as CSR columns, raw ids as float32 values (exact below
    2^24 by the writer's contract).  Every row must carry all F fields —
    raw-CTR is a dense-fields format, unlike one-hot libsvm.

    ``max_rows``/``stride`` select a row subsample at the LINE level
    (every ``stride``-th line, at most ``max_rows`` of them) without
    parsing or materializing the rest of the shard — the advisor's
    sampling path (:func:`resolve_auto_block_size`).
    """
    from distlr_tpu.data.libsvm import (  # noqa: PLC0415
        parse_libsvm_file,
        parse_libsvm_lines,
    )

    # num_features=None: keep ALL columns, so a shard with MORE fields
    # than expected fails the checks below instead of being silently
    # truncated to a passing width by the parser's column filter.
    if max_rows is None and stride == 1:
        (row_ptr, cols, vals), y = parse_libsvm_file(path, None, dense=False)
    else:
        import itertools  # noqa: PLC0415

        stop = None if max_rows is None else max_rows * stride
        with open(path) as f:  # text mode: the line parser wants str
            lines = list(itertools.islice(f, 0, stop, stride))
        (row_ptr, cols, vals), y = parse_libsvm_lines(lines, None, dense=False)
    return csr_to_raw_ids(row_ptr, cols, vals, num_fields, origin=path), y
