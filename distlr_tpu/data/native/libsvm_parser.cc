// Native libsvm tokenizer — the hot parse path of the data layer.
//
// The one place the reference is CPU-native and stays CPU-native in the
// TPU framework: its equivalent is the hand-rolled parser stack in
// include/data_iter.h:16-35 + src/util.cc (Split/ToInt/ToFloat), which
// (a) re-parses the whole shard from disk every epoch and (b) cannot
// parse signs or exponents in feature values (SURVEY.md Q6).  This
// parser is a two-pass CSR tokenizer over one contiguous buffer using
// strtof/strtol (full float syntax), exposed through a plain-C API for
// ctypes (distlr_tpu/data/_native.py).
//
// Pass 1 (libsvm_count): count rows and nonzeros so Python can allocate
// exact-size numpy arrays.  Pass 2 (libsvm_parse) fills them.
//
// Label rule matches the reference (data_iter.h:27): binary mode maps
// label != 1 -> 0; multiclass keeps the integer.

#include <cstdint>
#include <cstdlib>
#include <cstring>

namespace {

inline bool is_space(char c) { return c == ' ' || c == '\t' || c == '\r'; }

}  // namespace

extern "C" {

// Counts rows (non-empty lines) and total nonzero features.
// Returns 0 on success.
int libsvm_count(const char* buf, int64_t n, int64_t* n_rows, int64_t* n_nnz) {
  int64_t rows = 0, nnz = 0;
  int64_t i = 0;
  while (i < n) {
    // skip leading whitespace on the line
    while (i < n && is_space(buf[i])) ++i;
    if (i >= n) break;
    if (buf[i] == '\n') { ++i; continue; }  // empty line
    ++rows;
    // label token
    while (i < n && !is_space(buf[i]) && buf[i] != '\n') ++i;
    // feature tokens
    while (i < n && buf[i] != '\n') {
      while (i < n && is_space(buf[i])) ++i;
      if (i >= n || buf[i] == '\n') break;
      if (buf[i] == '#') {  // trailing comment: skip to EOL
        while (i < n && buf[i] != '\n') ++i;
        break;
      }
      ++nnz;
      while (i < n && !is_space(buf[i]) && buf[i] != '\n') ++i;
    }
    if (i < n) ++i;  // consume newline
  }
  *n_rows = rows;
  *n_nnz = nnz;
  return 0;
}

// Fills pre-allocated arrays:
//   labels:  int32 [n_rows]
//   row_ptr: int64 [n_rows + 1]   (row_ptr[0] = 0)
//   cols:    int32 [n_nnz]        (1-based input -> 0-based output)
//   vals:    float32 [n_nnz]
// Returns number of rows parsed, or -1 on malformed input.
int64_t libsvm_parse(const char* buf, int64_t n, int multiclass,
                     int32_t* labels, int64_t* row_ptr, int32_t* cols,
                     float* vals) {
  int64_t row = 0, k = 0;
  int64_t i = 0;
  row_ptr[0] = 0;
  while (i < n) {
    while (i < n && is_space(buf[i])) ++i;
    if (i >= n) break;
    if (buf[i] == '\n') { ++i; continue; }

    char* end = nullptr;
    const double raw_label = strtod(buf + i, &end);
    if (end == buf + i) return -1;  // no numeric label
    i = end - buf;
    labels[row] = multiclass ? static_cast<int32_t>(raw_label)
                             : (raw_label == 1.0 ? 1 : 0);

    while (i < n && buf[i] != '\n') {
      while (i < n && is_space(buf[i])) ++i;
      if (i >= n || buf[i] == '\n') break;
      if (buf[i] == '#') {
        while (i < n && buf[i] != '\n') ++i;
        break;
      }
      const long idx = strtol(buf + i, &end, 10);
      if (end == buf + i || *end != ':') return -1;
      i = (end - buf) + 1;  // skip ':'
      const float v = strtof(buf + i, &end);
      if (end == buf + i) return -1;
      i = end - buf;
      cols[k] = static_cast<int32_t>(idx - 1);  // 1-based -> 0-based
      vals[k] = v;
      ++k;
    }
    ++row;
    row_ptr[row] = k;
    if (i < n) ++i;
  }
  return row;
}

}  // extern "C"
