"""Shard preparation — the seeded replacement for ``examples/gen_data.py``.

The reference script (``examples/gen_data.py:9-45``) shuffles the a9a train
file with an *unseeded* ``random.shuffle`` and splits it into
``num_part=4`` equal shards named ``part-001..004`` plus ``test/part-001``.
This module does the same with a mandatory seed, any part count, and no
dependence on a downloaded dataset (pair with
:func:`distlr_tpu.data.synthetic.write_synthetic_shards`).
"""

from __future__ import annotations

import os
import random
import shutil


def part_name(i: int) -> str:
    """``part-001``-style shard name (reference gen_data.py:27,41)."""
    return f"part-{i + 1:03d}"


def shard_libsvm_file(
    src_path: str,
    out_dir: str,
    num_parts: int,
    *,
    seed: int = 0,
    shuffle: bool = True,
) -> list[str]:
    """Shuffle (seeded) and split a libsvm text file into equal shards."""
    with open(src_path) as f:
        # normalize endings: a missing final newline must not fuse two
        # samples into one line after shuffling
        lines = [ln.rstrip("\n") + "\n" for ln in f if ln.strip()]
    if shuffle:
        random.Random(seed).shuffle(lines)
    os.makedirs(out_dir, exist_ok=True)
    paths = []
    n = len(lines)
    for i in range(num_parts):
        chunk = lines[i * n // num_parts : (i + 1) * n // num_parts]
        path = os.path.join(out_dir, part_name(i))
        with open(path, "w") as f:
            f.writelines(chunk)
        paths.append(path)
    return paths


def prepare_data_dir(
    train_src: str,
    test_src: str,
    data_dir: str,
    num_parts: int = 4,
    *,
    seed: int = 0,
) -> dict:
    """Full gen_data.py equivalent: shard train, copy test, mk models/."""
    train_parts = shard_libsvm_file(train_src, os.path.join(data_dir, "train"), num_parts, seed=seed)
    test_dir = os.path.join(data_dir, "test")
    os.makedirs(test_dir, exist_ok=True)
    test_path = os.path.join(test_dir, part_name(0))
    shutil.copyfile(test_src, test_path)
    os.makedirs(os.path.join(data_dir, "models"), exist_ok=True)
    return {"train_parts": train_parts, "test_path": test_path}
