"""Epoch-based minibatch iterator over an in-memory shard.

TPU-native re-design of the reference ``distlr::DataIter``
(``include/data_iter.h:16-59``): one constructed iterator serves exactly
one pass (epoch) over its shard; ``batch_size=-1`` means the whole shard
(``data_iter.h:39-43``).  Differences, all deliberate:

* **Static shapes.** XLA compiles one program per distinct batch shape, so
  the final short batch is *padded* to ``batch_size`` and a boolean mask is
  returned — instead of the reference's Q5 wraparound quirk (which silently
  duplicates head samples into the last batch, ``data_iter.h:46-53``).
  ``drop_remainder=True`` gives the classic drop-last behavior; and
  ``wrap_compat=True`` reproduces Q5 exactly for parity experiments.
* Data lives in numpy on host; the training loop moves batches to device
  (``jax.device_put`` / sharding-aware placement in the trainer).
* Optional per-epoch shuffling (the reference never shuffles inside an
  epoch; it reshuffles only by re-running gen_data.py).
"""

from __future__ import annotations

import numpy as np


class DataIter:
    """One-epoch minibatch iterator with static batch shapes.

    Yields ``(X, y, mask)`` where mask flags real (non-padding) rows.
    """

    def __init__(
        self,
        X: np.ndarray,
        y: np.ndarray,
        batch_size: int = -1,
        *,
        shuffle: bool = False,
        seed: int = 0,
        drop_remainder: bool = False,
        wrap_compat: bool = False,
    ):
        self.X = np.asarray(X)
        self.y = np.asarray(y)
        if self.X.shape[0] != self.y.shape[0]:
            raise ValueError(f"X has {self.X.shape[0]} rows but y has {self.y.shape[0]}")
        n = self.X.shape[0]
        self.num_samples = n
        self.batch_size = n if batch_size == -1 else int(batch_size)
        if self.batch_size <= 0:
            raise ValueError(f"batch_size must be -1 or positive, got {batch_size}")
        self.drop_remainder = drop_remainder
        self.wrap_compat = wrap_compat
        self._order = np.arange(n)
        if shuffle:
            np.random.default_rng(seed).shuffle(self._order)
        self._offset = 0

    @classmethod
    def from_file(cls, path, num_features: int, batch_size: int = -1, *, multiclass: bool = False, **kw):
        from distlr_tpu.data.libsvm import parse_libsvm_file  # noqa: PLC0415
        X, y = parse_libsvm_file(path, num_features, multiclass=multiclass)
        return cls(X, y, batch_size, **kw)

    def has_next(self) -> bool:
        """True while this epoch still has unserved samples
        (mirrors reference ``HasNext``, ``data_iter.h:57-59``)."""
        if self.drop_remainder:
            return self._offset + self.batch_size <= self.num_samples
        return self._offset < self.num_samples

    def _next_idx(self):
        """Row indices + validity mask of the next static-shape batch."""
        if not self.has_next():
            raise StopIteration
        b = self.batch_size
        idx = self._order[self._offset : self._offset + b]
        if len(idx) < b and self.wrap_compat:
            # Q5 parity: wrap around and duplicate head samples, cycling as
            # many times as needed (the reference's NextBatch loop keeps
            # walking modulo the shard, data_iter.h:46-53).
            extra = np.take(self._order, np.arange(b - len(idx)), mode="wrap")
            idx = np.concatenate([idx, extra])
        self._offset += b
        real = len(idx)
        mask = np.ones(b, dtype=bool)
        if real < b:  # pad to static shape
            pad = b - real
            idx = np.concatenate([idx, np.zeros(pad, dtype=idx.dtype)])
            mask[real:] = False
        return idx, mask

    def next_batch(self):
        idx, mask = self._next_idx()
        return self.X[idx], self.y[idx], mask

    def __iter__(self):
        while self.has_next():
            yield self.next_batch()

    def reset(self) -> None:
        """Start a new epoch (the reference instead re-reads the file from
        disk every epoch — ``src/main.cc:158-159``; we keep the arrays)."""
        self._offset = 0

    @property
    def num_batches(self) -> int:
        if self.drop_remainder:
            return self.num_samples // self.batch_size
        return -(-self.num_samples // self.batch_size)


class SparseDataIter(DataIter):
    """Padded-COO variant: yields ``(cols, vals, y, mask)`` batches.

    ``cols``/``vals`` are ``(B, NNZ_MAX)`` per-row index/value arrays
    (pad col = 0, pad val = 0) — the ``SparseBinaryLR`` batch layout.
    Same epoch/batching semantics as :class:`DataIter` (the row arrays
    just carry two feature leaves instead of a dense matrix).
    """

    def __init__(self, cols, vals, y, batch_size: int = -1, **kw):
        cols = np.asarray(cols)
        self.vals = np.asarray(vals)
        if cols.shape != self.vals.shape:
            raise ValueError(f"cols {cols.shape} vs vals {self.vals.shape}")
        super().__init__(cols, y, batch_size, **kw)

    @property
    def cols(self) -> np.ndarray:
        return self.X

    @classmethod
    def from_file(cls, path, num_features: int | None = None, batch_size: int = -1,
                  *, nnz_max: int | None = None, multiclass: bool = False,
                  **kw):
        """Parse a libsvm shard WITHOUT densifying (CTR-scale feature
        spaces where ``(N, D)`` dense would not fit host RAM).
        ``multiclass`` keeps integer labels verbatim (sparse_softmax)."""
        from distlr_tpu.data.hashing import csr_to_padded_coo  # noqa: PLC0415
        from distlr_tpu.data.libsvm import parse_libsvm_file  # noqa: PLC0415

        (row_ptr, csr_cols, csr_vals), y = parse_libsvm_file(
            path, num_features, dense=False, multiclass=multiclass
        )
        cols, vals = csr_to_padded_coo(row_ptr, csr_cols, csr_vals, nnz_max=nnz_max)
        return cls(cols, vals, y, batch_size, **kw)

    def next_batch(self):
        idx, mask = self._next_idx()
        return self.X[idx], self.vals[idx], self.y[idx], mask


class BlockedDataIter(DataIter):
    """Row-blocked variant: yields ``(blocks, lane_vals, y, mask)`` —
    the :class:`distlr_tpu.models.BlockedSparseLR` batch layout.

    ``blocks`` is ``(B, G)`` int32 table-row ids, ``lane_vals`` is
    ``(B, G, R)`` float32 per-lane values (zero = padded lane).  Same
    epoch/batching semantics as :class:`DataIter`.
    """

    def __init__(self, blocks, lane_vals, y, batch_size: int = -1, **kw):
        blocks = np.asarray(blocks)
        self.lane_vals = np.asarray(lane_vals)
        if blocks.shape != self.lane_vals.shape[:2]:
            raise ValueError(
                f"blocks {blocks.shape} vs lane_vals {self.lane_vals.shape}"
            )
        super().__init__(blocks, y, batch_size, **kw)

    @property
    def blocks(self) -> np.ndarray:
        return self.X

    @classmethod
    def from_file(cls, path, num_fields: int, num_blocks: int, block_size: int,
                  batch_size: int = -1, *, seed: int = 0, num_groups: int = 0,
                  **kw):
        """Parse a raw-CTR shard (``write_raw_ctr_shards`` format) and
        hash its field groups into block rows at load time
        (``num_groups``: see ``hashing.split_field_groups``)."""
        from distlr_tpu.data.hashing import encode_blocked, read_raw_ctr_file  # noqa: PLC0415

        raw_ids, y = read_raw_ctr_file(path, num_fields)
        blocks, lane_vals = encode_blocked(
            raw_ids, num_blocks, block_size, seed=seed, num_groups=num_groups
        )
        return cls(blocks, lane_vals, y, batch_size, **kw)

    def next_batch(self):
        idx, mask = self._next_idx()
        return self.X[idx], self.lane_vals[idx], self.y[idx], mask
