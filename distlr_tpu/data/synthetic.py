"""Seeded synthetic dataset generation.

The reference's only data source is the external a9a libsvm files, shuffled
*without a seed* (``examples/gen_data.py:9-16`` uses unseeded
``random.shuffle``) — its fixtures are not reproducible.  This generator is
fully deterministic: a ground-truth weight vector is drawn, labels are
Bernoulli draws from the true logistic model, so convergence tests can
assert recovery of a known signal.

Also generates multiclass (softmax) and sparse one-hot style datasets for
BASELINE.json configs 4-5.
"""

from __future__ import annotations

import os

import numpy as np


def make_synthetic_dataset(
    num_samples: int,
    num_features: int,
    *,
    seed: int = 0,
    num_classes: int = 2,
    sparsity: float = 0.0,
    noise: float = 0.0,
    dtype=np.float32,
):
    """Deterministic synthetic classification data.

    Returns ``(X, y, w_true)``.  ``sparsity`` zeroes that fraction of
    entries (keeps the dense layout; use for sparse-path testing).
    For ``num_classes == 2`` labels are {0,1} and ``w_true`` is ``(D,)``;
    otherwise labels are {0..K-1} and ``w_true`` is ``(D, K)``.
    """
    rng = np.random.default_rng(seed)
    X = rng.standard_normal((num_samples, num_features)).astype(dtype)
    if sparsity > 0.0:
        X *= rng.random((num_samples, num_features)) >= sparsity
    if num_classes == 2:
        w_true = (rng.standard_normal(num_features) / np.sqrt(num_features)).astype(dtype)
        logits = X @ w_true * 3.0
        if noise > 0.0:
            logits += noise * rng.standard_normal(num_samples)
        p = 1.0 / (1.0 + np.exp(-logits))
        y = (rng.random(num_samples) < p).astype(np.int32)
    else:
        w_true = (rng.standard_normal((num_features, num_classes)) / np.sqrt(num_features)).astype(dtype)
        logits = X @ w_true * 3.0
        if noise > 0.0:
            logits += noise * rng.standard_normal((num_samples, num_classes))
        y = np.argmax(logits + rng.gumbel(size=logits.shape), axis=1).astype(np.int32)
    return X, y, w_true


def write_synthetic_shards(
    data_dir: str,
    num_samples: int,
    num_features: int,
    num_parts: int,
    *,
    seed: int = 0,
    test_fraction: float = 0.2,
    num_classes: int = 2,
    sparsity: float = 0.5,
) -> dict:
    """Create a reference-layout data directory from synthetic data.

    Layout matches ``examples/gen_data.py:29-45``:
    ``train/part-001..NNN``, ``test/part-001``, empty ``models/``.
    Returns a manifest dict (paths + ground truth weight file).
    """
    from distlr_tpu.data.libsvm import write_libsvm  # noqa: PLC0415
    from distlr_tpu.data.sharding import part_name  # noqa: PLC0415

    X, y, w_true = make_synthetic_dataset(
        num_samples, num_features, seed=seed, num_classes=num_classes, sparsity=sparsity
    )
    n_test = int(num_samples * test_fraction)
    Xtr, ytr, Xte, yte = X[n_test:], y[n_test:], X[:n_test], y[:n_test]

    train_dir = os.path.join(data_dir, "train")
    test_dir = os.path.join(data_dir, "test")
    os.makedirs(train_dir, exist_ok=True)
    os.makedirs(test_dir, exist_ok=True)
    os.makedirs(os.path.join(data_dir, "models"), exist_ok=True)

    parts = []
    binary = num_classes == 2
    for i in range(num_parts):
        sl = slice(i * len(Xtr) // num_parts, (i + 1) * len(Xtr) // num_parts)
        path = os.path.join(train_dir, part_name(i))
        write_libsvm(path, Xtr[sl], ytr[sl], binary_pm1=binary)
        parts.append(path)
    test_path = os.path.join(test_dir, part_name(0))
    write_libsvm(test_path, Xte, yte, binary_pm1=binary)
    w_path = os.path.join(data_dir, "w_true.npy")
    np.save(w_path, w_true)
    return {"train_parts": parts, "test_path": test_path, "w_true_path": w_path}
