"""libsvm text parsing — native C++ fast path with pure-Python fallback.

Replaces the reference's hand-rolled parser stack (``include/data_iter.h``
+ ``src/util.cc``) which densifies each sparse row eagerly and has several
parsing bugs the survey catalogues (SURVEY.md §3.5 Q6-Q7: ``ToFloat``
cannot parse signs or exponents; ``Split`` has a substr-length bug; any
label != 1 silently becomes 0).  This parser:

* handles signed / scientific-notation feature values correctly,
* maps labels configurably (default: the reference's ``label != 1 -> 0``
  rule, which is what a9a's ``-1/+1`` labels need),
* converts 1-based libsvm indices to 0-based (same as reference
  ``data_iter.h:30``),
* returns either a dense ``(N, D) float32`` matrix (what the TPU matmul
  path wants) or CSR arrays (for the sparse / segment_sum path),
* uses a native C extension (``distlr_tpu.data._native``) for the hot
  tokenize-and-convert loop when available, falling back to pure Python.
"""

from __future__ import annotations

import numpy as np

__all__ = [
    "parse_libsvm_lines",
    "parse_libsvm_file",
    "write_libsvm",
    "native_available",
]


def _map_label(raw: float, multiclass: bool) -> int:
    if multiclass:
        return int(raw)
    # Reference rule (data_iter.h:27): label is 1 iff the text parses to 1.
    return 1 if raw == 1 else 0


def _parse_python(lines, multiclass: bool):
    """Pure-Python tokenizer: returns (labels, row_ptr, cols, vals)."""
    labels: list[int] = []
    row_ptr = [0]
    cols: list[int] = []
    vals: list[float] = []
    for line in lines:
        toks = line.split()
        if not toks:
            continue
        labels.append(_map_label(float(toks[0]), multiclass))
        for tok in toks[1:]:
            if tok.startswith("#"):  # trailing comments per libsvm convention
                break
            idx, _, val = tok.partition(":")
            cols.append(int(idx) - 1)  # 1-based -> 0-based
            vals.append(float(val))
        row_ptr.append(len(cols))
    return (
        np.asarray(labels, dtype=np.int32),
        np.asarray(row_ptr, dtype=np.int64),
        np.asarray(cols, dtype=np.int32),
        np.asarray(vals, dtype=np.float32),
    )


def _try_native():
    try:
        from distlr_tpu.data import _native  # noqa: PLC0415
        return _native
    except Exception:
        return None


_NATIVE = _try_native()


def native_available() -> bool:
    """True iff the native parser is importable AND its .so builds/loads."""
    if _NATIVE is None:
        return False
    try:
        _NATIVE._load()
        return True
    except Exception:
        return False


def _parse_csr(text_or_lines, multiclass: bool):
    global _NATIVE
    if isinstance(text_or_lines, (bytes, str)):
        if _NATIVE is not None:
            data = text_or_lines.encode() if isinstance(text_or_lines, str) else text_or_lines
            try:
                return _NATIVE.parse_libsvm_bytes(data, multiclass)
            except ValueError:
                raise  # malformed input is a real error, not a fallback case
            except Exception:
                # build/load failure (no toolchain, bad .so): fall back to
                # the pure-Python tokenizer permanently for this process
                _NATIVE = None
        lines = (text_or_lines.decode() if isinstance(text_or_lines, bytes) else text_or_lines).splitlines()
        return _parse_python(lines, multiclass)
    return _parse_python(text_or_lines, multiclass)


def _densify(labels, row_ptr, cols, vals, num_features: int):
    n = len(labels)
    X = np.zeros((n, num_features), dtype=np.float32)
    keep = (cols >= 0) & (cols < num_features)  # out-of-range features dropped, not UB
    rows = np.repeat(np.arange(n), np.diff(row_ptr))
    X[rows[keep], cols[keep]] = vals[keep]
    return X


def parse_libsvm_lines(
    text_or_lines,
    num_features: int | None = None,
    *,
    dense: bool = True,
    multiclass: bool = False,
):
    """Parse libsvm content.

    Args:
      text_or_lines: a str/bytes blob or an iterable of lines.
      num_features: D. Required for dense output; optional for CSR output
        (used only to filter out-of-range columns).
      dense: if True return ``(X: (N,D) f32, y: (N,) i32)``; else return
        CSR ``((row_ptr, cols, vals), y)`` with out-of-range columns
        dropped when ``num_features`` is given (same rule as dense).
      multiclass: if True keep integer labels verbatim (softmax models);
        if False apply the reference's binary rule (!=1 -> 0).
    """
    labels, row_ptr, cols, vals = _parse_csr(text_or_lines, multiclass)
    if dense:
        if num_features is None:
            raise ValueError("num_features is required for dense parsing")
        return _densify(labels, row_ptr, cols, vals, num_features), labels
    if num_features is not None:
        keep = (cols >= 0) & (cols < num_features)
        if not keep.all():
            # recompute row_ptr after dropping filtered entries
            rows = np.repeat(np.arange(len(labels)), np.diff(row_ptr))
            rows, cols, vals = rows[keep], cols[keep], vals[keep]
            row_ptr = np.zeros(len(labels) + 1, dtype=np.int64)
            np.add.at(row_ptr, rows + 1, 1)
            row_ptr = np.cumsum(row_ptr)
    return (row_ptr, cols, vals), labels


def parse_libsvm_file(path, num_features: int | None = None, *, dense: bool = True, multiclass: bool = False):
    """Parse a libsvm file from disk (reads the whole file; shards are
    expected to fit in host RAM, same operating point as the reference's
    eager ``DataIter`` ctor, ``data_iter.h:16-35``)."""
    with open(path, "rb") as f:
        blob = f.read()
    return parse_libsvm_lines(blob, num_features, dense=dense, multiclass=multiclass)


def write_libsvm(path, X, y, *, binary_pm1: bool = False) -> None:
    """Write (X, y) as libsvm text (sparse: zero features omitted, 1-based)."""
    X = np.asarray(X)
    y = np.asarray(y)
    with open(path, "w") as f:
        for xi, yi in zip(X, y):
            label = int(yi)
            if binary_pm1:
                label = 1 if label == 1 else -1
            (nz,) = np.nonzero(xi)
            feats = " ".join(f"{j + 1}:{xi[j]:g}" for j in nz)
            f.write(f"{label} {feats}\n" if feats else f"{label}\n")
