"""Feature-axis (model) sharding: 2D ``data x model`` parallelism.

The reference scales its weight vector by range-sharding the key space
across S server processes (``GetServerKeyRanges`` / ``DecodeKey``,
reference ``src/main.cc:98-101``) while every worker still materializes
the FULL dense vector per step (``src/lr.cc:116-132``).  Here the shard
is real end-to-end: the weight vector (and the feature axis of every
batch) lives partitioned over the mesh's ``model`` axis — each device
touches only D/S features, so D can exceed single-device HBM.

Per step, for mesh axes (data=W, model=S):

* ``z_partial = X_shard @ w_shard``  — local matvec on each device
* ``z = psum(z_partial, 'model')``   — logits need all feature shards
* residual, per-example loss       — replicated along ``model``
* ``g_shard = X_shard^T r / n``      — local; already model-sharded
* ``g = pmean(g_shard, 'data')``     — the usual data-parallel mean
* ``w_shard -= lr * g_shard``        — update stays shard-local

i.e. exactly one small collective per direction (the (B,)-sized logit
psum and the gradient pmean) instead of the reference's full-D
pull/push per worker per step.

Supports :class:`BinaryLR` (w: (D,)) and :class:`SoftmaxRegression`
(W: (D, K), feature axis sharded).  The sparse model keeps its own path
(PS mode / segment_sum).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distlr_tpu.config import Config
from distlr_tpu.models import BinaryLR, SoftmaxRegression
from distlr_tpu.models.linear import _int8_contract, quantize_sym
from distlr_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, shard_map


def _check_mesh(mesh: Mesh, num_features: int) -> None:
    if MODEL_AXIS not in mesh.axis_names:
        raise ValueError("feature-sharded step needs a mesh with a 'model' axis")
    s = mesh.shape[MODEL_AXIS]
    if num_features % s != 0:
        raise ValueError(
            f"num_features={num_features} must be divisible by the model-axis "
            f"size {s} (pad the feature dimension)"
        )


def _per_sample_logloss(z, y, is_softmax: bool):
    """Per-sample logloss from global logits (shared by the train-metrics
    and eval paths; the canonical definition lives on the model classes —
    tests pin these against model.logloss)."""
    if is_softmax:
        return -jax.nn.log_softmax(z)[jnp.arange(z.shape[0]), y]
    return jax.nn.softplus(z) - y.astype(jnp.float32) * z


def partial_logits(model, w_shard, X_shard):
    """This device's feature-shard contribution to the logits (already
    feature-scaled); the caller reduces over ``model`` (psum or ring).

    int8_dot models quantize the weight shard on a GLOBAL grid (|w| max
    via pmax over shards), so the formulation matches the single-device
    int8_dot path bit-for-bit on the weight side, and feed the native
    int8 x int8 -> int32 contraction; others take the compute-dtype
    matmul with the convert fused in."""
    if getattr(model, "int8_dot", False):
        wq, s_w = quantize_sym(
            w_shard, lax.pmax(jnp.max(jnp.abs(w_shard)), MODEL_AXIS))
        return _int8_contract(X_shard, wq, X_shard.ndim - 1) * (
            s_w * model.feature_scale)
    cdt = jnp.dtype(model.compute_dtype)
    z_partial = jnp.dot(
        X_shard.astype(cdt), w_shard.astype(cdt), preferred_element_type=jnp.float32
    )
    if model.feature_scale != 1.0:  # int8-quantized features (BinaryLR doc)
        z_partial = z_partial * model.feature_scale
    return z_partial


def resid_grad(model, resid, X_shard, n):
    """Residual-times-features gradient term, int8_dot-aware.

    ``resid (B,)`` (binary) gives ``resid @ X / n -> (D_shard,)``;
    ``resid (B, K)`` (softmax) gives ``X^T @ resid / n -> (D_shard, K)``.

    Residuals are replicated along ``model`` (computed from the reduced
    logits), so a local max IS the model-axis global max; along ``data``
    each shard quantizes its own batch slice — the same semantics as the
    data-parallel int8_dot step.  feature_scale is NOT applied here (the
    callers multiply it with their other scale factors)."""
    if getattr(model, "int8_dot", False):
        rq, s_r = quantize_sym(resid, jnp.max(jnp.abs(resid)))
        if resid.ndim == 2:
            return _int8_contract(X_shard, rq, 0) * s_r / n
        return _int8_contract(rq, X_shard, 0) * s_r / n
    cdt = jnp.dtype(model.compute_dtype)
    if resid.ndim == 2:
        return jnp.dot(X_shard.astype(cdt).T, resid.astype(cdt),
                       preferred_element_type=jnp.float32) / n
    return jnp.dot(resid.astype(cdt), X_shard.astype(cdt),
                   preferred_element_type=jnp.float32) / n


def _local_forward(model, w_shard, X_shard):
    """Partial logits from this device's feature shard, then psum."""
    return lax.psum(partial_logits(model, w_shard, X_shard), MODEL_AXIS)


def make_feature_sharded_train_step(model, cfg: Config, mesh: Mesh, *, with_metrics: bool = True):
    """Jitted 2D-parallel sync step: ``step(w, (X, y, mask)) -> (w, metrics)``.

    ``w`` is model-axis sharded; ``X`` is ``(data, model)``-sharded;
    ``y``/``mask`` are data-sharded.  Weights are donated.
    """
    if not isinstance(model, (BinaryLR, SoftmaxRegression)):
        raise TypeError(f"feature sharding supports dense models, got {type(model).__name__}")
    _check_mesh(mesh, model.num_features)
    is_softmax = isinstance(model, SoftmaxRegression)

    def local_step(w, X, y, mask):
        n = jnp.maximum(jnp.sum(mask), 1).astype(jnp.float32)
        z = _local_forward(model, w, X)
        if is_softmax:
            p = jax.nn.softmax(z)
            onehot = jax.nn.one_hot(y, model.num_classes, dtype=jnp.float32)
            resid = (p - onehot) * mask[:, None]
        else:
            resid = (jax.nn.sigmoid(z) - y.astype(jnp.float32)) * mask
        g = resid_grad(model, resid, X, n)
        ll = _per_sample_logloss(z, y, is_softmax)
        if model.feature_scale != 1.0:  # d/dw of (X*scale) @ w
            g = g * model.feature_scale
        # L2 on the local shard (gradient of 0.5*C*|w|^2 is shard-local)
        l2 = cfg.l2_c * w
        if cfg.l2_scale_by_batch:
            l2 = l2 / n
        g = lax.pmean(g + l2, DATA_AXIS)
        w_new = w - cfg.learning_rate * g
        if not with_metrics:
            return w_new, {}
        # include the L2 term so this metric is comparable with the
        # data-parallel path's model.loss (reg needs all weight shards)
        reg = 0.5 * cfg.l2_c * lax.psum(jnp.sum(w * w), MODEL_AXIS)
        if cfg.l2_scale_by_batch:
            reg = reg / n
        loss = lax.pmean(jnp.sum(ll * mask) / n + reg, DATA_AXIS)
        gn2 = lax.psum(jnp.sum(g * g), MODEL_AXIS)
        return w_new, {"loss": loss, "grad_norm": jnp.sqrt(gn2)}

    w_spec = P(MODEL_AXIS) if not is_softmax else P(MODEL_AXIS, None)
    x_spec = P(DATA_AXIS, MODEL_AXIS)

    def step(w, batch):
        X, y, mask = batch
        return shard_map(
            local_step,
            mesh=mesh,
            in_specs=(w_spec, x_spec, P(DATA_AXIS), P(DATA_AXIS)),
            out_specs=(w_spec, P()),
            check_vma=False,
        )(w, X, y, mask)

    return jax.jit(step, donate_argnums=0)


def make_feature_sharded_eval_step(model, mesh: Mesh):
    """Global masked eval (``{"accuracy", "logloss"}`` like
    :func:`make_eval_step`) with model-axis-sharded weights."""
    _check_mesh(mesh, model.num_features)
    is_softmax = isinstance(model, SoftmaxRegression)

    def local_eval(w, X, y, mask):
        z = _local_forward(model, w, X)
        pred = (
            jnp.argmax(z, axis=-1).astype(jnp.int32)
            if is_softmax
            else (z > 0).astype(jnp.int32)
        )
        ll = _per_sample_logloss(z, y, is_softmax)
        correct = lax.psum(jnp.sum((pred == y) * mask), DATA_AXIS)
        ll_sum = lax.psum(jnp.sum(ll * mask), DATA_AXIS)
        total = jnp.maximum(lax.psum(jnp.sum(mask), DATA_AXIS), 1)
        return {
            "accuracy": correct.astype(jnp.float32) / total,
            "logloss": ll_sum / total,
        }

    w_spec = P(MODEL_AXIS) if not is_softmax else P(MODEL_AXIS, None)

    def evaluate(w, batch):
        X, y, mask = batch
        return shard_map(
            local_eval,
            mesh=mesh,
            in_specs=(w_spec, P(DATA_AXIS, MODEL_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
            out_specs=P(),
            check_vma=False,
        )(w, X, y, mask)

    return jax.jit(evaluate)


def shard_batch_2d(batch, mesh: Mesh):
    """Place ``(X, y, mask)`` with X sharded (data, model), rest data-sharded."""
    X, y, mask = batch
    return (
        jax.device_put(X, NamedSharding(mesh, P(DATA_AXIS, MODEL_AXIS))),
        jax.device_put(y, NamedSharding(mesh, P(DATA_AXIS))),
        jax.device_put(mask, NamedSharding(mesh, P(DATA_AXIS))),
    )


def shard_weights(w, mesh: Mesh):
    """Place weights sharded over the model axis (feature shards)."""
    spec = P(MODEL_AXIS) if w.ndim == 1 else P(MODEL_AXIS, None)
    return jax.device_put(w, NamedSharding(mesh, spec))
