"""Device mesh construction and canonical shardings.

The reference's cluster topology is env-var driven process roles
(``DMLC_NUM_WORKER`` / ``DMLC_NUM_SERVER`` / ``DMLC_ROLE``,
``examples/local.sh:22-33``) rendezvoused by a scheduler over TCP.  On TPU
the topology is a :class:`jax.sharding.Mesh` over the chip grid:

* ``data`` axis — data parallelism; replaces the W worker processes.
  Per-shard gradients meet in a ``psum`` over ICI instead of W push RPCs.
* ``model`` axis — feature-dimension sharding; replaces ps-lite's
  range-partitioned key space across S servers (reference
  ``src/main.cc:98-101``, ``GetServerKeyRanges``).

Multi-host: the same mesh spans processes after
``jax.distributed.initialize()`` — DCN between hosts, ICI within — with no
code change here (`make_mesh` uses the global device list).
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # single shim point for the whole package (and tests)
    _shard_map_impl = jax.shard_map
except AttributeError:  # pragma: no cover — older jax
    from jax.experimental.shard_map import shard_map as _shard_map_impl  # type: ignore

import inspect as _inspect

_SM_PARAMS = frozenset(_inspect.signature(_shard_map_impl).parameters)


def shard_map(f, **kw):
    """``jax.shard_map`` with the replication-check kwarg normalized:
    newer jax renamed ``check_rep`` to ``check_vma`` — accept either and
    pass whichever the installed version understands."""
    if "check_vma" in kw and "check_vma" not in _SM_PARAMS:
        kw["check_rep"] = kw.pop("check_vma")
    elif "check_rep" in kw and "check_rep" not in _SM_PARAMS:
        kw["check_vma"] = kw.pop("check_rep")
    return _shard_map_impl(f, **kw)

DATA_AXIS = "data"
MODEL_AXIS = "model"


def axis_size(axis_name: str) -> int:
    """Static size of a named mesh axis, from inside a shard_map body.

    ``jax.lax.axis_size`` only exists on newer jax; ``lax.psum(1, name)``
    const-folds to a Python int at trace time on every version this
    package supports, so callers that need a STATIC size (loop bounds,
    permutation tables) can rely on it."""
    if hasattr(jax.lax, "axis_size"):  # pragma: no cover — newer jax
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


def make_mesh(shape: dict | None = None, *, devices=None) -> Mesh:
    """Build a mesh. ``shape`` maps axis name -> size, e.g. ``{"data": 8}``
    or ``{"data": 4, "model": 2}``.  Default: all devices on ``data``."""
    devices = jax.devices() if devices is None else devices
    if shape is None:
        shape = {DATA_AXIS: len(devices)}
    sizes = list(shape.values())
    total = int(np.prod(sizes))
    if total > len(devices):
        raise ValueError(f"mesh shape {shape} needs {total} devices, have {len(devices)}")
    grid = np.asarray(devices[:total]).reshape(sizes)
    return Mesh(grid, tuple(shape.keys()))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Batch rows sharded over ``data`` (feature cols over ``model`` if present)."""
    if MODEL_AXIS in mesh.axis_names:
        return NamedSharding(mesh, P(DATA_AXIS, MODEL_AXIS))
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def feature_sharding(mesh: Mesh) -> NamedSharding:
    """Weight vector sharded over the ``model`` axis (ps-lite key-range
    analogue); replicated if the mesh has no model axis."""
    if MODEL_AXIS in mesh.axis_names:
        return NamedSharding(mesh, P(MODEL_AXIS))
    return NamedSharding(mesh, P())


def num_data_shards(mesh: Mesh) -> int:
    return mesh.shape[DATA_AXIS] if DATA_AXIS in mesh.axis_names else 1
