"""Synchronous data-parallel training as one compiled SPMD program.

This replaces the reference's entire BSP protocol — W workers each
``Push``-ing a gradient, the server buffering ``KVMeta`` requests and
withholding every ``Response`` until all ``NumWorkers()`` pushes arrived,
then applying SGD and releasing the barrier (reference
``src/main.cc:57-78``, ``src/lr.cc:116-132``) — with a single
``shard_map``-ped step: per-shard gradients meet in a ``psum`` over the
mesh's ``data`` axis (ICI collectives, no RPC), the SGD update is computed
replicated, and the BSP barrier is implicit in the collective.

Quirk Q1 (SURVEY.md §3.5): the reference's sync server applies the
*last-arriving* worker's gradient divided by W — not the merged mean
(``src/main.cc:63-77``).  ``cfg.sync_last_gradient`` reproduces that
(deterministically: the highest-rank shard stands in for "last-arriving",
which in the reference is a race); the default is the correct ``pmean``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distlr_tpu.config import Config
from distlr_tpu.parallel.mesh import DATA_AXIS, axis_size, shard_map


def _batch_spec(batch) -> tuple:
    """Every leaf of the batch pytree is sharded along its leading (batch)
    axis over ``data``."""
    return jax.tree.map(lambda _: P(DATA_AXIS), batch)


def make_sync_train_step(model, cfg: Config, mesh: Mesh, *, with_metrics: bool = True):
    """Build the jitted sync step: ``step(w, batch) -> (w_new, metrics)``.

    ``batch`` is the model's batch pytree (dense: ``(X, y, mask)``), with
    leading axes divisible by the mesh's ``data`` size.  Weights are
    donated, so the update is in-place in HBM.
    """

    def local_step(w, batch):
        g_local = model.grad(w, batch, cfg)
        if cfg.sync_last_gradient:
            # Q1 compat: psum of (g_i masked to the top rank) == g_last;
            # the reference then divides by the number of workers.
            n_shards = axis_size(DATA_AXIS)
            is_last = (lax.axis_index(DATA_AXIS) == n_shards - 1)
            g = lax.psum(jax.tree.map(lambda t: t * is_last, g_local), DATA_AXIS)
            g = jax.tree.map(lambda t: t / n_shards, g)
        else:
            g = lax.pmean(g_local, DATA_AXIS)
        w_new = jax.tree.map(lambda p, t: p - cfg.learning_rate * t, w, g)
        if not with_metrics:
            return w_new, {}
        metrics = {
            "loss": lax.pmean(model.loss(w, batch, cfg), DATA_AXIS),
            "grad_norm": jnp.sqrt(
                sum(jnp.sum(t * t) for t in jax.tree.leaves(g))
            ),
        }
        return w_new, metrics

    def step(w, batch):
        return shard_map(
            local_step,
            mesh=mesh,
            in_specs=(P(), _batch_spec(batch)),
            out_specs=(P(), P()),
        )(w, batch)

    return jax.jit(step, donate_argnums=0)


def make_eval_step(model, mesh: Mesh):
    """Jitted global eval over a data-sharded eval batch:
    ``step(w, batch) -> {"accuracy": a, "logloss": l}``.

    Sums correct-prediction counts, per-sample loglosses and mask counts
    with ``psum`` so both results are exact global masked means.  The
    reference evaluates accuracy only, on rank 0, over the full test set
    (``src/lr.cc:47-63``); test logloss is the driver's parity metric
    (BASELINE.json epochs-to-logloss) so it is first-class here."""

    def local_eval(w, batch):
        *inputs, y, mask = batch
        pred = model.predict(w, *inputs)
        correct = lax.psum(jnp.sum((pred == y) * mask), DATA_AXIS)
        # per-shard logloss SUM (masked mean would double-normalize)
        ll_mean = model.logloss(w, batch)
        ll_sum = lax.psum(ll_mean * jnp.sum(mask), DATA_AXIS)
        total = jnp.maximum(lax.psum(jnp.sum(mask), DATA_AXIS), 1)
        return {
            "accuracy": correct.astype(jnp.float32) / total,
            "logloss": ll_sum / total,
        }

    def evaluate(w, batch):
        return shard_map(
            local_eval,
            mesh=mesh,
            in_specs=(P(), _batch_spec(batch)),
            out_specs=P(),
        )(w, batch)

    return jax.jit(evaluate)


def shard_batch(batch, mesh: Mesh):
    """Place a host batch pytree onto the mesh, sharded over ``data``.

    Host->HBM streaming: the successor of the reference's per-step
    ``DataIter`` -> ``Push``/``Pull`` flow (``include/data_iter.h`` +
    ``src/lr.cc:116-132``)."""
    return jax.tree.map(
        lambda x: jax.device_put(x, NamedSharding(mesh, P(DATA_AXIS))), batch
    )
