"""Explicit ring collectives over the mesh (ppermute), and a ring-based
feature-sharded training step.

The reference's only "collective" is W independent full-model RPCs
meeting at servers (SURVEY.md §2.4: reduce+broadcast split across two
ZeroMQ round trips).  The framework's default SPMD paths use XLA's
built-in collectives (``lax.psum``), which XLA already schedules as ICI
rings; this module provides the *explicit* ring formulation —
neighbor-exchange ``lax.ppermute`` steps moving one chunk per hop, the
same communication pattern ring attention / ring allreduce use for
sequence parallelism on TPU pods:

* chunked **reduce-scatter** (S-1 hops), then chunked **all-gather**
  (S-1 hops) == allreduce, with each hop touching only 1/S of the data —
  peak per-hop traffic is ``|x|/S``, and each hop can overlap with the
  consumer's compute when XLA finds the schedule;
* building block for the framework's SP-shaped axis: the *feature* axis
  (the reference's analogue of a long sequence axis is its 1M-feature
  weight vector, SURVEY.md §5.7).

Used where profiling favors it; numerically identical (up to f32
reduction order) to the psum path — pinned by tests.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from distlr_tpu.config import Config
from distlr_tpu.models import BinaryLR
from distlr_tpu.parallel.feature_parallel import (
    _check_mesh,
    resid_grad,
    partial_logits,
)
from distlr_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, axis_size, shard_map


def _ring_perm(s: int, reverse: bool = False):
    """Neighbor permutation i -> i+1 (mod s) on the named axis."""
    if reverse:
        return [((i + 1) % s, i) for i in range(s)]
    return [(i, (i + 1) % s) for i in range(s)]


def ring_reduce_scatter(x, axis_name: str):
    """Ring reduce-scatter of ``x`` (flat leading dim) over ``axis_name``.

    Returns this device's fully-reduced chunk, shape ``(ceil(n/s),)`` —
    device ``i`` owns chunk ``(i + 1) % s`` of the padded input.  S-1
    neighbor hops, each carrying one chunk.
    """
    s = axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    n = x.shape[0]
    chunk = -(-n // s)
    x = jnp.pad(x, (0, chunk * s - n))
    chunks = x.reshape(s, chunk)

    def hop(state, step):
        acc, = state
        send_i = (idx - step) % s
        block = lax.dynamic_index_in_dim(acc, send_i, axis=0, keepdims=False)
        recvd = lax.ppermute(block, axis_name, _ring_perm(s))
        recv_i = (idx - step - 1) % s
        prev = lax.dynamic_index_in_dim(acc, recv_i, axis=0, keepdims=False)
        acc = lax.dynamic_update_index_in_dim(acc, prev + recvd, recv_i, axis=0)
        return (acc,), None

    (chunks,), _ = lax.scan(hop, (chunks,), jnp.arange(s - 1))
    own = (idx + 1) % s
    return lax.dynamic_index_in_dim(chunks, own, axis=0, keepdims=False)


def ring_all_gather(chunk, axis_name: str, *, owner_offset: int = 0):
    """Ring all-gather: every device contributes its ``chunk`` and ends
    with all S chunks, ordered by owner rank.  ``owner_offset=k`` means
    device ``i`` contributes the chunk logically numbered ``(i + k) % s``
    (reduce-scatter above leaves ownership rotated by one).  S-1 hops.
    """
    s = axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    out = jnp.zeros((s,) + chunk.shape, chunk.dtype)
    own = (idx + owner_offset) % s
    out = lax.dynamic_update_index_in_dim(out, chunk, own, axis=0)

    def hop(state, step):
        out, cur = state
        block = lax.dynamic_index_in_dim(out, cur, axis=0, keepdims=False)
        recvd = lax.ppermute(block, axis_name, _ring_perm(s))
        nxt = (cur - 1) % s
        out = lax.dynamic_update_index_in_dim(out, recvd, nxt, axis=0)
        return (out, nxt), None

    (out, _), _ = lax.scan(hop, (out, own), jnp.arange(s - 1))
    return out.reshape((-1,) + chunk.shape[1:])


def ring_psum(x, axis_name: str):
    """Allreduce as ring reduce-scatter + ring all-gather (ppermute only).

    Numerically equivalent to ``lax.psum(x, axis_name)`` up to f32
    reduction order; 2(S-1) hops of ``|x|/S`` each.
    """
    shape = x.shape
    flat = x.reshape(-1)
    chunk = ring_reduce_scatter(flat, axis_name)
    full = ring_all_gather(chunk, axis_name, owner_offset=1)
    return full[: flat.shape[0]].reshape(shape)


def make_ring_train_step(model, cfg: Config, mesh: Mesh, *, with_metrics: bool = True):
    """Feature-sharded sync step using explicit ring collectives on the
    ``model`` axis (interface-compatible with
    :func:`make_feature_sharded_train_step`; BinaryLR only).

    Per step: local partial logits -> **ring allreduce** over feature
    shards -> local gradient -> pmean over ``data`` -> shard-local update.
    """
    if not isinstance(model, BinaryLR):
        raise TypeError("ring step supports BinaryLR (dense weights)")
    _check_mesh(mesh, model.num_features)

    def local_step(w, X, y, mask):
        n = jnp.maximum(jnp.sum(mask), 1).astype(jnp.float32)
        # same int8_dot-aware partials as the psum step; only the
        # reduction differs (explicit ppermute ring vs XLA psum)
        z = ring_psum(partial_logits(model, w, X), MODEL_AXIS)
        resid = (jax.nn.sigmoid(z) - y.astype(jnp.float32)) * mask
        g = resid_grad(model, resid, X, n)
        if model.feature_scale != 1.0:  # d/dw of (X*scale) @ w
            g = g * model.feature_scale
        l2 = cfg.l2_c * w
        if cfg.l2_scale_by_batch:
            l2 = l2 / n
        g = lax.pmean(g + l2, DATA_AXIS)
        w_new = w - cfg.learning_rate * g
        if not with_metrics:
            return w_new, {}
        ll = jax.nn.softplus(z) - y.astype(jnp.float32) * z
        reg = 0.5 * cfg.l2_c * ring_psum(jnp.sum(w * w)[None], MODEL_AXIS)[0]
        if cfg.l2_scale_by_batch:
            reg = reg / n
        loss = lax.pmean(jnp.sum(ll * mask) / n + reg, DATA_AXIS)
        return w_new, {"loss": loss}

    def step(w, batch):
        X, y, mask = batch
        return shard_map(
            local_step,
            mesh=mesh,
            in_specs=(P(MODEL_AXIS), P(DATA_AXIS, MODEL_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
            out_specs=(P(MODEL_AXIS), P()),
            check_vma=False,
        )(w, X, y, mask)

    return jax.jit(step, donate_argnums=0)
