from distlr_tpu.parallel.mesh import (  # noqa: F401
    make_mesh,
    batch_sharding,
    replicated_sharding,
    feature_sharding,
)
from distlr_tpu.parallel.data_parallel import make_sync_train_step, make_eval_step  # noqa: F401
from distlr_tpu.parallel.ring import (  # noqa: F401
    make_ring_train_step,
    ring_all_gather,
    ring_psum,
    ring_reduce_scatter,
)
