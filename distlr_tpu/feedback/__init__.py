"""Online learning from served traffic — the closed loop.

The subsystem that turns train-then-serve into one organism under load
(ROADMAP "close the loop"): a scored request's journey back into
training takes four steps, each its own module —

* :mod:`~distlr_tpu.feedback.spool` — the serving front-end journals
  every scored request (features, score, weights version, timestamp)
  into a bounded on-disk spool with importance-aware retention (reusing
  the hot-set tracker's key statistics);
* :mod:`~distlr_tpu.feedback.join` — delayed labels (``LABEL <id> <y>``
  protocol lines) join their spooled request within a configurable
  window; never-labeled requests resolve through a negative-sampling
  policy; joined examples emit as libsvm training shards;
* :mod:`~distlr_tpu.feedback.online` — ``launch online``: a long-running
  Hogwild worker consumes shards as they appear and pushes into the
  same live PS the engines hot-reload from, with AdaBatch-style growing
  local accumulation;
* :mod:`~distlr_tpu.feedback.drift` — block-wise PSI over served scores
  exported as ``distlr_alert_score_drift``: fires while the
  distribution shifts (labels flipped, trainer adapting), clears once
  it restabilizes.

The server-side half is the FTRL-Proximal optimizer
(``--ps-optimizer ftrl``, :mod:`distlr_tpu.ps`): per-coordinate z/n
accumulators with L1 sparsification — the production sparse-CTR update
the loop trains through.

Lazy exports (PEP 562): the spool/join/drift pieces import jax-free;
only :class:`OnlineTrainer` touches the training stack.
"""

import importlib

_LAZY = {
    "FeedbackSink": "distlr_tpu.feedback.sink",
    "FeedbackSpool": "distlr_tpu.feedback.spool",
    "SpoolRecord": "distlr_tpu.feedback.spool",
    "per_row_keys": "distlr_tpu.feedback.spool",
    "strip_label": "distlr_tpu.feedback.spool",
    "LabelJoiner": "distlr_tpu.feedback.join",
    "OnlineTrainer": "distlr_tpu.feedback.online",
    "ScoreDriftDetector": "distlr_tpu.feedback.drift",
    "psi": "distlr_tpu.feedback.drift",
}

__all__ = sorted(_LAZY)


def __getattr__(name: str):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    return getattr(importlib.import_module(mod), name)


def __dir__():
    return sorted(set(globals()) | set(_LAZY))
