"""Request-log spool — the serving tier's journal of what it scored.

The first quarter of the online-learning loop (ROADMAP "close the
loop"): every scored request is journaled — feature line, served score,
the engine weights version that produced it, and a timestamp — so a
label arriving seconds-to-minutes later can be joined back to the exact
impression it describes (:mod:`distlr_tpu.feedback.join`).

Two bounds, because production request streams are unbounded:

* **on disk** — an append-only JSONL journal rotated into segments of
  ``segment_records`` lines, keeping at most ``max_segments`` segments
  (oldest deleted first).  The journal is the audit trail; the join
  works from memory.
* **in memory** — at most ``capacity`` records await their label.  Past
  it, eviction is **importance-aware**: the candidate window (the oldest
  ``evict_scan`` records) is scored by the serving
  :class:`~distlr_tpu.serve.hotset.HotSetTracker`'s decayed key counts —
  the same statistics hot-row reload already maintains — and the LEAST
  important record is dropped.  Under pressure the spool sheds requests
  that touched only cold rows (whose labels move the model least) and
  keeps hot-row impressions joinable.  Without a tracker, plain FIFO.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re

import numpy as np

from distlr_tpu import sync
from distlr_tpu.obs.registry import get_registry

_reg = get_registry()
_SPOOLED = _reg.counter(
    "distlr_feedback_spooled_total",
    "scored requests journaled into the feedback spool",
)
_SPOOL_SIZE = _reg.gauge(
    "distlr_feedback_spool_size",
    "spooled requests currently awaiting a label",
)
_DROPPED = _reg.counter(
    "distlr_feedback_dropped_total",
    "feedback-loop records dropped, by reason (capacity = spool "
    "eviction under pressure; expired = window elapsed and the "
    "negative-sampling coin came up drop; duplicate_label = a label "
    "for an already-joined request; unmatched_label = a label whose "
    "request was never seen within the window)",
    labelnames=("reason",),
)


def drop(reason: str, n: int = 1) -> None:
    """Count a feedback-loop drop (shared with the joiner so every
    discarded record lands in ONE series, split by reason)."""
    _DROPPED.labels(reason=reason).inc(n)


@dataclasses.dataclass
class SpoolRecord:
    """One scored request awaiting its label."""

    rid: str                   # request id (caller-supplied or auto)
    ts: float                  # wall-clock seconds at scoring time
    line: str                  # feature line, libsvm grammar, NO label
    score: float               # served score (P(y=1) / max class prob)
    version: int               # engine weights version that scored it
    #: PS row keys the request touched (importance input); None = unknown
    keys: np.ndarray | None = None
    #: distributed-trace (trace_id, span_id) of the scoring request's
    #: feedback.spool span — the delayed-label join continues it
    trace: tuple[int, int] | None = None
    #: model version that scored the request (multi-tenant serving,
    #: ISSUE 10): the joiner emits this record's example into the
    #: model's OWN shard stream so online training stays per-tenant;
    #: None = single-model serving (flat shards, pre-tenant behavior)
    model: str | None = None


class FeedbackSpool:
    """Bounded spool of scored requests, journaled to disk.

    Thread-safe: request-handler threads ``add`` while the joiner's
    ticker expires and label lines ``pop``.
    """

    def __init__(self, directory: str, *, capacity: int = 100_000,
                 tracker=None, segment_records: int = 10_000,
                 max_segments: int = 8, evict_scan: int = 16):
        if capacity <= 0:
            raise ValueError(f"capacity must be positive, got {capacity}")
        if segment_records <= 0 or max_segments <= 0:
            raise ValueError(
                "segment_records and max_segments must be positive, got "
                f"{segment_records}/{max_segments}")
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self.capacity = int(capacity)
        self.tracker = tracker
        self.segment_records = int(segment_records)
        self.max_segments = int(max_segments)
        self.evict_scan = max(int(evict_scan), 1)
        self._lock = sync.Lock()
        #: insertion-ordered (dict preserves it): front = oldest
        self._records: dict[str, SpoolRecord] = {}
        # resume the journal AFTER any segment a previous run left
        # behind: restarting at 0 would mix two runs' records into one
        # segment and leave the old run's tail outside the rotation
        # window (the max_segments disk bound) indefinitely
        existing = sorted(
            int(m.group(1)) for name in os.listdir(directory)
            if (m := re.match(r"spool-(\d+)\.jsonl$", name)))
        self._seg_index = existing[-1] + 1 if existing else 0
        for idx in existing:
            if idx <= self._seg_index - self.max_segments:
                try:
                    os.unlink(self._seg_path(idx))
                except OSError:
                    pass
        self._seg_count = 0
        self._seg_file = None
        self.spooled = 0
        self.evicted = 0
        self.replayed = 0

    # -- journal ----------------------------------------------------------
    def _seg_path(self, index: int) -> str:
        return os.path.join(self.directory, f"spool-{index:06d}.jsonl")

    def _journal_locked(self, rec: SpoolRecord) -> None:
        doc = {
            "id": rec.rid, "ts": round(rec.ts, 3), "line": rec.line,
            "score": round(rec.score, 6), "version": rec.version,
        }
        if rec.model is not None:
            # the model id rides the journal so a label joined across a
            # restart still lands in its tenant's shard stream
            doc["model"] = rec.model
        if rec.trace is not None:
            # the trace rides the journal so a label joined AFTER a
            # restart (replay) still continues the original request's
            # distributed trace
            doc["trace"] = f"{rec.trace[0]:016x}/{rec.trace[1]:016x}"
        self._journal_line_locked(doc)

    def _journal_line_locked(self, doc: dict) -> None:
        if self._seg_file is None or self._seg_count >= self.segment_records:
            if self._seg_file is not None:
                self._seg_file.close()
                self._seg_index += 1
            self._seg_file = open(self._seg_path(self._seg_index), "a")
            self._seg_count = 0
            old = self._seg_index - self.max_segments
            if old >= 0:
                try:
                    os.unlink(self._seg_path(old))
                except OSError:
                    pass  # already rotated away (restart) — bound holds
        self._seg_file.write(json.dumps(doc) + "\n")
        self._seg_count += 1

    def mark_joined(self, rid: str) -> None:
        """Journal a join tombstone: replay after a restart must not
        resurrect an already-joined request (a re-arriving label would
        re-emit the example and bias the positive rate)."""
        with self._lock:
            self._journal_line_locked({"joined": rid})

    def replay(self, *, window_s: float, now: float | None = None) -> int:
        """Rebuild the in-memory joinable set from the on-disk journal
        (a previous run's segments): every journaled record still inside
        the join window and not tombstoned as joined becomes joinable
        again, so labels that arrive ACROSS a serve restart join their
        real impression instead of negative-sampling.  Touched keys are
        not journaled, so replayed records carry ``keys=None`` (they
        evict first under pressure — the honest default).  Returns the
        number of records restored."""
        now = sync.wall() if now is None else now
        cutoff = now - float(window_s)
        segs = sorted(
            int(m.group(1)) for name in os.listdir(self.directory)
            if (m := re.match(r"spool-(\d+)\.jsonl$", name)))
        recovered: dict[str, SpoolRecord] = {}
        for idx in segs:
            try:
                with open(self._seg_path(idx)) as f:
                    lines = f.read().splitlines()
            except OSError:
                continue
            for raw in lines:
                try:
                    doc = json.loads(raw)
                except ValueError:
                    continue  # torn tail line of a crashed run
                if "joined" in doc:
                    recovered.pop(str(doc["joined"]), None)
                    continue
                if doc.get("ts", 0.0) < cutoff:
                    continue
                trace = None
                tok = doc.get("trace")
                if tok:
                    try:
                        tid, _, sid = tok.partition("/")
                        trace = (int(tid, 16), int(sid, 16))
                    except ValueError:
                        pass
                model = doc.get("model")
                rec = SpoolRecord(
                    rid=str(doc["id"]), ts=float(doc["ts"]),
                    line=str(doc.get("line", "")),
                    score=float(doc.get("score", 0.0)),
                    version=int(doc.get("version", 0)), trace=trace,
                    model=None if model is None else str(model))
                recovered[rec.rid] = rec
        with self._lock:
            n = 0
            for rid, rec in recovered.items():
                if rid in self._records:
                    continue
                self._records[rid] = rec
                n += 1
                if len(self._records) > self.capacity:
                    self._evict_one_locked()
            self.replayed += n
            size = len(self._records)
        _SPOOL_SIZE.set(size)
        return n

    # -- importance -------------------------------------------------------
    def _importances(self, window: list[SpoolRecord]) -> list[float]:
        """Tracker-count mass of each record's touched rows — the same
        decayed statistics hot-row reload retains rows by.  One
        ``importance_many`` call: the tracker lock (contended by the
        scoring hot path's ``observe``) is taken once per eviction, not
        once per candidate."""
        if self.tracker is None:
            return [0.0] * len(window)
        many = getattr(self.tracker, "importance_many", None)
        if many is not None:
            return many([rec.keys for rec in window])
        # tracker-like object without the batched API
        return [0.0 if rec.keys is None or not len(rec.keys)
                else float(self.tracker.importance(rec.keys))
                for rec in window]

    # -- ingest / claim ---------------------------------------------------
    def add(self, rec: SpoolRecord) -> bool:
        """Spool one scored request.  Returns False when the record was
        immediately evicted (it WAS journaled — the audit trail is
        append-only; only the joinable working set is bounded)."""
        kept = True
        with self._lock:
            self._journal_locked(rec)
            self._records[rec.rid] = rec
            self.spooled += 1
            if len(self._records) > self.capacity:
                evicted = self._evict_one_locked()
                kept = evicted != rec.rid
            size = len(self._records)
        _SPOOLED.inc()
        _SPOOL_SIZE.set(size)
        return kept

    def _evict_one_locked(self) -> str:
        """Drop the least-important record among the oldest
        ``evict_scan`` (importance-aware retention; FIFO without a
        tracker since all importances tie at 0 and the scan keeps
        insertion order)."""
        it = iter(self._records.values())
        window = []
        for _ in range(self.evict_scan):
            try:
                window.append(next(it))
            except StopIteration:
                break
        scores = self._importances(window)
        victim = window[min(range(len(window)), key=scores.__getitem__)]
        del self._records[victim.rid]
        self.evicted += 1
        drop("capacity")
        return victim.rid

    def pop(self, rid: str) -> SpoolRecord | None:
        """Claim a spooled request by id (the label-join hit path)."""
        with self._lock:
            rec = self._records.pop(rid, None)
            size = len(self._records)
        _SPOOL_SIZE.set(size)
        return rec

    def expire_before(self, cutoff_ts: float) -> list[SpoolRecord]:
        """Remove and return every record scored before ``cutoff_ts``
        (the joiner's never-labeled set — negative-sampling input).
        Records are insertion-ordered, but eviction punches holes, so
        the scan walks until the first fresh record."""
        out = []
        with self._lock:
            for rid, rec in list(self._records.items()):
                if rec.ts >= cutoff_ts:
                    break
                out.append(self._records.pop(rid))
            size = len(self._records)
        _SPOOL_SIZE.set(size)
        return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)

    def stats(self) -> dict:
        with self._lock:
            return {
                "size": len(self._records),
                "capacity": self.capacity,
                "spooled": self.spooled,
                "evicted": self.evicted,
                "replayed": self.replayed,
                "journal_segment": self._seg_index,
            }

    def close(self) -> None:
        with self._lock:
            if self._seg_file is not None:
                self._seg_file.close()
                self._seg_file = None


def per_row_keys(model: str, rows: tuple, *, max_keys: int = 128
                 ) -> list[np.ndarray]:
    """PS row keys touched by EACH request row (the per-record twin of
    :meth:`distlr_tpu.serve.engine.ScoringEngine.row_keys`, which is
    batch-level): sparse/blocked families read their id leaf per row,
    dense rows their nonzero columns.  Capped at ``max_keys`` per row —
    importance needs a sample, not an index."""
    first = np.asarray(rows[0])
    out = []
    if model in ("sparse_lr", "sparse_softmax", "blocked_lr"):
        for i in range(first.shape[0]):
            k = np.unique(first[i].astype(np.int64)).astype(np.uint64)
            out.append(k[:max_keys])
        return out
    for i in range(first.shape[0]):
        k = np.flatnonzero(first[i] != 0).astype(np.uint64)
        out.append(k[:max_keys])
    return out


def strip_label(line: str) -> str:
    """The feature part of a request line: drop a leading label token
    when present (same rule the engine's ``encode_lines`` normalizes
    by — a first token without ``:`` is a label)."""
    line = line.strip()
    if not line:
        return line
    first = line.split(None, 1)
    if ":" in first[0]:
        return line
    return first[1] if len(first) > 1 else ""


def now_ts() -> float:
    return sync.wall()
