"""Continuous (online) trainer — train on what you serve.

The third quarter of the loop: a long-running Hogwild worker that
consumes joined training shards (:mod:`distlr_tpu.feedback.join`
output, the repo's libsvm grammar) AS THEY APPEAR and pushes gradients
into the same live PS group the serving engines hot-reload from
(``launch serve --ps-hosts``).  There are no epochs and no exit
barrier: the trainer never votes in barriers, never retires the group,
and tolerates the servers' other clients (the serving tier's pulls, a
batch trainer's pushes) by construction — it is just one more async
client of the Hogwild PS (the lock-free continuous-update regime of
arXiv:1508.05711).

AdaBatch-style local accumulation (arXiv:1712.02029) rides the shared
:class:`~distlr_tpu.compress.GradientAccumulator` (extracted from this
module once the batch trainers adopted the pattern): gradients are
accumulated locally and pushed as a mean every ``k`` batches, with
``k`` GROWING on a schedule (multiply by ``accum_growth`` every
``accum_growth_every`` pushes, capped at ``accum_max``).  Early in the
loop's life small ``k`` keeps served weights fresh; as the model
stabilizes, growing ``k`` cuts push traffic — the cadence axis of the
communication dial whose encoding axis is ``cfg.ps_compress`` (the
negotiated wire codec; this trainer's pushes ride it too).

Multi-worker sharding: any number of online trainers may share one
shard dir.  A trainer takes a shard by atomically renaming it to
``<shard>.claim`` (exactly one rename wins; losers skip), consumes it,
then retires it to ``<shard>.done`` — and a ``.claim`` whose owner died
is reclaimed after ``claim_stale_s`` (claim time is the file's mtime,
touched at claim).  ``claim_stale_s`` must exceed the worst-case
consume time of one shard, or a slow-but-alive worker's shard gets
double-trained (Hogwild-tolerable, but logged).

Requires an ASYNC server group: against a sync (BSP) group a lone
online push would block forever in the deferred-reply barrier.
"""

from __future__ import annotations

import json
import os

import numpy as np

from distlr_tpu import sync

from distlr_tpu.config import Config
from distlr_tpu.obs import dtrace
from distlr_tpu.obs.registry import get_registry
from distlr_tpu.utils.logging import get_logger

log = get_logger(__name__)

_reg = get_registry()
_SHARDS_CONSUMED = _reg.counter(
    "distlr_feedback_shards_consumed_total",
    "joined training shards consumed by the online trainer",
)
_EXAMPLES = _reg.counter(
    "distlr_feedback_examples_trained_total",
    "joined examples the online trainer computed gradients over",
)
_PUSHES = _reg.counter(
    "distlr_feedback_online_pushes_total",
    "gradient pushes issued by the online trainer (after AdaBatch "
    "local accumulation)",
)
_LAG = _reg.gauge(
    "distlr_feedback_shard_lag",
    "joined shards written but not yet consumed by the online trainer "
    "(the loop's freshness debt)",
)
_ACCUM_K = _reg.gauge(
    "distlr_feedback_accum_batches",
    "current AdaBatch accumulation span: batches per push",
)

#: models the online loop supports: dense full-vector pushes
#: (binary_lr / softmax), keyed sparse pushes (sparse_lr), and keyed
#: per-class rows (sparse_softmax — each feature key owns its
#: num_classes lanes, pushed vals_per_key=K when the group's range
#: boundaries align, expanded per-lane keys otherwise)
_SUPPORTED = ("binary_lr", "softmax", "sparse_lr", "sparse_softmax")


class OnlineTrainer:
    """Shard-watching Hogwild worker over a live async PS group."""

    #: client id: out of the way of batch-trainer ranks (0..) and the
    #: serving pull client (4095)
    ONLINE_CLIENT_ID = 0x0E00

    def __init__(self, cfg: Config, hosts: str, shard_dir: str, *,
                 accum_start: int = 1, accum_growth: float = 2.0,
                 accum_growth_every: int = 32, accum_max: int = 64,
                 poll_interval_s: float = 0.5, idle_flush_s: float = 2.0,
                 client_id: int | None = None, seed_init: bool = True,
                 worker_id: int = 0, claim_stale_s: float = 300.0,
                 ns_base: int = 0, ns_total_dim: int | None = None,
                 route=None):
        if cfg.model == "blocked_lr":
            # named rejection, not a generic unsupported-model error: the
            # blocked path's raw-CTR hashing happens at shard INGEST
            # (write_raw_ctr_shards) while feedback shards carry already-
            # hashed libsvm rows — re-deriving the grouped (R, groups)
            # row layout from them is not possible, so blocked models
            # keep training through `launch ps`
            raise ValueError(
                "online training does not support blocked_lr: feedback "
                "shards are hashed libsvm rows, but blocked_lr's grouped "
                "row layout is only derivable from RAW categorical "
                "shards at ingest time — train blocked models with "
                "`launch ps` on raw-CTR data instead")
        if cfg.model not in _SUPPORTED:
            raise ValueError(
                f"online training supports {_SUPPORTED}, got {cfg.model!r}")
        if worker_id < 0:
            raise ValueError(f"worker_id must be >= 0, got {worker_id}")
        # imported here, not at module top: these helpers live with the
        # batch PS trainer (the asked-for reuse), which imports jax —
        # acceptable for a trainer process, deferred for everyone else
        from distlr_tpu.compress import GradientAccumulator  # noqa: PLC0415
        from distlr_tpu.ps import KVWorker, RetryPolicy  # noqa: PLC0415
        from distlr_tpu.train.ps_trainer import ps_param_dim  # noqa: PLC0415

        self.cfg = cfg
        self.shard_dir = shard_dir
        self.dim = ps_param_dim(cfg)
        self.poll_interval_s = float(poll_interval_s)
        self.idle_flush_s = float(idle_flush_s)
        self.worker_id = int(worker_id)
        self.claim_stale_s = float(claim_stale_s)
        #: multi-tenant namespace scoping (ISSUE 10): when the group
        #: hosts several model namespaces, train only the slice
        #: ``[ns_base, ns_base + dim)`` — each tenant's online trainer
        #: watches its own shard subdir and pushes into its own
        #: namespace of the shared group
        wire_dim = int(ns_total_dim) if ns_total_dim else self.dim
        worker = KVWorker(
            hosts, wire_dim,
            client_id=self.ONLINE_CLIENT_ID + worker_id if client_id is None
            else client_id,
            timeout_ms=cfg.ps_timeout_ms,
            sync_group=False,  # Hogwild client: no barriers, keyed shortcut
            retry=RetryPolicy.from_config(cfg),
            compress=cfg.ps_compress,
            # elastic fleet: with a membership route provider (`launch
            # online --ps-ctl`), a live reshard costs this trainer one
            # routing re-negotiation — never a restart
            route=route,
        )
        self.kv = (worker if wire_dim == self.dim and not ns_base
                   else worker.namespace(int(ns_base), self.dim))
        if seed_init:
            # idempotent: seeds an unseeded group with zeros (FTRL's
            # natural origin), no-ops against live weights — so the
            # online trainer can be the loop's FIRST trainer or join an
            # already-trained group without a flag.  (In a multi-
            # namespace group the first namespace's seed initializes the
            # whole table to zeros — later namespaces' no-ops land on
            # the same zeros.)
            self.kv.push_init(np.zeros(self.dim, np.float32))
        self._accum = GradientAccumulator(
            self.dim, start=accum_start, growth=accum_growth,
            growth_every=accum_growth_every, max_k=accum_max,
            gauge=_ACCUM_K)
        self._w_cache: np.ndarray | None = None
        self.shards_consumed = 0
        self.examples = 0
        self.pushes = 0
        self._num_classes = (cfg.num_classes
                             if cfg.model in ("softmax", "sparse_softmax")
                             else None)
        # sparse_softmax keyed rows: one feature key owns K class lanes;
        # vals_per_key rides the wire when the group's range boundaries
        # align, else keys expand per lane (the keyed trainers' rule)
        self._row_vpk = 1
        if cfg.model == "sparse_softmax" and self.kv.supports_vals_per_key(
                cfg.num_classes):
            self._row_vpk = cfg.num_classes

    @property
    def accum_k(self) -> int:
        """Current AdaBatch span (batches per push)."""
        return self._accum.k

    # -- gradient plumbing -------------------------------------------------
    def _dense_batch(self, X, y) -> None:
        from distlr_tpu.train.ps_trainer import _np_dense_grad  # noqa: PLC0415

        cfg = self.cfg
        if self._accum.batches == 0:
            # pull once per accumulation span: batches within a span ride
            # the same weights (AdaBatch local accumulation; the span is
            # the self-staleness bound)
            self._w_cache = self.kv.pull()
        K = self._num_classes
        w = (self._w_cache.reshape(cfg.num_feature_dim, K) if K
             else self._w_cache)
        mask = np.ones(len(y), np.float32)
        g = _np_dense_grad(w, X, y, mask, cfg.l2_c,
                           bool(cfg.l2_scale_by_batch), K)
        self._accum.add(g)
        self.examples += len(y)
        _EXAMPLES.inc(len(y))

    def _sparse_batch(self, pc, pv, y) -> None:
        from distlr_tpu.train.ps_trainer import _sparse_batch_grad  # noqa: PLC0415

        cfg = self.cfg
        ub, pos = np.unique(pc, return_inverse=True)
        keys = ub.astype(np.uint64)
        w_u = self.kv.pull(keys=keys)
        mask = np.ones(len(y), np.float32)
        g_u = _sparse_batch_grad(w_u, pos.reshape(pc.shape), pv, y, mask,
                                 cfg.l2_c, bool(cfg.l2_scale_by_batch))
        self._accum.add_at(ub, g_u)
        self.examples += len(y)
        _EXAMPLES.inc(len(y))

    def _sparse_softmax_batch(self, pc, pv, y) -> None:
        """Keyed rows per class (the ISSUE-6 follow-on): each unique
        feature key owns its K class lanes of the row-major (D, K)
        table — pulled/pushed vals_per_key=K when aligned, expanded
        per-lane keys otherwise."""
        from distlr_tpu.train.ps_trainer import (  # noqa: PLC0415
            _expand_block_keys,
            _sparse_softmax_batch_grad,
        )

        cfg = self.cfg
        K = cfg.num_classes
        ub, pos = np.unique(pc, return_inverse=True)
        rows = ub.astype(np.uint64)
        if self._row_vpk > 1:
            w_u = self.kv.pull(keys=rows, vals_per_key=K)
        else:
            w_u = self.kv.pull(keys=_expand_block_keys(rows, K))
        mask = np.ones(len(y), np.float32)
        g_u = _sparse_softmax_batch_grad(
            w_u.reshape(-1, K), pos.reshape(pc.shape), pv, y, mask,
            cfg.l2_c, bool(cfg.l2_scale_by_batch))
        self._accum.add_rows(ub, g_u.reshape(-1), K)
        self.examples += len(y)
        _EXAMPLES.inc(len(y))

    def _flush_push(self) -> None:
        """Push the accumulated MEAN gradient (one Hogwild update of
        batch size span*B); the accumulator advances its own AdaBatch
        schedule per flush."""
        cfg = self.cfg
        if cfg.model == "sparse_lr":
            res = self._accum.flush_keyed()
            if res is None:
                return
            keys, vals = res
            if keys.size:  # async Hogwild: a cancelled span pushes nothing
                self.kv.wait(self.kv.push(vals, keys=keys))
        elif cfg.model == "sparse_softmax":
            res = self._accum.flush_keyed(vpk=cfg.num_classes)
            if res is None:
                return
            rows, vals = res
            if rows.size:
                if self._row_vpk > 1:
                    self.kv.wait(self.kv.push(
                        vals, keys=rows, vals_per_key=cfg.num_classes))
                else:
                    from distlr_tpu.train.ps_trainer import (  # noqa: PLC0415
                        _expand_block_keys,
                    )

                    self.kv.wait(self.kv.push(
                        vals, keys=_expand_block_keys(rows,
                                                      cfg.num_classes)))
        else:
            g = self._accum.flush_dense()
            if g is None:
                return
            self.kv.wait(self.kv.push(g))
        self._w_cache = None
        self.pushes += 1
        _PUSHES.inc()

    # -- shard consumption -------------------------------------------------
    def _scan(self) -> list[str]:
        # ".libsvm.claim" / ".libsvm.done" fail the endswith filter, so
        # the scan (and the lag gauge) only ever see unclaimed work
        try:
            names = sorted(os.listdir(self.shard_dir))
        except OSError:
            return []
        return [os.path.join(self.shard_dir, n) for n in names
                if n.startswith("shard-") and n.endswith(".libsvm")]

    def _claim(self, path: str) -> str | None:
        """Take exclusive ownership of a shard via the ``.claim`` rename
        protocol: the atomic rename is the lock (exactly one of N
        workers wins; losers get ENOENT and move on).  The claim
        file's mtime records CLAIM time."""
        claim = path + ".claim"
        # Fresh mtime BEFORE the claim becomes visible: rename preserves
        # the shard's own (arbitrarily old) mtime, and a claim that is
        # born looking stale can be stolen back by a peer's
        # _reclaim_stale before our utime lands — then consume crashes
        # on the vanished file instead of losing the race cleanly.
        try:
            os.utime(path)
        except OSError:
            return None  # shard vanished (a peer claimed or consumed it)
        try:
            os.rename(path, claim)
        except OSError:
            return None  # a peer worker won the race (or shard vanished)
        return claim

    def _reclaim_stale(self) -> None:
        """Return orphaned claims to the pool: a worker that died
        mid-shard leaves a ``.claim`` nobody will finish; after
        ``claim_stale_s`` (measured from claim time) any worker renames
        it back.  Racing reclaimers are safe — one rename wins."""
        if self.claim_stale_s <= 0:
            return
        try:
            names = os.listdir(self.shard_dir)
        except OSError:
            return
        now = sync.wall()
        for nm in names:
            if not nm.endswith(".libsvm.claim"):
                continue
            p = os.path.join(self.shard_dir, nm)
            try:
                if now - os.path.getmtime(p) < self.claim_stale_s:
                    continue
                os.rename(p, p[:-len(".claim")])
            except OSError:
                continue  # raced a peer reclaimer, or owner just finished
            log.warning("online[%d]: reclaimed stale claim %s (owner "
                        "presumed dead)", self.worker_id, nm)

    @staticmethod
    def _sidecar_path(path: str) -> str:
        """Trace sidecar of a shard (written by the joiner before the
        shard became visible).  ``path`` may be the claimed name — the
        sidecar always lives next to the ORIGINAL shard name."""
        if path.endswith(".claim"):
            path = path[:-len(".claim")]
        return path + ".trace"

    def _shard_traces(self, path: str) -> list:
        """Distinct trace contexts the shard's records carried, in
        first-appearance order ([] = untraced shard / no sidecar)."""
        try:
            with open(self._sidecar_path(path)) as f:
                tokens = json.load(f)
        except (OSError, ValueError):
            return []
        out, seen = [], set()
        for tok in tokens:
            if not tok or tok in seen:
                continue
            seen.add(tok)
            try:
                out.append(dtrace.parse_token(tok))
            except ValueError:
                continue
        return out

    def consume_shard(self, path: str) -> int:
        """Train over one joined shard; returns examples consumed.

        Distributed tracing: the consume interval runs under the FIRST
        trace the shard carried (so this shard's flush pushes — and the
        servers' apply spans under them — chain back to that request's
        score->label->join timeline), and is retrospectively attributed
        to every OTHER trace in the shard's sidecar."""
        from distlr_tpu.data.hashing import csr_to_padded_coo  # noqa: PLC0415
        from distlr_tpu.data.libsvm import parse_libsvm_lines  # noqa: PLC0415

        with open(path) as f:
            lines = [ln for ln in f.read().splitlines() if ln.strip()]
        if not lines:
            return 0
        traces = self._shard_traces(path)
        shard = os.path.basename(path)
        cfg = self.cfg
        B = cfg.batch_size if cfg.batch_size > 0 else 256
        n = 0
        t0_wall, t0 = sync.wall(), sync.monotonic()
        with dtrace.use(traces[0] if traces else None), dtrace.span(
                "online.consume",
                tags={"shard": shard, "records": len(lines),
                      "worker": self.worker_id}):
            if cfg.model in ("sparse_lr", "sparse_softmax"):
                (row_ptr, cols, vals), y = parse_libsvm_lines(
                    lines, cfg.num_feature_dim, dense=False,
                    multiclass=cfg.model == "sparse_softmax")
                pc, pv = csr_to_padded_coo(row_ptr, cols, vals,
                                           nnz_max=cfg.nnz_max)
                batch_fn = (self._sparse_softmax_batch
                            if cfg.model == "sparse_softmax"
                            else self._sparse_batch)
                for lo in range(0, len(y), B):
                    batch_fn(pc[lo:lo + B], pv[lo:lo + B], y[lo:lo + B])
                    if self._accum.ready:
                        self._flush_push()
                    n += len(y[lo:lo + B])
            else:
                X, y = parse_libsvm_lines(
                    lines, cfg.num_feature_dim, dense=True,
                    multiclass=self._num_classes is not None)
                for lo in range(0, len(y), B):
                    self._dense_batch(X[lo:lo + B], y[lo:lo + B])
                    if self._accum.ready:
                        self._flush_push()
                    n += len(y[lo:lo + B])
        dur = sync.monotonic() - t0
        for ctx in traces[1:]:
            # the other traces coalesced into this shard each get the
            # same interval attributed (ring + journal), so "where did
            # my label go" has an answer for every request
            dtrace.record_span("online.consume", ctx, t0_wall, dur,
                               tags={"shard": shard, "shared": True})
        self.shards_consumed += 1
        _SHARDS_CONSUMED.inc()
        return n

    # -- the loop ----------------------------------------------------------
    def run(self, *, stop: sync.Event | None = None,
            max_shards: int = 0, idle_exit_s: float | None = None) -> dict:
        """Consume shards until ``stop`` is set, ``max_shards`` shards
        were trained (0 = unbounded), or nothing new appeared for
        ``idle_exit_s`` seconds (None = wait forever) — the latter two
        are the scriptable exits benches and tests use; production runs
        pass neither and live as long as the serving tier."""
        stop = stop or sync.Event()
        idle_since = sync.monotonic()
        consumed_this_run = 0
        while not stop.is_set():
            # every cycle, not just idle ones: under sustained traffic
            # `pending` may never drain, and a dead peer's orphaned
            # .claim must still re-pool (its shard re-enters next scan)
            self._reclaim_stale()
            pending = self._scan()
            _LAG.set(len(pending))
            if not pending:
                now = sync.monotonic()
                if (self._accum.batches
                        and now - idle_since >= self.idle_flush_s):
                    # traffic lull: a partial accumulation span must not
                    # strand its gradients locally forever
                    self._flush_push()
                if idle_exit_s is not None and now - idle_since >= idle_exit_s:
                    break
                stop.wait(self.poll_interval_s)
                continue
            for path in pending:
                if stop.is_set():
                    break
                claimed = self._claim(path)
                if claimed is None:
                    continue  # a peer worker owns this shard
                try:
                    n = self.consume_shard(claimed)
                except FileNotFoundError:
                    # claim outlived claim_stale_s before we opened it
                    # and a peer reclaimed: the shard re-pooled, a live
                    # worker owns it — lose the race, don't die
                    log.warning(
                        "online[%d]: claim on %s stolen before consume "
                        "(raise claim_stale_s?)", self.worker_id,
                        os.path.basename(path))
                    continue
                # consumed shards step aside (audit trail kept), so the
                # scan and the lag gauge only ever see fresh work; the
                # trace sidecar retires with its shard
                try:
                    os.replace(claimed, path + ".done")
                    side = self._sidecar_path(path)
                    if os.path.exists(side):
                        os.replace(side, side + ".done")
                except OSError:
                    # our claim outlived claim_stale_s and a peer
                    # reclaimed it mid-consume: the shard may train
                    # twice — Hogwild-tolerable, but worth a line
                    log.warning("online[%d]: claim on %s expired while "
                                "consuming (raise claim_stale_s?)",
                                self.worker_id, os.path.basename(path))
                idle_since = sync.monotonic()
                consumed_this_run += 1
                log.info("online[%d]: consumed %s (%d examples, k=%d, "
                         "%d pushes)", self.worker_id,
                         os.path.basename(path), n,
                         self.accum_k, self.pushes)
                if max_shards and consumed_this_run >= max_shards:
                    self._flush_push()
                    _LAG.set(len(self._scan()))
                    return self.stats()
        self._flush_push()
        return self.stats()

    def stats(self) -> dict:
        return {
            "shards_consumed": self.shards_consumed,
            "examples": self.examples,
            "pushes": self.pushes,
            "accum_k": self.accum_k,
            "pending": len(self._scan()),
        }

    def close(self) -> None:
        self.kv.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
