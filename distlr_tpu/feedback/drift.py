"""Score-distribution drift detection for the serving tier.

The closed loop's canary: when the world shifts (labels flip, a
feature pipeline breaks, the online trainer adapts the model), the
FIRST externally visible symptom is the served score distribution
moving.  This detector compares consecutive fixed-size blocks of served
scores with the Population Stability Index over a fixed [0, 1] bin
grid:

    PSI = sum_b (p_b - q_b) * ln(p_b / q_b)

where ``q`` is the previous completed block (the reference window) and
``p`` the current one.  PSI > threshold ⇒ ``distlr_alert_score_drift``
fires (threshold carried as a label, like every ``distlr_alert_*``
gauge).  Because the reference window ROLLS (each completed block
becomes the next comparison's reference), the alert fires while the
distribution is MOVING and clears once it stabilizes — even at a new
level.  That is exactly the acceptance shape: labels flip mid-run, the
online trainer adapts, scores shift (alert fires), adaptation
completes, scores settle (alert clears), zero restarts.

Deterministic and cheap: integer bin counts, no timestamps — block
boundaries are request-count-driven, so tests replay exact traffic.
"""

from __future__ import annotations

from distlr_tpu import sync

import numpy as np

from distlr_tpu.obs.registry import get_registry

_reg = get_registry()
_PSI = _reg.gauge(
    "distlr_feedback_score_psi",
    "population stability index of the served score distribution: "
    "latest completed block vs the previous one (the drift signal)",
)
_DRIFT = _reg.gauge(
    "distlr_alert_score_drift",
    "1 while the served score distribution is shifting (block-to-block "
    "PSI above the threshold label); clears when scores stabilize, "
    "even at a new level",
    labelnames=("threshold",),
)


class ScoreDriftDetector:
    """Block-wise PSI over served scores in [0, 1].

    Thread-safe; ``observe`` is called from request-handler threads.
    """

    def __init__(self, *, block: int = 512, bins: int = 10,
                 threshold: float = 0.25, smoothing: float = 1e-3):
        if block <= 0 or bins <= 1:
            raise ValueError(
                f"need block > 0 and bins > 1, got {block}/{bins}")
        if threshold <= 0:
            raise ValueError(f"threshold must be positive, got {threshold}")
        if smoothing <= 0:
            raise ValueError(f"smoothing must be positive, got {smoothing}")
        self.block = int(block)
        self.bins = int(bins)
        self.threshold = float(threshold)
        self.smoothing = float(smoothing)
        self._lock = sync.Lock()
        self._cur = np.zeros(self.bins, np.int64)
        self._cur_n = 0
        self._ref: np.ndarray | None = None
        self.psi_last: float | None = None
        self.blocks = 0
        self.firing = False
        self.fired_total = 0
        self.cleared_total = 0
        self._gauge = _DRIFT.labels(threshold=f"{self.threshold:g}")
        self._gauge.set(0.0)

    def observe(self, scores) -> None:
        """Feed served scores (any array-like of floats in [0, 1];
        out-of-range values clamp into the edge bins).  Blocks close at
        EXACTLY ``block`` observations regardless of call granularity —
        a burst larger than a block splits, so block boundaries (and
        with them the PSI series) are deterministic in traffic count."""
        scores = np.asarray(scores, np.float64).reshape(-1)
        if scores.size == 0:
            return
        idx = np.clip((scores * self.bins).astype(np.int64), 0, self.bins - 1)
        with self._lock:
            pos = 0
            while pos < idx.size:
                take = min(self.block - self._cur_n, idx.size - pos)
                self._cur += np.bincount(idx[pos:pos + take],
                                         minlength=self.bins)
                self._cur_n += int(take)
                pos += take
                if self._cur_n >= self.block:
                    self._roll_locked()

    def _roll_locked(self) -> None:
        """Close the current block: compare against the reference block,
        publish, and make it the next reference."""
        cur = self._cur.copy()
        self._cur[:] = 0
        self._cur_n = 0
        self.blocks += 1
        if self._ref is not None:
            p = cur / cur.sum() + self.smoothing
            q = self._ref / self._ref.sum() + self.smoothing
            psi = float(np.sum((p - q) * np.log(p / q)))
            self.psi_last = psi
            _PSI.set(psi)
            firing = psi > self.threshold
            if firing and not self.firing:
                self.fired_total += 1
            elif self.firing and not firing:
                self.cleared_total += 1
            self.firing = firing
            self._gauge.set(1.0 if firing else 0.0)
        self._ref = cur

    def stats(self) -> dict:
        with self._lock:
            return {
                "blocks": self.blocks,
                "psi": None if self.psi_last is None
                else round(self.psi_last, 6),
                "firing": self.firing,
                "fired_total": self.fired_total,
                "cleared_total": self.cleared_total,
                "block_size": self.block,
                "threshold": self.threshold,
            }


def psi(p_counts, q_counts, *, smoothing: float = 1e-3) -> float:
    """Standalone PSI of two histograms (test oracle / offline use)."""
    p = np.asarray(p_counts, np.float64)
    q = np.asarray(q_counts, np.float64)
    if p.shape != q.shape or p.sum() <= 0 or q.sum() <= 0:
        raise ValueError("need two same-shape non-empty histograms")
    p = p / p.sum() + smoothing
    q = q / q.sum() + smoothing
    return float(np.sum((p - q) * np.log(p / q)))


__all__ = ["ScoreDriftDetector", "psi"]
