"""Delayed-label join — turn scored requests + late labels into
training shards.

The second quarter of the online-learning loop: CTR-style labels
(click / no-click) arrive seconds to minutes after the impression was
scored, over the SAME serve line protocol (an additive ``LABEL <id>
<y>`` line, extended exactly like STATS was).  The joiner matches each
label against the spooled request within a configurable delay window
and emits joined examples — ``<label> <features>`` lines in the
repo's existing libsvm/ingest grammar — as rotating shard files the
continuous trainer (:mod:`distlr_tpu.feedback.online`) consumes.

Edge cases, all regression-tested (tests/test_feedback.py):

* **label-before-request** — labels can outrun their impression across
  a routed fleet; unknown ids are held in a bounded pending buffer and
  joined the moment the request shows up.
* **duplicate labels** — the first label wins; repeats for an
  already-joined id are counted (``duplicate_label``), never re-emitted
  (a double-counted click would bias the positive rate).
* **expired window** — a request never labeled within ``window_s`` is
  resolved by the NEGATIVE-SAMPLING policy: with probability
  ``negative_rate`` it is emitted as a label-0 example (the standard
  CTR assumption — no click within the window ≈ no click), otherwise
  dropped.  ``negative_rate`` both caps the induced class skew and
  keeps shard volume proportional to traffic, not to silence.
"""

from __future__ import annotations

import json
import os
import random
import re

from distlr_tpu import sync
from distlr_tpu.obs import dtrace
from distlr_tpu.obs.registry import get_registry
from distlr_tpu.feedback.spool import FeedbackSpool, SpoolRecord, drop

_reg = get_registry()
_JOINED = _reg.counter(
    "distlr_feedback_joined_total",
    "label events joined to their spooled request within the window",
)
_NEGATIVE = _reg.counter(
    "distlr_feedback_negative_sampled_total",
    "never-labeled requests emitted as negative (label-0) examples by "
    "the negative-sampling policy at window expiry",
)
_JOIN_DELAY = _reg.histogram(
    "distlr_feedback_join_delay_seconds",
    "seconds between a request being scored and its label joining",
)
_SHARDS = _reg.counter(
    "distlr_feedback_shards_written_total",
    "joined training shards emitted for the online trainer",
)
_PENDING_LABELS = _reg.gauge(
    "distlr_feedback_pending_labels",
    "label events holding for a request that has not arrived yet",
)


class LabelJoiner:
    """Join labels to spooled requests; emit libsvm training shards.

    Thread-safe: request-handler threads call :meth:`scored` /
    :meth:`label` while a ticker thread calls :meth:`tick`.  All spool
    membership operations happen under the joiner lock — a request
    check-then-spool and a label pop-then-hold that interleaved would
    otherwise strand the label in the pending buffer while its request
    ages out through negative sampling (the spool keeps its own lock
    for direct callers, and never calls back into the joiner, so the
    joiner→spool ordering cannot deadlock).
    """

    def __init__(self, spool: FeedbackSpool, out_dir: str, *,
                 window_s: float = 60.0, negative_rate: float = 0.0,
                 shard_records: int = 1024, max_pending_labels: int = 10_000,
                 recent_joined: int = 8192, seed: int = 0):
        if window_s <= 0:
            raise ValueError(f"window_s must be positive, got {window_s}")
        if not 0.0 <= negative_rate <= 1.0:
            raise ValueError(
                f"negative_rate must be in [0, 1], got {negative_rate}")
        if shard_records <= 0:
            raise ValueError(
                f"shard_records must be positive, got {shard_records}")
        self.spool = spool
        self.out_dir = out_dir
        os.makedirs(out_dir, exist_ok=True)
        self.window_s = float(window_s)
        self.negative_rate = float(negative_rate)
        self.shard_records = int(shard_records)
        self.max_pending_labels = int(max_pending_labels)
        self._recent_cap = int(recent_joined)
        self._rng = random.Random(seed)
        self._lock = sync.Lock()
        #: labels that arrived before their request: rid -> (label, ts)
        self._pending: dict[str, tuple[int, float]] = {}
        #: recently joined rids (bounded, insertion-ordered) — the
        #: duplicate-label detector
        self._recent: dict[str, None] = {}
        #: pending shard lines PER MODEL (multi-tenant serving: each
        #: model version's examples emit into its own shard stream under
        #: ``<out_dir>/<model>/``, so per-tenant online trainers watch
        #: disjoint dirs; the ``None`` stream is the pre-tenant flat
        #: layout).  Entries: (text, trace ids or None, rid or None).
        self._buffers: dict[
            str | None,
            list[tuple[str, tuple[int, int] | None, str | None]]] = {}
        # per-model shard sequence, resumed lazily AFTER any shard a
        # previous run left behind (consumed or not) — restarting at 0
        # would os.replace-clobber unconsumed work
        self._seqs: dict[str | None, int] = {}
        self.joined = 0
        self.negatives = 0
        self.shards_written = 0

    @staticmethod
    def _next_shard_seq(out_dir: str) -> int:
        # .claim (a shard some online trainer currently owns — it may be
        # reclaimed back to its original name) and orphaned .trace
        # sidecars count too: reusing their sequence number would
        # os.replace-clobber a reclaimed unconsumed shard, or attribute
        # a new shard to a previous run's traces
        seq = 0
        try:
            names = os.listdir(out_dir)
        except OSError:
            return 0
        for name in names:
            m = re.match(
                r"shard-(\d+)\.libsvm(\.done|\.claim|\.trace(\.done)?)?$",
                name)
            if m:
                seq = max(seq, int(m.group(1)) + 1)
        return seq

    # -- ingest ------------------------------------------------------------
    def scored(self, rec: SpoolRecord) -> None:
        """A request was scored: spool it — or join it on the spot when
        its label already arrived (label-before-request)."""
        with self._lock:
            pend = self._pending.pop(rid := rec.rid, None)
            if pend is not None:
                y, label_ts = pend
                self._join_locked(rid, y, rec, now=label_ts)
                _PENDING_LABELS.set(len(self._pending))
                return
            self.spool.add(rec)

    def label(self, rid: str, y: int, *, ts: float | None = None) -> str:
        """A label event arrived.  Returns the outcome: ``"joined"``,
        ``"pending"`` (request not seen yet), or ``"duplicate"``."""
        now = sync.wall() if ts is None else ts
        y = int(y)
        with self._lock:
            rec = self.spool.pop(rid)
            if rec is not None:
                self._join_locked(rid, y, rec, now=now)
                return "joined"
            if rid in self._recent or rid in self._pending:
                drop("duplicate_label")
                return "duplicate"
            if len(self._pending) >= self.max_pending_labels:
                # bounded: shed the OLDEST held label (insertion order)
                oldest = next(iter(self._pending))
                del self._pending[oldest]
                drop("unmatched_label")
            self._pending[rid] = (y, now)
            _PENDING_LABELS.set(len(self._pending))
            return "pending"

    # -- the join ----------------------------------------------------------
    def _join_locked(self, rid: str, y: int, rec: SpoolRecord, *,
                     now: float) -> None:
        delay = max(0.0, now - rec.ts)
        _JOIN_DELAY.observe(delay)
        self._remember_locked(rid)
        self.joined += 1
        _JOINED.inc()
        trace = rec.trace
        if trace is not None:
            # continue the scoring request's distributed trace: the join
            # span parents under the feedback.spool span, and its child
            # ids ride the shard sidecar to the online trainer
            ctx = dtrace.TraceContext(trace[0], trace[1], True)
            with dtrace.span("feedback.join",
                             tags={"delay_s": round(delay, 3), "y": int(y)},
                             ctx=ctx) as sp:
                trace = (sp.ctx.trace_id, sp.ctx.span_id)
        self._emit_locked(y, rec.line, trace, rid=rid, model=rec.model)

    def _remember_locked(self, rid: str) -> None:
        self._recent[rid] = None
        while len(self._recent) > self._recent_cap:
            del self._recent[next(iter(self._recent))]

    def _model_dir(self, model: str | None) -> str:
        return (self.out_dir if model is None
                else os.path.join(self.out_dir, model))

    def _emit_locked(self, y: int, line: str,
                     trace: tuple[int, int] | None = None,
                     rid: str | None = None,
                     model: str | None = None) -> None:
        buf = self._buffers.setdefault(model, [])
        buf.append((f"{int(y)} {line}", trace, rid))
        if len(buf) >= self.shard_records:
            self._write_shard_locked(model)

    def _write_shard_locked(self, model: str | None = None) -> None:
        buffer = self._buffers.get(model)
        if not buffer:
            return
        out_dir = self._model_dir(model)
        seq = self._seqs.get(model)
        if seq is None:
            os.makedirs(out_dir, exist_ok=True)
            seq = self._next_shard_seq(out_dir)
        path = os.path.join(out_dir, f"shard-{seq:06d}.libsvm")
        # trace sidecar first, shard second: the rename that makes the
        # shard claimable must find the sidecar already in place (the
        # trainer reads it at claim time)
        side = f"{path}.trace"
        if any(tr is not None for _, tr, _r in buffer):
            stmp = f"{side}.tmp"
            with open(stmp, "w") as f:
                json.dump([None if tr is None else f"{tr[0]:016x}/{tr[1]:016x}"
                           for _, tr, _r in buffer], f)
            os.replace(stmp, side)
        elif os.path.exists(side):
            # a crash between sidecar and shard write left an orphan; a
            # same-numbered traceless shard must not inherit it
            try:
                os.unlink(side)
            except OSError:
                pass
        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            f.write("\n".join(text for text, _tr, _r in buffer) + "\n")
        os.replace(tmp, path)  # atomic: the trainer never sees a torn shard
        # tombstone AFTER the shard is durable: a crash in between
        # replays the record and at worst re-joins a re-arriving label
        # (deduped in-session by _recent) — never silently drops one
        for _text, _tr, rid in buffer:
            if rid is not None:
                self.spool.mark_joined(rid)
        self._seqs[model] = seq + 1
        buffer.clear()
        self.shards_written += 1
        _SHARDS.inc()

    # -- window expiry -----------------------------------------------------
    def tick(self, now: float | None = None) -> None:
        """Resolve everything older than the window: never-labeled
        requests go through the negative-sampling policy; held labels
        whose request never arrived are dropped as unmatched."""
        now = sync.wall() if now is None else now
        cutoff = now - self.window_s
        with self._lock:
            expired = self.spool.expire_before(cutoff)
            for rec in expired:
                self._remember_locked(rec.rid)
                if self.negative_rate and self._rng.random() < self.negative_rate:
                    self.negatives += 1
                    _NEGATIVE.inc()
                    self._emit_locked(0, rec.line, rec.trace,
                                      model=rec.model)
                else:
                    drop("expired")
            stale = [rid for rid, (_, ts) in self._pending.items()
                     if ts < cutoff]
            for rid in stale:
                del self._pending[rid]
                drop("unmatched_label")
            if stale:
                _PENDING_LABELS.set(len(self._pending))

    def flush(self) -> None:
        """Force out partial shards — every model's (shutdown, tests,
        idle flushes)."""
        with self._lock:
            for model in list(self._buffers):
                self._write_shard_locked(model)

    def stats(self) -> dict:
        with self._lock:
            return {
                "joined": self.joined,
                "negatives": self.negatives,
                "pending_labels": len(self._pending),
                "buffered": sum(len(b) for b in self._buffers.values()),
                "shards_written": self.shards_written,
                "window_s": self.window_s,
                "negative_rate": self.negative_rate,
            }
