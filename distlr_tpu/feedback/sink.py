"""FeedbackSink — the serving front-end's one-object handle on the
feedback loop.

Bundles the spool (:mod:`~distlr_tpu.feedback.spool`), the label joiner
(:mod:`~distlr_tpu.feedback.join`) and the drift detector
(:mod:`~distlr_tpu.feedback.drift`) behind the two calls the
:class:`~distlr_tpu.serve.server.ScoringServer` makes per request:

* :meth:`scored` — after a batch is scored: journal each row (id,
  feature line, score, weights version, touched keys) and feed the
  drift detector.
* :meth:`label` — on a ``LABEL <id> <y>`` protocol line.

A daemon ticker drives window expiry (negative sampling) and flushes
partial shards after ``idle_flush_s`` without new joins, so a
low-traffic tail still reaches the online trainer instead of sitting
in a forever-partial buffer.
"""

from __future__ import annotations

import itertools
from distlr_tpu import sync
from distlr_tpu.feedback.drift import ScoreDriftDetector
from distlr_tpu.feedback.join import LabelJoiner
from distlr_tpu.feedback.spool import (
    FeedbackSpool,
    SpoolRecord,
    per_row_keys,
    strip_label,
)
from distlr_tpu.obs import dtrace


class FeedbackSink:
    """Spool + joiner + drift detector behind the serve front-end."""

    def __init__(self, spool_dir: str, shard_dir: str, *,
                 model: str = "binary_lr", capacity: int = 100_000,
                 window_s: float = 60.0, negative_rate: float = 0.0,
                 shard_records: int = 1024, tracker=None,
                 drift_block: int = 512, drift_threshold: float = 0.25,
                 tick_interval_s: float = 0.5, idle_flush_s: float = 5.0,
                 seed: int = 0, replay: bool = True):
        self.model = model
        self.spool = FeedbackSpool(spool_dir, capacity=capacity,
                                   tracker=tracker)
        if replay:
            # rebuild the joinable set from a previous run's journal:
            # labels arriving across a serve restart join their real
            # impression instead of only ever negative-sampling
            self.spool.replay(window_s=window_s)
        self.joiner = LabelJoiner(self.spool, shard_dir, window_s=window_s,
                                  negative_rate=negative_rate,
                                  shard_records=shard_records, seed=seed)
        self.drift = ScoreDriftDetector(block=drift_block,
                                        threshold=drift_threshold)
        self.tick_interval_s = float(tick_interval_s)
        self.idle_flush_s = float(idle_flush_s)
        self._auto_ids = itertools.count()
        self._last_emit_seen = 0
        self._last_emit_at = sync.monotonic()
        self._stop = sync.Event()
        self._thread: sync.Thread | None = None

    # -- serve-side entry points ------------------------------------------
    def scored(self, lines: list[str], rows: tuple, scores, *,
               version: int, ids: list[str | None] | None = None,
               trace: tuple[int, int] | None = None,
               model: str | None = None) -> None:
        """Journal one scored batch.  ``lines`` are the raw request
        lines (label token optional — stripped here), ``rows`` the
        engine's encoded feature leaves for the SAME batch, ``scores``
        the served scores.  ``ids[i] = None`` auto-assigns an id; such
        rows can never be positively labeled but still feed the drift
        detector and the negative-sampling pool.

        ``trace``: the scoring request's sampled distributed-trace
        ``(trace_id, span_id)`` — the spool entry remembers it, so a
        label arriving minutes later (or across a restart, via the
        journal) continues the ORIGINATING request's trace through
        join -> shard -> online push -> server apply.

        ``model``: the model VERSION that scored the batch
        (multi-tenant serving) — joined examples emit into the model's
        own shard subdir so online training stays per-tenant; None =
        the pre-tenant flat shard layout."""
        now = sync.wall()
        keys = per_row_keys(self.model, rows)
        ctx = (dtrace.TraceContext(trace[0], trace[1], True)
               if trace is not None else None)
        with dtrace.span("feedback.spool", tags={"rows": len(lines)},
                         ctx=ctx) as sp:
            tr = ((sp.ctx.trace_id, sp.ctx.span_id)
                  if sp is not None and sp.ctx.sampled else None)
            for i, line in enumerate(lines):
                rid = ids[i] if ids is not None and ids[i] is not None \
                    else f"auto-{next(self._auto_ids)}"
                self.joiner.scored(SpoolRecord(
                    rid=str(rid), ts=now, line=strip_label(line),
                    score=float(scores[i]), version=int(version),
                    keys=keys[i] if i < len(keys) else None,
                    trace=tr, model=model,
                ))
        self.drift.observe(scores)

    def label(self, rid: str, y: int) -> str:
        """Outcome string (``joined`` / ``pending`` / ``duplicate``)."""
        return self.joiner.label(str(rid), int(y))

    # -- ticker ------------------------------------------------------------
    def _run(self) -> None:
        while not self._stop.wait(self.tick_interval_s):
            self.tick()

    def tick(self, now: float | None = None) -> None:
        self.joiner.tick(now)
        emitted = self.joiner.joined + self.joiner.negatives
        mono = sync.monotonic()
        if emitted != self._last_emit_seen:
            self._last_emit_seen = emitted
            self._last_emit_at = mono
        elif (self.joiner.stats()["buffered"]
              and mono - self._last_emit_at >= self.idle_flush_s):
            # quiet tail: push the partial shard out so the online
            # trainer sees the last few joins of a traffic burst
            self.joiner.flush()
            self._last_emit_at = mono

    def start(self) -> "FeedbackSink":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = sync.Thread(
                target=self._run, daemon=True, name="distlr-feedback-tick")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self.joiner.flush()
        self.spool.close()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    def stats(self) -> dict:
        return {
            "spool": self.spool.stats(),
            "join": self.joiner.stats(),
            "drift": self.drift.stats(),
        }
