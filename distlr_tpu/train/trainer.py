"""Synchronous SPMD trainer — the role-collapsed successor of the
reference's worker loop.

Reference control flow (``src/main.cc:124-170`` + ``src/lr.cc:28-45``):
each of W worker processes re-reads its libsvm shard every epoch, pulls the
full weight vector, computes a mean gradient over its (full-shard) batch,
pushes it, and blocks on the server's deferred response — the BSP barrier.
Rank 0 evaluates every ``TEST_INTERVAL`` epochs and each worker text-dumps
its weights at the end.

Here the W workers become the ``data`` axis of one mesh and the whole
epoch is minibatch steps of a single jitted SPMD program
(:func:`distlr_tpu.parallel.make_sync_train_step`).  Shard->device-row
mapping preserves the reference semantics: worker i's shard rows live on
mesh position i, and with ``batch_size=-1`` each step consumes every
worker's full shard, exactly one reference "iteration".
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import queue
import threading

import jax
import numpy as np

from distlr_tpu.config import Config
from distlr_tpu.data import DataIter, parse_libsvm_file
from distlr_tpu.data.sharding import part_name
from distlr_tpu.models import get_model
from distlr_tpu.obs import jaxrt
from distlr_tpu.obs.tracing import trace_phase
from distlr_tpu.parallel import (
    make_eval_step,
    make_mesh,
    make_sync_train_step,
)
from distlr_tpu.parallel.data_parallel import shard_batch
from distlr_tpu.parallel.mesh import MODEL_AXIS, num_data_shards
from distlr_tpu.train.export import save_model_text
from distlr_tpu.train.metrics import MetricsLogger, StepTimer
from distlr_tpu.utils.logging import get_logger, log_eval_line

log = get_logger(__name__)


class GlobalShardedData:
    """W per-worker shards packed as one global array with lockstep batching.

    Shards are padded to a common length ``n_pad`` and stacked to
    ``(W, n_pad, ...)``; a global minibatch of per-worker size ``b`` is the
    flattened ``(W*b, ...)`` slice ``[:, k*b:(k+1)*b]`` with a validity
    mask.  Laying worker i's rows contiguously at block i makes a plain
    leading-axis ``data`` sharding put each reference-worker's shard on its
    own mesh slot.
    """

    def __init__(self, shards: list[tuple[np.ndarray, ...]]):
        """Each shard is ``(*feature_leaves, y)`` — dense ``(X, y)`` or
        padded-COO sparse ``(cols, vals, y)``; all leaves share the sample
        (leading) axis."""
        if not shards:
            raise ValueError("need at least one shard")
        self.num_shards = len(shards)
        self.shard_sizes = [len(s[-1]) for s in shards]
        n_pad = max(self.shard_sizes)
        if n_pad == 0:
            raise ValueError("all shards are empty — no training data")
        W = self.num_shards
        n_feat_leaves = len(shards[0]) - 1
        # sparse shards may disagree on NNZ_MAX; pad trailing dims to match
        trail = [
            tuple(
                max(s[k].shape[j] for s in shards)
                for j in range(1, shards[0][k].ndim)
            )
            for k in range(n_feat_leaves)
        ]
        self._feats = [
            np.zeros((W, n_pad) + trail[k], dtype=shards[0][k].dtype)
            for k in range(n_feat_leaves)
        ]
        self.y = np.zeros((W, n_pad), dtype=shards[0][-1].dtype)
        self.mask = np.zeros((W, n_pad), dtype=np.float32)
        for i, shard in enumerate(shards):
            n = len(shard[-1])
            for k in range(n_feat_leaves):
                leaf = shard[k]
                sl = (i, slice(0, n)) + tuple(slice(0, d) for d in leaf.shape[1:])
                self._feats[k][sl] = leaf
            self.y[i, :n] = shard[-1]
            self.mask[i, :n] = 1.0
        self.n_pad = n_pad

    @property
    def X(self) -> np.ndarray:
        """The single dense feature matrix (dense datasets only)."""
        if len(self._feats) != 1:
            raise AttributeError("X is only defined for dense (single-leaf) data")
        return self._feats[0]

    @classmethod
    def from_data_dir(
        cls,
        data_dir: str,
        split: str,
        num_shards: int,
        num_features: int,
        *,
        multiclass=False,
        sparse: bool = False,
        nnz_max: int | None = None,
    ):
        """Load ``data_dir/{split}/part-001..W`` (reference layout,
        ``src/main.cc:158-159``). If fewer parts exist than mesh shards,
        parts are round-robined; if more, they are concatenated down.

        ``sparse=True`` keeps rows as padded-COO ``(cols, vals)`` for the
        ``segment_sum`` path instead of densifying (CTR-style data where
        ``(N, D)`` dense would not fit host RAM)."""
        paths = cls._discover_parts(data_dir, split)
        parts = []
        for p in paths:
            if sparse:
                from distlr_tpu.data.hashing import csr_to_padded_coo  # noqa: PLC0415

                (row_ptr, cols, vals), y = parse_libsvm_file(
                    p, num_features, dense=False, multiclass=multiclass
                )
                pc, pv = csr_to_padded_coo(row_ptr, cols, vals, nnz_max=nnz_max)
                parts.append((pc, pv, y))
            else:
                parts.append(parse_libsvm_file(p, num_features, multiclass=multiclass))
        return cls._from_parts(parts, num_shards)

    @staticmethod
    def _discover_parts(data_dir: str, split: str) -> list[str]:
        paths = []
        i = 0
        while True:
            p = os.path.join(data_dir, split, part_name(i))
            if not os.path.exists(p):
                break
            paths.append(p)
            i += 1
        if not paths:
            raise FileNotFoundError(f"no shards under {data_dir}/{split}")
        return paths

    @classmethod
    def from_raw_ctr_dir(cls, data_dir: str, split: str, num_shards: int, cfg):
        """Load raw-CTR shards (``write_raw_ctr_shards`` format) as
        row-blocked leaves ``(blocks, lane_vals, y)`` — the on-disk path
        of the ``blocked_lr`` model.  Hashing happens at load time
        (``encode_blocked``) so train/test share the grouping and seed by
        construction."""
        from distlr_tpu.data.hashing import (  # noqa: PLC0415
            encode_blocked,
            read_raw_ctr_file,
            resolve_ctr_fields,
        )

        num_fields = resolve_ctr_fields(data_dir, cfg.ctr_fields)
        num_blocks = cfg.num_feature_dim // cfg.block_size
        parts = []
        for p in cls._discover_parts(data_dir, split):
            raw_ids, y = read_raw_ctr_file(p, num_fields)
            blocks, lane_vals = encode_blocked(
                raw_ids, num_blocks, cfg.block_size, seed=cfg.hash_seed,
                num_groups=cfg.block_groups,
            )
            parts.append((blocks, lane_vals, y))
        return cls._from_parts(parts, num_shards)

    @classmethod
    def _from_parts(cls, parts, num_shards: int):
        """Redistribute loaded parts onto ``num_shards`` mesh slots
        (round-robin split when fewer parts, interleaved merge when
        more)."""
        if len(parts) != num_shards:

            def _concat(arrs):
                # parts may disagree on trailing dims (per-part NNZ_MAX)
                trail = tuple(
                    max(a.shape[j] for a in arrs) for j in range(1, arrs[0].ndim)
                )
                padded = [
                    np.pad(a, [(0, 0)] + [(0, t - s) for t, s in zip(trail, a.shape[1:])])
                    for a in arrs
                ]
                return np.concatenate(padded)

            leaves = [_concat([p[k] for p in parts]) for k in range(len(parts[0]))]
            shards = [
                tuple(leaf[i::num_shards] for leaf in leaves) for i in range(num_shards)
            ]
        else:
            shards = parts
        return cls(shards)

    @property
    def num_samples(self) -> int:
        return int(sum(self.shard_sizes))

    def batches(self, per_worker_batch: int, *, wrap: bool = False):
        """One epoch of lockstep global batches ``(*feats, y, mask)``
        shaped ``(W*b, ...)``. ``-1`` = full shard per worker (one
        step/epoch).

        ``wrap=True`` reproduces the reference's Q5 final-batch semantics
        (``include/data_iter.h:44-56``): the short final batch wraps to the
        shard head and re-serves leading samples instead of being
        padded+masked.  Lockstep batching can only express this when every
        shard wraps at the same offset, so unequal shard sizes reject
        loudly rather than silently approximating the quirk.
        """
        b = self.n_pad if per_worker_batch == -1 else min(per_worker_batch, self.n_pad)
        # Q5 is defined on REAL per-shard sizes, before padding/clamping:
        # batch=-1 is one whole-shard batch (no wrap possible,
        # data_iter.h:39-43), and a batch larger than the shard cycles it.
        if wrap and per_worker_batch != -1 and any(
            sz % per_worker_batch for sz in self.shard_sizes
        ):
            if any(n != self.n_pad for n in self.shard_sizes):
                raise ValueError(
                    "wrap_final_batch (Q5 compat) requires equal-size shards "
                    f"in the sync trainer (got sizes {self.shard_sizes}); "
                    "per-shard wraparound points diverge otherwise — use the "
                    "PS trainer for Q5 parity on unequal shards, or "
                    "compat_mode='correct'"
                )
            bw, n = per_worker_batch, self.n_pad
            for k in range(-(-n // bw)):
                idx = np.arange(k * bw, (k + 1) * bw) % n
                yield tuple(
                    a[:, idx].reshape((-1,) + a.shape[2:])
                    for a in (*self._feats, self.y, self.mask)
                )
            return

        def _slice(arr, sl, bw):
            out = arr[:, sl]
            if bw < b:  # pad the short final batch to static shape
                pad = [(0, 0), (0, b - bw)] + [(0, 0)] * (arr.ndim - 2)
                out = np.pad(out, pad)
            return out.reshape((-1,) + arr.shape[2:])

        for k in range(-(-self.n_pad // b)):
            sl = slice(k * b, min((k + 1) * b, self.n_pad))
            bw = sl.stop - sl.start
            yield tuple(
                _slice(a, sl, bw) for a in (*self._feats, self.y, self.mask)
            )

    def full_batch(self):
        return tuple(
            a.reshape((-1,) + a.shape[2:]) for a in (*self._feats, self.y, self.mask)
        )


def _prefetch_to_device(shard_fn, host_batches, depth: int):
    """Double-buffered host->device streaming: yield
    ``(host_batch, device_batch)`` pairs with up to ``depth`` batches
    sliced + ``device_put`` ahead of the consumer, from a background
    thread.

    The reference's ``DataIter`` role streams shards to the compute each
    epoch on the worker's own thread (``include/data_iter.h:16-35``);
    here the host-side work (numpy slice/pad of batch k+1 + the transfer
    dispatch) overlaps step k's device compute — H2D DMA rides its own
    stream, so the copy itself also overlaps.  Without this, every
    step paid the slice + dispatch latency serially
    (SURVEY.md §7 hard part (d); VERDICT r3 item 3).

    Safe because :meth:`GlobalShardedData.batches` yields independent
    arrays (fancy-indexed / reshaped slices, never a reused buffer).
    """
    q: queue.Queue = queue.Queue(maxsize=depth)
    stop = threading.Event()
    end = object()
    errs: list[BaseException] = []

    def produce():
        try:
            for hb in host_batches:
                if stop.is_set():
                    return
                q.put((hb, shard_fn(hb)))
        except BaseException as e:  # propagate to the consumer
            errs.append(e)
        q.put(end)

    t = threading.Thread(target=produce, daemon=True,
                         name="distlr-prefetch")
    t.start()
    try:
        while True:
            item = q.get()
            if item is end:
                if errs:
                    raise errs[0]
                return
            yield item
    finally:
        # Consumer may exit early (exception mid-epoch): unblock a
        # producer stuck in q.put so the thread can observe `stop`.
        stop.set()
        with contextlib.suppress(queue.Empty):
            q.get_nowait()


class Trainer:
    """End-to-end sync training: data -> mesh -> SPMD steps -> eval -> export."""

    def __init__(self, cfg: Config, *, mesh=None, metrics: MetricsLogger | None = None):
        self.cfg = cfg
        if mesh is None:
            # honor a local.sh-style DMLC_NUM_WORKER > 1 as the data-axis
            # size; otherwise default to all devices
            shape = cfg.mesh_shape
            if shape is None and cfg.num_workers > 1:
                shape = {"data": cfg.num_workers}
            mesh = make_mesh(shape)
        self.mesh = mesh
        self.model = get_model(cfg)
        self.metrics = metrics or MetricsLogger()
        # A mesh with a 'model' axis selects the 2D data x feature-sharded
        # path (weights partitioned like ps-lite's server key ranges).
        self.feature_sharded = MODEL_AXIS in mesh.axis_names
        if self.feature_sharded and cfg.model in ("sparse_lr",
                                                  "sparse_softmax",
                                                  "blocked_lr"):
            # w[cols] / t[blocks] gathers arbitrary buckets; a partitioned
            # table would turn every gather into a cross-shard collective.
            # Shard the data axis instead (sparse batches are small by
            # construction).
            raise NotImplementedError(
                f"{cfg.model} supports data-parallel meshes only (no 'model' axis)"
            )
        self._build_steps()
        self.timer = StepTimer()
        self.weights = None
        self._train_data: GlobalShardedData | None = None
        self._test_data: GlobalShardedData | None = None

    def _build_steps(self) -> None:
        """(Re)compile the train/eval step closures over the current
        model — called again when load-time feature quantization bakes a
        dequantization scale into the model."""
        cfg = self.cfg
        if self.feature_sharded:
            from distlr_tpu.parallel.feature_parallel import (  # noqa: PLC0415
                make_feature_sharded_eval_step,
                make_feature_sharded_train_step,
                shard_batch_2d,
                shard_weights,
            )

            self.train_step = make_feature_sharded_train_step(self.model, cfg, self.mesh)
            self.eval_step = make_feature_sharded_eval_step(self.model, self.mesh)
            self._shard_batch = lambda b: shard_batch_2d(b, self.mesh)
            self._shard_weights = lambda w: shard_weights(w, self.mesh)
        else:
            self.train_step = make_sync_train_step(self.model, cfg, self.mesh)
            self.eval_step = make_eval_step(self.model, self.mesh)
            self._shard_batch = lambda b: shard_batch(b, self.mesh)
            self._shard_weights = lambda w: jax.device_put(
                w, jax.sharding.NamedSharding(self.mesh, jax.sharding.PartitionSpec())
            )
        # runtime introspection (obs.jaxrt): compile-cache probes for the
        # jitted step closures, ticked per epoch in fit() — a re-build
        # (load-time quantization) re-baselines them
        self._jit_probes = [
            jaxrt.JitCacheProbe(fn, site)
            for fn, site in ((self.train_step, "train.sync.step"),
                             (self.eval_step, "train.sync.eval"))
        ]

    def _quantize_features(self) -> None:
        """Convert loaded dense feature storage to ``cfg.feature_dtype``.

        int8: symmetric per-dataset quantization — one scale from the
        train split's max |x| (the test split reuses it, clipped), folded
        into the model as ``feature_scale`` so the jitted steps dequantize
        on the fly (XLA fuses the convert into the matmul read).
        """
        fd = self.cfg.feature_dtype
        datasets = [d for d in (self._train_data, self._test_data) if d is not None]
        # Datasets can be shared across Trainers (load_data(train=...)),
        # so quantization is recorded on the object: already-quantized
        # datasets keep their stored scale (re-quantizing ints would
        # silently compute scale=1), freshly loaded ones are quantized
        # WITH that scale, and a dtype mismatch fails loudly.
        prev = {d._quant_dtype for d in datasets if getattr(d, "_quant_dtype", None)}
        if prev and prev != {fd}:
            raise ValueError(
                f"dataset was already quantized as {sorted(prev)} by another "
                f"Trainer; this one wants {fd!r}"
            )
        fresh = [d for d in datasets if getattr(d, "_quant_dtype", None) is None]
        if fd == "bfloat16":
            import ml_dtypes  # noqa: PLC0415  (ships with jax)

            for d in fresh:
                d._feats[0] = d._feats[0].astype(ml_dtypes.bfloat16)
                d._quant_dtype, d._quant_scale = fd, 1.0
            return
        prev_scales = {
            d._quant_scale for d in datasets if getattr(d, "_quant_dtype", None)
        }
        if len(prev_scales) > 1:
            raise ValueError(
                f"shared datasets carry inconsistent quantization scales {prev_scales}"
            )
        if prev_scales:
            scale = prev_scales.pop()
        else:
            X = self._train_data._feats[0]
            scale = float(np.abs(X).max()) / 127.0
            if scale == 0.0:  # all-zero features: nothing to represent
                scale = 1.0
        for d in fresh:
            d._feats[0] = np.clip(
                np.rint(d._feats[0] / scale), -127, 127
            ).astype(np.int8)
            d._quant_dtype, d._quant_scale = fd, scale
        self.model = dataclasses.replace(self.model, feature_scale=scale)
        self._build_steps()

    # -- data ---------------------------------------------------------------
    def load_data(self, train: GlobalShardedData | None = None, test: GlobalShardedData | None = None, *, test_only: bool = False):
        """Load the data dir's splits.  ``test_only=True`` skips the
        train split entirely (eval-only workflows: the train ingest is
        the dominant I/O cost and evaluate_metrics never touches it) —
        float32 features only, since quantized dtypes derive their scale
        from the train split."""
        if test_only:
            if train is not None:
                raise ValueError("test_only=True contradicts passing train data")
            if self.cfg.feature_dtype != "float32":
                raise ValueError(
                    "test_only loading requires feature_dtype='float32' "
                    "(quantization scales come from the train split)"
                )
        W = num_data_shards(self.mesh)
        multiclass = self.cfg.model in ("softmax", "sparse_softmax")
        sparse = self.cfg.model in ("sparse_lr", "sparse_softmax")
        if self.cfg.model == "blocked_lr":
            self._test_data = test or GlobalShardedData.from_raw_ctr_dir(
                self.cfg.data_dir, "test", W, self.cfg
            )
            if test_only:
                return self
            self._train_data = train or GlobalShardedData.from_raw_ctr_dir(
                self.cfg.data_dir, "train", W, self.cfg
            )
            return self
        if test_only:
            self._test_data = test or GlobalShardedData.from_data_dir(
                self.cfg.data_dir, "test", W, self.cfg.num_feature_dim,
                multiclass=multiclass, sparse=sparse, nnz_max=self.cfg.nnz_max,
            )
            return self
        self._train_data = train or GlobalShardedData.from_data_dir(
            self.cfg.data_dir, "train", W, self.cfg.num_feature_dim,
            multiclass=multiclass, sparse=sparse, nnz_max=self.cfg.nnz_max,
        )
        self._test_data = test or GlobalShardedData.from_data_dir(
            self.cfg.data_dir, "test", W, self.cfg.num_feature_dim,
            multiclass=multiclass, sparse=sparse, nnz_max=self.cfg.nnz_max,
        )
        if self.cfg.feature_dtype != "float32" and not sparse:
            self._quantize_features()
        elif any(
            getattr(d, "_quant_dtype", None)
            for d in (self._train_data, self._test_data)
        ):
            raise ValueError(
                "dataset was quantized by a previous Trainer; a "
                "feature_dtype='float32' run would train on raw quantized "
                "ints — reload the data or match feature_dtype"
            )
        return self

    # -- training -----------------------------------------------------------
    def init_weights(self):
        self.weights = self._shard_weights(self.model.init(self.cfg))
        return self.weights

    def fit(self, *, epochs: int | None = None, eval_fn=None, resume: bool = False):
        """Run the full training loop; returns final weights.

        ``eval_fn(epoch, accuracy)`` is called at each test interval
        (default: print the reference-format line).  With ``resume=True``
        and a configured ``checkpoint_dir``, training restarts from the
        latest saved epoch (the load path the reference never had).
        """
        cfg = self.cfg
        if self._train_data is None:
            self.load_data()

        ckpt = None
        start_epoch = 0
        if cfg.checkpoint_dir:
            from distlr_tpu.train.checkpoint import Checkpointer  # noqa: PLC0415

            ckpt = Checkpointer(cfg.checkpoint_dir)
            if resume:
                state = ckpt.restore()
                if state is not None:
                    self.weights = self._shard_weights(
                        np.asarray(state["weights"]).reshape(
                            np.asarray(self.model.init(cfg)).shape
                        )
                    )
                    start_epoch = int(state["epoch"])
                    log.info("resumed from checkpoint at epoch %d", start_epoch)
        if self.weights is None:
            self.init_weights()
        epochs = cfg.num_iteration if epochs is None else epochs
        test_batch = None
        if self._test_data is not None:
            test_batch = self._shard_batch(self._test_data.full_batch())

        # exceptions mid-training must not leak the profiler trace or the
        # checkpoint manager (pending async saves)
        with contextlib.ExitStack() as stack:
            if cfg.profile_dir:
                stack.enter_context(jax.profiler.trace(cfg.profile_dir))
            if ckpt is not None:
                stack.callback(ckpt.close)

            def shard_traced(hb):
                with trace_phase("h2d"):
                    return self._shard_batch(hb)

            for epoch in range(start_epoch, epochs):
                host_iter = self._train_data.batches(
                    cfg.batch_size, wrap=bool(cfg.wrap_final_batch)
                )
                if cfg.prefetch > 1:
                    # h2d spans land on the producer thread's timeline —
                    # the trace shows the overlap the prefetch buys
                    pairs = _prefetch_to_device(
                        shard_traced, host_iter, cfg.prefetch - 1
                    )
                else:  # prefetch=1: the strictly-serial reference shape
                    pairs = ((hb, shard_traced(hb)) for hb in host_iter)
                # closing() runs the generator's finally DETERMINISTICALLY
                # when a step raises — relying on GC leaves the producer
                # thread blocked on the queue for as long as the caller
                # retains the exception traceback (which run_ps_workers
                # does), and a retried fit() would stack a second
                # producer on top.
                with contextlib.closing(pairs):
                    it = iter(pairs)
                    while True:
                        # data_load = time this consumer spent WAITING for
                        # the next device-ready batch (0-ish when prefetch
                        # keeps up; the ingest wall when it does not)
                        with trace_phase("data_load"):
                            pair = next(it, None)
                        if pair is None:
                            break
                        host_batch, batch = pair
                        self.timer.start()
                        with trace_phase("compute"):
                            self.weights, step_metrics = self.train_step(self.weights, batch)
                            jax.block_until_ready(self.weights)
                        self.timer.stop(int(host_batch[-1].sum()))
                if test_batch is not None and cfg.test_interval > 0 and (epoch + 1) % cfg.test_interval == 0:
                    with trace_phase("eval"):
                        em = self.eval_step(self.weights, test_batch)
                        acc = float(em["accuracy"])
                    self.metrics.log(
                        epoch=epoch + 1,
                        accuracy=acc,
                        # the driver's parity metric (BASELINE.json
                        # epochs-to-logloss), logged at every eval
                        test_logloss=float(em["logloss"]),
                        loss=float(step_metrics["loss"]),
                        samples_per_sec=self.timer.samples_per_sec,
                    )
                    if eval_fn is not None:
                        eval_fn(epoch + 1, acc)
                    else:
                        log_eval_line(epoch + 1, acc)
                if (
                    ckpt is not None
                    and cfg.checkpoint_interval > 0
                    and (epoch + 1) % cfg.checkpoint_interval == 0
                ):
                    with trace_phase("checkpoint"):
                        ckpt.save(epoch + 1, self.weights, extra={"epoch": epoch + 1})
                # runtime introspection (obs.jaxrt): epoch-end compile-
                # cache deltas + throttled live device-buffer gauges
                for probe in self._jit_probes:
                    probe.tick()
                jaxrt.maybe_sample_device_bytes()

            if ckpt is not None and epochs > start_epoch and ckpt.latest_step() != epochs:
                with trace_phase("checkpoint"):
                    ckpt.save(epochs, self.weights, extra={"epoch": epochs})
        return self.weights

    def evaluate(self) -> float:
        return self.evaluate_metrics()["accuracy"]

    def evaluate_metrics(self) -> dict:
        """Full-test-set ``{"accuracy", "logloss"}`` as Python floats."""
        with trace_phase("eval"):
            test_batch = self._shard_batch(self._test_data.full_batch())
            em = self.eval_step(self.weights, test_batch)
            return {k: float(v) for k, v in em.items()}

    def save_model(self, path: str | None = None) -> str:
        """Text export, reference format & layout: ``models/part-00{i+1}``
        with i = this host's process index — the reference's per-worker
        model files (Q8, ``src/main.cc:168-169``; single-process runs
        write ``part-001`` as before).  In a ``jax.distributed`` run each
        process exports the same replicated weights to its own file, so
        cross-process agreement is checkable from the artifacts."""
        if path is None:
            path = os.path.join(
                self.cfg.data_dir, "models", part_name(jax.process_index())
            )
            os.makedirs(os.path.dirname(path), exist_ok=True)
        save_model_text(path, np.asarray(self.weights))
        return path
