"""Synchronous SPMD trainer — the role-collapsed successor of the
reference's worker loop.

Reference control flow (``src/main.cc:124-170`` + ``src/lr.cc:28-45``):
each of W worker processes re-reads its libsvm shard every epoch, pulls the
full weight vector, computes a mean gradient over its (full-shard) batch,
pushes it, and blocks on the server's deferred response — the BSP barrier.
Rank 0 evaluates every ``TEST_INTERVAL`` epochs and each worker text-dumps
its weights at the end.

Here the W workers become the ``data`` axis of one mesh and the whole
epoch is minibatch steps of a single jitted SPMD program
(:func:`distlr_tpu.parallel.make_sync_train_step`).  Shard->device-row
mapping preserves the reference semantics: worker i's shard rows live on
mesh position i, and with ``batch_size=-1`` each step consumes every
worker's full shard, exactly one reference "iteration".
"""

from __future__ import annotations

import contextlib
import os

import jax
import numpy as np

from distlr_tpu.config import Config
from distlr_tpu.data import DataIter, parse_libsvm_file
from distlr_tpu.data.sharding import part_name
from distlr_tpu.models import get_model
from distlr_tpu.parallel import (
    make_eval_step,
    make_mesh,
    make_sync_train_step,
)
from distlr_tpu.parallel.data_parallel import shard_batch
from distlr_tpu.parallel.mesh import MODEL_AXIS, num_data_shards
from distlr_tpu.train.export import save_model_text
from distlr_tpu.train.metrics import MetricsLogger, StepTimer
from distlr_tpu.utils.logging import get_logger, log_eval_line

log = get_logger(__name__)


class GlobalShardedData:
    """W per-worker shards packed as one global array with lockstep batching.

    Shards are padded to a common length ``n_pad`` and stacked to
    ``(W, n_pad, ...)``; a global minibatch of per-worker size ``b`` is the
    flattened ``(W*b, ...)`` slice ``[:, k*b:(k+1)*b]`` with a validity
    mask.  Laying worker i's rows contiguously at block i makes a plain
    leading-axis ``data`` sharding put each reference-worker's shard on its
    own mesh slot.
    """

    def __init__(self, shards: list[tuple[np.ndarray, np.ndarray]]):
        if not shards:
            raise ValueError("need at least one shard")
        self.num_shards = len(shards)
        self.shard_sizes = [len(y) for _, y in shards]
        n_pad = max(self.shard_sizes)
        if n_pad == 0:
            raise ValueError("all shards are empty — no training data")
        feat_shape = shards[0][0].shape[1:]
        W = self.num_shards
        self.X = np.zeros((W, n_pad) + feat_shape, dtype=shards[0][0].dtype)
        self.y = np.zeros((W, n_pad), dtype=shards[0][1].dtype)
        self.mask = np.zeros((W, n_pad), dtype=np.float32)
        for i, (Xi, yi) in enumerate(shards):
            self.X[i, : len(yi)] = Xi
            self.y[i, : len(yi)] = yi
            self.mask[i, : len(yi)] = 1.0
        self.n_pad = n_pad

    @classmethod
    def from_data_dir(cls, data_dir: str, split: str, num_shards: int, num_features: int, *, multiclass=False):
        """Load ``data_dir/{split}/part-001..W`` (reference layout,
        ``src/main.cc:158-159``). If fewer parts exist than mesh shards,
        parts are round-robined; if more, they are concatenated down."""
        paths = []
        i = 0
        while True:
            p = os.path.join(data_dir, split, part_name(i))
            if not os.path.exists(p):
                break
            paths.append(p)
            i += 1
        if not paths:
            raise FileNotFoundError(f"no shards under {data_dir}/{split}")
        parts = [parse_libsvm_file(p, num_features, multiclass=multiclass) for p in paths]
        if len(parts) != num_shards:
            X = np.concatenate([p[0] for p in parts])
            y = np.concatenate([p[1] for p in parts])
            shards = [
                (X[i::num_shards], y[i::num_shards]) for i in range(num_shards)
            ]
        else:
            shards = parts
        return cls(shards)

    @property
    def num_samples(self) -> int:
        return int(sum(self.shard_sizes))

    def batches(self, per_worker_batch: int):
        """One epoch of lockstep global batches ``(X, y, mask)`` shaped
        ``(W*b, ...)``. ``-1`` = full shard per worker (one step/epoch)."""
        b = self.n_pad if per_worker_batch == -1 else min(per_worker_batch, self.n_pad)
        for k in range(-(-self.n_pad // b)):
            sl = slice(k * b, min((k + 1) * b, self.n_pad))
            bw = sl.stop - sl.start
            X = self.X[:, sl].reshape((-1,) + self.X.shape[2:])
            y = self.y[:, sl].reshape(-1)
            mask = self.mask[:, sl].reshape(-1)
            if bw < b:  # pad the short final batch to static shape
                pad = b - bw
                W = self.num_shards
                X = np.concatenate(
                    [X.reshape(W, bw, -1), np.zeros((W, pad, X.shape[-1]), X.dtype)], axis=1
                ).reshape(W * b, -1)
                y = np.concatenate([y.reshape(W, bw), np.zeros((W, pad), y.dtype)], axis=1).reshape(-1)
                mask = np.concatenate(
                    [mask.reshape(W, bw), np.zeros((W, pad), mask.dtype)], axis=1
                ).reshape(-1)
            yield X, y, mask

    def full_batch(self):
        X = self.X.reshape((-1,) + self.X.shape[2:])
        return X, self.y.reshape(-1), self.mask.reshape(-1)


class Trainer:
    """End-to-end sync training: data -> mesh -> SPMD steps -> eval -> export."""

    def __init__(self, cfg: Config, *, mesh=None, metrics: MetricsLogger | None = None):
        if cfg.model == "sparse_lr":
            # The padded-COO data path is served by SparseBinaryLR directly;
            # Trainer's shard loader is dense-only for now.
            raise NotImplementedError(
                "Trainer supports dense models (binary_lr, softmax); drive "
                "sparse_lr via distlr_tpu.models.SparseBinaryLR directly"
            )
        self.cfg = cfg
        if mesh is None:
            # honor a local.sh-style DMLC_NUM_WORKER > 1 as the data-axis
            # size; otherwise default to all devices
            shape = cfg.mesh_shape
            if shape is None and cfg.num_workers > 1:
                shape = {"data": cfg.num_workers}
            mesh = make_mesh(shape)
        self.mesh = mesh
        self.model = get_model(cfg)
        self.metrics = metrics or MetricsLogger()
        # A mesh with a 'model' axis selects the 2D data x feature-sharded
        # path (weights partitioned like ps-lite's server key ranges).
        self.feature_sharded = MODEL_AXIS in mesh.axis_names
        if self.feature_sharded:
            from distlr_tpu.parallel.feature_parallel import (  # noqa: PLC0415
                make_feature_sharded_eval_step,
                make_feature_sharded_train_step,
                shard_batch_2d,
                shard_weights,
            )

            self.train_step = make_feature_sharded_train_step(self.model, cfg, self.mesh)
            self.eval_step = make_feature_sharded_eval_step(self.model, self.mesh)
            self._shard_batch = lambda b: shard_batch_2d(b, self.mesh)
            self._shard_weights = lambda w: shard_weights(w, self.mesh)
        else:
            self.train_step = make_sync_train_step(self.model, cfg, self.mesh)
            self.eval_step = make_eval_step(self.model, self.mesh)
            self._shard_batch = lambda b: shard_batch(b, self.mesh)
            self._shard_weights = lambda w: jax.device_put(
                w, jax.sharding.NamedSharding(self.mesh, jax.sharding.PartitionSpec())
            )
        self.timer = StepTimer()
        self.weights = None
        self._train_data: GlobalShardedData | None = None
        self._test_data: GlobalShardedData | None = None

    # -- data ---------------------------------------------------------------
    def load_data(self, train: GlobalShardedData | None = None, test: GlobalShardedData | None = None):
        W = num_data_shards(self.mesh)
        multiclass = self.cfg.model == "softmax"
        self._train_data = train or GlobalShardedData.from_data_dir(
            self.cfg.data_dir, "train", W, self.cfg.num_feature_dim, multiclass=multiclass
        )
        self._test_data = test or GlobalShardedData.from_data_dir(
            self.cfg.data_dir, "test", W, self.cfg.num_feature_dim, multiclass=multiclass
        )
        return self

    # -- training -----------------------------------------------------------
    def init_weights(self):
        self.weights = self._shard_weights(self.model.init(self.cfg))
        return self.weights

    def fit(self, *, epochs: int | None = None, eval_fn=None, resume: bool = False):
        """Run the full training loop; returns final weights.

        ``eval_fn(epoch, accuracy)`` is called at each test interval
        (default: print the reference-format line).  With ``resume=True``
        and a configured ``checkpoint_dir``, training restarts from the
        latest saved epoch (the load path the reference never had).
        """
        cfg = self.cfg
        if self._train_data is None:
            self.load_data()

        ckpt = None
        start_epoch = 0
        if cfg.checkpoint_dir:
            from distlr_tpu.train.checkpoint import Checkpointer  # noqa: PLC0415

            ckpt = Checkpointer(cfg.checkpoint_dir)
            if resume:
                state = ckpt.restore()
                if state is not None:
                    self.weights = self._shard_weights(
                        np.asarray(state["weights"]).reshape(
                            np.asarray(self.model.init(cfg)).shape
                        )
                    )
                    start_epoch = int(state["epoch"])
                    log.info("resumed from checkpoint at epoch %d", start_epoch)
        if self.weights is None:
            self.init_weights()
        epochs = cfg.num_iteration if epochs is None else epochs
        test_batch = None
        if self._test_data is not None:
            test_batch = self._shard_batch(self._test_data.full_batch())

        # exceptions mid-training must not leak the profiler trace or the
        # checkpoint manager (pending async saves)
        with contextlib.ExitStack() as stack:
            if cfg.profile_dir:
                stack.enter_context(jax.profiler.trace(cfg.profile_dir))
            if ckpt is not None:
                stack.callback(ckpt.close)

            for epoch in range(start_epoch, epochs):
                for host_batch in self._train_data.batches(cfg.batch_size):
                    batch = self._shard_batch(host_batch)
                    self.timer.start()
                    self.weights, step_metrics = self.train_step(self.weights, batch)
                    jax.block_until_ready(self.weights)
                    self.timer.stop(int(host_batch[2].sum()))
                if test_batch is not None and cfg.test_interval > 0 and (epoch + 1) % cfg.test_interval == 0:
                    acc = float(self.eval_step(self.weights, test_batch))
                    self.metrics.log(
                        epoch=epoch + 1,
                        accuracy=acc,
                        loss=float(step_metrics["loss"]),
                        samples_per_sec=self.timer.samples_per_sec,
                    )
                    if eval_fn is not None:
                        eval_fn(epoch + 1, acc)
                    else:
                        log_eval_line(epoch + 1, acc)
                if (
                    ckpt is not None
                    and cfg.checkpoint_interval > 0
                    and (epoch + 1) % cfg.checkpoint_interval == 0
                ):
                    ckpt.save(epoch + 1, self.weights, extra={"epoch": epoch + 1})

            if ckpt is not None and epochs > start_epoch and ckpt.latest_step() != epochs:
                ckpt.save(epochs, self.weights, extra={"epoch": epochs})
        return self.weights

    def evaluate(self) -> float:
        test_batch = self._shard_batch(self._test_data.full_batch())
        return float(self.eval_step(self.weights, test_batch))

    def save_model(self, path: str | None = None) -> str:
        """Text export, reference format & layout (``models/part-001``)."""
        if path is None:
            path = os.path.join(self.cfg.data_dir, "models", part_name(0))
            os.makedirs(os.path.dirname(path), exist_ok=True)
        save_model_text(path, np.asarray(self.weights))
        return path
