"""Reference-compatible text model export / import.

The reference's only persistence is ``LR::SaveModel`` (``src/lr.cc:73-82``):
line 1 = ``num_feature_dim``, line 2 = the weights space-separated (with a
trailing space), written once after training per worker to
``DATA_DIR/models/part-00{rank+1}`` (``src/main.cc:168-169``).  There is
**no load path in the reference at all** — this module adds one, plus the
same format for export so models can be cross-validated against reference
output files.  Durable checkpoint/resume lives in
:mod:`distlr_tpu.train.checkpoint` (orbax).
"""

from __future__ import annotations

import os

import numpy as np


def save_model_text(path: str, weights) -> None:
    w = np.asarray(weights, dtype=np.float32).reshape(-1)
    with open(path, "w") as f:
        f.write(f"{w.shape[0]}\n")
        # %g matches the reference's default ostream float formatting.
        f.write(" ".join(f"{v:g}" for v in w) + " \n")


def load_model_text(path: str, shape=None) -> np.ndarray:
    with open(path) as f:
        d = int(f.readline().strip())
        vals = np.array(f.readline().split(), dtype=np.float32)
    if vals.shape[0] != d:
        raise ValueError(f"{path}: header says {d} weights, found {vals.shape[0]}")
    return vals.reshape(shape) if shape is not None else vals


def load_weights(path: str, shape=None) -> np.ndarray:
    """Load model weights from EITHER persistence format this repo
    writes: a reference-format text model file, or an orbax checkpoint
    directory (latest step) — the serving tier's one-stop read path
    (``launch serve --model-file``).
    """
    if os.path.isdir(path):
        from distlr_tpu.train.checkpoint import Checkpointer  # noqa: PLC0415

        with Checkpointer(path) as ckpt:
            state = ckpt.restore()
        if state is None:
            raise FileNotFoundError(f"{path}: no checkpoint steps found")
        w = np.asarray(state["weights"], dtype=np.float32)
        return w.reshape(shape) if shape is not None else w.reshape(-1)
    return load_model_text(path, shape=shape)
