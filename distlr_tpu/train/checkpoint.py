"""Durable checkpoint + resume (orbax-backed).

Closes the reference's biggest persistence gap (SURVEY.md §5.4): the
reference can only text-dump final weights (``LR::SaveModel``,
``src/lr.cc:73-82``) and has **no load path at all** — no function in the
codebase reads a model file, and a crashed run restarts from scratch.

Here training state (weights + epoch + config fingerprint) checkpoints
every ``cfg.checkpoint_interval`` epochs and ``Trainer.fit`` resumes from
the latest step.  The reference-compatible text export
(:mod:`distlr_tpu.train.export`) remains available for cross-validation
against reference model files.
"""

from __future__ import annotations

import os

import jax
import numpy as np
import orbax.checkpoint as ocp


class Checkpointer:
    """Thin orbax CheckpointManager wrapper for training state."""

    def __init__(self, directory: str, *, max_to_keep: int = 3):
        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep, create=True),
        )

    def save(self, step: int, weights, *, extra: dict | None = None) -> None:
        state = {"weights": np.asarray(weights)}
        if extra:
            state.update({k: np.asarray(v) for k, v in extra.items()})
        self._mgr.save(step, args=ocp.args.StandardSave(state))
        self._mgr.wait_until_finished()

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def restore(self, step: int | None = None) -> dict | None:
        """Restore state at ``step`` (default: latest); None if empty."""
        step = self.latest_step() if step is None else step
        if step is None:
            return None
        # Explicit StandardRestore: newer orbax releases refuse a bare
        # restore() of a StandardSave item ('Item "default" ... could not
        # be restored') unless told how to interpret it.
        return self._mgr.restore(step, args=ocp.args.StandardRestore())

    def all_steps(self) -> list[int]:
        return list(self._mgr.all_steps())

    def close(self) -> None:
        self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
