"""Parameter-server training mode (sync-BSP or async/Hogwild over the
native KV server group).

This is the reference-faithful alternative to the SPMD fast path: the
control flow is a line-for-line behavioral mirror of the reference worker
(``RunWorker``, ``src/main.cc:124-170`` + ``LR::Train``, ``src/lr.cc:28-45``)
— pull weights, compute the minibatch gradient, push, repeat — except the
gradient math is a jitted JAX step on the accelerator instead of the
O(B*D^2) scalar loop.  Use this mode to reproduce the reference's
*asynchronous* convergence behavior (stale gradients are real here: each
worker pulls whatever the servers have now) and for PS-style deployments
where workers and servers are separate hosts over DCN.

Worker lifecycle parity:
  * every worker computes the identical init (Q2 — reference ``srand(0)``),
    rank 0 pushes it as the first push (server init branch), others wait
    at the group barrier (``src/main.cc:141-150``)
  * sync mode: the blocking push IS the BSP barrier (deferred replies)
  * rank 0 evaluates every ``test_interval`` epochs and prints the
    reference-format line
  * each worker text-exports its final *pulled* weights to
    ``models/part-00{rank+1}`` (Q8: per-worker files, ``src/main.cc:168-169``)
"""

from __future__ import annotations

import contextlib
import functools
import json
import os
import threading
import time
import types

import jax
import numpy as np

from distlr_tpu.compress import GradientAccumulator
from distlr_tpu.config import Config
from distlr_tpu.data import DataIter
from distlr_tpu.data.iterator import SparseDataIter
from distlr_tpu.data.sharding import part_name
from distlr_tpu.models import get_model
from distlr_tpu.obs import dtrace, jaxrt
from distlr_tpu.obs.registry import COUNT_BUCKETS, get_registry
from distlr_tpu.obs.tracing import trace_phase
from distlr_tpu.ps import KVWorker, RetryPolicy, ServerGroup
from distlr_tpu.train.export import save_model_text
from distlr_tpu.train.metrics import MetricsLogger, StepTimer
from distlr_tpu.utils.logging import get_logger, log_eval_line

log = get_logger(__name__)

#: Gradient staleness, measured as WEIGHT AGE: seconds between pulling
#: the weights a gradient was computed from and that gradient landing on
#: the servers.  In sync BSP this is just the round latency; in async
#: (Hogwild) it is the real staleness bound the convergence analyses
#: (arXiv:1508.05711) reason about — peers' pushes during this window are
#: what the gradient is stale against.
_STALENESS = get_registry().gauge(
    "distlr_train_staleness_seconds",
    "age of the weights behind the most recent gradient push",
    labelnames=("rank",),
)
#: The SAME staleness, but in the unit the Hogwild convergence analyses
#: actually bound (arXiv:1508.05711 states tau in *updates*, not
#: seconds): the server group's global push clock
#: (:meth:`KVWorker.global_pushes`) sampled after the pull and again
#: just before the push — the delta is how many peer updates landed on
#: the weights this gradient was computed from.  Sampling is throttled
#: (one probed pair per _PUSHES_SAMPLE_INTERVAL_S per worker) so the
#: extra stats round trips never show up in the step rate.
_STALENESS_PUSHES = get_registry().histogram(
    "distlr_train_staleness_pushes",
    "Hogwild gradient staleness in pushes-behind: peer updates applied "
    "between this worker's pull and its push",
    labelnames=("rank",),
    buckets=COUNT_BUCKETS,
)
#: Min seconds between probed pull/push clock pairs per worker.  A stats
#: probe costs one round trip per server rank; at 20 samples/s the
#: overhead is noise even for the ~1 ms localhost dense steps, while a
#: multi-epoch run still banks thousands of histogram observations.
_PUSHES_SAMPLE_INTERVAL_S = 0.05
#: Cooldown before rebuilding a failed push-clock probe connection.  A
#: probe failure used to disable the staleness histogram for the
#: worker's lifetime — defensible when the only failures were dying
#: groups, wrong once a chaos plan makes transient probe faults routine
#: (the reset can land on the probe's frame instead of a training op's).
#: One reconnect attempt per cooldown keeps observability self-healing
#: without reconnect spam against a genuinely gone group.
_PROBE_RETRY_COOLDOWN_S = 5.0
_RESTARTS = get_registry().counter(
    "distlr_ps_worker_restarts_total",
    "PS workers rebuilt in place after a failure (max_restarts path)",
)
#: Current AdaBatch span of each PS worker (batches per push) — moves
#: on the growth schedule, so a dashboard shows the push-traffic divisor
#: next to the push-byte compression ratio it multiplies.
_ACCUM_K = get_registry().gauge(
    "distlr_train_accum_batches",
    "current AdaBatch accumulation span of the PS worker loop "
    "(batches per push)",
    labelnames=("rank",),
)


class _StepTrace:
    """StepTimer proxy that puts each ``start()``/``stop()`` bracket —
    one training batch, in every loop variant — under its own
    distributed-trace root (:mod:`distlr_tpu.obs.dtrace`).  Sampled
    steps get a ``train.step`` span whose KV pulls/pushes carry the
    trace trailer, so the server-side apply is causally linked to the
    pull that staled it on the ``trace-agg`` timeline.  With tracing
    unconfigured, ``new_trace()`` is None and each step pays one
    function call."""

    def __init__(self, timer: StepTimer, rank: int):
        self._timer = timer
        self._rank = rank
        self._scope: contextlib.ExitStack | None = None

    def start(self) -> None:
        if self._scope is not None:  # an exception ended the last step
            self._scope.close()
        self._timer.start()
        ctx = dtrace.new_trace()
        if ctx is not None:
            scope = contextlib.ExitStack()
            scope.enter_context(dtrace.use(ctx))
            scope.enter_context(
                dtrace.span("train.step", tags={"rank": self._rank}))
            self._scope = scope

    def stop(self, n: int):
        if self._scope is not None:
            self._scope.close()
            self._scope = None
        return self._timer.stop(n)

    def __getattr__(self, name):
        return getattr(self._timer, name)


# Below this many per-batch elements (param_dim * batch), the gradient
# step is cheaper on the host CPU backend than the accelerator's dispatch
# latency (~0.1 ms of math vs 1-80 ms of round trip for reference-scale
# D=123 steps; measured in benchmarks/exp_sparse.py context — the config-2
# PS bench went dispatch-bound without this).  2^25 elements ≈ 5-10 ms of
# CPU math — the crossover against typical remote-dispatch cost.
_PS_AUTO_CPU_THRESHOLD = 1 << 25
# Below this, even the jitted host-CPU step is dominated by jax dispatch
# overhead (measured 213 us dispatch vs 44 us of numpy math at D=123,
# B=256 — and dispatch is GIL-bound, so threaded workers serialize on
# it): "auto" drops to plain numpy/BLAS.  f32 numpy is also CLOSER to
# the f32 reference trajectory than the bf16-matmul jax step.
_PS_AUTO_NUMPY_THRESHOLD = 1 << 20


def ps_retry_policy(cfg: Config) -> RetryPolicy | None:
    """The worker-side retry policy a config asks for, or None.

    Retry sits BEFORE the restart/resume ladder: a transient transport
    fault (reset, delay spike, short partition) costs an in-place
    reconnect + re-issue inside :class:`KVWorker`; only when the policy
    exhausts does the failure surface to ``run_ps_workers``'s
    ``max_restarts`` / job-level checkpoint-resume machinery.  Async
    only — a sync (BSP) round's failed push is the named straggler
    signal and must stay fail-fast (the barrier cannot be retried
    without mixing gradients across rounds).
    """
    if cfg.sync_mode:
        return None
    return RetryPolicy.from_config(cfg)


def server_optimizer(cfg: Config) -> str:
    """The update rule the server group actually runs: ``signsgd``
    compression replaces the rule wholesale (1-bit votes through any
    other optimizer would be sign-mean, not majority vote), otherwise
    the configured ``ps_optimizer`` — shared by local spawns and
    ``launch ps-server`` so the two deployment shapes cannot diverge."""
    return "signsgd" if cfg.ps_compress == "signsgd" else cfg.ps_optimizer


def ps_compute_device(cfg: Config, rows: int | None = None):
    """Where PS workers run their dense step: the string ``"numpy"``
    (host numpy/BLAS, no jax dispatch), a jax device, or None (default
    backend).

    The reference's workers are host-CPU programs (``src/lr.cc:35-41``);
    our PS mode jits the same math, but for tiny models the accelerator
    round trip per minibatch dwarfs the math, so "auto" keeps small
    steps on the host — below ``_PS_AUTO_NUMPY_THRESHOLD`` as plain
    numpy (jit dispatch itself dominates there), below
    ``_PS_AUTO_CPU_THRESHOLD`` on the jitted CPU backend — and sends big
    ones to the accelerator.

    ``rows`` is the actual per-step row count (minibatch size, full train
    shard, or full test set — the train and eval steps each pass their
    own).  When it is unknown (``None`` with ``batch_size=-1``), the step
    is assumed big enough to amortize accelerator dispatch.
    """
    choice = cfg.ps_compute_backend
    if choice == "default":
        return None
    if choice == "numpy":
        return "numpy"
    if choice == "cpu":
        return jax.devices("cpu")[0]
    if jax.default_backend() == "cpu" and rows is None:
        return None
    if rows is None:
        rows = cfg.batch_size
    if rows <= 0:
        return None
    work = ps_param_dim(cfg) * rows
    if work < _PS_AUTO_NUMPY_THRESHOLD:
        return "numpy"
    if jax.default_backend() == "cpu" or work >= _PS_AUTO_CPU_THRESHOLD:
        return None
    try:
        return jax.devices("cpu")[0]
    except RuntimeError:
        # JAX_PLATFORMS=tpu (no cpu backend initialized): degrade to the
        # default backend rather than abort — "auto" is best-effort.
        return None


def _np_dense_grad(w, X, y, mask, l2_c, l2_scale_by_batch, num_classes=None):
    """f32 numpy mirror of BinaryLR.grad / SoftmaxRegression.grad
    (models/linear.py) for the tiny-step regime where jax dispatch
    dominates; quirk gates (Q4 L2/B) identical."""
    y = np.asarray(y)
    mask = np.asarray(mask, np.float32)
    n = np.float32(max(mask.sum(), 1.0))
    if num_classes is None:
        z = X @ w
        sig = (0.5 * (1.0 + np.tanh(0.5 * z))).astype(np.float32)
        resid = (sig - y.astype(np.float32)) * mask
        g = resid @ X / n
    else:
        z = X @ w  # (B, K)
        z -= z.max(axis=1, keepdims=True)
        p = np.exp(z)
        p /= p.sum(axis=1, keepdims=True)
        p[np.arange(len(y)), y] -= 1.0
        g = X.T @ (p * mask[:, None]) / n
    if l2_c:
        term = np.float32(l2_c) * w
        g = g + (term / n if l2_scale_by_batch else term)
    return np.asarray(g, dtype=np.float32)


def _binary_eval_from_logits(z, y, mask) -> tuple[float, float]:
    """(accuracy, logloss) of binary logits — THE masked-mean definition,
    shared by the numpy dense eval and the keyed (sparse/blocked) evals
    so the metrics cannot silently diverge."""
    z = np.asarray(z, np.float64)
    m = np.asarray(mask, np.float64)
    n = max(m.sum(), 1.0)
    acc = float((((z > 0).astype(np.int64) == y) * m).sum() / n)
    ll = float(((np.logaddexp(0.0, z) - y * z) * m).sum() / n)
    return acc, ll


def _np_dense_eval(w, X, y, mask, num_classes=None):
    """f32 numpy ``(accuracy, logloss)`` for the dense models — one
    forward pass, no jax dispatch."""
    z = np.asarray(X @ w, np.float64)
    if num_classes is None:
        return _binary_eval_from_logits(z, y, mask)
    m = np.asarray(mask, np.float64)
    n = max(m.sum(), 1.0)
    pred = z.argmax(axis=1)
    zs = z - z.max(axis=1, keepdims=True)
    ll = np.log(np.exp(zs).sum(axis=1)) - zs[np.arange(len(y)), y]
    acc = float(((pred == y) * m).sum() / n)
    return acc, float((ll * m).sum() / n)


@functools.lru_cache(maxsize=None)
def _compiled_fns(model, l2_c: float, l2_scale_by_batch: bool):
    """Jitted gradient step shared across PSWorker instances and runs.

    ``jax.jit`` keys its compile cache on function identity, so
    per-instance lambdas would recompile on every run (models are frozen
    dataclasses — hashable cache keys).  The gradient math reads exactly
    ``l2_c`` and ``l2_scale_by_batch`` from the config (models/linear.py
    ``_l2_grad``), which is why those two are the only cfg-derived keys;
    a model that grows a new cfg dependency fails loudly here with
    AttributeError."""
    gcfg = types.SimpleNamespace(l2_c=l2_c, l2_scale_by_batch=l2_scale_by_batch)
    return jax.jit(lambda w, X, y, mask: model.grad(w, (X, y, mask), gcfg))


@functools.lru_cache(maxsize=None)
def _compiled_acc(model):
    """Eval takes no cfg, so its cache is keyed on the model alone
    (an L2 sweep must not recompile the full-test-set eval program).
    Returns ``(accuracy, test_logloss)`` — logloss is the driver's
    parity metric (BASELINE.json epochs-to-logloss)."""
    return jax.jit(lambda w, X, y, mask: (
        model.accuracy(w, (X, y, mask)),
        model.logloss(w, (X, y, mask)),
    ))


def _sparse_batch_grad(w_u, pos, vals, y, mask, l2_c, l2_scale_by_batch):
    """Gradient of the sparse one-hot LR loss wrt the batch's UNIQUE
    touched weights (numpy, host-side).

    Mirrors ``SparseBinaryLR.grad`` (models/linear.py) restricted to the
    touched key set: ``w_u`` are the pulled weights for the batch's unique
    columns, ``pos`` maps each (row, slot) to its index in ``w_u``.  The
    scatter is ``np.bincount`` (vectorized C) — PS-sparse batches are
    exactly the tiny host-side steps where jit dispatch would dominate,
    and a per-batch-varying unique-key count would recompile every step.

    L2 is applied *lazily* (only the touched coordinates, like every
    sparse parameter server): with ``l2_c > 0`` the effective decay per
    weight scales with how often it is touched, unlike the dense path's
    every-step decay — callers comparing against the dense trainer should
    set ``l2_c = 0`` or account for touch frequency.
    """
    z = (w_u[pos] * vals).sum(axis=-1)
    sig = 0.5 * (1.0 + np.tanh(0.5 * z))  # overflow-stable sigmoid
    n = np.float32(max(mask.sum(), 1))
    resid = ((sig - y) * mask).astype(np.float32)
    contrib = (resid[:, None] * vals).ravel() / n
    g = np.bincount(pos.ravel(), weights=contrib, minlength=len(w_u)).astype(np.float32)
    if l2_c:
        # Decay only genuinely-active keys: COO padding (col 0, val 0)
        # puts key 0 in EVERY batch's unique set, which would give bucket
        # 0 dense-style every-step decay while real features decay per
        # touch.
        active = np.bincount(pos.ravel(), weights=(vals != 0).ravel().astype(np.float32),
                             minlength=len(w_u)) > 0
        term = np.float32(l2_c) * w_u * active
        g += term / n if l2_scale_by_batch else term
    return g


def _sparse_softmax_batch_grad(W_u, pos, vals, y, mask, l2_c,
                               l2_scale_by_batch):
    """Gradient of the sparse softmax loss wrt the batch's UNIQUE touched
    (D, K) table rows (numpy, host-side).

    Mirrors ``SparseSoftmaxRegression.grad`` (models/linear.py)
    restricted to the touched row set: ``W_u`` is the ``(n_u, K)``
    pulled slice, ``pos`` maps each (sample, slot) to its row.  Lazy L2
    at ROW granularity with the same active-key discount as the binary
    sparse path (COO padding aliases row 0 in every batch)."""
    z = (W_u[pos] * vals[..., None]).sum(axis=1)      # (B, K)
    z -= z.max(axis=1, keepdims=True)
    p = np.exp(z, dtype=np.float32)
    p /= p.sum(axis=1, keepdims=True)
    p[np.arange(len(y)), y] -= 1.0
    n = np.float32(max(mask.sum(), 1))
    resid = p * np.asarray(mask, np.float32)[:, None]  # (B, K)
    contrib = (vals[..., None] * resid[:, None, :]).reshape(
        -1, W_u.shape[1]) / n                          # (B*F, K)
    g = np.zeros_like(W_u, dtype=np.float32)
    np.add.at(g, pos.ravel(), contrib)
    if l2_c:
        active = np.bincount(
            pos.ravel(), weights=(vals != 0).ravel().astype(np.float32),
            minlength=len(W_u)) > 0
        term = np.float32(l2_c) * W_u * active[:, None]
        g += term / n if l2_scale_by_batch else term
    return g


def _expand_block_keys(blocks: np.ndarray, block_size: int) -> np.ndarray:
    """Unique block-row ids -> their flat KV keys (row b owns the
    contiguous range ``[b*R, (b+1)*R)`` of the ``ps_param_dim`` key
    space — the row-major layout of the (num_blocks, R) table)."""
    r = np.arange(block_size, dtype=np.uint64)
    return (blocks.astype(np.uint64)[:, None] * np.uint64(block_size) + r).reshape(-1)


def _blocked_batch_grad(t_u, pos, lane_vals, y, mask, l2_c, l2_scale_by_batch):
    """Gradient of the blocked LR loss wrt the batch's UNIQUE touched
    table rows (numpy, host-side).

    Mirrors ``BlockedSparseLR.grad`` (models/linear.py) restricted to the
    touched row set: ``t_u`` is the ``(n_u, R)`` pulled slice, ``pos``
    maps each (sample, group) to its row in ``t_u``.  Like the sparse
    path, L2 is applied lazily — and at ROW granularity: a gathered row
    decays as a unit (all R lanes), because the row is the parameter unit
    of this model (one conjunction's weights).
    """
    z = (t_u[pos] * lane_vals).sum(axis=(-1, -2))
    sig = 0.5 * (1.0 + np.tanh(0.5 * z))  # overflow-stable sigmoid
    n = np.float32(max(mask.sum(), 1))
    resid = ((sig - y) * mask).astype(np.float32)
    contrib = (resid[:, None, None] * lane_vals).reshape(-1, t_u.shape[1]) / n
    g = np.zeros_like(t_u, dtype=np.float32)
    np.add.at(g, pos.reshape(-1), contrib)
    if l2_c:
        # Padded groups (all-zero lanes) alias row pos of block id 0's
        # slot; only rows gathered with a real (nonzero) lane decay.
        touched = (lane_vals != 0).any(axis=-1).reshape(-1)
        active = np.zeros(len(t_u), bool)
        np.logical_or.at(active, pos.reshape(-1), touched)
        term = np.float32(l2_c) * t_u * active[:, None]
        g += term / n if l2_scale_by_batch else term
    return g


def _ps_resume_state(cfg: Config, rank: int):
    """``(start_epoch, weights | None, attempt | None)`` from
    ``cfg.checkpoint_dir`` (``attempt`` is None when no sidecar exists).

    Every rank reads the epoch from a JSON sidecar (``ps_latest.json``,
    written atomically by rank 0 at each checkpoint) so sync-mode workers
    agree on how many epochs remain without concurrently opening the
    orbax manager; rank 0 additionally restores the weights, which reach
    the servers through its init push.  Multi-host deployments need
    ``checkpoint_dir`` on a shared filesystem — the same rule orbax has.
    """
    sidecar = os.path.join(cfg.checkpoint_dir, "ps_latest.json")
    if not os.path.exists(sidecar):
        return 0, None, None
    with open(sidecar) as f:
        data = json.load(f)
    epoch = int(data["epoch"])
    attempt = int(data.get("attempt", 0))
    if rank != 0 or epoch == 0:
        # epoch 0 = a resume-attempt sidecar written before the first
        # checkpoint existed (bump_resume_attempt on a crashed-early run):
        # there is no orbax step to restore, only a barrier generation to
        # advance.
        return epoch, None, attempt
    from distlr_tpu.train.checkpoint import Checkpointer  # noqa: PLC0415

    with Checkpointer(cfg.checkpoint_dir) as ckpt:
        # Restore exactly the sidecar's step, NOT latest: a crash between
        # orbax save N and the sidecar rename leaves latest=N with the
        # sidecar still naming N-interval — resuming N-interval epochs on
        # top of step-N weights would double-train the gap.
        state = ckpt.restore(epoch) if epoch in ckpt.all_steps() else None
    if state is None:  # sidecar without its orbax step: corrupt dir
        raise FileNotFoundError(
            f"{sidecar} names epoch {epoch} but {cfg.checkpoint_dir} holds "
            f"no orbax checkpoint for that step"
        )
    return epoch, np.asarray(state["weights"]).reshape(-1), attempt


def bump_resume_attempt(cfg: Config) -> None:
    """Advance the sidecar's resume-attempt counter (launcher-side).

    Called ONCE per resumed job, on the rank-0 host, BEFORE any worker
    starts (multi-host: start the rank-0 host first).  Each resume then
    rendezvouses on barrier generations the server group has never
    released: a surviving group already released the previous run's
    startup generation, and a barrier vote on a released generation
    returns immediately — which would let peers pull stale crash-time
    weights before rank 0's forced init overwrites them.
    """
    if not cfg.checkpoint_dir:
        return
    sidecar = os.path.join(cfg.checkpoint_dir, "ps_latest.json")
    if os.path.exists(sidecar):
        with open(sidecar) as f:
            data = json.load(f)
    else:
        # Workers can crash BEFORE the first checkpoint writes a sidecar;
        # a resume must still advance the barrier generation, or peers
        # ride barrier(0) — which a surviving server group already
        # released — straight past rank 0's re-init (the race this
        # counter exists to close).  Create the sidecar at epoch 0.
        os.makedirs(cfg.checkpoint_dir, exist_ok=True)
        data = {"epoch": 0, "attempt": 0}
    data["attempt"] = int(data.get("attempt", 0)) + 1
    tmp = sidecar + ".tmp"
    with open(tmp, "w") as f:
        json.dump(data, f)
    os.replace(tmp, sidecar)


class PSWorker:
    """One worker's training loop against a KV server group.

    Dense models (``binary_lr``, ``softmax``) pull/push the full weight
    vector per batch like the reference worker.  ``sparse_lr`` uses
    *keyed* Push/Pull (the ps-lite capability the reference app never
    exercises — its key set is always dense 0..D-1, ``src/lr.cc:117-121``):
    each batch pulls and pushes only its unique touched columns, so a
    D=1M-bucket CTR model ships KBs per step instead of 12 MB.
    """

    def __init__(self, cfg: Config, rank: int, hosts: str, *, train_iter=None, test_iter=None):
        self.cfg = cfg
        self.rank = rank
        self.model = get_model(cfg)
        if cfg.feature_dtype != "float32":
            # PS workers stream numpy batches from host RAM per step —
            # there is no resident device feature matrix whose HBM
            # footprint quantization would shrink. Reject rather than
            # silently ignore the documented +11%/2x expectation.
            raise ValueError(
                "feature_dtype quantization applies to the sync SPMD "
                "trainer's device-resident features; PS mode streams "
                "host batches (set feature_dtype='float32')"
            )
        if cfg.model in ("sparse_lr", "blocked_lr") and cfg.sync_last_gradient:
            # Q1 is a dense-reference parity quirk; with keyed pushes
            # "the last worker's gradient" touches an arbitrary key
            # subset per server — no reference behavior exists to mirror.
            raise ValueError(
                "sync_last_gradient (Q1 compat) is a dense-model parity "
                f"quirk; {cfg.model} PS training requires the correct-mean "
                "update (compat_mode='correct')"
            )
        self.kv = KVWorker(
            hosts, self._param_dim(), client_id=rank,
            timeout_ms=cfg.ps_timeout_ms, sync_group=cfg.sync_mode,
            retry=ps_retry_policy(cfg),
            # negotiated gradient wire codec (dense f32 when the group
            # doesn't advertise it — KVWorker logs the fallback)
            compress=cfg.ps_compress,
        )
        self._hosts = hosts
        # Push-clock probe for the pushes-behind staleness histogram
        # (async only): a DEDICATED connection, because the main one may
        # have a fused op in flight on the comm thread, and KV ops must
        # never overlap on one stream.  Lazy: first sample connects.
        self._push_probe: KVWorker | None = None
        self._push_probe_dead = cfg.sync_mode  # sync BSP: staleness is 0
        self._probe_retry_at = 0.0  # monotonic; rebuild cooldown gate
        self._last_pushes_sample = float("-inf")
        self._staleness_pushes = _STALENESS_PUSHES.labels(rank=str(rank))
        self._train_iter = train_iter
        self._test_iter = test_iter
        # Keyed models never use the jitted dense-batch fns (their
        # per-batch unique-key count varies, so they run numpy host math
        # instead — _sparse_batch_grad / _blocked_batch_grad); building
        # them would plant a lambda whose (X, y, mask) signature crashes
        # on padded-COO / blocked batches.
        if cfg.model in ("sparse_lr", "blocked_lr"):
            self._grad_fn = self._acc_fn = None
        else:
            self._grad_fn = _compiled_fns(self.model, cfg.l2_c, bool(cfg.l2_scale_by_batch))
            self._acc_fn = _compiled_acc(self.model)
        # runtime introspection (obs.jaxrt): compile-cache probes for the
        # jitted dense step/eval fns (sparse/blocked paths run numpy host
        # math — nothing to probe); ticked at each epoch end
        self._jit_probes = [
            jaxrt.JitCacheProbe(fn, site)
            for fn, site in ((self._grad_fn, "train.ps.grad"),
                             (self._acc_fn, "train.ps.eval"))
            if fn is not None
        ]
        self.metrics = MetricsLogger()
        # Registry-backed step accounting; "ps" counters are cumulative
        # across the process's worker threads (Hogwild runs several),
        # while each worker's throughput gauge is its own instance.
        # _StepTrace additionally puts each start()/stop() bracket under
        # its own distributed-trace root (sampled per cfg.trace_sample),
        # so the step's pull/push KV ops — and their server-side apply
        # spans — land on the merged trace-agg timeline.
        self.timer = _StepTrace(StepTimer(loop="ps", instance=str(rank)),
                                rank)
        self.final_weights: np.ndarray | None = None
        self._barrier_base = 0
        self._sidecar_attempt = 0
        # pipelined dense path state: last fused-reply weights, and a
        # single comm thread (KV ops must never overlap on one connection)
        self._w_cache: np.ndarray | None = None
        self._w_time = 0.0  # when _w_cache was pulled (staleness gauge)
        self._w_pushes: float | None = None  # push clock at _w_cache arrival
        self._comm = None
        if cfg.model in ("sparse_lr", "blocked_lr") and cfg.l2_c > 0:
            # Keyed PS applies L2 lazily (only a batch's touched keys/rows
            # decay, scaled by touch frequency) while the sync trainer
            # decays every weight every step — same l2_c, different
            # effective regularization (PARITY.md).
            log.warning(
                "%s PS mode applies L2 lazily (touched keys only); "
                "effective regularization differs from the sync trainer "
                "at the same l2_c — see PARITY.md", cfg.model
            )

    def _param_dim(self) -> int:
        return ps_param_dim(self.cfg)

    # -- pushes-behind staleness probing (async/Hogwild only) -------------
    def _sample_push_clock(self) -> float | None:
        """The group's global push clock now, or None when throttled or
        the probe is unavailable.  A non-None return arms one
        :meth:`_record_pushes_behind` call at push time."""
        now = time.perf_counter()
        if now - self._last_pushes_sample < _PUSHES_SAMPLE_INTERVAL_S:
            return None
        if self._push_probe is None:
            if self._push_probe_dead or time.monotonic() < self._probe_retry_at:
                return None
            try:
                self._push_probe = KVWorker(
                    self._hosts, self._param_dim(),
                    client_id=0xFD00 + self.rank, timeout_ms=2000,
                    sync_group=False,
                )
            except Exception:
                # No probe, no histogram for now — observability must
                # never take the training loop down or spin on
                # reconnects; try again after the cooldown (a transient
                # fault must not silence the staleness series forever)
                self._probe_retry_at = (time.monotonic()
                                        + _PROBE_RETRY_COOLDOWN_S)
                return None
        try:
            clock = self._push_probe.global_pushes()
        except Exception:
            self._drop_push_probe()
            return None
        self._last_pushes_sample = now
        return clock

    def _record_pushes_behind(self, pulled_clock: float | None) -> None:
        """Observe the staleness of the gradient about to be pushed:
        push-time clock minus ``pulled_clock`` (the pull-time sample) =
        peer updates the weights aged by while this worker computed."""
        if pulled_clock is None or self._push_probe is None:
            return
        try:
            clock = self._push_probe.global_pushes()
        except Exception:
            self._drop_push_probe()
            return
        self._staleness_pushes.observe(max(0.0, clock - pulled_clock))

    def _drop_push_probe(self) -> None:
        # A failed probe may mean the group is dying — or, under a
        # chaos plan, a routine transient fault that happened to land
        # on the probe's connection.  Close it and rebuild after the
        # cooldown rather than going dark for the worker's lifetime; a
        # genuinely gone group just fails the rebuild once per cooldown
        # while the worker's own ops surface the real outage.
        probe, self._push_probe = self._push_probe, None
        self._probe_retry_at = time.monotonic() + _PROBE_RETRY_COOLDOWN_S
        if probe is not None:
            try:
                probe.close()
            except Exception:
                pass

    def _blocked_iter(self, path: str, batch_size: int, *, wrap=False):
        from distlr_tpu.data.hashing import resolve_ctr_fields  # noqa: PLC0415
        from distlr_tpu.data.iterator import BlockedDataIter  # noqa: PLC0415

        cfg = self.cfg
        return BlockedDataIter.from_file(
            path, resolve_ctr_fields(cfg.data_dir, cfg.ctr_fields),
            cfg.num_feature_dim // cfg.block_size, cfg.block_size,
            batch_size, seed=cfg.hash_seed, num_groups=cfg.block_groups,
            wrap_compat=wrap,
        )

    def _load_train_iter(self) -> DataIter:
        # Reference re-reads its shard every epoch (src/main.cc:158-159);
        # we parse once and reset (same samples, no quirk).
        path = os.path.join(self.cfg.data_dir, "train", part_name(self.rank))
        wrap = bool(self.cfg.wrap_final_batch)  # Q5
        if self.cfg.model in ("sparse_lr", "sparse_softmax"):
            return SparseDataIter.from_file(
                path, self.cfg.num_feature_dim, self.cfg.batch_size,
                nnz_max=self.cfg.nnz_max,
                multiclass=self.cfg.model == "sparse_softmax",
                wrap_compat=wrap)
        if self.cfg.model == "blocked_lr":
            return self._blocked_iter(path, self.cfg.batch_size, wrap=wrap)
        return DataIter.from_file(path, self.cfg.num_feature_dim, self.cfg.batch_size,
                                  multiclass=self.cfg.model == "softmax",
                                  wrap_compat=wrap)

    def _load_test_iter(self) -> DataIter:
        path = os.path.join(self.cfg.data_dir, "test", part_name(0))
        if self.cfg.model in ("sparse_lr", "sparse_softmax"):
            return SparseDataIter.from_file(
                path, self.cfg.num_feature_dim, -1,
                nnz_max=self.cfg.nnz_max,
                multiclass=self.cfg.model == "sparse_softmax")
        if self.cfg.model == "blocked_lr":
            return self._blocked_iter(path, -1)
        return DataIter.from_file(path, self.cfg.num_feature_dim, -1,
                                  multiclass=self.cfg.model == "softmax")

    def run(self, *, eval_fn=None, save=True, resume=False,
            rejoin=False) -> np.ndarray:
        cfg = self.cfg
        train = self._train_iter if self._train_iter is not None else self._load_train_iter()
        test = self._test_iter if self._test_iter is not None else (
            self._load_test_iter() if self.rank == 0 else None
        )

        start_epoch = 0
        restored = None
        attempt = None
        if resume and cfg.checkpoint_dir:
            start_epoch, restored, attempt = _ps_resume_state(cfg, self.rank)

        # Identical deterministic init on every worker (Q2); only rank 0
        # pushes — via the IDEMPOTENT init op, so a restarted rank 0
        # re-sending it cannot corrupt live weights (a plain re-push
        # would land in the async path as a bogus gradient).  On resume,
        # the restored weights take the init push's place.
        #
        # Barrier generations: fresh runs use (0, 1) for (startup, exit).
        # Resumed runs derive a FRESH pair from the sidecar's attempt
        # counter (bumped once per resume by the launcher,
        # bump_resume_attempt): a surviving server group already released
        # the previous run's generations, and votes on a released
        # generation return immediately — reusing one would let peers
        # pull stale crash-time weights before rank 0's forced init
        # lands.  All ranks read the same sidecar, so they agree; late
        # re-votes of a released generation (worker rejoin) still return
        # immediately, so a restarted worker neither hangs nor pairs
        # with peers' exit votes.
        w0 = (restored if restored is not None
              else np.asarray(self.model.init(cfg)).reshape(-1))
        if self.rank == 0:
            # force on resume: against a SURVIVING (already-initialized)
            # server group the restored checkpoint — or, when the crash
            # predated the first checkpoint, the fresh epoch-0 init —
            # must overwrite the stale crash-time weights; a plain
            # idempotent init would no-op and silently resume from the
            # wrong state.  A restarted worker (rejoin) must NOT force:
            # it would roll peers back mid-run.
            force = resume and not rejoin
            with trace_phase("push"):
                self.kv.wait(self.kv.push_init(w0, force=force))
        self._barrier_base = 0 if attempt is None else 2 * (attempt + 1)
        self._sidecar_attempt = 0 if attempt is None else attempt
        with trace_phase("barrier_wait"):
            self.kv.barrier(self._barrier_base)

        ckpt = None
        if self.rank == 0 and cfg.checkpoint_dir:
            from distlr_tpu.train.checkpoint import Checkpointer  # noqa: PLC0415

            ckpt = Checkpointer(cfg.checkpoint_dir)

        with contextlib.ExitStack() as stack:
            # §5.1 tracing hook, PS flavor: rank 0's worker loop (jit
            # steps + KV round trips) lands in a jax.profiler trace.
            if self.rank == 0 and cfg.profile_dir:
                stack.enter_context(jax.profiler.trace(cfg.profile_dir))
            if ckpt is not None:
                stack.callback(ckpt.close)
            return self._run_epochs(
                start_epoch, w0, train, test, ckpt,
                eval_fn=eval_fn, save=save,
            )

    def _checkpoint(self, ckpt, epoch: int) -> None:
        """Rank 0: snapshot the servers' weights + the epoch sidecar
        (atomic rename) every ``checkpoint_interval`` epochs."""
        ckpt.save(epoch, self.kv.pull(), extra={"epoch": epoch})
        sidecar = os.path.join(self.cfg.checkpoint_dir, "ps_latest.json")
        tmp = sidecar + ".tmp"
        with open(tmp, "w") as f:
            # attempt is preserved, not reset: a rejoining worker re-reads
            # the sidecar mid-run and must derive the same barrier base.
            json.dump({"epoch": epoch, "attempt": self._sidecar_attempt}, f)
        os.replace(tmp, sidecar)

    def _flush_keyed_accum(self, accum: GradientAccumulator,
                           vpk: int) -> None:
        """Push one keyed accumulation span (mean gradient over the
        span's touched rows).  A span whose gradients cancelled to exact
        zeros still pushes an EMPTY keyed frame in sync mode — the BSP
        "present" vote peers' deferred replies are waiting on."""
        res = accum.flush_keyed(vpk)
        if res is None:
            return  # empty span (no batches) — symmetric across workers
        rows, vals = res
        if rows.size == 0 and not self.cfg.sync_mode:
            return
        with trace_phase("push"):
            self.kv.wait(self.kv.push(vals, keys=rows, vals_per_key=vpk))

    def _flush_dense_accum(self, accum: GradientAccumulator) -> None:
        """Push one dense accumulation span (mean gradient)."""
        g = accum.flush_dense()
        if g is None:
            return
        if not self.cfg.sync_mode:
            _STALENESS.labels(rank=self.rank).set(
                time.perf_counter() - self._w_time)
            self._record_pushes_behind(self._w_pushes)
        with trace_phase("push"):
            self.kv.wait(self.kv.push(g))

    def _run_epochs(self, start_epoch, w0, train, test, ckpt, *, eval_fn, save):
        cfg = self.cfg

        # AdaBatch local accumulation (--accum-start/--accum-max): push
        # the span's MEAN every k batches, k growing on the schedule —
        # divides push traffic by k on top of the wire codec's ratio.
        # Spans flush at epoch end too (partial), so epochs stay
        # self-contained for eval and BSP workers stay in lockstep.
        accum = None
        if cfg.ps_accum_max > 1:
            accum = GradientAccumulator(
                self._param_dim(), start=cfg.ps_accum_start,
                growth=cfg.ps_accum_growth,
                growth_every=cfg.ps_accum_growth_every,
                max_k=cfg.ps_accum_max,
                gauge=_ACCUM_K.labels(rank=str(self.rank)))

        sparse = cfg.model in ("sparse_lr", "sparse_softmax")
        blocked = cfg.model == "blocked_lr"
        # keyed rows wider than one value: blocked tables gather R-lane
        # rows, sparse softmax gathers K-class rows — both ride the
        # vals_per_key wire encoding where the group's ranges align
        row_width = (cfg.block_size if blocked
                     else cfg.num_classes if cfg.model == "sparse_softmax"
                     else 1)
        if not (sparse or blocked):
            # Committed inputs pin each jitted step to its device; jax.jit
            # keys its executable cache on input placement, so both
            # backends can coexist in one process.  Train and eval steps
            # size their choice independently (a tiny minibatch must not
            # drag a huge full-test-set eval onto the host CPU).
            train_rows = cfg.batch_size if cfg.batch_size > 0 else train.num_samples
            step_dev = ps_compute_device(cfg, train_rows)
            eval_dev = ps_compute_device(cfg, test.num_samples) if test is not None else None
            K = cfg.num_classes if cfg.model == "softmax" else None
            if step_dev == "numpy":
                def compute_g(wf, X, y, mask):
                    W = wf.reshape(cfg.num_feature_dim, K) if K else wf
                    return _np_dense_grad(
                        W, X, y, mask, cfg.l2_c, bool(cfg.l2_scale_by_batch), K
                    ).reshape(-1)
            else:
                def compute_g(wf, X, y, mask):
                    return np.asarray(self._grad_fn(*self._place(
                        step_dev, self._shape_params(wf), X, y, mask))).reshape(-1)
        w = w0
        for epoch in range(start_epoch, cfg.num_iteration):
            train.reset()
            if sparse or blocked:
                # Keyed Push/Pull: only the batch's unique touched columns
                # (sparse) / R-wide block-row key ranges (blocked) travel —
                # ps-lite's sliced-key capability, SURVEY.md §2.2 E1.d/g,
                # which the reference app itself never exercises.
                # Blocked rows prefer the vals_per_key wire encoding
                # (one u64 row id per R-lane row, ps-lite lens-style —
                # ~2.7x fewer keyed bytes at R=32 than R expanded keys);
                # groups whose range boundaries don't align to R fall
                # back to the expanded encoding, bit-identical
                # semantics either way (the server expands at parse
                # time onto the same code paths).
                vpk = (row_width
                       if row_width > 1 and self.kv.supports_vals_per_key(
                           row_width)
                       else 1)
                if row_width > 1 and epoch == start_epoch:
                    # visible (and test-assertable) record of which wire
                    # encoding the keyed rounds actually used
                    log.info(
                        "rank %d keyed wire encoding: %s", self.rank,
                        f"vals_per_key={vpk}" if vpk > 1
                        else "expanded per-lane keys")

                def prep(b):
                    ids = b[0]
                    ub, pos = np.unique(ids, return_inverse=True)
                    if row_width > 1 and vpk == 1:
                        keys = _expand_block_keys(ub, row_width)
                    else:
                        keys = ub.astype(np.uint64)
                    return keys, (pos.reshape(ids.shape), *b[1:])

                def kgrad(w_u, rest):
                    if blocked:
                        pos, lane_vals, y, mask = rest
                        return _blocked_batch_grad(
                            w_u.reshape(-1, cfg.block_size), pos, lane_vals,
                            y, mask, cfg.l2_c, bool(cfg.l2_scale_by_batch),
                        ).reshape(-1)
                    pos, vals, y, mask = rest
                    if cfg.model == "sparse_softmax":
                        return _sparse_softmax_batch_grad(
                            w_u.reshape(-1, cfg.num_classes), pos, vals,
                            y, mask, cfg.l2_c, bool(cfg.l2_scale_by_batch),
                        ).reshape(-1)
                    return _sparse_batch_grad(
                        w_u, pos, vals, y, mask,
                        cfg.l2_c, bool(cfg.l2_scale_by_batch),
                    )

                # Keyed rounds stay serialized in BOTH modes.  Sync: a
                # pull issued before the round's push would read pre-round
                # weights and change the BSP trajectory.  Async: a
                # comm-thread pipeline (pull k+1 overlapping grad k) was
                # measured ~10% SLOWER at CTR scale (4 workers, D=200k,
                # B=512: 560-570k serialized vs ~490-520k pipelined) — the
                # per-op executor handoff under GIL contention costs more
                # than the ~50us localhost round trip it hides; unlike the
                # dense path, there is no fused op here to REMOVE a round
                # trip (pull and push key sets differ per batch).
                for b in train:
                    self.timer.start()
                    with trace_phase("data_load"):
                        keys, rest = prep(b)
                    t_pull = time.perf_counter()
                    with trace_phase("pull"):
                        w_u = self.kv.pull(keys=keys, vals_per_key=vpk)
                    p0 = None if cfg.sync_mode else self._sample_push_clock()
                    with trace_phase("compute"):
                        g = kgrad(w_u, rest)
                    if not cfg.sync_mode:
                        _STALENESS.labels(rank=self.rank).set(
                            time.perf_counter() - t_pull)
                        self._record_pushes_behind(p0)
                    if accum is not None:
                        # accumulate at the batch's own key granularity;
                        # the flush unions the span's touched rows into
                        # ONE keyed frame (deduped keys = fewer keyed
                        # bytes on top of the k-fold frequency cut)
                        if vpk > 1:
                            accum.add_rows(keys, g, vpk)
                        else:
                            accum.add_at(keys, g)
                        if accum.ready:
                            self._flush_keyed_accum(accum, vpk)
                    else:
                        with trace_phase("push"):
                            self.kv.wait(self.kv.push(g, keys=keys,
                                                      vals_per_key=vpk))
                    self.timer.stop(int(b[-1].sum()))
                if accum is not None:
                    self._flush_keyed_accum(accum, vpk)
            elif accum is not None:
                # Dense + AdaBatch accumulation: pull once per span,
                # compute k batches against the span's weights, push the
                # mean (one PS round per span — in sync mode the BSP
                # round IS per span, workers in lockstep on the shared
                # schedule).  The fused/pipelined dense protocols are
                # bypassed: the span already removes k-1 of every k
                # round trips, which is the same wall-clock win
                # pipelining buys, without overlapping state.
                for X, y, mask in train:
                    self.timer.start()
                    if accum.batches == 0:
                        with trace_phase("pull"):
                            self._w_cache = self.kv.pull()
                        self._w_time = time.perf_counter()
                        self._w_pushes = (None if cfg.sync_mode
                                          else self._sample_push_clock())
                    with trace_phase("compute"):
                        g = compute_g(self._w_cache, X, y, mask)
                    accum.add(g)
                    if accum.ready:
                        self._flush_dense_accum(accum)
                    self.timer.stop(int(mask.sum()))
                self._flush_dense_accum(accum)
            elif not cfg.ps_pipeline:
                # Reference-faithful serialized protocol: two blocking
                # round trips per batch (src/lr.cc:116-132).
                for X, y, mask in train:
                    self.timer.start()
                    t_pull = time.perf_counter()
                    with trace_phase("pull"):
                        w = self.kv.pull()
                    p0 = None if cfg.sync_mode else self._sample_push_clock()
                    with trace_phase("compute"):
                        g = compute_g(w, X, y, mask)
                    if not cfg.sync_mode:
                        _STALENESS.labels(rank=self.rank).set(
                            time.perf_counter() - t_pull)
                        self._record_pushes_behind(p0)
                    with trace_phase("push"):
                        self.kv.wait(self.kv.push(g))
                    self.timer.stop(int(mask.sum()))
            elif cfg.sync_mode:
                # Fused BSP: ONE deferred round trip per batch; the reply
                # is the post-round weights = what the next pull would
                # return (rounds totally ordered -> bit-identical
                # trajectory, pinned by the oracle parity tests).
                if self._w_cache is None:
                    with trace_phase("pull"):
                        self._w_cache = self.kv.pull()
                for X, y, mask in train:
                    self.timer.start()
                    with trace_phase("compute"):
                        g = compute_g(self._w_cache, X, y, mask)
                    with trace_phase("push"):
                        self._w_cache = self.kv.push_pull(g)
                    self.timer.stop(int(mask.sum()))
            else:
                # Pipelined async (Hogwild): fused round trips double-
                # buffered against compute — batch k+1's gradient is
                # computed while batch k's push_pull is in flight.  The
                # weights used are stale by exactly the one in-flight
                # push; KV ops stay serialized on the comm thread (one
                # connection, never two ops concurrently).
                if self._w_cache is None:
                    with trace_phase("pull"):
                        self._w_cache = self.kv.pull()
                    self._w_time = time.perf_counter()
                    self._w_pushes = self._sample_push_clock()
                fut = None
                for X, y, mask in train:
                    self.timer.start()
                    with trace_phase("compute"):
                        g = compute_g(self._w_cache, X, y, mask)
                    # g rides weights pulled at _w_time; its round trip
                    # starts now — the age at landing is ~this (+ one
                    # in-flight RTT, bounded by the next result() wait)
                    _STALENESS.labels(rank=self.rank).set(
                        time.perf_counter() - self._w_time)
                    # pushes-behind twin: clock now minus the clock when
                    # _w_cache arrived — peer updates plus our own (<=1)
                    # in-flight fused push, i.e. exactly how many updates
                    # behind the weights under this gradient are
                    self._record_pushes_behind(self._w_pushes)
                    if fut is not None:
                        with trace_phase("push"):
                            self._w_cache = fut.result()
                        self._w_time = time.perf_counter()
                        self._w_pushes = self._sample_push_clock()
                    # the step's dtrace context rides along explicitly:
                    # the comm thread is a different thread, and the
                    # fused op belongs to the step that SUBMITTED it
                    fut = self._comm_pool().submit(
                        self._traced_push_pull, g, dtrace.current())
                    self.timer.stop(int(mask.sum()))
                if fut is not None:
                    with trace_phase("push"):
                        self._w_cache = fut.result()
                    self._w_time = time.perf_counter()
                    self._w_pushes = self._sample_push_clock()
            # runtime introspection (obs.jaxrt): fold this epoch's jit
            # cache growth into distlr_jax_compiles_total and refresh
            # the live device-buffer gauges (walk throttled process-wide)
            for probe in self._jit_probes:
                probe.tick()
            jaxrt.maybe_sample_device_bytes()
            if (
                self.rank == 0
                and test is not None
                and cfg.test_interval > 0
                and (epoch + 1) % cfg.test_interval == 0
            ):
                with trace_phase("eval"):
                    if cfg.model == "sparse_softmax":
                        acc, test_ll = self._sparse_softmax_eval(test)
                    elif sparse:
                        acc, test_ll = self._sparse_eval(test)
                    elif blocked:
                        acc, test_ll = self._blocked_eval(test)
                    else:
                        w = self.kv.pull()
                        test.reset()
                        Xt, yt, mt = test.next_batch()
                        if eval_dev == "numpy":
                            acc, test_ll = _np_dense_eval(
                                w.reshape(cfg.num_feature_dim, K) if K else w,
                                Xt, yt, mt.astype(np.float32), K)
                        else:
                            a, ll = self._acc_fn(*self._place(eval_dev, self._shape_params(w), Xt, yt, mt))
                            acc, test_ll = float(a), float(ll)
                self.metrics.log(epoch=epoch + 1, accuracy=acc,
                                 test_logloss=test_ll,
                                 samples_per_sec=self.timer.samples_per_sec)
                if eval_fn is not None:
                    eval_fn(epoch + 1, acc)
                else:
                    log_eval_line(epoch + 1, acc)
            if (
                ckpt is not None
                and cfg.checkpoint_interval > 0
                and (epoch + 1) % cfg.checkpoint_interval == 0
            ):
                with trace_phase("checkpoint"):
                    self._checkpoint(ckpt, epoch + 1)

        if (
            ckpt is not None
            and cfg.num_iteration > start_epoch
            and ckpt.latest_step() != cfg.num_iteration
        ):
            with trace_phase("checkpoint"):
                self._checkpoint(ckpt, cfg.num_iteration)

        with trace_phase("pull"):
            self.final_weights = self.kv.pull()
        if save:
            path = os.path.join(cfg.data_dir, "models", part_name(self.rank))
            os.makedirs(os.path.dirname(path), exist_ok=True)
            save_model_text(path, self.final_weights)
        # ps::Finalize(do_barrier=true) parity (reference src/main.cc:179):
        # a global exit barrier (startup generation + 1) so no server
        # retires while a peer still trains, then rank 0 retires the
        # group — this is what lets foreground `launch ps-server` hosts
        # exit when training is done (local mode: ServerGroup.stop()
        # finds the procs exited).
        with trace_phase("barrier_wait"):
            self.kv.barrier(self._barrier_base + 1)
        if self.rank == 0:
            self.kv.shutdown_servers()
        return self.final_weights

    @staticmethod
    def _eval_from_logits(z, y, mask) -> tuple[float, float]:
        """(accuracy, logloss) from ONE forward pass's logits — numpy,
        host-side (the keyed eval paths are exactly the small-step regime
        where a second full-test-set forward would double the eval cost)."""
        return _binary_eval_from_logits(z, y, mask)

    def _blocked_eval(self, test) -> tuple[float, float]:
        """Full-test-set ``(accuracy, logloss)``: keyed pull of the test
        set's unique block rows, scattered into a full (num_blocks, R)
        table."""
        test.reset()
        blocks, lane_vals, y, mask = test.next_batch()
        R = self.cfg.block_size
        ub = np.unique(blocks)
        t = np.zeros((self.cfg.num_feature_dim // R, R), np.float32)
        if self.kv.supports_vals_per_key(R):
            pulled = self.kv.pull(keys=ub.astype(np.uint64), vals_per_key=R)
        else:
            pulled = self.kv.pull(keys=_expand_block_keys(ub, R))
        t[ub] = pulled.reshape(len(ub), R)
        z = (t[blocks] * lane_vals).sum(axis=(-1, -2))
        return self._eval_from_logits(z, y, mask)

    def _sparse_eval(self, test) -> tuple[float, float]:
        """Full-test-set ``(accuracy, logloss)``: keyed pull of the test
        set's unique columns scattered into a full-width vector, then one
        forward pass for both metrics."""
        test.reset()
        cols, vals, y, mask = test.next_batch()
        keys = np.unique(cols).astype(np.uint64)
        w = np.zeros(self.cfg.num_feature_dim, np.float32)
        w[keys] = self.kv.pull(keys=keys)
        z = (w[cols] * vals).sum(axis=-1)
        return self._eval_from_logits(z, y, mask)

    def _sparse_softmax_eval(self, test) -> tuple[float, float]:
        """Full-test-set ``(accuracy, cross-entropy)``: keyed pull of the
        test set's unique (D, K) rows (vals_per_key=K where the group's
        ranges align), scattered into a full table."""
        test.reset()
        cols, vals, y, mask = test.next_batch()
        K = self.cfg.num_classes
        ub = np.unique(cols).astype(np.uint64)
        W = np.zeros((self.cfg.num_feature_dim, K), np.float32)
        if self.kv.supports_vals_per_key(K):
            pulled = self.kv.pull(keys=ub, vals_per_key=K)
        else:
            pulled = self.kv.pull(keys=_expand_block_keys(ub, K))
        W[ub] = pulled.reshape(len(ub), K)
        z = np.asarray((W[cols] * vals[..., None]).sum(axis=1), np.float64)
        m = np.asarray(mask, np.float64)
        n = max(m.sum(), 1.0)
        acc = float(((z.argmax(axis=1) == y) * m).sum() / n)
        zs = z - z.max(axis=1, keepdims=True)
        ll = np.log(np.exp(zs).sum(axis=1)) - zs[np.arange(len(y)), y]
        return acc, float((ll * m).sum() / n)

    @staticmethod
    def _place(device, *arrays):
        if device is None:
            return arrays
        return tuple(jax.device_put(a, device) for a in arrays)

    def _shape_params(self, flat: np.ndarray):
        if self.cfg.model in ("softmax", "sparse_softmax"):
            return flat.reshape(self.cfg.num_feature_dim, self.cfg.num_classes)
        return flat

    def _comm_pool(self):
        if self._comm is None:
            from concurrent.futures import ThreadPoolExecutor  # noqa: PLC0415

            self._comm = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix=f"ps-comm-{self.rank}"
            )
        return self._comm

    def _traced_push_pull(self, g, ctx):
        """Comm-thread half of the pipelined fused op: re-install the
        submitting step's distributed-trace context (thread-local, so it
        doesn't cross the executor by itself) before issuing."""
        with dtrace.use(ctx):
            return self.kv.push_pull(g)

    def close(self, *, wait: bool = True):
        self._drop_push_probe()
        comm, self._comm = self._comm, None
        if comm is None:
            self.kv.close()
            return
        if wait:
            comm.shutdown(wait=True)
            self.kv.close()
            return
        # wait=False: the failure/restart path must not block behind an
        # in-flight push_pull to a dead server (its ps_timeout_ms is
        # minutes — far past the 5 s server-respawn reconnect window);
        # the rebuilt PSWorker creates a fresh executor.  But the native
        # handle must NOT be freed under a live ctypes call (the GIL is
        # released inside it — kv_close then is a use-after-free), so
        # the handle close rides a reaper thread that first drains the
        # executor.
        comm.shutdown(wait=False, cancel_futures=True)

        def _reap():
            comm.shutdown(wait=True)
            self.kv.close()

        threading.Thread(target=_reap, daemon=True,
                         name=f"ps-close-{self.rank}").start()


def run_ps_workers(cfg: Config, hosts: str, ranks, *, eval_fn=None, save=False,
                   on_error=None, resume=False, max_restarts=0):
    """Run the given worker ranks (threads) against an EXISTING server
    group at ``hosts`` — the multi-host entry point: each host runs its
    subset of ranks against remote servers (started via
    ``python -m distlr_tpu.launch ps-server`` or :class:`ServerGroup`).

    Worker threads share one JAX backend/jit cache; each blocks
    independently in the native client (the GIL is released during
    ctypes calls), so async staleness is real.  ``on_error`` runs once
    if any worker raises (local mode uses it to tear the servers down so
    peers blocked on the sync barrier fail fast instead of hanging).
    Returns ``{rank: final_weights}``.

    ``max_restarts`` (async mode only): a failed worker is rebuilt on a
    fresh connection and rejoins up to N times — Hogwild tolerates
    arbitrary rejoin, and the server's disconnect rollback already
    undid any half-round state.  Sync (BSP) runs keep fail-fast
    semantics: rounds are counted per worker, so the recovery path for
    sync is job-level ``checkpoint_dir`` + ``resume``, not in-place
    restart.  The reference has neither path (SURVEY.md §5.3: its only
    outcome is an eternal deadlock).
    """
    ranks = list(ranks)
    if resume and 0 in ranks:
        # Once per resumed job, before any worker reads the sidecar:
        # advance the barrier-generation epoch so the rendezvous below
        # cannot ride generations a surviving server group already
        # released (multi-host: the rank-0 host must launch first).
        bump_resume_attempt(cfg)
    results: dict[int, np.ndarray | None] = {r: None for r in ranks}
    errors: list[Exception] = []
    workers = [PSWorker(cfg, r, hosts) for r in ranks]

    def run_one(i, r):
        attempts = 0
        while True:
            try:
                results[r] = workers[i].run(eval_fn=eval_fn if r == 0 else None,
                                            save=save, resume=resume,
                                            rejoin=attempts > 0)
                return
            except Exception as e:  # surface worker failures to the caller
                workers[i].close(wait=False)
                attempts += 1
                if cfg.sync_mode or attempts > max_restarts:
                    errors.append(e)
                    if on_error is not None:
                        # A dead worker would deadlock every peer blocked
                        # on the sync barrier (the reference's named
                        # straggler failure, SURVEY.md §5.3).
                        on_error()
                    return
                _RESTARTS.inc()
                log.warning("worker %d failed (%s); restart %d/%d",
                            r, e, attempts, max_restarts)
                # Rebuild with a short reconnect window: when the failure
                # was a SERVER death, a supervisor needs a beat to respawn
                # the rank before this worker's fresh connect can succeed
                # (ServerSupervisor poll+respawn is ~100 ms; 5 s covers a
                # slow spawn without masking genuinely-gone servers).
                deadline = time.monotonic() + 5.0
                while True:
                    try:
                        workers[i] = PSWorker(cfg, r, hosts)
                        break
                    except Exception as e2:
                        if time.monotonic() >= deadline:
                            errors.append(e2)  # servers gone: give up
                            if on_error is not None:
                                on_error()
                            return
                        time.sleep(0.2)

    threads = [
        threading.Thread(target=run_one, args=(i, r), daemon=True)
        for i, r in enumerate(ranks)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for wk in workers:
        wk.close()
    if errors:
        raise errors[0]
    return results


def ps_param_dim(cfg: Config) -> int:
    """Flat KV key-space size for a config (must match between servers
    and workers — softmax flattens its (D, K) weight matrix)."""
    return cfg.num_feature_dim * (
        cfg.num_classes if cfg.model in ("softmax", "sparse_softmax") else 1)


def run_ps_local(cfg: Config, *, eval_fn=None, save=False, resume=False,
                 max_restarts=0, supervise_servers=False):
    """Single-host PS run: native server subprocesses + threaded workers.

    The local-mode successor of ``examples/local.sh`` for the PS path
    (the scheduler role is gone — rendezvous is just TCP connect).
    Multi-host deployments start servers with ``launch ps-server`` and
    per-host workers with :func:`run_ps_workers` instead.

    ``supervise_servers`` (async mode only) attaches a
    :class:`distlr_tpu.ps.ServerSupervisor`: dead server ranks are
    respawned and re-seeded from a rolling snapshot, completing the
    two-sided §5.3 recovery story (pair it with ``max_restarts > 0`` so
    workers whose stream broke rejoin).
    """
    via_chaos = None
    if cfg.chaos_plan:
        from distlr_tpu.chaos import load_plan  # noqa: PLC0415

        # parsed HERE, before any server spawns: a malformed plan must
        # fail the launch, not leak a fault-free run that looks chaotic
        via_chaos = load_plan(cfg.chaos_plan, seed=cfg.chaos_seed)
    group = ServerGroup(
        cfg.num_servers,
        cfg.num_workers,
        ps_param_dim(cfg),
        learning_rate=cfg.learning_rate,
        sync=cfg.sync_mode,
        last_gradient=bool(cfg.sync_last_gradient),
        via_chaos=via_chaos,
        optimizer=server_optimizer(cfg),
        ftrl_alpha=cfg.ftrl_alpha,
        ftrl_beta=cfg.ftrl_beta,
        ftrl_l1=cfg.ftrl_l1,
        ftrl_l2=cfg.ftrl_l2,
        # distributed tracing (ISSUE 8): locally spawned server ranks
        # journal their handler spans into the run dir's spans/ next to
        # the Python ranks' journals, so `launch trace-agg` sees both
        trace_journal_dir=(
            os.path.join(cfg.obs_run_dir.split(os.pathsep)[0], "spans")
            if cfg.obs_run_dir and cfg.trace_sample > 0 else None),
        # continuous profiling (ISSUE 9): locally spawned ranks journal
        # per-handler thread-CPU windows into the run dir's profiles/
        # next to the Python samplers', so `launch prof-agg` sees both
        prof_journal_dir=(
            os.path.join(cfg.obs_run_dir.split(os.pathsep)[0], "profiles")
            if cfg.obs_run_dir and cfg.prof_hz > 0 else None),
        prof_window_s=cfg.prof_window_s,
        # durable store (ISSUE 20): ranks persist + self-recover their
        # slices under <ps_store_dir>/rank-<r>/; with supervise_servers
        # the supervisor prefers the disk state over its RAM snapshot
        store_dir=cfg.ps_store_dir,
        store_interval_s=cfg.ps_store_interval_s,
        store_wal=cfg.ps_store_wal,
        store_wal_fsync_s=cfg.ps_store_wal_fsync_s,
    )
    with contextlib.ExitStack() as stack:
        stack.enter_context(group)
        if supervise_servers:
            from distlr_tpu.ps import ServerSupervisor  # noqa: PLC0415

            stack.enter_context(ServerSupervisor(group))
        results = run_ps_workers(
            cfg, group.hosts, range(cfg.num_workers),
            eval_fn=eval_fn, save=save, on_error=group.stop, resume=resume,
            max_restarts=max_restarts,
        )
    return [results[r] for r in range(cfg.num_workers)]
