from distlr_tpu.train.trainer import Trainer, GlobalShardedData  # noqa: F401
from distlr_tpu.train.export import save_model_text, load_model_text  # noqa: F401
from distlr_tpu.train.metrics import MetricsLogger, StepTimer  # noqa: F401
