"""Structured per-step metrics — the observability layer the reference lacks.

The reference emits exactly one metric ever (rank-0 test accuracy on
stdout, ``src/lr.cc:56-62``).  Here every step can record loss, accuracy,
samples/sec and step latency as structured records, optionally mirrored as
JSON lines, while keeping the reference-format accuracy line for parity
diffs (:func:`distlr_tpu.utils.logging.log_eval_line`).

Since ISSUE 2 both classes are thin wrappers over the process-wide
:mod:`distlr_tpu.obs` registry: a :class:`StepTimer` feeds the
``distlr_train_steps_total`` / ``distlr_train_samples_total`` counters,
the ``distlr_train_step_seconds`` histogram and the
``distlr_train_samples_per_second`` gauge; a :class:`MetricsLogger`
mirrors every numeric record field into ``distlr_train_last{field=}`` —
so the /metrics scrape sees the same numbers the structured records
carry, without any call-site changes.
"""

from __future__ import annotations

import json
import time

from distlr_tpu.obs.registry import MetricsRegistry
from distlr_tpu.obs.registry import get_registry as _get_registry


class StepTimer:
    """Wall-clock step timer with samples/sec accounting.

    Note: callers must block on device results (``jax.block_until_ready``)
    before ``stop`` for honest timings — JAX dispatch is async.

    ``loop`` labels this timer's registry series (``"sync"`` for the SPMD
    trainer, ``"ps"`` for PS workers) so concurrent loops in one process
    stay distinguishable in a scrape.  Counters and the step histogram
    are additive, so concurrent timers share one ``loop`` child; the
    throughput GAUGE is per-timer state, so it additionally carries
    ``instance`` (the PS worker rank) — N Hogwild workers scrape as N
    rates to sum, not one last-writer-wins value.
    """

    def __init__(self, loop: str = "sync", instance: str = "0",
                 registry: MetricsRegistry | None = None):
        self.steps = 0
        self.samples = 0
        self.elapsed = 0.0
        self._t0 = None
        reg = registry or _get_registry()
        labels = ("loop",)
        self._steps_c = reg.counter(
            "distlr_train_steps_total", "training steps completed", labels
        ).labels(loop=loop)
        self._samples_c = reg.counter(
            "distlr_train_samples_total", "training samples consumed", labels
        ).labels(loop=loop)
        self._step_h = reg.histogram(
            "distlr_train_step_seconds", "wall seconds per training step",
            labels,
        ).labels(loop=loop)
        self._rate_g = reg.gauge(
            "distlr_train_samples_per_second",
            "cumulative training throughput per timer (sum instances for "
            "process throughput)", ("loop", "instance"),
        ).labels(loop=loop, instance=instance)

    def start(self):
        self._t0 = time.perf_counter()

    def stop(self, num_samples: int):
        if self._t0 is None:
            raise RuntimeError("StepTimer.stop() called without a matching start()")
        dt = time.perf_counter() - self._t0
        self.elapsed += dt
        self.steps += 1
        self.samples += num_samples
        self._t0 = None
        self._steps_c.inc()
        self._samples_c.inc(num_samples)
        self._step_h.observe(dt)
        if self.elapsed > 0:
            self._rate_g.set(self.samples / self.elapsed)

    @property
    def samples_per_sec(self) -> float:
        return self.samples / self.elapsed if self.elapsed > 0 else 0.0

    @property
    def sec_per_step(self) -> float:
        return self.elapsed / self.steps if self.steps else 0.0


class MetricsLogger:
    """Collects structured metric records; optional JSONL sink.

    Context-manager friendly: ``with MetricsLogger(path) as m: ...``
    closes the sink on exit.  ``log()`` after ``close()`` raises — a
    silently closed file previously swallowed records.
    """

    def __init__(self, jsonl_path: str | None = None,
                 registry: MetricsRegistry | None = None):
        self.records: list[dict] = []
        self._file = open(jsonl_path, "a") if jsonl_path else None
        self._had_file = self._file is not None
        self._closed = False
        reg = registry or _get_registry()
        self._last_g = reg.gauge(
            "distlr_train_last",
            "most recent value of each numeric structured metric field",
            ("field",),
        )

    def log(self, **record) -> dict:
        if self._closed:
            raise RuntimeError(
                "MetricsLogger is closed; log() would lose the record"
                + (" (the JSONL sink is gone)" if self._had_file else "")
            )
        record.setdefault("time", time.time())
        self.records.append(record)
        for key, val in record.items():
            if key != "time" and isinstance(val, (int, float)) \
                    and not isinstance(val, bool):
                self._last_g.labels(field=key).set(val)
        if self._file:
            self._file.write(json.dumps(record) + "\n")
            self._file.flush()
        return record

    def close(self):
        if self._file:
            self._file.close()
            self._file = None
        self._closed = True

    @property
    def closed(self) -> bool:
        return self._closed

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def latest(self, key: str):
        for rec in reversed(self.records):
            if key in rec:
                return rec[key]
        return None
