"""Structured per-step metrics — the observability layer the reference lacks.

The reference emits exactly one metric ever (rank-0 test accuracy on
stdout, ``src/lr.cc:56-62``).  Here every step can record loss, accuracy,
samples/sec and step latency as structured records, optionally mirrored as
JSON lines, while keeping the reference-format accuracy line for parity
diffs (:func:`distlr_tpu.utils.logging.log_eval_line`).
"""

from __future__ import annotations

import json
import time


class StepTimer:
    """Wall-clock step timer with samples/sec accounting.

    Note: callers must block on device results (``jax.block_until_ready``)
    before ``stop`` for honest timings — JAX dispatch is async.
    """

    def __init__(self):
        self.steps = 0
        self.samples = 0
        self.elapsed = 0.0
        self._t0 = None

    def start(self):
        self._t0 = time.perf_counter()

    def stop(self, num_samples: int):
        if self._t0 is None:
            raise RuntimeError("StepTimer.stop() called without a matching start()")
        self.elapsed += time.perf_counter() - self._t0
        self.steps += 1
        self.samples += num_samples
        self._t0 = None

    @property
    def samples_per_sec(self) -> float:
        return self.samples / self.elapsed if self.elapsed > 0 else 0.0

    @property
    def sec_per_step(self) -> float:
        return self.elapsed / self.steps if self.steps else 0.0


class MetricsLogger:
    """Collects structured metric records; optional JSONL sink."""

    def __init__(self, jsonl_path: str | None = None):
        self.records: list[dict] = []
        self._file = open(jsonl_path, "a") if jsonl_path else None

    def log(self, **record) -> dict:
        record.setdefault("time", time.time())
        self.records.append(record)
        if self._file:
            self._file.write(json.dumps(record) + "\n")
            self._file.flush()
        return record

    def close(self):
        if self._file:
            self._file.close()
            self._file = None

    def latest(self, key: str):
        for rec in reversed(self.records):
            if key in rec:
                return rec[key]
        return None
