"""Pallas TPU kernel: single-HBM-pass fused logistic-regression gradient.

The XLA path computes ``g = X^T (sigmoid(X w) - y)`` as two matmuls, so
the (B, D) feature matrix streams HBM -> MXU **twice** per step; for the
wide-feature workloads this framework targets, that HBM traffic IS the
step time (see bench.py).  This kernel streams X exactly once:

* the weight vector ``w`` (bf16) and a float32 gradient accumulator live
  in VMEM for the whole kernel,
* the grid walks batch tiles; each (BT, D) tile of X is DMA'd in once,
  used for the forward matvec ``z_t = X_t @ w``, turned into the residual
  ``r_t = (sigmoid(z_t) - y_t) * mask_t`` on the VPU, and immediately
  re-used (still in VMEM) for the backward rank-BT update
  ``g += r_t @ X_t`` on the MXU,
* the final grid step writes the accumulator out.

In theory halved HBM traffic -> up to 2x step throughput while the VMEM
working set fits (w bf16 + g f32 + a double-buffered X tile within the
16 MB scoped-VMEM budget).  ``fused_lr_supported`` reports the budget
check; callers fall back to the XLA two-matmul path above it.

**Measured reality on this bench target (v5e via the axon tunnel):** the
XLA matmul path streams ~310 GB/s while pallas/VPU streaming paths
plateau at ~66-126 GB/s regardless of tile shape (a trivial
pallas-sum kernel hits the same wall, so it is a platform streaming
limit, not this kernel's schedule; degenerate N=1/M=1 MXU matmuls are
equally bad for a different reason).  The single-pass advantage is
therefore not realizable here and :class:`BinaryLR` keeps the XLA path
by default; the kernel stays as the reference implementation of the
fused formulation for hardware where HBM truly bounds the step, and as
the framework's pallas exemplar (grid pipelining, VMEM accumulators,
``pl.when`` epilogues).

This is the TPU-native answer to the reference's O(B*D^2) scalar hot
loop (``src/lr.cc:35-41``) at the opposite end of the efficiency scale.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Conservative VMEM budget (bytes) for w + g + double-buffered X tile.
_VMEM_BUDGET = 14 * 1024 * 1024


def fused_lr_supported(batch: int, dim: int, batch_tile: int = 64) -> bool:
    if batch % batch_tile != 0 or dim % 128 != 0 or batch_tile % 16 != 0:
        return False
    working_set = (
        dim * 2          # w bf16
        + dim * 4        # g accumulator f32
        + 2 * batch_tile * dim * 2  # double-buffered bf16 X tile
    )
    return working_set <= _VMEM_BUDGET


def _kernel(x_ref, y_ref, mask_ref, w_ref, g_ref, acc_ref):
    # Matvec-shaped contractions (N=1 / M=1) waste 127/128 of the MXU, so
    # both directions run on the VPU as broadcast-multiply + axis
    # reduction — that keeps the kernel DMA-bound instead of
    # degenerate-matmul-bound.
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    x = x_ref[:].astype(jnp.float32)  # (BT, D); the only HBM read of this tile
    w = w_ref[:].astype(jnp.float32)  # (1, D), VMEM-resident across the grid
    z = jnp.sum(x * w, axis=1, keepdims=True)  # (BT, 1) forward matvec
    r = (jax.nn.sigmoid(z) - y_ref[:]) * mask_ref[:]  # (BT, 1)
    # backward re-uses the SAME VMEM tile: outer-product accumulation
    acc_ref[:] += jnp.sum(x * r, axis=0, keepdims=True)  # (1, D)

    @pl.when(t == pl.num_programs(0) - 1)
    def _flush():
        g_ref[:] = acc_ref[:]


@functools.partial(jax.jit, static_argnames=("batch_tile", "interpret"))
def fused_lr_grad(
    w,
    X,
    y,
    mask,
    *,
    batch_tile: int = 64,
    interpret: bool = False,
):
    """Unnormalized logistic gradient ``X^T ((sigmoid(Xw) - y) * mask)``.

    One HBM pass over ``X``.  Caller divides by the batch size and adds
    the L2 term (matching :meth:`BinaryLR.grad` semantics).

    Args:
      w: (D,) float32/bfloat16 weights. D must be a multiple of 128.
      X: (B, D) features (cast to bf16 for the MXU). B must be a
        multiple of ``batch_tile`` (pad + mask).
      y: (B,) labels; mask: (B,) validity.
      batch_tile: rows per grid step (multiple of 16 for bf16 tiling).
    """
    B, D = X.shape
    if not fused_lr_supported(B, D, batch_tile):
        raise ValueError(
            f"fused kernel unsupported for B={B} D={D} batch_tile={batch_tile}; "
            "use the XLA path (BinaryLR.grad)"
        )
    grid = (B // batch_tile,)
    g = pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((batch_tile, D), lambda t: (t, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((batch_tile, 1), lambda t: (t, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((batch_tile, 1), lambda t: (t, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, D), lambda t: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, D), lambda t: (0, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((1, D), jnp.float32),
        scratch_shapes=[pltpu.VMEM((1, D), jnp.float32)],
        interpret=interpret,
    )(
        X.astype(jnp.bfloat16),
        y.astype(jnp.float32).reshape(B, 1),
        mask.astype(jnp.float32).reshape(B, 1),
        w.astype(jnp.bfloat16).reshape(1, D),
    )
    return g.reshape(D)
