from distlr_tpu.ops.pallas_lr import fused_lr_grad, fused_lr_supported  # noqa: F401
