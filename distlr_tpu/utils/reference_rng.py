"""Bitwise reproduction of glibc ``rand()`` for reference init parity.

The reference initializes weights as ``rand()/RAND_MAX`` after
``srand(random_state)`` (reference ``src/lr.cc:92-98``, default state 0 per
``include/lr.h:10``; every worker computes the identical vector — SURVEY.md
Q2).  To validate bitwise-identical initial weights against a reference
run, this module re-implements glibc's TYPE_3 additive-feedback generator
(the documented algorithm, e.g. the glibc manual's random_r description):

* ``r[0] = seed`` (glibc maps seed 0 -> 1)
* ``r[i] = 16807 * r[i-1] mod 2^31-1`` for i in 1..30 (Lehmer stepping,
  computed without overflow)
* ``r[i] = r[i-31]`` for i in 31..33
* ``r[i] = (r[i-3] + r[i-31]) mod 2^32`` for i >= 34
* srandom discards the first 310 outputs (10 x degree warm-up), so
  ``rand()`` call k returns ``r[k+344] >> 1``
"""

from __future__ import annotations

import numpy as np

GLIBC_RAND_MAX = 2147483647  # 2^31 - 1


def glibc_rand_sequence(seed: int, n: int) -> np.ndarray:
    """First ``n`` outputs of glibc ``rand()`` after ``srand(seed)``."""
    seed = seed & 0xFFFFFFFF
    if seed == 0:
        seed = 1
    warmup = 310
    total = n + 34 + warmup
    state = np.empty(total, dtype=np.uint64)
    state[0] = seed
    for i in range(1, 31):
        # 16807 * r mod 2^31-1 (Schrage in glibc; plain 64-bit mod here).
        state[i] = (16807 * int(state[i - 1])) % 2147483647
    for i in range(31, 34):
        state[i] = state[i - 31]
    for i in range(34, total):
        state[i] = (state[i - 3] + state[i - 31]) & 0xFFFFFFFF
    return (state[34 + warmup :] >> np.uint64(1)).astype(np.int64)


def reference_init_weights(num_features: int, seed: int = 0) -> np.ndarray:
    """The reference's exact initial weight vector: uniform [0,1) as
    float32 ``rand()/RAND_MAX`` draws (``src/lr.cc:92-98``)."""
    draws = glibc_rand_sequence(seed, num_features)
    return (
        draws.astype(np.float32) / np.float32(GLIBC_RAND_MAX)
    ).astype(np.float32)
