"""Structured logging + CHECK-style invariants.

Replaces the reference's only two observability mechanisms: dmlc-style
fatal ``CHECK``/``CHECK_EQ`` macros (reference ``src/main.cc:49,86``) and
the timestamped stdout eval line (``src/lr.cc:56-62``).  Unlike the
reference, failed checks raise a structured exception instead of aborting
the process, and eval output is also available as structured records.
"""

from __future__ import annotations

import logging
import sys
import time


class CheckError(AssertionError):
    """Invariant violation — the framework's equivalent of a failed CHECK."""


def check(cond: bool, msg: str = "") -> None:
    if not cond:
        raise CheckError(f"Check failed: {msg}")


def check_eq(a, b, msg: str = "") -> None:
    if a != b:
        raise CheckError(f"Check failed: {a!r} != {b!r}. {msg}")


_FORMAT = "%(asctime)s %(levelname).1s %(name)s] %(message)s"


class _LazyStderrHandler(logging.StreamHandler):
    """StreamHandler that re-resolves ``sys.stderr`` at every emit.

    ``StreamHandler(sys.stderr)`` captures the stream object live at
    first-logger creation, which is order-fragile: a logger created while
    something (pytest capture, ``contextlib.redirect_stderr``) has
    temporarily replaced ``sys.stderr`` keeps writing to that dead stream
    forever after.  Binding lazily makes log output follow wherever
    ``sys.stderr`` points *now* — same trick as stdlib
    ``logging._StderrHandler``.
    """

    def __init__(self):
        logging.Handler.__init__(self)

    @property
    def stream(self):
        return sys.stderr


#: extra-handler providers consulted on every get_logger call.  Each is
#: a zero-arg callable returning a Handler (or None when disarmed); the
#: handler is attached alongside — never instead of — the stderr
#: handler.  The structured-log tee (distlr_tpu.obs.log) registers here
#: so loggers created *after* log.configure() still reach the journal.
_EXTRA_HANDLER_PROVIDERS: list = []


def register_extra_handler(provider) -> None:
    if provider not in _EXTRA_HANDLER_PROVIDERS:
        _EXTRA_HANDLER_PROVIDERS.append(provider)


def unregister_extra_handler(provider) -> None:
    if provider in _EXTRA_HANDLER_PROVIDERS:
        _EXTRA_HANDLER_PROVIDERS.remove(provider)


def get_logger(name: str = "distlr_tpu") -> logging.Logger:
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = _LazyStderrHandler()
        handler.setFormatter(logging.Formatter(_FORMAT, datefmt="%H:%M:%S"))
        logger.addHandler(handler)
        logger.setLevel(logging.INFO)
        logger.propagate = False
    for provider in list(_EXTRA_HANDLER_PROVIDERS):
        extra = provider()
        if extra is not None and extra not in logger.handlers:
            logger.addHandler(extra)
    return logger


def log_eval_line(iteration: int, accuracy: float, *, stream=None) -> str:
    """Emit the reference-format eval line: ``HH:MM:SS Iteration N, accuracy: A``.

    Format-compatible with reference ``src/lr.cc:56-62`` so convergence
    trajectories can be diffed line-for-line against a reference run.
    """
    line = f"{time.strftime('%H:%M:%S')} Iteration {iteration}, accuracy: {accuracy:g}"
    print(line, file=stream if stream is not None else sys.stdout, flush=True)
    return line
