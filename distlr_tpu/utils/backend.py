"""Backend liveness probing and safe CPU forcing.

The default accelerator backend in some environments (e.g. a TPU chip
reached through an experimental tunnel) can be *wedged*: any call that
initializes it — ``jax.devices()``, ``jax.default_backend()``, building a
``jnp`` array — hangs forever rather than erroring.  Entry points that
must never hang (``bench.py``, ``__graft_entry__.dryrun_multichip``)
therefore must decide CPU-vs-accelerator *without* touching the backend
in-process.  The only safe probe is a killable subprocess with a timeout;
the only safe fallback is ``jax.config.update("jax_platforms", "cpu")``
issued before the first in-process backend initialization (env vars do
not work when a sitecustomize pre-imports jax and pins the platform).
"""

from __future__ import annotations

import os
import subprocess
import sys

_PROBE_SRC = (
    "import jax, jax.numpy as jnp;"
    # A device->host readback is the only honest liveness check: on some
    # experimental platforms block_until_ready returns at dispatch time.
    "v = float(jnp.sum(jnp.ones(8)));"
    "print(jax.default_backend(), len(jax.devices()), v)"
)


def probe_default_backend_ex(
    timeout_s: float | None = None,
) -> tuple[str, tuple[str, int] | None]:
    """Run one tiny computation on the default backend in a subprocess.

    Returns ``(status, payload)``:

    * ``("ok", (backend_name, n_devices))`` — live backend,
    * ``("timeout", None)`` — the probe HUNG (wedged tunnel; transient,
      worth retrying),
    * ``("error", None)`` — the probe crashed or printed garbage
      (broken install; permanent, retrying is pointless).

    Never initializes a backend in-process.  Default timeout is 60s
    (override via ``DISTLR_PROBE_TIMEOUT_S``) — it must stay comfortably
    inside any outer artifact-timeout budget, or a hung probe turns back
    into the hung-artifact failure it prevents.
    """
    if timeout_s is None:
        timeout_s = float(os.environ.get("DISTLR_PROBE_TIMEOUT_S", "60"))
    try:
        out = subprocess.run(
            [sys.executable, "-c", _PROBE_SRC],
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return "timeout", None
    except OSError:
        return "error", None
    if out.returncode != 0:
        return "error", None
    try:
        name, n, v = out.stdout.split()
        if float(v) != 8.0:
            return "error", None
        return "ok", (name, int(n))
    except ValueError:
        return "error", None


def probe_default_backend(timeout_s: float | None = None) -> tuple[str, int] | None:
    """Back-compat wrapper: ``(backend_name, n_devices)`` or ``None``
    (hung OR broken — callers that care which use
    :func:`probe_default_backend_ex`)."""
    return probe_default_backend_ex(timeout_s)[1]


def force_cpu(n_devices: int | None = None) -> None:
    """Switch jax to the CPU platform, optionally with virtual devices.

    Must run before the first in-process backend initialization to be
    hang-proof; if a backend was already initialized, this clears it
    first (that path can only be reached when the prior backend is
    live, so it cannot hang).
    """
    import jax

    try:
        import jax.extend.backend

        jax.clear_caches()
        jax.extend.backend.clear_backends()
    except Exception:
        pass  # no backend initialized yet — nothing to clear
    jax.config.update("jax_platforms", "cpu")
    if n_devices is not None:
        jax.config.update("jax_num_cpu_devices", n_devices)
