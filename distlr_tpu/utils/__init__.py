from distlr_tpu.utils.logging import check, check_eq, get_logger, log_eval_line  # noqa: F401
