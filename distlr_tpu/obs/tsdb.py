"""Embedded fleet time-series store — ring buffers, rollups, queries.

Every :class:`~distlr_tpu.obs.federate.FleetScraper` poll feeds one
frame into a :class:`FleetTSDB`: the ``/fleet.json`` per-rank rows
become scalar series (``route_requests{role=route,rank=0}``), the
merged registry's counter/gauge families become labeled scalar series,
and its histogram families become bucket-vector series — so windowed
questions ("requests/s over the last 30s", "p99 over the last 5m",
"how fast is the error budget burning") answer from ONE store instead
of the three hand-rolled rate windows that grew around the fleet
(``launch top``'s frame tracker, the autopilot's ``_RateWindow``, and
ad-hoc deltas in benches).

Storage is bounded by construction:

* a **raw tier** — one fixed-size ring per series (``raw_points``
  frames; at obs-agg's default 2s interval the default 512 points is
  ~17 minutes);
* staged **rollups** — 10s and 60s buckets carrying sum/count/min/max
  + last (and, for histograms, the bucket-count deltas within the
  bucket), each tier bounded by ``rollup_retention_s``.

Every eviction is counted (:meth:`FleetTSDB.stats` ->
``distlr_tsdb_points_dropped_total``), never silent.  The on-disk raw
tier stays ``history.jsonl`` (one fleet doc per line, written by the
scraper) so ``launch top --replay`` and rate seeding keep working on
the same file they always read.

The query layer is a deliberately small Prometheus-shaped expression
language (:func:`FleetTSDB.query`)::

    rate(route_requests{role=route})
    increase(distlr_route_shed_total)
    histogram_quantile(0.99, distlr_route_request_seconds)
    avg_over_time(samples_per_s) / 2 + 1

exposed as helpers, as obs-agg's ``/query?expr=...&window=...`` JSON
endpoint, and as the ``launch fleet-query`` CLI.  Recording rules
(:class:`RecordingRule`) evaluate expressions every scrape tick and
write the result back as a derived series (``fleet:req_rate``) that
later queries — and the SLO engine (:mod:`distlr_tpu.obs.slo`) — can
reference like any other name.

Concurrency: the scrape-tick writer, ``/query`` HTTP readers, and the
rule/SLO evaluator cross threads, so all mutation and point reads go
through ``_lock`` (:mod:`distlr_tpu.sync` facade — virtualized under
schedcheck's ``tsdb_write_query_rollup`` scenario); :meth:`stats` is a
deliberately lock-free monitoring snapshot (audited in the concurrency
baseline).
"""

from __future__ import annotations

import collections
import json
import math
import re

from distlr_tpu import sync
from distlr_tpu.obs.registry import percentile_from_counts

#: rollup tiers, seconds per bucket, coarsest last
ROLLUP_STEPS = (10.0, 60.0)


# ---------------------------------------------------------------------------
# the one shared rate arithmetic (satellite: dedupe the three windows)
# ---------------------------------------------------------------------------

def delta_rate(t0: float, v0, t1: float, v1) -> float | None:
    """Counter rate between two observations: ``max(0, dv/dt)``.

    ``None`` when either endpoint is missing or time did not advance;
    negative deltas clamp to 0 (a restarted process reset the counter).
    This is THE rate arithmetic — ``launch top``'s per-rank columns,
    the autopilot's windowed signals, and :meth:`FleetTSDB.query`'s
    ``rate()`` all call it, so they can never disagree about what a
    rate means.
    """
    if v0 is None or v1 is None:
        return None
    dt = t1 - t0
    if dt <= 0:
        return None
    return max(0.0, (v1 - v0) / dt)


class RateWindow:
    """Windowed rates from successive cumulative-counter observations:
    append ``(t, totals-dict)``, read back delta/dt over the horizon.
    Keeps one observation at/past the horizon so the window always
    spans at least ``window_s`` once enough history exists (the
    autopilot daemon's contract, moved here from
    ``autopilot/daemon.py``)."""

    def __init__(self, window_s: float):
        self.window_s = float(window_s)
        self._obs: collections.deque = collections.deque()

    def push(self, t: float, totals: dict) -> None:
        self._obs.append((t, totals))
        while len(self._obs) > 2 and t - self._obs[1][0] >= self.window_s:
            self._obs.popleft()

    def rate(self, key: str) -> float | None:
        if len(self._obs) < 2:
            return None
        (t0, a), (t1, b) = self._obs[0], self._obs[-1]
        if key not in a or key not in b:
            return None
        return delta_rate(t0, a[key], t1, b[key])


def load_history(path: str, *, limit: int = 64) -> list[tuple[float, dict]]:
    """Parse the tail of a scraper ``history.jsonl`` into
    ``[(t, fleet_doc), ...]`` (oldest first).  Rows written by the live
    aggregator stamp ``updated``; test fixtures (and the pre-tsdb
    seeding contract) stamp ``t`` — both are accepted, because seeding
    from a REAL history file silently primed nothing when only ``t``
    was recognized.  Unparseable lines are skipped (a torn tail line
    is normal)."""
    try:
        with open(path) as f:
            lines = f.readlines()[-limit:]
    except OSError:
        return []
    rows: list[tuple[float, dict]] = []
    for line in lines:
        try:
            doc = json.loads(line)
        except ValueError:
            continue
        if not isinstance(doc, dict):
            continue
        t = doc.get("t")
        if not isinstance(t, (int, float)):
            t = doc.get("updated")
        if isinstance(t, (int, float)) and math.isfinite(t):
            rows.append((float(t), doc))
    return rows


# ---------------------------------------------------------------------------
# the store
# ---------------------------------------------------------------------------

def _label_key(labels: dict | None) -> tuple:
    return tuple(sorted((str(k), str(v))
                        for k, v in (labels or {}).items()))


class _Rollup:
    """One rollup tier of one series: fixed-width buckets carrying
    sum/count/min/max/last (+ histogram bucket deltas)."""

    __slots__ = ("step", "buckets")

    def __init__(self, step: float):
        self.step = float(step)
        self.buckets: collections.deque = collections.deque()

    def add_scalar(self, t: float, v: float) -> None:
        b = math.floor(t / self.step) * self.step
        if self.buckets and self.buckets[-1][0] == b:
            agg = self.buckets[-1]
            agg[1] += v
            agg[2] += 1
            agg[3] = min(agg[3], v)
            agg[4] = max(agg[4], v)
            agg[5] = v
            agg[6] = t
        else:
            # [bucket_t, sum, count, min, max, last, last_t]
            self.buckets.append([b, v, 1, v, v, v, t])

    def add_hist(self, t: float, counts: list[float]) -> None:
        b = math.floor(t / self.step) * self.step
        if self.buckets and self.buckets[-1][0] == b:
            agg = self.buckets[-1]
            agg[2] = counts          # last cumulative vector
            agg[3] = t
        else:
            # [bucket_t, first_counts, last_counts, last_t]
            self.buckets.append([b, counts, counts, t])

    def evict(self, now: float, retention_s: float) -> int:
        dropped = 0
        while self.buckets and self.buckets[0][0] < now - retention_s:
            self.buckets.popleft()
            dropped += 1
        return dropped


class _Series:
    __slots__ = ("name", "labels", "kind", "bounds", "raw", "rollups")

    def __init__(self, name: str, labels: tuple, kind: str,
                 raw_points: int, bounds: tuple = ()):
        self.name = name
        self.labels = labels
        self.kind = kind            # "scalar" | "histogram"
        self.bounds = bounds        # histogram bucket boundaries
        self.raw: collections.deque = collections.deque(maxlen=raw_points)
        self.rollups = [_Rollup(s) for s in ROLLUP_STEPS]


class FleetTSDB:
    """The embedded store.  All timestamps are caller-provided (the
    scraper passes each frame's ``updated`` stamp), so the store is
    fully deterministic under a virtual clock — tests and schedcheck
    drive it without wall time."""

    def __init__(self, *, raw_points: int = 512,
                 rollup_retention_s: float = 3600.0):
        if raw_points < 2:
            raise ValueError(
                f"raw_points must be >= 2 (a rate needs two), got "
                f"{raw_points}")
        if rollup_retention_s <= 0:
            raise ValueError("rollup_retention_s must be positive, got "
                             f"{rollup_retention_s}")
        self.raw_points = int(raw_points)
        self.rollup_retention_s = float(rollup_retention_s)
        self._lock = sync.Lock()
        self._series: dict[tuple[str, tuple], _Series] = {}
        self._last_t: float | None = None
        # monitoring counters: written under _lock, read lock-free by
        # stats() (monotonic ints; audited in the concurrency baseline,
        # raced by the tsdb_write_query_rollup schedcheck scenario)
        self.points_total = 0
        self.frames_total = 0
        self.dropped = {"raw": 0, "rollup": 0, "history": 0}

    # -- ingest ------------------------------------------------------------
    def _append(self, name: str, labels: tuple, t: float, value,
                *, kind: str = "scalar", bounds: tuple = ()) -> None:
        key = (name, labels)
        s = self._series.get(key)
        if s is None:
            s = self._series[key] = _Series(name, labels, kind,
                                            self.raw_points, bounds)
        if len(s.raw) == s.raw.maxlen:
            self.dropped["raw"] += 1
        s.raw.append((t, value))
        for r in s.rollups:
            if kind == "histogram":
                r.add_hist(t, value)
            else:
                r.add_scalar(t, float(value))
            self.dropped["rollup"] += r.evict(t, self.rollup_retention_s)
        self.points_total += 1

    def ingest(self, fleet: dict, snapshot: dict | None = None) -> int:
        """Feed one scrape frame: the ``/fleet.json`` doc's per-rank
        numeric fields (+ totals) and, optionally, the merged registry
        snapshot's families.  Returns points ingested (0 for a
        duplicate frame — same ``updated`` stamp as the last one, the
        aggregator has not rescraped)."""
        t = fleet.get("updated")
        if not isinstance(t, (int, float)) or not math.isfinite(t):
            return 0
        t = float(t)
        with self._lock:
            if self._last_t is not None and t <= self._last_t:
                return 0
            before = self.points_total
            self._last_t = t
            for row in fleet.get("ranks", []):
                labels = _label_key({"role": row.get("role", "?"),
                                     "rank": row.get("rank", "?")})
                for field, v in row.items():
                    if field == "rank" or isinstance(v, bool) \
                            or not isinstance(v, (int, float)):
                        continue  # rank is identity (a label), not data
                    self._append(field, labels, t, v)
            for field, v in (fleet.get("totals") or {}).items():
                if not isinstance(v, bool) and isinstance(v, (int, float)):
                    self._append(f"fleet:{field}", (), t, v)
            if snapshot:
                self._ingest_snapshot_locked(snapshot, t)
            self.frames_total += 1
            return self.points_total - before

    def _ingest_snapshot_locked(self, snap: dict, t: float) -> None:
        for name, fam in snap.items():
            kind = fam.get("type")
            for series in fam.get("series", []):
                labels = _label_key(series.get("labels"))
                if kind == "histogram":
                    buckets = series.get("buckets") or {}
                    try:
                        bounds = tuple(sorted(float(b) for b in buckets))
                    except (TypeError, ValueError):
                        continue
                    # cumulative per-bound counts + the +Inf slot, in
                    # boundary order — one vector per frame
                    counts = [float(buckets[b]) for b in
                              sorted(buckets, key=float)]
                    counts.append(float(series.get("inf", 0)))
                    self._append(name, labels, t, counts,
                                 kind="histogram", bounds=bounds)
                else:
                    v = series.get("value")
                    if isinstance(v, bool) or not isinstance(
                            v, (int, float)) or not math.isfinite(v):
                        continue
                    self._append(name, labels, t, v)

    def record(self, name: str, labels: dict | None, t: float,
               value: float | None) -> None:
        """Write one derived point (recording rules, SLO bad-tick
        series).  ``None`` values record nothing — absence of data must
        stay distinguishable from 0."""
        if value is None:
            return
        with self._lock:
            self._append(name, _label_key(labels), t, float(value))

    def count_dropped(self, tier: str, n: int) -> None:
        """Attribute ``n`` externally-evicted points (the on-disk
        history tier's rotation) to the drop counter."""
        if n > 0:
            with self._lock:
                self.dropped[tier] = self.dropped.get(tier, 0) + int(n)

    # -- reads -------------------------------------------------------------
    def _match_locked(self, name: str, labels: dict | None) -> list[_Series]:
        want = dict(_label_key(labels))
        out = []
        for (n, _k), s in self._series.items():
            if n != name:
                continue
            have = dict(s.labels)
            if all(have.get(k) == v for k, v in want.items()):
                out.append(s)
        return out

    @staticmethod
    def _scalar_points(s: _Series, start: float, end: float) -> list:
        """Merged (t, value) points inside [start, end]: rollup tiers
        (coarsest first) cover history the raw ring has already
        evicted; raw covers the recent end.  Rollup buckets contribute
        their last sample at its true timestamp."""
        raw = [(t, v) for t, v in s.raw if start <= t <= end]
        oldest_raw = raw[0][0] if raw else end + 1.0
        pts: list = []
        for r in reversed(s.rollups):          # coarsest tier first
            for b in r.buckets:
                t = b[6]
                if start <= t <= end and t < oldest_raw and (
                        not pts or t > pts[-1][0]):
                    pts.append((t, b[5]))
        pts = [p for p in pts if p[0] < oldest_raw]
        pts.extend(raw)
        return pts

    @staticmethod
    def _hist_endpoints(s: _Series, start: float, end: float):
        """(first, last) cumulative bucket vectors inside the window:
        raw points, with rollup buckets (coarsest first) covering
        history the raw ring evicted.  A rollup bucket contributes its
        last vector at its true timestamp; the earliest contributing
        bucket may also lend its FIRST vector, but only when the whole
        bucket lies inside the window — a bucket straddling the window
        edge would smuggle pre-window counts into the delta."""
        raw = [(t, v) for t, v in s.raw if start <= t <= end]
        oldest_raw = raw[0][0] if raw else end + 1.0
        pts: list = []
        for r in reversed(s.rollups):          # coarsest tier first
            for b in r.buckets:
                t = b[3]
                if start <= t <= end and t < oldest_raw and (
                        not pts or t > pts[-1][0]):
                    if not pts and b[0] >= start:
                        pts.append((b[0], b[1]))
                    pts.append((t, b[2]))
        pts = [p for p in pts if p[0] < oldest_raw]
        pts.extend(raw)
        if len(pts) < 2:
            return None
        return pts[0][1], pts[-1][1]

    def series_names(self) -> list[dict]:
        with self._lock:
            return [{"name": s.name, "labels": dict(s.labels),
                     "kind": s.kind, "points": len(s.raw)}
                    for s in self._series.values()]

    def window_snapshot(self, start: float, end: float, *,
                        prefix: str = "fleet:") -> dict:
        """Export every ``prefix``-named scalar series' (t, value)
        points inside ``[start, end]`` — the incident bundle's
        ``tsdb.json`` payload: the headline recorded series around the
        alert edge, frozen into the bundle so the postmortem does not
        depend on the live store's retention."""
        out: dict = {}
        with self._lock:
            for s in self._series.values():
                if not s.name.startswith(prefix) or s.kind == "hist":
                    continue
                pts = self._scalar_points(s, start, end)
                if not pts:
                    continue
                key = s.name
                if s.labels:
                    key += "{" + ",".join(
                        f"{k}={v}" for k, v in s.labels) + "}"
                out[key] = [[round(t, 3), v] for t, v in pts]
        return out

    def latest_time(self) -> float | None:
        with self._lock:
            return self._last_t

    def stats(self) -> dict:
        """Lock-free monitoring snapshot: the counters are monotonic
        ints and a racing reader sees the previous frame's values —
        what a monitor means (same stance as ``AutopilotDaemon.
        status()``; audited in the concurrency baseline)."""
        return {
            "series": len(self._series),
            "frames": self.frames_total,
            "points": self.points_total,
            "dropped": dict(self.dropped),
        }

    # -- query functions ---------------------------------------------------
    def _eval_fn(self, fn: str, name: str, labels: dict | None,
                 window_s: float, now: float, q: float | None):
        start, end = now - window_s, now
        with self._lock:
            series = self._match_locked(name, labels)
            if fn == "histogram_quantile":
                deltas: list[float] | None = None
                bounds: tuple | None = None
                for s in series:
                    if s.kind != "histogram":
                        continue
                    ep = self._hist_endpoints(s, start, end)
                    if ep is None:
                        continue
                    first, last = ep
                    if bounds is None:
                        bounds = s.bounds
                        deltas = [0.0] * len(last)
                    elif s.bounds != bounds or len(last) != len(deltas):
                        continue   # mismatched ladders never merge
                    for i in range(len(last)):
                        deltas[i] += max(0.0, last[i] - first[i])
                if deltas is None or bounds is None:
                    return None
                # cumulative -> per-bucket decomposition (+Inf last)
                per = [deltas[0]]
                per.extend(deltas[i] - deltas[i - 1]
                           for i in range(1, len(deltas)))
                per = [max(0.0, c) for c in per]
                if sum(per) == 0:
                    return None
                return percentile_from_counts(bounds, per, q)
            total = None
            agg: list[float] = []
            for s in series:
                if s.kind != "scalar":
                    continue
                pts = self._scalar_points(s, start, end)
                if fn in ("rate", "increase"):
                    if len(pts) < 2:
                        continue
                    (t0, v0), (t1, v1) = pts[0], pts[-1]
                    r = delta_rate(t0, v0, t1, v1)
                    if r is None:
                        continue
                    total = (total or 0.0) + (
                        r if fn == "rate" else r * (t1 - t0))
                elif fn == "last":
                    if pts:
                        total = (total or 0.0) + pts[-1][1]
                else:
                    agg.extend(v for _t, v in pts)
            if fn in ("rate", "increase", "last"):
                return total
            if not agg:
                return None
            if fn == "avg_over_time":
                return sum(agg) / len(agg)
            if fn == "min_over_time":
                return min(agg)
            if fn == "max_over_time":
                return max(agg)
            if fn == "sum_over_time":
                return sum(agg)
            if fn == "count_over_time":
                return float(len(agg))
            raise ValueError(f"unknown query function {fn!r}")

    def query(self, expr: str, *, window_s: float = 60.0,
              now: float | None = None):
        """Evaluate one expression over the trailing window.  Returns a
        float, or ``None`` when the window holds no data (callers must
        distinguish "no traffic yet" from 0)."""
        if now is None:
            now = self.latest_time()
            if now is None:
                return None
        return _eval_expr(self, expr, float(window_s), float(now))


# ---------------------------------------------------------------------------
# the expression mini-language
# ---------------------------------------------------------------------------

_FUNCS = ("rate", "increase", "avg_over_time", "min_over_time",
          "max_over_time", "sum_over_time", "count_over_time", "last",
          "histogram_quantile")

_TOKEN = re.compile(r"""
    \s*(?:
      (?P<num>\d+(?:\.\d+)?(?:[eE][+-]?\d+)?)
    | (?P<name>[A-Za-z_][A-Za-z0-9_:.]*)
    | (?P<sel>\{[^}]*\})
    | (?P<op>[()+\-*/,])
    )""", re.VERBOSE)


def _tokenize(expr: str) -> list[tuple[str, str]]:
    out, i = [], 0
    while i < len(expr):
        m = _TOKEN.match(expr, i)
        if m is None or m.end() == i:
            raise ValueError(f"bad query syntax at {expr[i:]!r}")
        i = m.end()
        for kind in ("num", "name", "sel", "op"):
            v = m.group(kind)
            if v is not None:
                out.append((kind, v))
                break
    return out


def _parse_labels(sel: str) -> dict:
    body = sel.strip()[1:-1].strip()
    labels: dict = {}
    if not body:
        return labels
    for part in body.split(","):
        k, eq, v = part.partition("=")
        if not eq:
            raise ValueError(f"bad label matcher {part!r} (need k=v)")
        labels[k.strip()] = v.strip().strip('"').strip("'")
    return labels


class _Parser:
    """Recursive descent over +- / */ with function calls and parens.
    Arithmetic over ``None`` (a term with no data) propagates ``None``
    — a budget must read "unknown", never "fine", when its inputs are
    missing; division by zero reads ``None`` too."""

    def __init__(self, db: FleetTSDB, tokens: list, window_s: float,
                 now: float):
        self.db = db
        self.toks = tokens
        self.i = 0
        self.window_s = window_s
        self.now = now

    def peek(self):
        return self.toks[self.i] if self.i < len(self.toks) else (None, None)

    def take(self, kind=None, value=None):
        k, v = self.peek()
        if k is None or (kind and k != kind) or (value and v != value):
            raise ValueError(
                f"bad query syntax near token {self.i} "
                f"(expected {value or kind}, got {v!r})")
        self.i += 1
        return v

    def expr(self):
        left = self.term()
        while self.peek() == ("op", "+") or self.peek() == ("op", "-"):
            op = self.take("op")
            right = self.term()
            if left is None or right is None:
                left = None
            else:
                left = left + right if op == "+" else left - right
        return left

    def term(self):
        left = self.factor()
        while self.peek() == ("op", "*") or self.peek() == ("op", "/"):
            op = self.take("op")
            right = self.factor()
            if left is None or right is None:
                left = None
            elif op == "*":
                left = left * right
            else:
                left = left / right if right != 0 else None
        return left

    def factor(self):
        k, v = self.peek()
        if k == "op" and v == "(":
            self.take("op", "(")
            inner = self.expr()
            self.take("op", ")")
            return inner
        if k == "op" and v == "-":
            self.take("op", "-")
            inner = self.factor()
            return None if inner is None else -inner
        if k == "num":
            self.take("num")
            return float(v)
        if k == "name" and v in _FUNCS:
            return self.call(self.take("name"))
        if k == "name":
            name = self.take("name")
            labels = self.selector()
            return self.db._eval_fn("last", name, labels,
                                    self.window_s, self.now, None)
        raise ValueError(f"bad query syntax near {v!r}")

    def selector(self) -> dict:
        if self.peek()[0] == "sel":
            return _parse_labels(self.take("sel"))
        return {}

    def call(self, fn: str):
        self.take("op", "(")
        q = None
        if fn == "histogram_quantile":
            q = float(self.take("num"))
            if not 0.0 <= q <= 1.0:
                raise ValueError(f"quantile must be in [0, 1], got {q}")
            self.take("op", ",")
        name = self.take("name")
        labels = self.selector()
        self.take("op", ")")
        return self.db._eval_fn(fn, name, labels, self.window_s,
                                self.now, q)


def _eval_expr(db: FleetTSDB, expr: str, window_s: float, now: float):
    p = _Parser(db, _tokenize(expr), window_s, now)
    out = p.expr()
    if p.i != len(p.toks):
        raise ValueError(f"trailing junk in query: {expr!r}")
    return out


def check_expr(expr: str) -> None:
    """Full grammar check without data: parse-and-evaluate against an
    empty store (every selector reads None), so malformed expressions
    fail at LOAD time with a ValueError instead of mid-scrape."""
    _eval_expr(FleetTSDB(), str(expr), 60.0, 0.0)


# ---------------------------------------------------------------------------
# recording rules
# ---------------------------------------------------------------------------

class RecordingRule:
    """One derived series: ``expr`` evaluated over ``window_s`` every
    scrape tick, recorded back under ``name`` — the engine behind the
    fleet's windowed rates (one implementation, queried everywhere)."""

    def __init__(self, name: str, expr: str, window_s: float = 30.0):
        if not name or not str(name).strip():
            raise ValueError("recording rule needs a series name")
        self.name = str(name)
        self.expr = str(expr)
        self.window_s = float(window_s)
        if self.window_s <= 0:
            raise ValueError(
                f"rule {name!r}: window_s must be positive, got {window_s}")
        check_expr(self.expr)  # syntax-check eagerly, not mid-scrape

    def evaluate(self, db: FleetTSDB, now: float) -> float | None:
        value = db.query(self.expr, window_s=self.window_s, now=now)
        db.record(self.name, None, now, value)
        return value


#: the recording rules every aggregator evaluates (the unified windowed
#: fleet rates the bespoke trackers used to duplicate); an SLO file's
#: "rules" list appends to these
DEFAULT_RULES = (
    ("fleet:push_rate", "rate(pushes)", 30.0),
    ("fleet:shed_rate", "rate(route_shed)", 30.0),
    ("fleet:req_rate", "rate(route_requests)", 30.0),
    # windowed fleet ERROR-record rate (the structured-log signal the
    # `launch top` log_errors column and incident bundles read)
    ("fleet:log_error_rate", "rate(log_errors_total)", 30.0),
)


def default_rules() -> list[RecordingRule]:
    return [RecordingRule(n, e, w) for n, e, w in DEFAULT_RULES]
