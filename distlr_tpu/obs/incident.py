"""Incident engine: alert edges become self-contained postmortem bundles.

The fleet already *detects* trouble (PR 17 burn-rate alerts), *reacts*
to it (PR 16 autopilot, PR 10 rollout gating), and *records* fragments
of it — PR 8 flight dumps, PR 9 profiler bursts, chaos instants,
autopilot decision journals, rollout ramp journals, tsdb history, and
(this PR) structured log journals.  A human debugging one incident had
to hand-correlate those eight artifact families across run-dir
subdirectories on three different clocks.  This module is the
correlation engine: when obs-agg sees the same NOT-FIRING→FIRING alert
edge that already fires the flight recorder, it assembles

    <run_dir>/incidents/<seq>/
        incident.json     what fired, SLO state, window, artifact refs
        timeline.jsonl    every event, shifted onto ONE clock, sorted
        tsdb.json         headline fleet series around the edge
        POSTMORTEM.md     rendered detection → evidence → actions

``seq`` is the flight-recorder trigger sequence — the SAME number PR 8
stamps into ``flightrec/<role>-<rank>-<seq>.json`` and PR 9 stamps into
burst profwindows, so the bundle, the dumps, and the bursts all
cross-reference each other by construction.

Clock alignment reuses the PR-8 kHello probe: ``clock`` records in any
spans journal give per-peer offsets keyed by listen port, and every
collected event is shifted by its emitting process's offset before the
merge — ``timeline.jsonl`` reads in true causal wall order even when a
server's clock is seconds off the observer's.

Stdlib-only and jax-free, like the rest of ``obs``.  Assembly runs on
the obs-agg scrape thread; everything here is read-only over journals
other processes write, plus atomic writes into a fresh bundle dir.
"""

from __future__ import annotations

import json
import os
import time

from distlr_tpu.obs import dtrace
from distlr_tpu.obs import log as fleetlog
from distlr_tpu.obs.registry import get_registry
from distlr_tpu.utils.logging import get_logger

logger = get_logger("distlr_tpu.obs.incident")

_reg = get_registry()
_BUNDLES = _reg.counter(
    "distlr_incident_bundles_total",
    "incident bundles assembled under <run_dir>/incidents/, by trigger",
    labelnames=("trigger",),
)
_EVENTS = _reg.counter(
    "distlr_incident_timeline_events_total",
    "events merged into incident timelines, by kind",
    labelnames=("kind",),
)
_PRUNED = _reg.counter(
    "distlr_incident_pruned_total",
    "old incident bundles removed by the incident_max retention cap",
)

#: default seconds of history collected before the alert edge
WINDOW_S = 120.0
#: default seconds waited after the edge before assembly (must outlast
#: the profiler's burst window so the burst doc lands in its journal)
SETTLE_S = 6.0


# ---------------------------------------------------------------------------
# clock alignment (the PR-8 kHello offsets, reused record-for-record)
# ---------------------------------------------------------------------------

def clock_shifts(run_dirs) -> tuple[dict, dict]:
    """``(shifts, offsets)``: per-journal-stem second shifts and the
    raw port-keyed peer offsets they derive from.  Same join as
    :func:`dtrace.merge_run_dirs` — ``clock`` records observed by any
    client name a peer ``host:port``; a journal whose ``meta.listen``
    port matches is shifted by ``-offset`` onto the observer's clock.
    Stems without a measured offset shift by 0 (already local)."""
    if isinstance(run_dirs, str):
        run_dirs = [run_dirs]
    journals: list[tuple[str, list[dict]]] = []
    for d in run_dirs:
        spans_dir = os.path.join(d, "spans")
        if not os.path.isdir(spans_dir):
            continue
        for name in sorted(os.listdir(spans_dir)):
            if name.endswith(".jsonl"):
                journals.append(
                    (name[:-len(".jsonl")],
                     dtrace.read_journal(os.path.join(spans_dir, name))))
    offsets: dict[str, float] = {}
    for _stem, recs in journals:
        for r in recs:
            if r.get("type") == "clock" and r.get("peer"):
                port = str(r["peer"]).rpartition(":")[2]
                offsets[port] = float(r.get("offset_s", 0.0))
    shifts: dict[str, float] = {}
    for stem, recs in journals:
        shift = 0.0
        for r in recs:
            if r.get("type") == "meta" and r.get("listen"):
                port = str(r["listen"]).rpartition(":")[2]
                if port in offsets:
                    shift = -offsets[port]
                break
        shifts[stem] = shift
    return shifts, offsets


# ---------------------------------------------------------------------------
# per-artifact-family collectors -> one event schema
# ---------------------------------------------------------------------------
# every collector returns events {"t": shifted_wall_s, "kind": ...,
# "src": journal-stem-or-file, ...detail}


def _collect_logs(run_dirs, shifts, t_lo, t_hi) -> list[dict]:
    events = []
    for rec in fleetlog.read_records(run_dirs, level="warning"):
        stem = f"{rec.get('role', '?')}-{rec.get('rank', '?')}"
        t = float(rec.get("ts", 0.0)) + shifts.get(stem, 0.0)
        if not t_lo <= t <= t_hi:
            continue
        ev = {"t": t, "kind": "log", "src": stem,
              "level": rec.get("level"), "logger": rec.get("logger"),
              "msg": rec.get("msg")}
        for k in ("trace", "span", "suppressed"):
            if rec.get(k) is not None:
                ev[k] = rec[k]
        events.append(ev)
    return events


def _collect_flight_dumps(run_dirs, shifts, seqs) -> list[dict]:
    """The incident's own flight dumps: ``flightrec/<stem>-<seq>.json``
    for that run dir's trigger seq, matched by seq (not window — they
    ARE the incident's artifacts)."""
    events = []
    for d, seq in zip(run_dirs, seqs):
        fdir = os.path.join(d, "flightrec")
        if seq is None or not os.path.isdir(fdir):
            continue
        suffix = f"-{seq}.json"
        for name in sorted(os.listdir(fdir)):
            if not name.endswith(suffix) or name == dtrace.TRIGGER_NAME:
                continue
            path = os.path.join(fdir, name)
            try:
                with open(path) as f:
                    doc = json.load(f)
            except (OSError, ValueError):
                continue
            stem = f"{doc.get('role', '?')}-{doc.get('rank', '?')}"
            ev = {"t": float(doc.get("dumped_at", 0.0))
                  + shifts.get(stem, 0.0),
                  "kind": "flight_dump", "src": stem, "path": path,
                  "reason": doc.get("reason"),
                  "spans": len(doc.get("spans") or [])}
            for k in ("log_journal", "profile_journal"):
                if doc.get(k):
                    ev[k] = doc[k]
            events.append(ev)
    return events


def _collect_bursts(run_dirs, shifts, seqs) -> list[dict]:
    """PR-9 burst windows stamped with this incident's seq."""
    events = []
    for d, seq in zip(run_dirs, seqs):
        pdir = os.path.join(d, "profiles")
        if seq is None or not os.path.isdir(pdir):
            continue
        for name in sorted(os.listdir(pdir)):
            if not name.endswith(".jsonl"):
                continue
            path = os.path.join(pdir, name)
            for doc in dtrace.read_journal(path):
                if doc.get("type") != "profwindow" \
                        or doc.get("kind") != "burst" \
                        or doc.get("incident") != seq:
                    continue
                stem = f"{doc.get('role', '?')}-{doc.get('rank', '?')}"
                events.append({
                    "t": float(doc.get("t1", 0.0)) + shifts.get(stem, 0.0),
                    "kind": "profiler_burst", "src": stem, "path": path,
                    "reason": doc.get("reason"),
                    "hz": doc.get("hz"), "samples": doc.get("samples"),
                })
    return events


def _collect_chaos(run_dirs, shifts, t_lo, t_hi) -> list[dict]:
    """Chaos-proxy fault instants out of the spans journals (``ts`` is
    trace microseconds)."""
    events = []
    for d in ([run_dirs] if isinstance(run_dirs, str) else run_dirs):
        spans_dir = os.path.join(d, "spans")
        if not os.path.isdir(spans_dir):
            continue
        for name in sorted(os.listdir(spans_dir)):
            if not name.endswith(".jsonl"):
                continue
            stem = name[:-len(".jsonl")]
            for r in dtrace.read_journal(os.path.join(spans_dir, name)):
                if r.get("type") != "instant" \
                        or not str(r.get("name", "")).startswith("chaos."):
                    continue
                t = float(r.get("ts", 0.0)) / 1e6 + shifts.get(stem, 0.0)
                if not t_lo <= t <= t_hi:
                    continue
                events.append({"t": t, "kind": "chaos", "src": stem,
                               "fault": r.get("name"),
                               "args": dict(r.get("args") or {})})
    return events


def _collect_autopilot(run_dirs, t_lo, t_hi) -> list[dict]:
    """PR-16 autopilot decisions (journaled on the observer's clock —
    the daemon runs beside obs-agg, no shift needed).  ``ts`` is the
    journal line's wall anchor; ``t`` is the policy clock (monotonic
    in production), accepted as a fallback for synthetic fixtures that
    stamp epoch seconds directly."""
    events = []
    for d in ([run_dirs] if isinstance(run_dirs, str) else run_dirs):
        path = os.path.join(d, "autopilot", "decisions.jsonl")
        for doc in dtrace.read_journal(path):
            t = float(doc.get("ts", doc.get("t", 0.0)))
            if not t_lo <= t <= t_hi:
                continue
            ev = {"t": t, "kind": "autopilot", "src": "autopilot"}
            for k in ("rule", "action", "outcome"):
                if doc.get(k) is not None:
                    ev[k] = doc[k]
            events.append(ev)
    return events


def _collect_rollout(run_dirs, t_lo, t_hi) -> list[dict]:
    """PR-10 rollout ramp transitions (stage/abort/rollback/promoted)."""
    events = []
    for d in ([run_dirs] if isinstance(run_dirs, str) else run_dirs):
        rdir = os.path.join(d, "rollout")
        if not os.path.isdir(rdir):
            continue
        for name in sorted(os.listdir(rdir)):
            if not name.endswith(".jsonl"):
                continue
            for doc in dtrace.read_journal(os.path.join(rdir, name)):
                t = float(doc.get("t", 0.0))
                if not t_lo <= t <= t_hi:
                    continue
                ev = {"t": t, "kind": "rollout", "src": name,
                      "event": doc.get("event")}
                for k, v in doc.items():
                    if k not in ("t", "event"):
                        ev[k] = v
                events.append(ev)
    return events


# ---------------------------------------------------------------------------
# assembly
# ---------------------------------------------------------------------------

def bundle_dir(run_dir: str, seq: int) -> str:
    return os.path.join(run_dir, "incidents", f"{int(seq):04d}")


def assemble(run_dirs, *, seq: int, reason: str,
             detected_ts: float | None = None,
             alerts: list | None = None, slo: dict | None = None,
             per_dir_seqs: list | None = None,
             window_s: float = WINDOW_S, settle_s: float = SETTLE_S,
             tsdb=None, trigger: str = "alert") -> str | None:
    """Assemble ONE bundle for trigger sequence ``seq`` under
    ``run_dirs[0]/incidents/``.  Idempotent by construction: an
    existing bundle dir for the seq returns ``None`` untouched — the
    exactly-one-bundle-per-incident contract while an alert stays
    firing.  ``per_dir_seqs`` carries each federated run dir's own
    trigger seq (they advance independently); defaults to ``seq`` for
    every dir."""
    if isinstance(run_dirs, str):
        run_dirs = [run_dirs]
    out = bundle_dir(run_dirs[0], seq)
    if os.path.isdir(out):
        return None
    if detected_ts is None:
        detected_ts = time.time()
    if per_dir_seqs is None:
        per_dir_seqs = [seq] * len(run_dirs)
    t_lo = detected_ts - float(window_s)
    t_hi = detected_ts + float(settle_s)

    shifts, offsets = clock_shifts(run_dirs)
    events = [{"t": detected_ts, "kind": "alert_edge", "src": "obs-agg",
               "reason": reason,
               "alerts": [a.get("name") for a in (alerts or [])
                          if a.get("firing")]}]
    events += _collect_chaos(run_dirs, shifts, t_lo, t_hi)
    events += _collect_logs(run_dirs, shifts, t_lo, t_hi)
    events += _collect_flight_dumps(run_dirs, shifts, per_dir_seqs)
    events += _collect_bursts(run_dirs, shifts, per_dir_seqs)
    events += _collect_autopilot(run_dirs, t_lo, t_hi)
    events += _collect_rollout(run_dirs, t_lo, t_hi)
    events.sort(key=lambda e: (e.get("t", 0.0), e.get("kind", "")))

    tmp = f"{out}.{os.getpid()}.tmp"
    os.makedirs(tmp, exist_ok=True)
    with open(os.path.join(tmp, "timeline.jsonl"), "w") as f:
        for ev in events:
            ev = dict(ev)
            ev["t"] = round(float(ev["t"]), 6)
            f.write(json.dumps(ev) + "\n")

    if tsdb is not None:
        try:
            snap = tsdb.window_snapshot(t_lo, t_hi)
        except Exception:  # noqa: BLE001 — a bundle beats a perfect bundle
            snap = {}
        with open(os.path.join(tmp, "tsdb.json"), "w") as f:
            json.dump({"window": [round(t_lo, 3), round(t_hi, 3)],
                       "series": snap}, f, indent=1)

    kinds: dict[str, int] = {}
    for ev in events:
        kinds[ev["kind"]] = kinds.get(ev["kind"], 0) + 1
    doc = {
        "seq": int(seq),
        "reason": str(reason),
        "trigger": trigger,
        "detected_ts": round(float(detected_ts), 3),
        "window": [round(t_lo, 3), round(t_hi, 3)],
        "alerts": alerts or [],
        "slo": slo or {},
        "run_dirs": [os.path.abspath(d) for d in run_dirs],
        "per_dir_seqs": list(per_dir_seqs),
        "clock_offsets": offsets,
        "clock_shifts": {k: v for k, v in shifts.items() if v},
        "events": kinds,
        "flight_dumps": [e["path"] for e in events
                         if e["kind"] == "flight_dump"],
        "bursts": [e["path"] for e in events
                   if e["kind"] == "profiler_burst"],
    }
    with open(os.path.join(tmp, "incident.json"), "w") as f:
        json.dump(doc, f, indent=1)
    _render_postmortem(tmp, doc, events)
    try:
        os.rename(tmp, out)
    except OSError:
        # a concurrent assembler won the rename: exactly one bundle
        import shutil  # noqa: PLC0415

        shutil.rmtree(tmp, ignore_errors=True)
        return None
    _BUNDLES.labels(trigger=trigger).inc()
    for k, n in kinds.items():
        _EVENTS.labels(kind=k).inc(n)
    logger.warning("incident %04d (%s): bundle assembled -> %s "
                   "(%d events)", seq, reason, out, len(events))
    return out


# ---------------------------------------------------------------------------
# postmortem rendering
# ---------------------------------------------------------------------------

def _fmt_t(t: float) -> str:
    return time.strftime("%H:%M:%S", time.localtime(t)) \
        + f".{int((t % 1) * 1000):03d}"


def _event_line(ev: dict) -> str:
    k = ev["kind"]
    if k == "alert_edge":
        return f"alert edge: **{ev.get('reason')}** fired"
    if k == "chaos":
        args = ev.get("args") or {}
        link = args.get("link", "?")
        return f"chaos fault `{ev.get('fault')}` on link `{link}`"
    if k == "log":
        extra = f" (x{ev['suppressed']} suppressed)" \
            if ev.get("suppressed") else ""
        return f"{ev.get('level', '?').upper()} " \
               f"`{ev.get('logger')}`: {ev.get('msg')}{extra}"
    if k == "flight_dump":
        return f"flight dump ({ev.get('spans')} spans, " \
               f"reason `{ev.get('reason')}`) -> `{ev.get('path')}`"
    if k == "profiler_burst":
        return f"profiler burst ({ev.get('samples')} samples @ " \
               f"{ev.get('hz')} Hz) -> `{ev.get('path')}`"
    if k == "autopilot":
        act = ev.get("action") or {}
        what = f"{act.get('actuator', '?')} {act.get('direction', '?')} " \
               f"-> {act.get('to', '?')}" if act else "?"
        return f"autopilot `{ev.get('rule')}`: {what} " \
               f"({ev.get('outcome', '?')})"
    if k == "rollout":
        detail = {kk: vv for kk, vv in ev.items()
                  if kk not in ("t", "kind", "src", "event")}
        return f"rollout `{ev.get('event')}` {detail or ''}".rstrip()
    return json.dumps({kk: vv for kk, vv in ev.items() if kk != "t"})


def _render_postmortem(out_dir: str, doc: dict, events: list) -> str:
    t0 = doc["detected_ts"]
    lines = [
        f"# Incident {doc['seq']:04d} — {doc['reason']}",
        "",
        f"*Auto-generated postmortem skeleton; detected "
        f"{time.strftime('%Y-%m-%d %H:%M:%S', time.localtime(t0))} "
        f"(bundle window {doc['window'][0]:.0f}..{doc['window'][1]:.0f}).*",
        "",
        "## Detection",
        "",
    ]
    firing = [a for a in doc.get("alerts", []) if a.get("firing")]
    if firing:
        for a in firing:
            labels = a.get("labels") or {}
            lab = " ".join(f"{k}={v}" for k, v in sorted(labels.items()))
            lines.append(f"- alert **{a.get('name')}**"
                         + (f" ({lab})" if lab else "")
                         + (f" — {a.get('detail')}" if a.get("detail")
                            else ""))
    else:
        lines.append(f"- trigger: {doc.get('trigger')} ({doc['reason']})")
    slo = doc.get("slo") or {}
    for s in slo.get("slos", []) if isinstance(slo, dict) else []:
        lines.append(
            f"- SLO `{s.get('name')}`: budget_remaining="
            f"{s.get('budget_remaining')} burn={s.get('burn', s)}")
    if doc.get("clock_shifts"):
        lines.append("- clock shifts applied: "
                     + ", ".join(f"`{k}` {v:+.3f}s" for k, v in
                                 sorted(doc["clock_shifts"].items())))
    n_by = doc.get("events", {})
    lines += [
        "",
        "## Evidence",
        "",
        f"- {n_by.get('log', 0)} WARN+ log records from "
        f"{len({e['src'] for e in events if e['kind'] == 'log'})} ranks "
        "(`timeline.jsonl`, kind=log)",
        f"- {n_by.get('flight_dump', 0)} flight dumps: "
        + (", ".join(f"`{p}`" for p in doc.get("flight_dumps", []))
           or "none"),
        f"- {n_by.get('profiler_burst', 0)} profiler bursts: "
        + (", ".join(f"`{p}`" for p in doc.get("bursts", [])) or "none"),
        f"- {n_by.get('chaos', 0)} chaos fault events",
        "- headline series around the edge: `tsdb.json`",
        "",
        "## Actions taken",
        "",
    ]
    actions = [e for e in events if e["kind"] in ("autopilot", "rollout")]
    if actions:
        for ev in actions:
            lines.append(f"- `{_fmt_t(ev['t'])}` ({ev['t'] - t0:+.1f}s) "
                         + _event_line(ev))
    else:
        lines.append("- none recorded in the window")
    lines += [
        "",
        "## Timeline",
        "",
        "| t | Δ | src | event |",
        "|---|---|-----|-------|",
    ]
    for ev in events:
        lines.append(f"| {_fmt_t(ev['t'])} | {ev['t'] - t0:+.1f}s "
                     f"| {ev.get('src', '?')} | {_event_line(ev)} |")
    lines.append("")
    path = os.path.join(out_dir, "POSTMORTEM.md")
    with open(path, "w") as f:
        f.write("\n".join(lines))
    return path


# ---------------------------------------------------------------------------
# reading + retention + the `launch incident` verbs
# ---------------------------------------------------------------------------

def list_incidents(run_dir: str) -> list[dict]:
    """Every bundle under ``<run_dir>/incidents/``, oldest first."""
    root = os.path.join(run_dir, "incidents")
    out = []
    if not os.path.isdir(root):
        return out
    for name in sorted(os.listdir(root)):
        path = os.path.join(root, name, "incident.json")
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        doc["path"] = os.path.join(root, name)
        out.append(doc)
    return out


def latest_seq(run_dir: str) -> int | None:
    """Newest bundle seq (what the `launch top` ``inc`` column shows
    while its alert is still firing)."""
    incidents = list_incidents(run_dir)
    return incidents[-1]["seq"] if incidents else None


def load(run_dir: str, seq: int) -> dict | None:
    """One bundle: its ``incident.json`` plus parsed timeline."""
    d = bundle_dir(run_dir, seq)
    try:
        with open(os.path.join(d, "incident.json")) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    doc["path"] = d
    doc["timeline"] = dtrace.read_journal(
        os.path.join(d, "timeline.jsonl"))
    return doc


def render(run_dir: str, seq: int) -> str | None:
    """(Re-)render a bundle's POSTMORTEM.md from its journaled facts."""
    doc = load(run_dir, seq)
    if doc is None:
        return None
    return _render_postmortem(doc["path"], doc, doc["timeline"])


def prune(run_dir: str, keep: int) -> int:
    """Retention: drop the oldest bundles beyond ``keep`` — loudly,
    via ``distlr_incident_pruned_total`` and a WARNING record."""
    import shutil  # noqa: PLC0415

    incidents = list_incidents(run_dir)
    removed = 0
    for doc in incidents[:max(0, len(incidents) - int(keep))]:
        shutil.rmtree(doc["path"], ignore_errors=True)
        _PRUNED.inc()
        removed += 1
        logger.warning("incident %04d pruned by incident_max=%d retention",
                       doc.get("seq", -1), keep)
    return removed


def manual_trigger(run_dirs, reason: str = "manual", *,
                   window_s: float = WINDOW_S, settle_s: float = SETTLE_S,
                   tsdb=None, wait: bool = True) -> str | None:
    """The ``launch incident --trigger`` path: bump every run dir's
    flight-recorder trigger (dumps rings AND fires profiler bursts —
    the PR 8/9 machinery), wait out the settle window so those
    artifacts land, then assemble.  Returns the bundle path."""
    if isinstance(run_dirs, str):
        run_dirs = [run_dirs]
    detected = time.time()
    seqs = []
    for d in run_dirs:
        dtrace.trigger(d, alert=reason)
        try:
            with open(os.path.join(d, "flightrec",
                                   dtrace.TRIGGER_NAME)) as f:
                seqs.append(int(json.load(f).get("seq", 0)))
        except (OSError, ValueError):
            seqs.append(None)
    if wait:
        time.sleep(float(settle_s))
    return assemble(run_dirs, seq=seqs[0] if seqs and seqs[0] is not None
                    else 0, reason=reason, detected_ts=detected,
                    per_dir_seqs=seqs, window_s=window_s,
                    settle_s=settle_s, tsdb=tsdb, trigger="manual")
