"""JAX runtime introspection -> the metrics registry (ISSUE 9).

The sampling profiler (:mod:`distlr_tpu.obs.profile`) sees Python
frames; what it cannot see is the JAX runtime underneath them — a
recompile storm (every new batch shape costs a fresh XLA compile) reads
as "time in ``jit`` dispatch", and HBM pressure is invisible entirely.
This module exports the two runtime signals that close that gap:

* **compile / trace-cache misses** — :class:`JitCacheProbe` wraps one
  jitted callable's executable cache (``_cache_size()``) and diffs it
  per tick into ``distlr_jax_compiles_total{site,bucket}``: a steadily
  ticking counter IS the recompile storm (the serving engine labels the
  batch bucket that triggered each one, so "bucket 1024 keeps
  recompiling" is one scrape away).
* **live device buffers** — :func:`sample_device_bytes` sums
  ``jax.live_arrays()`` into ``distlr_jax_device_buffer_bytes`` /
  ``distlr_jax_live_buffers`` gauges.  Walking every live array has a
  real cost, so call sites use :func:`maybe_sample_device_bytes` —
  throttled to one walk per ``min_interval_s`` process-wide.

This module imports jax and therefore lives OUTSIDE the jax-free core
of ``obs`` — only jax-using call sites (engine, trainers) import it;
the router, obs-agg, prof-agg, and top stay jax-free.
"""

from __future__ import annotations

import threading
import time

import jax

from distlr_tpu.obs.registry import get_registry

_reg = get_registry()
_COMPILES = _reg.counter(
    "distlr_jax_compiles_total",
    "XLA compiles (jit executable-cache misses) by instrumented call "
    "site; the serving engine labels the padded-batch bucket that "
    "triggered each one",
    labelnames=("site", "bucket"),
)
_DEVICE_BYTES = _reg.gauge(
    "distlr_jax_device_buffer_bytes",
    "bytes held by live jax arrays at the last introspection walk "
    "(device HBM on accelerators; host RAM on the CPU backend)",
)
_LIVE_BUFFERS = _reg.gauge(
    "distlr_jax_live_buffers",
    "live jax arrays at the last introspection walk",
)

_lock = threading.Lock()
_last_walk = 0.0


class JitCacheProbe:
    """Diff one jitted callable's executable-cache size into the
    compile counter.  ``tick()`` after a call (or a batch of calls)
    attributes any cache growth since the last tick to the given
    bucket — cache sizes are cumulative, so throttled ticking never
    loses a compile, it only coarsens the attribution."""

    def __init__(self, jitfn, site: str):
        self._fn = jitfn
        self.site = str(site)
        self._tick_lock = threading.Lock()
        self._seen = self._size()

    def _size(self) -> int:
        try:
            return int(self._fn._cache_size())
        except Exception:  # noqa: BLE001 — private API; absent = opt out
            return 0

    def tick(self, bucket: str | int = "-") -> int:
        """Record compiles since the last tick under ``bucket``;
        returns the delta.  Locked: the serve probe is process-shared,
        and two scoring threads ticking after one recompile must not
        both claim the same cache-size delta."""
        with self._tick_lock:
            size = self._size()
            delta = size - self._seen
            if delta <= 0:
                return 0
            self._seen = size
        _COMPILES.labels(site=self.site, bucket=str(bucket)).inc(delta)
        return delta


def sample_device_bytes() -> int:
    """Walk ``jax.live_arrays()`` now and publish the gauges; returns
    the byte total."""
    global _last_walk
    try:
        arrays = jax.live_arrays()
        total = sum(int(a.nbytes) for a in arrays)
        n = len(arrays)
    except Exception:  # noqa: BLE001 — introspection must never fail work
        return 0
    _DEVICE_BYTES.set(total)
    _LIVE_BUFFERS.set(n)
    with _lock:
        _last_walk = time.monotonic()
    return total


def maybe_sample_device_bytes(min_interval_s: float = 5.0) -> None:
    """Throttled :func:`sample_device_bytes` — the form hot loops call:
    one live-array walk per interval process-wide, however many call
    sites tick it."""
    with _lock:
        due = time.monotonic() - _last_walk >= min_interval_s
    if due:
        sample_device_bytes()
