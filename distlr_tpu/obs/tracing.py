"""Phase spans — per-step timing breakdown + Chrome trace-event dumps.

Every trainer step in this repo is a pipeline of phases (data load,
host->device, compute, pull, push, barrier wait, weight swap, eval) and
every perf question — "why is the async run slower?", "did the prefetch
actually overlap?" — is a question about where the time went *between*
them.  ``trace_phase("pull")`` wraps a block; each span is

* accumulated into a per-phase (total seconds, count) breakdown that
  survives any event-buffer cap — this is what ``bench.py``'s
  ``phase_breakdown`` and the ROADMAP's on-chip captures report; and
* recorded into the registry histogram ``distlr_phase_seconds{phase=}``
  so the /metrics scrape carries the same story; and
* appended (bounded) as a Chrome trace event, dumpable as JSON that
  loads directly in Perfetto / ``chrome://tracing``.

Spans may run concurrently on many threads (prefetch producer, PS comm
thread, microbatch flusher, N Hogwild workers); each event carries its
thread id so the trace shows real overlap, not an interleaved fiction.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time

from distlr_tpu.obs.registry import MetricsRegistry, get_registry

#: Bounded event buffer: a long training run must not grow without limit.
#: At ~100 B/event this caps trace memory near 20 MB; the per-phase
#: breakdown keeps aggregating past the cap (only the *timeline* truncates,
#: and the dump records how many events were dropped).
MAX_TRACE_EVENTS = 200_000


class PhaseTracer:
    """Thread-safe span recorder with Chrome trace export."""

    def __init__(self, registry: MetricsRegistry | None = None,
                 max_events: int = MAX_TRACE_EVENTS):
        self._registry = registry or get_registry()
        self._max_events = max_events
        self._lock = threading.Lock()
        self._events: list[tuple[str, int, float, float]] = []
        self._dropped = 0
        self._totals: dict[str, list] = {}  # phase -> [seconds, count]
        self._epoch = time.perf_counter()
        self._hist = self._registry.histogram(
            "distlr_phase_seconds",
            "wall seconds spent per pipeline phase",
            labelnames=("phase",),
        )

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            t1 = time.perf_counter()
            dur = t1 - t0
            self._hist.labels(phase=name).observe(dur)
            tid = threading.get_ident()
            with self._lock:
                tot = self._totals.get(name)
                if tot is None:
                    self._totals[name] = [dur, 1]
                else:
                    tot[0] += dur
                    tot[1] += 1
                if len(self._events) < self._max_events:
                    self._events.append((name, tid, t0 - self._epoch, dur))
                else:
                    self._dropped += 1

    def breakdown(self) -> dict[str, dict]:
        """``{phase: {"seconds", "count"}}`` accumulated since reset."""
        with self._lock:
            return {
                name: {"seconds": round(sec, 6), "count": count}
                for name, (sec, count) in sorted(self._totals.items())
            }

    def phase_names(self) -> set[str]:
        with self._lock:
            return set(self._totals)

    def reset(self) -> None:
        with self._lock:
            self._events.clear()
            self._totals.clear()
            self._dropped = 0
            self._epoch = time.perf_counter()

    # -- Chrome trace-event export ---------------------------------------
    def chrome_trace(self) -> dict:
        """Trace-event JSON object (``ph: "X"`` complete events, us
        timestamps) — loadable in Perfetto / chrome://tracing."""
        pid = os.getpid()
        with self._lock:
            events = [
                {
                    "name": name,
                    "cat": "phase",
                    "ph": "X",
                    "pid": pid,
                    "tid": tid,
                    "ts": round(t0 * 1e6, 3),
                    "dur": round(dur * 1e6, 3),
                }
                for name, tid, t0, dur in self._events
            ]
            dropped = self._dropped
        doc = {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {"producer": "distlr_tpu.obs", "pid": pid},
        }
        if dropped:
            doc["otherData"]["dropped_events"] = dropped
        return doc

    def dump_chrome_trace(self, path: str) -> str:
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.chrome_trace(), f)
        os.replace(tmp, path)
        return path


_TRACER = PhaseTracer()


def get_tracer() -> PhaseTracer:
    """The process-wide tracer every instrumented loop records into."""
    return _TRACER


def trace_phase(name: str):
    """``with trace_phase("compute"): ...`` on the default tracer."""
    return _TRACER.phase(name)
