"""``docs/METRICS.md`` generator + drift lint for the ``distlr_*``
metric namespace (ISSUE 8 satellite).

The namespace has grown PR over PR (ps client/server, trainer, serve,
route, feedback, chaos, fleet/alert, trace) with no single reference —
and nothing stopped a new series from shipping undocumented.  Two
pieces close that:

* :func:`collect_registrations` — a STATIC scan (``ast``, no imports:
  jax-heavy modules stay unimported and the scan sees every series even
  ones only registered on rare code paths) of every
  ``<registry>.counter/gauge/histogram("distlr_...", "help", ...)``
  call under ``distlr_tpu/``, keeping name, kind, label names, help
  text, and the defining module.
* :func:`generate` — renders those into ``docs/METRICS.md`` grouped by
  namespace prefix.

The tier-1 lint (``tests/test_metrics_doc.py``) runs the same scan plus
a raw ``distlr_[a-z0-9_]+`` string-literal grep over the sources and
fails when either direction drifts: a series emitted but missing from
the doc, or a doc entry whose series no longer exists.

Regenerate after adding/removing a series::

    python -m distlr_tpu.obs.metrics_doc        # rewrites docs/METRICS.md
    python -m distlr_tpu.obs.metrics_doc --check  # lint only (exit 1 on drift)
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
import sys

#: registry factory method -> metric kind
_KINDS = {"counter": "counter", "gauge": "gauge", "histogram": "histogram"}

#: ``distlr_``-prefixed string literals that are NOT metric series
#: (binary/package names, doc prose); the literal grep skips these.
NON_METRIC_LITERALS = frozenset({
    "distlr_tpu",
    "distlr_kv",          # native lib stem (libdistlr_kv.so)
    "distlr_kv_server",   # native server binary name
    "distlr_kv_server_tsan",
    "distlr_x_total",     # registry docstring example
})


@dataclasses.dataclass(frozen=True)
class Registration:
    name: str
    kind: str
    labels: tuple[str, ...]
    help: str
    module: str


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def _iter_py(pkg_dir: str):
    for dirpath, dirnames, filenames in os.walk(pkg_dir):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def _const_str(node) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _tuple_strs(node) -> tuple[str, ...]:
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for el in node.elts:
            s = _const_str(el)
            if s is None:
                return ()
            out.append(s)
        return tuple(out)
    return ()


def collect_registrations(pkg_dir: str | None = None) -> list[Registration]:
    """Every ``.counter/.gauge/.histogram("distlr_...", ...)`` call
    under the package, statically."""
    pkg_dir = pkg_dir or os.path.join(repo_root(), "distlr_tpu")
    found: dict[str, Registration] = {}
    for path in _iter_py(pkg_dir):
        with open(path) as f:
            try:
                tree = ast.parse(f.read(), filename=path)
            except SyntaxError:
                continue
        module = os.path.relpath(path, repo_root())
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _KINDS and node.args):
                continue
            name = _const_str(node.args[0])
            if name is None or not name.startswith("distlr_"):
                continue
            help_text = (_const_str(node.args[1])
                         if len(node.args) > 1 else None) or ""
            labels: tuple[str, ...] = ()
            if len(node.args) > 2:
                labels = _tuple_strs(node.args[2])
            for kw in node.keywords:
                if kw.arg == "labelnames":
                    labels = _tuple_strs(kw.value)
            prev = found.get(name)
            if prev is None or (not prev.help and help_text):
                found[name] = Registration(
                    name=name, kind=_KINDS[node.func.attr], labels=labels,
                    help=" ".join(help_text.split()), module=module)
    return sorted(found.values(), key=lambda r: r.name)


def collect_literals(pkg_dir: str | None = None) -> dict[str, list[str]]:
    """Every ``distlr_[a-z0-9_]+`` string literal in the package (the
    grep half of the lint) -> the modules mentioning it.  Catches a
    series emitted through a name the AST scan cannot see (f-strings,
    concatenation) — those should be rare and documented by hand."""
    pkg_dir = pkg_dir or os.path.join(repo_root(), "distlr_tpu")
    pat = re.compile(r'"(distlr_[a-z0-9_]+)"')
    out: dict[str, list[str]] = {}
    for path in _iter_py(pkg_dir):
        module = os.path.relpath(path, repo_root())
        with open(path) as f:
            for name in pat.findall(f.read()):
                # a trailing underscore names a namespace PREFIX used in
                # prose/format strings ("distlr_alert_" + name), never a
                # series
                if name in NON_METRIC_LITERALS or name.endswith("_"):
                    continue
                out.setdefault(name, [])
                if module not in out[name]:
                    out[name].append(module)
    return out


#: namespace prefix -> section heading, in render order
_SECTIONS = (
    ("distlr_ps_", "Parameter server (client + server lifecycle)"),
    ("distlr_train_", "Training loops"),
    ("distlr_serve_", "Serving tier (engine / batcher / front-end)"),
    ("distlr_route_", "Routing front-end"),
    ("distlr_feedback_", "Feedback loop (spool / join / online trainer)"),
    ("distlr_chaos_", "Chaos fault injection"),
    ("distlr_fleet_", "Fleet federation meta-series"),
    ("distlr_tsdb_", "Embedded fleet time-series store"),
    ("distlr_slo_", "SLO engine (error budgets / burn rates)"),
    ("distlr_alert_", "Derived alert gauges"),
    ("distlr_autopilot_", "Fleet autopilot (closed-loop scaling)"),
    ("distlr_log_", "Structured fleet logging"),
    ("distlr_incident_", "Incident engine (bundles / postmortems)"),
    ("distlr_trace_", "Distributed tracing"),
    ("distlr_prof_", "Continuous profiling"),
    ("distlr_jax_", "JAX runtime introspection"),
    ("distlr_kv_server_", "Native KV-server runtime"),
    ("distlr_phase_", "Phase tracing"),
)


def generate(regs: list[Registration] | None = None) -> str:
    regs = collect_registrations() if regs is None else regs
    lines = [
        "# distlr_* metric reference",
        "",
        "Every Prometheus series the fleet emits, one row per family.",
        "GENERATED — do not edit by hand:",
        "",
        "    python -m distlr_tpu.obs.metrics_doc",
        "",
        "regenerates this file from the registration sites; the tier-1",
        "lint (`tests/test_metrics_doc.py`) fails the build when code and",
        "doc drift in either direction.  Scrape endpoints: every launch",
        "subcommand serves `/metrics` (+ `/metrics.json`) with",
        "`--metrics-port`/`--obs-run-dir`; `launch obs-agg` federates the",
        "fleet (counters sum, histograms merge, gauges gain role/rank).",
        "",
    ]
    used: set[str] = set()
    for prefix, title in _SECTIONS:
        rows = [r for r in regs
                if r.name.startswith(prefix) and r.name not in used]
        if not rows:
            continue
        used.update(r.name for r in rows)
        lines += [f"## {title}", "",
                  "| series | kind | labels | meaning |",
                  "|---|---|---|---|"]
        for r in rows:
            labels = ", ".join(r.labels) if r.labels else "—"
            lines.append(
                f"| `{r.name}` | {r.kind} | {labels} | {r.help} |")
        lines.append("")
    rest = [r for r in regs if r.name not in used]
    if rest:
        lines += ["## Other", "",
                  "| series | kind | labels | meaning |",
                  "|---|---|---|---|"]
        for r in rest:
            labels = ", ".join(r.labels) if r.labels else "—"
            lines.append(
                f"| `{r.name}` | {r.kind} | {labels} | {r.help} |")
        lines.append("")
    return "\n".join(lines)


def doc_path() -> str:
    return os.path.join(repo_root(), "docs", "METRICS.md")


def documented_names(text: str | None = None) -> set[str]:
    if text is None:
        try:
            with open(doc_path()) as f:
                text = f.read()
        except OSError:
            return set()
    return set(re.findall(r"`(distlr_[a-z0-9_]+)`", text))


def check() -> list[str]:
    """Both lint directions; returns human-readable problems ([] = ok)."""
    regs = collect_registrations()
    reg_names = {r.name for r in regs}
    doc = documented_names()
    problems = []
    for r in regs:
        if r.name not in doc:
            problems.append(
                f"undocumented series {r.name} (registered in {r.module}) "
                "— regenerate docs/METRICS.md")
    for name, modules in sorted(collect_literals().items()):
        # a literal that is neither a registered family nor a child/
        # documented name is either an emission the AST scan missed or
        # a typo'd reference — both are drift
        if name not in reg_names and name not in doc:
            problems.append(
                f"string literal {name!r} in {modules[0]} matches no "
                "registered or documented series (typo, or add it to "
                "NON_METRIC_LITERALS if it is not a metric)")
    for name in sorted(doc - reg_names):
        problems.append(
            f"docs/METRICS.md documents {name} but no registration site "
            "exists — regenerate the doc")
    return problems


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if "--check" in argv:
        problems = check()
        for p in problems:
            print(f"METRICS LINT: {p}", file=sys.stderr)
        return 1 if problems else 0
    path = doc_path()
    os.makedirs(os.path.dirname(path), exist_ok=True)
    text = generate()
    with open(path, "w") as f:
        f.write(text)
    print(f"wrote {path} ({len(documented_names(text))} series)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
