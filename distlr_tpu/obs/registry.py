"""Process-wide metrics registry — labeled counters, gauges, histograms.

The reference emits exactly one metric ever (rank-0 accuracy on stdout,
``src/lr.cc:56-62``); before this module our reproduction was barely
better — per-trainer private loggers, zero PS-side counters, and a
hand-rolled percentile deque in the serving front-end.  This is the one
shared sink every layer writes to: the PS server supervisor, the native
client wrapper, both trainer loops, the microbatcher, and the serving
front-end all run threads that record concurrently, so every update is
lock-protected (exact counts under contention are a test contract,
``tests/test_obs.py``).

Model mirrors the Prometheus client library:

* a *family* is a named metric with a fixed label-name tuple
  (``registry.counter("distlr_x_total", "help", labelnames=("op",))``);
* ``family.labels(op="push")`` resolves one *child* (the time series);
  families declared with no label names act as their own child, so
  ``family.inc()`` works directly;
* declaring the same family twice returns the existing one (call sites
  in different modules may race to declare) — a type/label mismatch
  raises instead of silently aliasing two meanings onto one name.

Histograms use FIXED buckets (cumulative, Prometheus semantics): no
per-observation storage, so a million RPCs cost the same memory as ten.
``Histogram.percentile`` interpolates within the owning bucket — the
serving STATS p50/p99 now answer from this instead of a raw-sample deque.
"""

from __future__ import annotations

import bisect
import threading
import time

#: Default latency ladder, seconds.  Spans 100 us (jit dispatch, localhost
#: RPC) to 10 s (full-test-set eval, cold compile) — the ranges measured
#: across this repo's phases.
DEFAULT_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

#: Ladder for count-valued histograms (e.g. pushes-behind staleness):
#: 0 = perfectly fresh, then doublings to deeply stale, with one wide
#: 4096 top bucket.  Shared as a constant because the fleet merge
#: rejects mismatched boundary ladders — two call sites retuning the
#: "same" metric independently would drop it from every federated view.
COUNT_BUCKETS = (
    0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0, 512.0,
    1024.0, 4096.0,
)


def percentile_from_counts(bounds: tuple[float, ...], counts,
                           q: float) -> float:
    """q-quantile (q in [0, 1]) by linear interpolation inside the
    owning bucket, over decomposed per-bucket counts (last slot =
    +Inf).  Observations past the top bucket clamp to the largest
    finite boundary — fixed buckets trade tail resolution for O(1)
    memory; widen the ladder if the tail matters.  ONE implementation,
    shared by live histogram children and the fleet aggregator's
    snapshot math, so /metrics and /fleet.json can never disagree on
    the same data."""
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"quantile must be in [0, 1], got {q}")
    total = sum(counts)
    if total == 0:
        return 0.0
    rank, cum = q * total, 0.0
    for i, c in enumerate(counts[:-1]):
        prev_cum = cum
        cum += c
        if cum >= rank:
            lo = bounds[i - 1] if i > 0 else 0.0
            hi = bounds[i]
            frac = (rank - prev_cum) / c if c else 0.0
            return lo + (hi - lo) * frac
    return bounds[-1]


def _format_value(v: float) -> str:
    """Prometheus sample value: integral floats print as integers."""
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _label_str(names, values) -> str:
    if not names:
        return ""
    inner = ",".join(
        f'{n}="{_escape_label(str(v))}"' for n, v in zip(names, values)
    )
    return "{" + inner + "}"


class _CounterChild:
    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counters only go up (inc by {amount})")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class _GaugeChild:
    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class _HistogramChild:
    __slots__ = ("_lock", "_buckets", "_counts", "_sum", "_count")

    def __init__(self, buckets: tuple[float, ...]):
        self._lock = threading.Lock()
        self._buckets = buckets
        self._counts = [0] * (len(buckets) + 1)  # last slot = +Inf
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        i = bisect.bisect_left(self._buckets, value)  # bucket is "le" bound
        with self._lock:
            self._counts[i] += 1
            self._sum += value
            self._count += 1

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    def snapshot(self) -> dict:
        """Cumulative Prometheus-style view: ``{le: count}`` + sum/count."""
        with self._lock:
            counts = list(self._counts)
            total, s = self._count, self._sum
        cum, out = 0, {}
        for b, c in zip(self._buckets, counts):
            cum += c
            out[b] = cum
        return {"buckets": out, "inf": total, "sum": s, "count": total}

    def percentile(self, q: float) -> float:
        """Estimate the q-quantile via :func:`percentile_from_counts`
        over this child's live bucket counts."""
        with self._lock:
            counts = list(self._counts)
        return percentile_from_counts(self._buckets, counts, q)


class _Family:
    """One named metric + its children, keyed by label values."""

    kind = "untyped"
    _child_cls: type = _CounterChild

    def __init__(self, name: str, help: str, labelnames: tuple[str, ...],
                 **child_kw):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._child_kw = child_kw
        self._lock = threading.Lock()
        self._children: dict[tuple, object] = {}
        if not self.labelnames:  # unlabeled family IS its only child
            self._children[()] = self._child_cls(**child_kw)

    def labels(self, *values, **kw):
        if kw:
            if values:
                raise ValueError("pass labels positionally or by name, not both")
            try:
                values = tuple(kw[n] for n in self.labelnames)
            except KeyError as e:
                raise ValueError(
                    f"{self.name} expects labels {self.labelnames}, got {sorted(kw)}"
                ) from e
            if len(kw) != len(self.labelnames):
                raise ValueError(
                    f"{self.name} expects labels {self.labelnames}, got {sorted(kw)}"
                )
        values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name} expects {len(self.labelnames)} label values "
                f"{self.labelnames}, got {values}"
            )
        with self._lock:
            child = self._children.get(values)
            if child is None:
                child = self._children[values] = self._child_cls(**self._child_kw)
            return child

    def _default(self):
        if self.labelnames:
            raise ValueError(
                f"{self.name} is labeled {self.labelnames}; call .labels(...) first"
            )
        return self._children[()]

    def children(self) -> list[tuple[tuple, object]]:
        with self._lock:
            return sorted(self._children.items())

    # mismatch detection for duplicate declarations — includes child
    # construction args (histogram buckets), so two modules cannot
    # silently observe into different ladders under one name
    def signature(self):
        return (self.kind, self.labelnames,
                tuple(sorted(self._child_kw.items())))


class Counter(_Family):
    kind = "counter"
    _child_cls = _CounterChild

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    @property
    def value(self) -> float:
        return self._default().value


class Gauge(_Family):
    kind = "gauge"
    _child_cls = _GaugeChild

    def set(self, value: float) -> None:
        self._default().set(value)

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default().dec(amount)

    @property
    def value(self) -> float:
        return self._default().value


class Histogram(_Family):
    kind = "histogram"
    _child_cls = _HistogramChild

    def observe(self, value: float) -> None:
        self._default().observe(value)

    def percentile(self, q: float) -> float:
        return self._default().percentile(q)

    @property
    def count(self) -> int:
        return self._default().count

    @property
    def sum(self) -> float:
        return self._default().sum

    def time(self):
        """``with hist.time(): ...`` — observe the block's wall duration."""
        return _Timer(self._default())


class _Timer:
    __slots__ = ("_child", "_t0")

    def __init__(self, child: _HistogramChild):
        self._child = child

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._child.observe(time.perf_counter() - self._t0)


class MetricsRegistry:
    """Thread-safe collection of metric families with text/JSON export."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: dict[str, _Family] = {}

    def _declare(self, cls, name: str, help: str, labelnames, **kw):
        labelnames = tuple(labelnames)
        wanted = (cls.kind, labelnames, tuple(sorted(kw.items())))
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.signature() != wanted:
                    raise ValueError(
                        f"metric {name!r} already declared as "
                        f"{fam.signature()}, re-declared as {wanted}"
                    )
                return fam
            fam = self._families[name] = cls(name, help, labelnames, **kw)
            return fam

    def counter(self, name: str, help: str = "",
                labelnames: tuple[str, ...] = ()) -> Counter:
        return self._declare(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: tuple[str, ...] = ()) -> Gauge:
        return self._declare(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: tuple[str, ...] = (),
                  buckets: tuple[float, ...] = DEFAULT_BUCKETS) -> Histogram:
        buckets = tuple(sorted(float(b) for b in buckets))
        if not buckets:
            raise ValueError("histogram needs at least one bucket boundary")
        return self._declare(Histogram, name, help, labelnames,
                             buckets=buckets)

    def get(self, name: str) -> _Family | None:
        with self._lock:
            return self._families.get(name)

    def reset(self) -> None:
        """Drop every family (tests; production registries only grow)."""
        with self._lock:
            self._families.clear()

    # -- export ----------------------------------------------------------
    def prometheus_text(self) -> str:
        """Prometheus text exposition (format 0.0.4)."""
        lines: list[str] = []
        with self._lock:
            fams = sorted(self._families.values(), key=lambda f: f.name)
        for fam in fams:
            if fam.help:
                lines.append(f"# HELP {fam.name} {fam.help}")
            lines.append(f"# TYPE {fam.name} {fam.kind}")
            for values, child in fam.children():
                if fam.kind == "histogram":
                    snap = child.snapshot()
                    for b, cum in snap["buckets"].items():
                        lab = _label_str(fam.labelnames + ("le",),
                                         values + (_format_value(b),))
                        lines.append(f"{fam.name}_bucket{lab} {cum}")
                    lab = _label_str(fam.labelnames + ("le",),
                                     values + ("+Inf",))
                    lines.append(f"{fam.name}_bucket{lab} {snap['inf']}")
                    base = _label_str(fam.labelnames, values)
                    lines.append(
                        f"{fam.name}_sum{base} {_format_value(snap['sum'])}")
                    lines.append(f"{fam.name}_count{base} {snap['count']}")
                else:
                    lab = _label_str(fam.labelnames, values)
                    lines.append(
                        f"{fam.name}{lab} {_format_value(child.value)}")
        return "\n".join(lines) + "\n"

    def snapshot(self) -> dict:
        """JSON-ready nested view of every family."""
        out: dict = {}
        with self._lock:
            fams = sorted(self._families.values(), key=lambda f: f.name)
        for fam in fams:
            series = []
            for values, child in fam.children():
                labels = dict(zip(fam.labelnames, values))
                if fam.kind == "histogram":
                    snap = child.snapshot()
                    series.append({
                        "labels": labels,
                        "buckets": {_format_value(b): c
                                    for b, c in snap["buckets"].items()},
                        "inf": snap["inf"],
                        "sum": snap["sum"],
                        "count": snap["count"],
                    })
                else:
                    series.append({"labels": labels, "value": child.value})
            out[fam.name] = {"type": fam.kind, "help": fam.help,
                             "series": series}
        return out


#: The process-wide default registry every subsystem records into.
REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return REGISTRY


def family_total(name: str, registry: MetricsRegistry | None = None) -> float:
    """Sum of a counter/gauge family's children across all label sets in
    the process registry (0.0 when the family was never declared) — the
    snapshot primitive bench rows and delta-based tests are built on."""
    fam = (registry or REGISTRY).get(name)
    if fam is None:
        return 0.0
    return float(sum(child.value for _v, child in fam.children()))
