"""Metrics exporters: Prometheus/JSON over a stdlib HTTP endpoint.

``MetricsServer`` is a tiny threaded ``http.server`` (no dependencies —
the container rule) exposing the process registry:

* ``GET /metrics``       -> Prometheus text exposition (0.0.4)
* ``GET /metrics.json``  -> JSON snapshot of every family
* ``GET /healthz``       -> ``ok`` (liveness for deployment probes)

plus any ``extra_json`` routes the owner registers (the fleet
aggregator serves its ``/fleet.json`` summary this way).  ``registry``
may be anything exposing ``prometheus_text()``/``snapshot()`` — the
:class:`distlr_tpu.obs.federate.FleetScraper` duck-types it so one
server can re-serve a merged fleet view that is rebuilt every scrape.

Port 0 binds an OS-assigned ephemeral port (announced by the launcher as
``METRICS host:port``, same contract as ``SERVING``/``HOSTS``).  The
``DISTLR_METRICS_SNAPSHOT=<path>`` env hook writes the registry to a
file at interpreter exit — how one-shot processes (``bench.py`` under
``capture_all_tpu.sh``) bank their metrics without holding a port open.
Paths ending ``.json`` bank the machine-readable JSON snapshot (what the
fleet aggregator merges); anything else banks Prometheus text.  Several
``os.pathsep``-separated paths may be given to bank both forms at once.
"""

from __future__ import annotations

import http.server
import json
import os
import threading
import urllib.parse

from distlr_tpu.obs.registry import MetricsRegistry, get_registry


class _Handler(http.server.BaseHTTPRequestHandler):
    def do_GET(self):  # noqa: N802 (stdlib API name)
        registry: MetricsRegistry = self.server.registry  # type: ignore[attr-defined]
        path, _, query = self.path.partition("?")
        status = 200
        if path in ("/metrics", "/"):
            body = registry.prometheus_text().encode()
            ctype = "text/plain; version=0.0.4; charset=utf-8"
        elif path == "/metrics.json":
            body = (json.dumps(registry.snapshot()) + "\n").encode()
            ctype = "application/json"
        elif path == "/healthz":
            body, ctype = b"ok\n", "text/plain"
        elif path in (getattr(self.server, "extra_json", None) or {}):
            body = (json.dumps(self.server.extra_json[path]()) + "\n").encode()  # type: ignore[attr-defined]
            ctype = "application/json"
        elif path in (getattr(self.server, "extra_query", None) or {}):
            # parameterized JSON routes: the callable receives the
            # parsed query params ({k: first-value}) and may reject bad
            # input with ValueError -> a 400 JSON error body
            params = {k: v[0] for k, v in
                      urllib.parse.parse_qs(query).items()}
            try:
                doc = self.server.extra_query[path](params)  # type: ignore[attr-defined]
            except ValueError as e:
                doc, status = {"error": str(e)}, 400
            body = (json.dumps(doc) + "\n").encode()
            ctype = "application/json"
        else:
            self.send_error(404)
            return
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):  # scrapes must not spam stderr
        pass


class _HTTPServer(http.server.ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True


class MetricsServer:
    """Background /metrics endpoint over one registry."""

    def __init__(self, registry: MetricsRegistry | None = None, *,
                 host: str = "127.0.0.1", port: int = 0,
                 extra_json: dict | None = None,
                 extra_query: dict | None = None):
        self.registry = registry or get_registry()
        self._http = _HTTPServer((host, port), _Handler)
        self._http.registry = self.registry  # type: ignore[attr-defined]
        self._http.extra_json = dict(extra_json or {})  # type: ignore[attr-defined]
        self._http.extra_query = dict(extra_query or {})  # type: ignore[attr-defined]
        self.host, self.port = self._http.server_address[:2]
        self._thread = threading.Thread(
            target=self._http.serve_forever, daemon=True,
            name="distlr-metrics-http",
        )
        self._started = False
        self._closed = False

    def start(self) -> "MetricsServer":
        if self._closed:
            raise RuntimeError("MetricsServer is stopped; build a new one")
        if not self._started:
            self._thread.start()
            # only set once the thread is really running: a failed
            # start() must leave stop() on the no-shutdown path below
            self._started = True
        return self

    def stop(self) -> None:
        """Idempotent teardown, safe in EVERY lifecycle state.  In
        particular it must not call ``HTTPServer.shutdown()`` unless
        ``serve_forever`` actually ran: ``shutdown()`` blocks on an
        event that only ``serve_forever`` ever sets, so stopping a
        never-started (or failed-to-start) server used to deadlock
        forever."""
        if self._closed:
            return
        self._closed = True
        if self._started:
            self._http.shutdown()
        self._http.server_close()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


def start_metrics_server(*, host: str = "127.0.0.1", port: int = 0,
                         registry: MetricsRegistry | None = None) -> MetricsServer:
    return MetricsServer(registry, host=host, port=port).start()


def write_metrics_snapshot(path: str,
                           registry: MetricsRegistry | None = None) -> str:
    """Write the registry to ``path`` (atomic).  A ``.json`` path banks
    the JSON snapshot (the machine-readable twin the fleet aggregator
    and ``capture_all_tpu.sh`` consume); any other extension banks the
    Prometheus text exposition."""
    registry = registry or get_registry()
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    if path.endswith(".json"):
        body = json.dumps(registry.snapshot()) + "\n"
    else:
        body = registry.prometheus_text()
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(body)
    os.replace(tmp, path)
    return path


_snapshot_installed = False


def snapshot_env_paths(value: str | None = None) -> list[str]:
    """Parse ``DISTLR_METRICS_SNAPSHOT`` into its target paths: one
    file, or several ``os.pathsep``-separated ones (``a.prom:b.json``
    banks both the text AND the JSON form — ``capture_all_tpu.sh``
    feeds the second to the fleet aggregator's ``snapshots/`` dir)."""
    if value is None:
        value = os.environ.get("DISTLR_METRICS_SNAPSHOT", "")
    return [p for p in value.split(os.pathsep) if p]


def install_snapshot_atexit() -> bool:
    """If ``DISTLR_METRICS_SNAPSHOT`` names file path(s), dump the
    registry there at interpreter exit (format per extension, see
    :func:`write_metrics_snapshot`).  Returns whether a hook was
    installed.  Idempotent per process."""
    global _snapshot_installed
    paths = snapshot_env_paths()
    if not paths or _snapshot_installed:
        return _snapshot_installed
    import atexit  # noqa: PLC0415

    def _dump():
        for path in paths:
            try:
                write_metrics_snapshot(path)
            except OSError:
                pass  # a failed snapshot must never fail the process exit

    atexit.register(_dump)
    _snapshot_installed = True
    return True
