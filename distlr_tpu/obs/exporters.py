"""Metrics exporters: Prometheus/JSON over a stdlib HTTP endpoint.

``MetricsServer`` is a tiny threaded ``http.server`` (no dependencies —
the container rule) exposing the process registry:

* ``GET /metrics``       -> Prometheus text exposition (0.0.4)
* ``GET /metrics.json``  -> JSON snapshot of every family
* ``GET /healthz``       -> ``ok`` (liveness for deployment probes)

Port 0 binds an OS-assigned ephemeral port (announced by the launcher as
``METRICS host:port``, same contract as ``SERVING``/``HOSTS``).  The
``DISTLR_METRICS_SNAPSHOT=<path>`` env hook writes the registry's
Prometheus text to a file at interpreter exit — how one-shot processes
(``bench.py`` under ``capture_all_tpu.sh``) bank their metrics without
holding a port open.
"""

from __future__ import annotations

import http.server
import json
import os
import threading

from distlr_tpu.obs.registry import MetricsRegistry, get_registry


class _Handler(http.server.BaseHTTPRequestHandler):
    def do_GET(self):  # noqa: N802 (stdlib API name)
        registry: MetricsRegistry = self.server.registry  # type: ignore[attr-defined]
        path = self.path.split("?", 1)[0]
        if path in ("/metrics", "/"):
            body = registry.prometheus_text().encode()
            ctype = "text/plain; version=0.0.4; charset=utf-8"
        elif path == "/metrics.json":
            body = (json.dumps(registry.snapshot()) + "\n").encode()
            ctype = "application/json"
        elif path == "/healthz":
            body, ctype = b"ok\n", "text/plain"
        else:
            self.send_error(404)
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):  # scrapes must not spam stderr
        pass


class _HTTPServer(http.server.ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True


class MetricsServer:
    """Background /metrics endpoint over one registry."""

    def __init__(self, registry: MetricsRegistry | None = None, *,
                 host: str = "127.0.0.1", port: int = 0):
        self.registry = registry or get_registry()
        self._http = _HTTPServer((host, port), _Handler)
        self._http.registry = self.registry  # type: ignore[attr-defined]
        self.host, self.port = self._http.server_address[:2]
        self._thread = threading.Thread(
            target=self._http.serve_forever, daemon=True,
            name="distlr-metrics-http",
        )

    def start(self) -> "MetricsServer":
        if not self._thread.is_alive():  # idempotent: `with start_...()`
            self._thread.start()
        return self

    def stop(self) -> None:
        self._http.shutdown()
        self._http.server_close()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


def start_metrics_server(*, host: str = "127.0.0.1", port: int = 0,
                         registry: MetricsRegistry | None = None) -> MetricsServer:
    return MetricsServer(registry, host=host, port=port).start()


def write_metrics_snapshot(path: str,
                           registry: MetricsRegistry | None = None) -> str:
    """Write the registry's Prometheus text to ``path`` (atomic)."""
    registry = registry or get_registry()
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(registry.prometheus_text())
    os.replace(tmp, path)
    return path


_snapshot_installed = False


def install_snapshot_atexit() -> bool:
    """If ``DISTLR_METRICS_SNAPSHOT`` names a file, dump the registry's
    Prometheus text there at interpreter exit.  Returns whether a hook
    was installed.  Idempotent per process."""
    global _snapshot_installed
    path = os.environ.get("DISTLR_METRICS_SNAPSHOT")
    if not path or _snapshot_installed:
        return _snapshot_installed
    import atexit  # noqa: PLC0415

    def _dump():
        try:
            write_metrics_snapshot(path)
        except OSError:
            pass  # a failed snapshot must never fail the process exit

    atexit.register(_dump)
    _snapshot_installed = True
    return True
