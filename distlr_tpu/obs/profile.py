"""Fleet-wide continuous profiling (ISSUE 9).

The obs stack answers *what* is slow (metrics + alerts) and *where in
the request path* time goes (distributed traces); this module answers
what neither can: *what code was on-CPU* when a Hogwild worker stalls
or the router p99 spikes.  A stdlib sampling profiler — a daemon thread
walking ``sys._current_frames()`` at a default ~19 Hz — folds every
thread's stack into a bounded table and journals aggregation windows to
``<obs_run_dir>/profiles/<role>-<rank>.jsonl``.  Each sample is tagged
with the innermost active dtrace span name on the sampled thread
(:func:`distlr_tpu.obs.dtrace.active_span_name`), so flamegraphs split
by ``serve.request`` vs ``train.step`` vs ``feedback.*`` even though
the sampler itself knows nothing about roles.

Two capture regimes:

* **always-on** — the default ~19 Hz costs well under the 3% QPS
  overhead budget (``benchmarks/bench_prof.py`` enforces it) and runs
  for the life of the process, journaling one window doc per
  ``window_s``;
* **burst** — the SAME edge-triggered trigger file the flight recorder
  uses (``<run_dir>/flightrec/TRIGGER.json``, dropped by ``launch
  obs-agg`` when any ``distlr_alert_*`` gauge transitions to firing)
  switches the sampler to ``burst_hz`` for ``burst_s`` seconds, then
  closes exactly ONE high-resolution window stamped with the incident
  sequence number — once per incident, like the flight dump, and the
  flight dump cross-references this journal (the two postmortem
  artifacts name each other).  ``launch profrec`` drops a profiler-only
  trigger (``<run_dir>/profiles/TRIGGER.json``) for live debugging
  without a flight dump.

``launch prof-agg`` merges every rank's journal — Python samplers AND
the native ``distlr_kv_server``'s per-handler CPU windows
(``--prof_journal``), one ``profwindow`` schema — into a fleet-wide
collapsed-stack file plus a speedscope-compatible JSON with one track
per ``<role>-<rank>`` journal.  Stdlib-only and jax-free, like the rest
of ``obs``.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

from distlr_tpu.obs import dtrace
from distlr_tpu.obs.registry import get_registry
from distlr_tpu.utils.logging import get_logger

log = get_logger(__name__)

_reg = get_registry()
_SAMPLES = _reg.counter(
    "distlr_prof_samples_total",
    "sampling-profiler stack samples taken (one per observed thread per "
    "tick)",
)
_WINDOWS = _reg.counter(
    "distlr_prof_windows_total",
    "profile aggregation windows journaled, by capture regime",
    labelnames=("kind",),
)
_STACKS_DROPPED = _reg.counter(
    "distlr_prof_stacks_dropped_total",
    "samples folded into the (overflow) bucket after the per-window "
    "distinct-stack cap",
)
_WINDOWS_DROPPED = _reg.counter(
    "distlr_prof_windows_dropped_total",
    "profile windows dropped after the per-process journal cap "
    "(in-memory aggregation keeps running)",
)
_BURSTS = _reg.counter(
    "distlr_prof_bursts_total",
    "high-Hz burst captures begun (alert-edge incidents + manual "
    "`launch profrec` triggers)",
)
_HZ_GAUGE = _reg.gauge(
    "distlr_prof_hz",
    "current sampling rate of the continuous profiler (rises to the "
    "burst rate during an incident capture)",
)

#: default always-on sampling rate.  19 Hz is deliberately prime-ish:
#: a rate that divides common loop periods (10/20/100 Hz) would alias —
#: sampling the same phase of a periodic loop every time and reporting
#: one frame as 100% of a workload that merely shares its period.
DEFAULT_HZ = 19.0
#: default seconds of aggregation per journaled window
DEFAULT_WINDOW_S = 10.0
#: burst regime: rate and duration of the once-per-incident capture
BURST_HZ = 97.0
BURST_S = 3.0
#: distinct folded stacks kept per window; the excess folds into one
#: "(overflow)" bucket so a pathological workload bounds memory + disk
MAX_STACKS = 5000
#: frames kept per sampled stack (deeper recursion truncates, loudly,
#: inside the folded key itself)
MAX_DEPTH = 64
#: per-process journal window cap (like dtrace.MAX_JOURNAL_SPANS: a
#: runaway journal bounds disk, loudly)
MAX_JOURNAL_WINDOWS = 20_000
#: profiler-only trigger filename inside <run_dir>/profiles/
TRIGGER_NAME = "TRIGGER.json"


def _frame_name(code) -> str:
    """``module.function`` — no line numbers, so one logical frame folds
    into one flamegraph node instead of fragmenting per call site."""
    mod = os.path.splitext(os.path.basename(code.co_filename))[0]
    return f"{mod}.{code.co_name}"


def fold_stack(frame, tag: str | None, max_depth: int = MAX_DEPTH) -> str:
    """One thread's frame chain -> a semicolon-folded stack string,
    root-first, prefixed with the dtrace span tag (``-`` when the
    thread is outside every span) — the classic collapsed flamegraph
    format, one line-atom per sample."""
    parts = []
    depth = 0
    f = frame
    while f is not None and depth < max_depth:
        parts.append(_frame_name(f.f_code))
        f = f.f_back
        depth += 1
    if f is not None:
        parts.append("(truncated)")
    parts.append(tag or "-")
    parts.reverse()
    return ";".join(parts)


class SamplingProfiler:
    """Per-process continuous profiler: one daemon thread, two trigger
    watchers, a bounded folded-stack table, and a JSONL window journal.

    ``run_dir=None`` keeps the in-memory aggregate only (no journal, no
    burst triggers) — the mode bench rows use for their
    ``profile_top_frames`` snapshot.
    """

    def __init__(self, run_dir: str | None, role: str, rank: int, *,
                 hz: float = DEFAULT_HZ, window_s: float = DEFAULT_WINDOW_S,
                 burst_hz: float = BURST_HZ, burst_s: float = BURST_S,
                 max_stacks: int = MAX_STACKS):
        if hz <= 0:
            raise ValueError(f"hz must be positive, got {hz}")
        if window_s <= 0:
            raise ValueError(f"window_s must be positive, got {window_s}")
        self.run_dir = run_dir
        self.role, self.rank = str(role), int(rank)
        self.hz = float(hz)
        self.window_s = float(window_s)
        self.burst_hz = max(float(burst_hz), self.hz)
        self.burst_s = float(burst_s)
        self.max_stacks = int(max_stacks)
        self._lock = threading.Lock()
        self._table: dict[str, int] = {}
        self._window_t0 = time.time()
        self._window_samples = 0
        self._window_hz = self.hz
        #: lifetime aggregate (never cleared by window flushes) — what
        #: ``top_frames`` answers from, journal or not
        self._lifetime: dict[str, int] = {}
        self._lifetime_samples = 0
        self._journal_path: str | None = None
        self._journal_windows = 0
        self._cap_warned = False
        if run_dir:
            d = os.path.join(run_dir, "profiles")
            os.makedirs(d, exist_ok=True)
            self._journal_path = os.path.join(
                d, f"{self.role}-{self.rank}.jsonl")
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # burst state (mutated by the sampler thread only)
        self._burst_until = 0.0
        self._burst_seq: int | None = None
        self._burst_reason = ""
        self._incident_seq = self._read_seq(self._incident_trigger_path())
        self._manual_seq = self._read_seq(self._manual_trigger_path())

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "SamplingProfiler":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="distlr-prof-sampler")
            self._thread.start()
            _HZ_GAUGE.set(self.hz)
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=2.0)
        self._thread = None
        if self._burst_seq is not None:
            # stopping mid-burst: the incident capture is shorter than
            # asked, but it still lands as THE burst window — a process
            # exiting right after an alert must not lose the postmortem
            self._burst_until = 0.0
            self._close_burst()
        # final partial window: a short-lived process (bench, a one-shot
        # launch command) must still leave its profile behind
        self.flush_window(kind="final")
        _HZ_GAUGE.set(0.0)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- the sampler loop --------------------------------------------------
    def _run(self) -> None:
        own = threading.get_ident()
        next_tick = time.monotonic()
        next_trigger_check = 0.0
        while not self._stop.is_set():
            now_mono = time.monotonic()
            in_burst = now_mono < self._burst_until
            if not in_burst and self._burst_seq is not None:
                # a burst just ended: close ITS window before the next
                # regular sample (or a new trigger) lands in it —
                # exactly one burst window per incident
                self._close_burst()
            if now_mono >= next_trigger_check:
                # same 0.25s cadence as the flight recorder's watcher —
                # checking per sample tick would open the trigger files
                # ~40x/s for nothing
                self._check_triggers()
                next_trigger_check = now_mono + 0.25
            in_burst = time.monotonic() < self._burst_until
            hz = self.burst_hz if in_burst else self.hz
            _HZ_GAUGE.set(hz)
            self.sample_once(exclude={own})
            if not in_burst and \
                    time.time() - self._window_t0 >= self.window_s:
                self.flush_window(kind="window")
            next_tick += 1.0 / hz
            delay = next_tick - time.monotonic()
            if delay <= 0:
                next_tick = time.monotonic()  # fell behind: don't spiral
            else:
                self._stop.wait(delay)

    def sample_once(self, exclude: set | None = None) -> int:
        """Walk every live thread's current frame once; returns the
        number of samples folded in.  Public for deterministic tests."""
        try:
            frames = sys._current_frames()
        except Exception:  # noqa: BLE001 — profiling must never fail work
            return 0
        n = 0
        for tid, frame in frames.items():
            if exclude and tid in exclude:
                continue
            folded = fold_stack(frame, dtrace.active_span_name(tid))
            self._record(folded)
            n += 1
        if n:
            _SAMPLES.inc(n)
        return n

    def _record(self, folded: str, count: int = 1) -> None:
        with self._lock:
            # window and lifetime tables overflow INDEPENDENTLY: a stack
            # squeezed out of one busy window may long be tracked in the
            # lifetime aggregate, and folding it into "(overflow)" there
            # would misattribute the process's genuinely hot frames
            key = folded
            if key not in self._table and \
                    len(self._table) >= self.max_stacks:
                key = "(overflow)"
                _STACKS_DROPPED.inc(count)
            self._table[key] = self._table.get(key, 0) + count
            self._window_samples += count
            lkey = folded
            if lkey not in self._lifetime and \
                    len(self._lifetime) >= self.max_stacks:
                lkey = "(overflow)"
            self._lifetime[lkey] = self._lifetime.get(lkey, 0) + count
            self._lifetime_samples += count

    # -- windows -----------------------------------------------------------
    def _drain_window(self):
        with self._lock:
            table, n = self._table, self._window_samples
            t0 = self._window_t0
            hz = self._window_hz
            self._table = {}
            self._window_samples = 0
            self._window_t0 = time.time()
            self._window_hz = self.hz
        return table, n, t0, hz

    def flush_window(self, kind: str = "window",
                     incident: int | None = None,
                     reason: str | None = None) -> dict | None:
        """Close the current aggregation window and journal it (empty
        windows are skipped — an idle process stays silent on disk).
        Returns the window doc (None when empty)."""
        table, n, t0, hz = self._drain_window()
        if n == 0:
            return None
        doc = {
            "type": "profwindow",
            "role": self.role, "rank": self.rank, "pid": os.getpid(),
            "kind": kind,
            "t0": round(t0, 3), "t1": round(time.time(), 3),
            "hz": hz,
            "unit": "samples",
            "samples": n,
            "stacks": table,
        }
        if incident is not None:
            doc["incident"] = incident
        if reason:
            doc["reason"] = reason
        self._journal(doc)
        _WINDOWS.labels(kind=kind).inc()
        return doc

    def _journal(self, doc: dict) -> None:
        if self._journal_path is None:
            return
        if self._journal_windows >= MAX_JOURNAL_WINDOWS:
            # the cap bounds disk LOUDLY, like dtrace's span-journal
            # cap: count the drop and say so once — a silent stop would
            # read as "the run went idle" in every merged flamegraph
            _WINDOWS_DROPPED.inc()
            if not self._cap_warned:
                self._cap_warned = True
                log.warning(
                    "profile journal %s hit its %d-window cap; further "
                    "windows drop (in-memory aggregation continues)",
                    self._journal_path, MAX_JOURNAL_WINDOWS)
            return
        try:
            with open(self._journal_path, "a") as f:
                f.write(json.dumps(doc) + "\n")
            self._journal_windows += 1  # only LANDED lines consume cap
        except OSError:
            pass  # profiling must never fail the profiled work

    # -- bursts ------------------------------------------------------------
    def _incident_trigger_path(self) -> str | None:
        if not self.run_dir:
            return None
        return os.path.join(self.run_dir, "flightrec", dtrace.TRIGGER_NAME)

    def _manual_trigger_path(self) -> str | None:
        if not self.run_dir:
            return None
        return os.path.join(self.run_dir, "profiles", TRIGGER_NAME)

    @staticmethod
    def _read_seq(path: str | None) -> int:
        if path is None:
            return -1
        try:
            with open(path) as f:
                return int(json.load(f).get("seq", -1))
        except (OSError, ValueError):
            return -1

    def _check_triggers(self) -> None:
        """Edge-triggered burst arming from both trigger files: the
        flight recorder's (alert incidents — ONE incident number shared
        with the flight dump) and the profiler's own (``launch
        profrec``).  A trigger seen mid-burst extends nothing — once
        per incident."""
        for path, attr, source in (
            (self._incident_trigger_path(), "_incident_seq", "alert"),
            (self._manual_trigger_path(), "_manual_seq", "profrec"),
        ):
            if path is None:
                continue
            try:
                with open(path) as f:
                    doc = json.load(f)
            except (OSError, ValueError):
                continue
            seq = int(doc.get("seq", -1))
            if seq > getattr(self, attr):
                setattr(self, attr, seq)
                self._begin_burst(seq, str(doc.get("alert",
                                                   doc.get("reason",
                                                           source))))

    def _begin_burst(self, seq: int, reason: str) -> None:
        if time.monotonic() < self._burst_until:
            return  # already bursting: the running capture owns the window
        # the regular window closes first, so the burst window holds
        # ONLY high-Hz samples of the incident
        self.flush_window(kind="window")
        self._burst_seq = seq
        self._burst_reason = reason
        self._burst_until = time.monotonic() + self.burst_s
        with self._lock:
            self._window_hz = self.burst_hz
        _BURSTS.inc()
        log.info("profile burst: %.0f Hz for %.1fs (seq=%d, %s)",
                 self.burst_hz, self.burst_s, seq, reason)

    def _close_burst(self) -> None:
        seq, reason = self._burst_seq, self._burst_reason
        self._burst_seq = None
        self._burst_reason = ""
        self.flush_window(kind="burst", incident=seq, reason=reason)

    # -- reads -------------------------------------------------------------
    def top_frames(self, n: int = 10) -> list[dict]:
        """Leaf-frame ranking over the LIFETIME aggregate: the
        ``profile_top_frames`` snapshot bench rows carry.  Self time,
        not cumulative — the leaf is where the CPU actually was."""
        leaf: dict[str, int] = {}
        with self._lock:
            items = list(self._lifetime.items())
            total = self._lifetime_samples
        for folded, count in items:
            f = folded.rsplit(";", 1)[-1]
            leaf[f] = leaf.get(f, 0) + count
        ranked = sorted(leaf.items(), key=lambda kv: -kv[1])[:n]
        return [{"frame": f, "samples": c,
                 "share": round(c / total, 4) if total else 0.0}
                for f, c in ranked]

    def flight_info(self, reason: str, seq: int | None) -> dict:
        """dtrace flight-dump cross-reference: the incident's profile
        artifacts, so the two postmortems name each other."""
        return {
            "profile_journal": self._journal_path,
            "profile_incident_seq": seq,
        }


# ---------------------------------------------------------------------------
# module-level singleton (what _obs_scope arms per launch command)
# ---------------------------------------------------------------------------

_PROFILER: SamplingProfiler | None = None


def configure(run_dir: str | None, role: str, rank: int, *,
              hz: float = DEFAULT_HZ, window_s: float = DEFAULT_WINDOW_S,
              burst_hz: float = BURST_HZ,
              burst_s: float = BURST_S) -> SamplingProfiler:
    """Arm (or re-arm) this process's continuous profiler.  Safe to call
    again (tests, multi-command processes): the previous sampler stops
    and flushes first."""
    global _PROFILER
    if _PROFILER is not None:
        stop()
    _PROFILER = SamplingProfiler(run_dir, role, rank, hz=hz,
                                 window_s=window_s, burst_hz=burst_hz,
                                 burst_s=burst_s).start()
    dtrace.register_flight_info(_PROFILER.flight_info)
    return _PROFILER


def is_configured() -> bool:
    return _PROFILER is not None


def profiler() -> SamplingProfiler | None:
    return _PROFILER


def top_frames(n: int = 10) -> list[dict]:
    return _PROFILER.top_frames(n) if _PROFILER is not None else []


def stop() -> None:
    global _PROFILER
    if _PROFILER is not None:
        dtrace.unregister_flight_info(_PROFILER.flight_info)
        _PROFILER.stop()
        _PROFILER = None


def reset_for_tests() -> None:
    stop()


def trigger(run_dir: str, reason: str = "manual") -> str:
    """Drop/refresh the PROFILER-ONLY burst trigger under ``run_dir``
    (``launch profrec``): every sampler on the dir bursts to high Hz
    once, without a flight dump.  Alert incidents instead ride the
    flight recorder's trigger, which arms both."""
    d = os.path.join(run_dir, "profiles")
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, TRIGGER_NAME)
    seq = 0
    try:
        with open(path) as f:
            seq = int(json.load(f).get("seq", -1)) + 1
    except (OSError, ValueError):
        pass
    doc = {"seq": seq, "reason": str(reason), "ts": time.time()}
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
    return path


# ---------------------------------------------------------------------------
# prof-agg: merge per-rank profile journals into fleet-wide artifacts
# ---------------------------------------------------------------------------

#: journal "unit" -> speedscope weight unit
_SPEEDSCOPE_UNITS = {"samples": "none", "cpu_us": "microseconds"}


def _read_windows(path: str) -> list[dict]:
    out = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    doc = json.loads(line)
                except ValueError:
                    continue  # torn tail line: skip, keep the rest
                if doc.get("type") == "profwindow":
                    out.append(doc)
    except OSError:
        pass
    return out


def merge_run_dirs(run_dirs) -> dict:
    """Merge every ``<run_dir>/profiles/*.jsonl`` journal — Python
    samplers and native ``kv_server`` CPU windows, one schema — into
    per-track aggregates::

        {track: {"unit": ..., "samples": N, "windows": W,
                 "stacks": {folded: count}}}

    keyed by the journal's ``<role>-<rank>`` file stem (suffixed
    ``#2``... on a collision across federated dirs, like trace-agg).
    """
    if isinstance(run_dirs, str):
        run_dirs = [run_dirs]
    tracks: dict[str, dict] = {}
    seen: set[str] = set()
    for d in run_dirs:
        prof_dir = os.path.join(d, "profiles")
        if not os.path.isdir(prof_dir):
            continue
        for name in sorted(os.listdir(prof_dir)):
            if not name.endswith(".jsonl"):
                continue
            stem = name[:-len(".jsonl")]
            key, n = stem, 1
            while key in seen:
                n += 1
                key = f"{stem}#{n}"
            seen.add(key)
            windows = _read_windows(os.path.join(prof_dir, name))
            if not windows:
                continue
            agg: dict[str, int] = {}
            total = 0
            unit = windows[0].get("unit", "samples")
            for w in windows:
                if w.get("unit", "samples") != unit:
                    continue  # one unit per track; mixed lines are drift
                for folded, count in (w.get("stacks") or {}).items():
                    agg[folded] = agg.get(folded, 0) + int(count)
                total += int(w.get("samples", 0))
            tracks[key] = {"unit": unit, "samples": total,
                           "windows": len(windows), "stacks": agg}
    return tracks


def write_collapsed(tracks: dict, out_path: str) -> int:
    """Fleet-wide collapsed-stack file: ``track;frame;... count`` per
    line (the flamegraph.pl / inferno input format, the track prefix
    keeping ranks separable).  Returns the line count."""
    os.makedirs(os.path.dirname(os.path.abspath(out_path)), exist_ok=True)
    n = 0
    tmp = f"{out_path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        for track in sorted(tracks):
            for folded, count in sorted(tracks[track]["stacks"].items()):
                f.write(f"{track};{folded} {count}\n")
                n += 1
    os.replace(tmp, out_path)
    return n


def write_speedscope(tracks: dict, out_path: str) -> dict:
    """Speedscope-compatible JSON (https://www.speedscope.app file
    format, ``sampled`` profiles): one profile per track, shared frame
    table, each distinct folded stack one weighted sample."""
    frames: list[dict] = []
    index: dict[str, int] = {}

    def fi(name: str) -> int:
        i = index.get(name)
        if i is None:
            i = index[name] = len(frames)
            frames.append({"name": name})
        return i

    profiles = []
    for track in sorted(tracks):
        t = tracks[track]
        samples, weights = [], []
        total = 0
        for folded, count in sorted(t["stacks"].items()):
            samples.append([fi(p) for p in folded.split(";")])
            weights.append(count)
            total += count
        profiles.append({
            "type": "sampled",
            "name": track,
            "unit": _SPEEDSCOPE_UNITS.get(t["unit"], "none"),
            "startValue": 0,
            "endValue": total,
            "samples": samples,
            "weights": weights,
        })
    doc = {
        "$schema": "https://www.speedscope.app/file-format-schema.json",
        "shared": {"frames": frames},
        "profiles": profiles,
        "exporter": "distlr_tpu.obs.profile",
        "name": "distlr fleet profile",
    }
    os.makedirs(os.path.dirname(os.path.abspath(out_path)), exist_ok=True)
    tmp = f"{out_path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, out_path)
    return doc
