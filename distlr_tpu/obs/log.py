"""Fleet-wide structured logging (ISSUE 18).

Every prior observability layer made a *signal* first-class — metrics
(PR 3), traces (PR 8), profiles (PR 9), time series (PR 17) — while the
fleet's narrative stayed unstructured stderr: greppable by a human on
one host, invisible to the aggregator, uncorrelatable with anything.
This module makes log records the last first-class signal:

* a jax-free :class:`FleetLogger` journals JSONL records to
  ``<obs_run_dir>/logs/<role>-<rank>.jsonl`` — bounded (the dtrace
  span-journal cap discipline), batch-flushed (WARN+ records flush
  eagerly so an incident collector reading mid-flight sees them), and
  rate-limit deduped: identical ``(level, logger, template)`` records
  inside the dedupe window collapse into one journaled record carrying
  a ``suppressed`` count;
* each record is stamped with the active dtrace trace/span ids
  (:func:`distlr_tpu.obs.dtrace.current_ids`), so ``launch logs
  --trace <id>`` pulls one request's log+span story across ranks;
* a bounded in-memory ring keeps the most recent records regardless of
  the journal level — like the flight recorder's span ring, the ring
  holds what the level filter discarded;
* records derive ``distlr_log_records_total{level,role}`` (plus
  suppressed/dropped counters), so the fleet scrape — and the PR-17
  recording rules — see per-rank ERROR rates without reading a file.

The existing human-readable stderr path is untouched: the stdlib
loggers ``distlr_tpu.utils.logging.get_logger`` hands out keep their
stderr handler and formats, and this module merely attaches one extra
:class:`logging.Handler` that tees every record into the journal.  Call
sites keep writing ``log.warning(...)`` exactly as before.

Stdlib-only and jax-free, like the rest of ``obs``.  All shared state
is guarded by a :mod:`distlr_tpu.sync` lock (virtualized under
schedcheck's ``log_ring_incident_assemble`` scenario); the monitoring
counters are deliberately lock-free reads (audited in the concurrency
baseline).
"""

from __future__ import annotations

import collections
import contextlib
import json
import logging as _stdlib_logging
import os
import time

from distlr_tpu import sync
from distlr_tpu.obs import dtrace
from distlr_tpu.obs.registry import get_registry
from distlr_tpu.utils import logging as _ulog

_reg = get_registry()
_RECORDS = _reg.counter(
    "distlr_log_records_total",
    "structured log records journaled, by level and role (suppressed "
    "duplicates and below-level records are counted separately)",
    labelnames=("level", "role"),
)
_SUPPRESSED = _reg.counter(
    "distlr_log_suppressed_total",
    "log records collapsed into a dedupe summary instead of journaled, "
    "by level and role",
    labelnames=("level", "role"),
)
_DROPPED = _reg.counter(
    "distlr_log_journal_dropped_total",
    "records dropped after the per-process log-journal cap (the ring "
    "and metrics keep running)",
)

#: record levels, weakest first; numbers mirror the stdlib so stdlib
#: LogRecords map without a table
LEVELS = ("debug", "info", "warning", "error")
_LEVEL_NO = {"debug": 10, "info": 20, "warning": 30, "error": 40}

#: per-process record cap of the journal (dtrace.MAX_JOURNAL_SPANS
#: discipline: a runaway log stream bounds disk, loudly)
MAX_JOURNAL_RECORDS = 200_000
#: default bounded in-memory ring capacity
RING_CAPACITY = 2048
#: default dedupe window seconds (0 journals every record)
DEDUPE_WINDOW_S = 5.0
#: journal lines buffered before a flush (the PR-8 budget discipline);
#: WARN+ records flush eagerly regardless
FLUSH_EVERY = 64
#: dedupe-table size bound: past this, expired entries with nothing
#: pending are pruned on insert (stdlib templates are a bounded set,
#: but direct emit() callers with varying messages are not)
DEDUPE_TABLE_MAX = 4096


def _level_name(levelno: int) -> str:
    if levelno >= 40:
        return "error"
    if levelno >= 30:
        return "warning"
    if levelno >= 20:
        return "info"
    return "debug"


class FleetLogger:
    """Per-process structured log sink: dedupe table, bounded ring, and
    a JSONL journal.  ``run_dir=None`` keeps the ring + metrics only
    (no journal) — what bench rows and unit tests use."""

    def __init__(self, run_dir: str | None, role: str, rank: int, *,
                 level: str = "info", ring: int = RING_CAPACITY,
                 dedupe_s: float = DEDUPE_WINDOW_S):
        if level not in LEVELS:
            raise ValueError(f"level must be one of {LEVELS}, got {level!r}")
        if ring < 1:
            raise ValueError(f"ring must be >= 1, got {ring}")
        if dedupe_s < 0:
            raise ValueError(f"dedupe_s must be >= 0, got {dedupe_s}")
        self.run_dir = run_dir
        self.role, self.rank = str(role), int(rank)
        self.level = level
        self.levelno = _LEVEL_NO[level]
        self.dedupe_s = float(dedupe_s)
        self._lock = sync.Lock()
        self._ring: collections.deque = collections.deque(maxlen=int(ring))
        #: dedupe key -> [window_start_monotonic, suppressed_count]
        self._dedupe: dict[tuple, list] = {}
        self._journal_path: str | None = None
        self._journal_file = None
        self._journal_written = 0
        self._journal_unflushed = 0
        # monitoring counters: written under _lock, read lock-free by
        # stats() (monotonic ints; a racing reader sees the previous
        # record's values — audited in the concurrency baseline, raced
        # by the log_ring_incident_assemble schedcheck scenario)
        self.records_total = 0
        self.suppressed_total = 0
        # metric children resolved once (.labels() takes the registry
        # lock, and emit runs on every record)
        self._rec_children = {lv: _RECORDS.labels(level=lv, role=self.role)
                              for lv in LEVELS}
        self._sup_children = {lv: _SUPPRESSED.labels(level=lv,
                                                     role=self.role)
                              for lv in LEVELS}
        if run_dir:
            d = os.path.join(run_dir, "logs")
            os.makedirs(d, exist_ok=True)
            self._journal_path = os.path.join(
                d, f"{self.role}-{self.rank}.jsonl")
            self._journal_line({
                "type": "meta", "role": self.role, "rank": self.rank,
                "pid": os.getpid(), "level": self.level,
            }, eager=True)

    # -- the emit path -----------------------------------------------------
    def emit(self, level: str, msg: str, *, logger: str = "distlr_tpu",
             template: str | None = None, args: dict | None = None) -> dict:
        """Record one structured log record.  ``template`` is the
        dedupe identity (the pre-format message for stdlib records);
        it defaults to ``msg``.  Returns the record dict (its
        ``suppressed`` key is absent unless it closed a dedupe
        window)."""
        if level not in LEVELS:
            level = _level_name(_LEVEL_NO.get(level, 20))
        rec = {
            "type": "record",
            "ts": round(time.time(), 6),
            "level": level,
            "role": self.role,
            "rank": self.rank,
            "logger": logger,
            "msg": str(msg),
        }
        ids = dtrace.current_ids()
        if ids is not None:
            rec["trace"] = f"{ids[0]:016x}"
            rec["span"] = f"{ids[1]:016x}"
        if args:
            rec["args"] = dict(args)
        levelno = _LEVEL_NO[level]
        key = (level, logger, template if template is not None else str(msg))
        now_mono = sync.monotonic()
        with self._lock:
            self._ring.append(rec)
            if levelno < self.levelno:
                return rec  # ring-only: below the journal level
            if self.dedupe_s > 0:
                st = self._dedupe.get(key)
                if st is not None and now_mono - st[0] < self.dedupe_s:
                    st[1] += 1
                    self.suppressed_total += 1
                    self._sup_children[level].inc()
                    return rec
                if st is not None and st[1] > 0:
                    # window expired with duplicates folded in: this
                    # record closes it and carries the count
                    rec["suppressed"] = st[1]
                if len(self._dedupe) >= DEDUPE_TABLE_MAX:
                    # entries with a pending count survive the prune:
                    # their count still has to ride the key's next record
                    cutoff = now_mono - self.dedupe_s
                    for k in [k for k, s in self._dedupe.items()
                              if s[0] < cutoff and not s[1]]:
                        del self._dedupe[k]
                self._dedupe[key] = [now_mono, 0]
            self.records_total += 1
            self._rec_children[level].inc()
            # WARN+ flushes eagerly: the incident collector reads other
            # processes' journals seconds after the alert edge, and an
            # error buried in a 64-line buffer would miss its bundle
            self._journal_line_locked(rec, eager=levelno >= 30)
        return rec

    def handle_stdlib(self, record: _stdlib_logging.LogRecord) -> None:
        """Bridge one stdlib LogRecord (the tee handler's path).  The
        record's pre-format template is the dedupe identity, so a
        formatted message varying per occurrence ("rank 3 timed out")
        still collapses."""
        try:
            msg = record.getMessage()
        except Exception:  # noqa: BLE001 — logging must never fail work
            msg = str(record.msg)
        self.emit(_level_name(record.levelno), msg, logger=record.name,
                  template=str(record.msg))

    # -- journal I/O -------------------------------------------------------
    def _journal_line(self, doc: dict, *, eager: bool = False) -> None:
        with self._lock:
            self._journal_line_locked(doc, eager=eager)

    def _journal_line_locked(self, doc: dict, *, eager: bool = False) -> None:
        if self._journal_path is None:
            return
        if doc.get("type") == "record":
            if self._journal_written >= MAX_JOURNAL_RECORDS:
                _DROPPED.inc()
                return
            self._journal_written += 1
        try:
            if self._journal_file is None:
                self._journal_file = open(self._journal_path, "a")
            self._journal_file.write(json.dumps(doc) + "\n")
            self._journal_unflushed += 1
            if eager or self._journal_unflushed >= FLUSH_EVERY:
                self._journal_file.flush()
                self._journal_unflushed = 0
        except OSError:
            pass  # logging must never fail the logged work

    def flush(self) -> None:
        with self._lock:
            if self._journal_file is not None:
                with contextlib.suppress(OSError):
                    self._journal_file.flush()
                self._journal_unflushed = 0

    def close(self) -> None:
        with self._lock:
            if self._journal_file is not None:
                with contextlib.suppress(OSError):
                    self._journal_file.flush()
                    self._journal_file.close()
                self._journal_file = None

    # -- reads -------------------------------------------------------------
    def tail(self, n: int = 50) -> list[dict]:
        """The most recent ``n`` ring records (every level — the ring
        keeps what the journal level filtered out)."""
        with self._lock:
            recs = list(self._ring)
        return recs[-n:]

    def stats(self) -> dict:
        """Lock-free monitoring snapshot (``AutopilotDaemon.status()``
        stance: monotonic ints, a racing reader sees the previous
        record's values — audited in the concurrency baseline)."""
        return {
            "records": self.records_total,
            "suppressed": self.suppressed_total,
            "journal": self._journal_path,
        }

    def flight_info(self, reason: str, seq: int | None) -> dict:
        """dtrace flight-dump cross-reference: where this process's log
        journal lives, so the flight dump and the incident bundle name
        the same file."""
        return {"log_journal": self._journal_path}


# ---------------------------------------------------------------------------
# the stdlib tee handler + module singleton (what _obs_scope arms)
# ---------------------------------------------------------------------------


class _JournalHandler(_stdlib_logging.Handler):
    """The one extra handler attached to every ``distlr_tpu*`` stdlib
    logger while a FleetLogger is configured: tees each record into the
    journal without touching the stderr handler or its format."""

    def __init__(self, fleet: FleetLogger):
        super().__init__(level=0)
        self.fleet = fleet

    def emit(self, record: _stdlib_logging.LogRecord) -> None:
        try:
            self.fleet.handle_stdlib(record)
        except Exception:  # noqa: BLE001 — logging must never fail work
            pass


_LOGGER: FleetLogger | None = None
_HANDLER: _JournalHandler | None = None
_ATEXIT_INSTALLED = False


def _provider() -> _stdlib_logging.Handler | None:
    return _HANDLER


def _attach_everywhere(handler: _JournalHandler) -> None:
    """Attach to every live ``distlr_tpu*`` logger.  Loggers created
    AFTER configure get the handler through the get_logger provider
    hook (:func:`distlr_tpu.utils.logging.register_extra_handler`)."""
    for name, logger in list(
            _stdlib_logging.Logger.manager.loggerDict.items()):
        if not isinstance(logger, _stdlib_logging.Logger):
            continue
        if name == "distlr_tpu" or name.startswith("distlr_tpu."):
            if handler not in logger.handlers:
                logger.addHandler(handler)


def _detach_everywhere(handler: _JournalHandler) -> None:
    for logger in list(_stdlib_logging.Logger.manager.loggerDict.values()):
        if isinstance(logger, _stdlib_logging.Logger) \
                and handler in logger.handlers:
            logger.removeHandler(handler)


def configure(run_dir: str | None, role: str, rank: int, *,
              level: str = "info", ring: int = RING_CAPACITY,
              dedupe_s: float = DEDUPE_WINDOW_S) -> FleetLogger:
    """Arm (or re-arm) this process's structured log sink and tee every
    ``distlr_tpu*`` stdlib logger into it.  Safe to call again (tests,
    multi-command processes): the previous sink detaches and flushes
    first."""
    global _LOGGER, _HANDLER, _ATEXIT_INSTALLED
    if _LOGGER is not None:
        stop()
    _LOGGER = FleetLogger(run_dir, role, rank, level=level, ring=ring,
                          dedupe_s=dedupe_s)
    _HANDLER = _JournalHandler(_LOGGER)
    _attach_everywhere(_HANDLER)
    _ulog.register_extra_handler(_provider)
    dtrace.register_flight_info(_LOGGER.flight_info)
    if not _ATEXIT_INSTALLED:
        import atexit  # noqa: PLC0415

        atexit.register(flush)
        _ATEXIT_INSTALLED = True
    return _LOGGER


def is_configured() -> bool:
    return _LOGGER is not None


def fleet_logger() -> FleetLogger | None:
    return _LOGGER


def emit(level: str, msg: str, *, logger: str = "distlr_tpu",
         args: dict | None = None) -> dict | None:
    """Module-level emit (debug-level structured records, CLI paths):
    a no-op returning None until :func:`configure` ran."""
    if _LOGGER is None:
        return None
    return _LOGGER.emit(level, msg, logger=logger, args=args)


def flush() -> None:
    if _LOGGER is not None:
        _LOGGER.flush()


def stop() -> None:
    global _LOGGER, _HANDLER
    if _HANDLER is not None:
        _detach_everywhere(_HANDLER)
        _HANDLER = None
    _ulog.unregister_extra_handler(_provider)
    if _LOGGER is not None:
        dtrace.unregister_flight_info(_LOGGER.flight_info)
        _LOGGER.close()
        _LOGGER = None


def reset_for_tests() -> None:
    stop()


# ---------------------------------------------------------------------------
# journal reading (the `launch logs` CLI + the incident collector)
# ---------------------------------------------------------------------------


def read_journal(path: str) -> list[dict]:
    """One journal's records (meta lines skipped; torn tail lines
    skipped, like every obs merge reader)."""
    out = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    doc = json.loads(line)
                except ValueError:
                    continue
                if doc.get("type") == "record":
                    out.append(doc)
    except OSError:
        pass
    return out


def read_records(run_dirs, *, level: str | None = None,
                 grep: str | None = None, trace: str | None = None,
                 since: float | None = None, until: float | None = None,
                 limit: int | None = None) -> list[dict]:
    """Merge every ``<run_dir>/logs/*.jsonl`` journal into one
    time-ordered record list, optionally filtered by minimum level,
    substring, trace id, and a time window.  The fleet-wide query
    behind ``launch logs`` and the incident bundle's log collection."""
    if isinstance(run_dirs, str):
        run_dirs = [run_dirs]
    min_no = _LEVEL_NO[level] if level else 0
    want_trace = trace.lower().lstrip("0") if trace else None
    out: list[dict] = []
    for d in run_dirs:
        logs_dir = os.path.join(d, "logs")
        if not os.path.isdir(logs_dir):
            continue
        for name in sorted(os.listdir(logs_dir)):
            if not name.endswith(".jsonl"):
                continue
            for rec in read_journal(os.path.join(logs_dir, name)):
                if _LEVEL_NO.get(rec.get("level"), 0) < min_no:
                    continue
                ts = rec.get("ts", 0.0)
                if since is not None and ts < since:
                    continue
                if until is not None and ts > until:
                    continue
                if grep and grep not in rec.get("msg", ""):
                    continue
                if want_trace is not None and \
                        str(rec.get("trace", "")).lstrip("0") != want_trace:
                    continue
                out.append(rec)
    out.sort(key=lambda r: r.get("ts", 0.0))
    if limit is not None and limit > 0:
        out = out[-limit:]
    return out
