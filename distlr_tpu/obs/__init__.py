"""Unified observability: metrics registry, phase tracing, exporters.

The cross-cutting layer every subsystem reports through (ISSUE 2): one
process-wide :class:`MetricsRegistry` of labeled counters/gauges/
histograms (PS server lifecycle + supervisor events, PS client op
latency/bytes, trainer step rate and staleness, serving occupancy and
request latency), a :func:`trace_phase` span API whose per-phase
breakdown explains where step time went (Chrome trace-event dumps load
in Perfetto), and exporters: Prometheus text + JSON snapshot over a
stdlib HTTP endpoint (``--metrics-port`` / ``Config.obs_metrics_port``).

Metric namespace (see README "Observability" for the full table):

* ``distlr_ps_server_*``  — ServerGroup/ServerSupervisor lifecycle
* ``distlr_ps_client_*``  — native KV client ops, latency, bytes
* ``distlr_train_*``      — step/sample counters, rates, staleness
  (seconds gauge AND the ``_staleness_pushes`` Hogwild histogram)
* ``distlr_serve_*``      — request/engine/batcher series
* ``distlr_phase_seconds``— per-phase histogram behind the tracer
* ``distlr_fleet_*`` / ``distlr_alert_*`` — fleet-scrape meta-series
  and derived alert gauges (:mod:`distlr_tpu.obs.federate`, served by
  ``launch obs-agg`` and rendered live by ``launch top``)
* ``distlr_trace_*``      — distributed-trace span/journal/flight-
  recorder accounting (:mod:`distlr_tpu.obs.dtrace`, merged by
  ``launch trace-agg``)
* ``distlr_prof_*``       — continuous-profiling sampler/window/burst
  accounting (:mod:`distlr_tpu.obs.profile`, merged by
  ``launch prof-agg``)
* ``distlr_jax_*``        — JAX runtime introspection: jit compile
  counts + live device-buffer bytes (:mod:`distlr_tpu.obs.jaxrt`)
* ``distlr_kv_server_*``  — native-server runtime mirrored from the
  kStats probe (per-handler thread-CPU seconds)

The complete generated reference is ``docs/METRICS.md``
(:mod:`distlr_tpu.obs.metrics_doc`; a tier-1 lint keeps it in sync).
"""

from distlr_tpu.obs.exporters import (  # noqa: F401
    MetricsServer,
    install_snapshot_atexit,
    snapshot_env_paths,
    start_metrics_server,
    write_metrics_snapshot,
)
from distlr_tpu.obs.federate import (  # noqa: F401
    AlertThresholds,
    FleetMergeError,
    FleetScraper,
    discover_endpoints,
    evaluate_alerts,
    merge_snapshots,
    write_endpoint,
)
from distlr_tpu.obs.registry import (  # noqa: F401
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    get_registry,
)
from distlr_tpu.obs.tracing import (  # noqa: F401
    PhaseTracer,
    get_tracer,
    trace_phase,
)

# One-shot processes (bench.py under capture_all_tpu.sh) bank their
# metrics via DISTLR_METRICS_SNAPSHOT=<path> instead of holding a port.
install_snapshot_atexit()
