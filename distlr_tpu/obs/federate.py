"""Fleet federation: one scrape for a whole PS run (ISSUE 3).

A real ps deployment is 1 scheduler-equivalent + S server hosts + W
worker processes, and PR 2 left each of them an island: every process
serves its own ``/metrics`` and nothing sees the run as a whole.  This
module is the fleet layer on top of those per-process endpoints:

* **endpoint discovery** — every launched process with
  ``Config.obs_run_dir`` set writes ``<run_dir>/endpoints/<role>-<rank>
  .json`` (role, rank, host, port, pid) next to its ``METRICS
  host:port`` stdout announcement; :func:`discover_endpoints` re-lists
  the directory every poll, so late joiners appear without restarts.
  One-shot processes that cannot hold a port (``bench.py`` under
  ``capture_all_tpu.sh``) instead bank a JSON registry snapshot under
  ``<run_dir>/snapshots/<role>-<rank>.json`` (the
  ``DISTLR_METRICS_SNAPSHOT`` twin) — the scraper merges both sources.

* **federation** — :class:`FleetScraper` polls each endpoint's
  ``/metrics.json`` and merges the families into ONE fleet registry:
  counters SUM across ranks, histograms merge bucket-wise (boundary
  mismatches are rejected loudly, never silently summed), and gauges
  keep per-rank identity via added ``role``/``rank`` labels (an
  original label named ``role``/``rank`` is renamed ``exported_*``,
  the Prometheus federation convention).  ``distlr_fleet_scrape_*``
  meta-series mark every rank up / stale / down, so a dashboard can
  tell "worker 3 died" from "worker 3 has no errors".

* **derived alerts** — :func:`evaluate_alerts` computes
  ``distlr_alert_*`` 0/1 gauges (threshold carried as a label) from the
  merged families: barrier-wait p99 vs median step time (the straggler
  signal), PS push error rate, scrape staleness, and async weight age
  vs step time.  The inputs (``distlr_fleet_*`` value gauges) are
  exported too, so the thresholds are auditable from the same scrape.

``launch obs-agg`` serves the merged view as ``/metrics`` +
``/metrics.json`` + ``/fleet.json`` (the structured per-rank summary
``launch top`` renders live).
"""

from __future__ import annotations

import concurrent.futures
import dataclasses
import json
import math
import os
import threading
import time
import urllib.request

from distlr_tpu.obs import incident as incident_mod
from distlr_tpu.obs import slo as slo_mod
from distlr_tpu.obs import tsdb as tsdb_mod
from distlr_tpu.obs.registry import MetricsRegistry, percentile_from_counts
from distlr_tpu.utils.logging import get_logger

log = get_logger(__name__)

#: Ops whose failures count toward the push error-rate alert.
_PUSH_OPS = ("push", "push_pull", "push_init")


class FleetMergeError(ValueError):
    """Two ranks disagree on a family's shape (type, label names, or
    histogram bucket boundaries) — summing them would silently alias two
    meanings onto one series, so the merge refuses instead."""


# ---------------------------------------------------------------------------
# endpoint discovery
# ---------------------------------------------------------------------------

def endpoint_path(run_dir: str, role: str, rank: int | str) -> str:
    return os.path.join(run_dir, "endpoints", f"{role}-{rank}.json")


def write_endpoint(run_dir: str, role: str, rank: int | str, host: str,
                   port: int, *, pid: int | None = None) -> str:
    """Atomically publish this process's scrape endpoint into the run
    dir (the fleet-discovery contract every ``launch`` subcommand
    honors when ``--obs-run-dir`` is set)."""
    path = endpoint_path(run_dir, role, rank)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    try:
        with open(path) as f:
            prev = json.load(f)
        if (prev.get("host"), prev.get("port")) != (host, int(port)):
            # Two processes claimed the same (role, rank) — e.g. two
            # `ps-server` hosts sharing a run dir, neither passing
            # --process-id.  The merge keys on (role, rank), so the
            # first publisher silently vanishes from the fleet (no
            # scrape, no down alert).  Surface it loudly; the fix is a
            # distinct rank per process (--process-id / --worker-ranks).
            log.warning(
                "fleet endpoint %s-%s already published by %s:%s "
                "(pid %s); overwriting with %s:%s — give each process a "
                "distinct rank (--process-id) or the hidden one will "
                "neither scrape nor alert",
                role, rank, prev.get("host"), prev.get("port"),
                prev.get("pid"), host, port)
    except (OSError, ValueError):
        pass  # absent or unreadable: normal first publish
    doc = {
        "role": str(role),
        "rank": int(rank),
        "host": host,
        "port": int(port),
        "pid": os.getpid() if pid is None else int(pid),
        "started_at": time.time(),
    }
    # per-pid tmp name: two processes racing to publish the same (role,
    # rank) — e.g. replicas launched in the same instant without
    # --process-id — must land on the warning above, not crash in
    # os.replace because one mv'd the other's shared tmp file away
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
    return path


def discover_endpoints(run_dir: str) -> list[dict]:
    """All parseable endpoint files under ``<run_dir>/endpoints``,
    sorted by (role, rank).  Unparseable files (a writer mid-crash) are
    skipped, not fatal — the next poll retries them."""
    d = os.path.join(run_dir, "endpoints")
    out = []
    if not os.path.isdir(d):
        return out
    for name in sorted(os.listdir(d)):
        if not name.endswith(".json"):
            continue
        try:
            with open(os.path.join(d, name)) as f:
                doc = json.load(f)
            out.append({
                "role": str(doc["role"]),
                "rank": int(doc["rank"]),
                "host": str(doc["host"]),
                "port": int(doc["port"]),
                "pid": int(doc.get("pid", 0)),
            })
        except (OSError, ValueError, KeyError):
            continue
    out.sort(key=lambda e: (e["role"], e["rank"]))
    return out


def discover_snapshot_files(run_dir: str) -> list[dict]:
    """Banked JSON registry snapshots under ``<run_dir>/snapshots``
    (``<role>-<rank>.json``, the DISTLR_METRICS_SNAPSHOT twin) — the
    portless half of the fleet (one-shot bench processes)."""
    d = os.path.join(run_dir, "snapshots")
    out = []
    if not os.path.isdir(d):
        return out
    for name in sorted(os.listdir(d)):
        stem, ext = os.path.splitext(name)
        if ext != ".json" or "-" not in stem:
            continue
        role, _, rank = stem.rpartition("-")
        if not rank.isdigit():
            continue
        out.append({"role": role, "rank": int(rank),
                    "path": os.path.join(d, name)})
    out.sort(key=lambda e: (e["role"], e["rank"]))
    return out


# ---------------------------------------------------------------------------
# snapshot math helpers (shared by the merge and /fleet.json summaries)
# ---------------------------------------------------------------------------

def _hist_parts(entry: dict) -> tuple[tuple[float, ...], list[int], int]:
    """Decompose one histogram series snapshot into ``(boundaries,
    per-bucket counts incl. the +Inf slot, total count)`` — the
    snapshot's bucket dict is CUMULATIVE (Prometheus ``le`` semantics)."""
    pairs = sorted((float(b), int(c)) for b, c in entry["buckets"].items())
    bounds = tuple(b for b, _ in pairs)
    counts, prev = [], 0
    for _, cum in pairs:
        counts.append(cum - prev)
        prev = cum
    total = int(entry["count"])
    counts.append(total - prev)  # +Inf slot
    return bounds, counts, total


def _snap_hist_percentiles(snap: dict, name: str, qs: tuple[float, ...],
                           where: dict | None = None):
    """Percentiles of a histogram family in one rank's snapshot, summing
    every series whose labels contain ``where``.  None when absent/empty."""
    fam = snap.get(name)
    if not fam or fam.get("type") != "histogram":
        return None
    bounds = None
    counts: list[int] = []
    for s in fam.get("series", []):
        if where and any(s["labels"].get(k) != v for k, v in where.items()):
            continue
        b, c, _ = _hist_parts(s)
        if bounds is None:
            bounds, counts = b, list(c)
        elif b == bounds:
            counts = [x + y for x, y in zip(counts, c)]
    if bounds is None or sum(counts) == 0:
        return None
    return tuple(percentile_from_counts(bounds, counts, q) for q in qs)


def _snap_sum(snap: dict, name: str, where: dict | None = None) -> float:
    """Sum of a counter/gauge family's series values in one snapshot."""
    fam = snap.get(name)
    if not fam:
        return 0.0
    tot = 0.0
    for s in fam.get("series", []):
        if where and any(s["labels"].get(k) != v for k, v in where.items()):
            continue
        if "value" in s:
            tot += float(s["value"])
    return tot


def _snap_max(snap: dict, name: str) -> float | None:
    fam = snap.get(name)
    if not fam:
        return None
    vals = [float(s["value"]) for s in fam.get("series", []) if "value" in s]
    return max(vals) if vals else None


_bad_journals_warned: set[str] = set()


def _read_autopilot_last_action(run_dirs: list[str]) -> dict | None:
    """Tail the autopilot's decision journal for the last ACTION (not
    the last tick — steady/hold rows carry no action).  Best-effort:
    the journal is append-only JSONL, so reading the final few KB is
    enough, and a missing/partial file just yields None.  The FIRST
    line must be the ISSUE-19 ``{"schema": 1}`` header — a headerless
    or unknown-schema journal is rejected LOUDLY (warned once per
    path), because its decision lines may not mean what this build
    thinks they mean."""
    from distlr_tpu.autopilot.daemon import JOURNAL_SCHEMA  # noqa: PLC0415

    for d in run_dirs:
        path = os.path.join(d, "autopilot", "decisions.jsonl")
        try:
            with open(path, "rb") as f:
                try:
                    header = json.loads(f.readline().decode(
                        "utf-8", "replace"))
                except ValueError:
                    header = None
                if (not isinstance(header, dict)
                        or header.get("kind") != "autopilot_decisions"
                        or header.get("schema") != JOURNAL_SCHEMA):
                    if path not in _bad_journals_warned:
                        _bad_journals_warned.add(path)
                        log.warning(
                            "ignoring autopilot journal %s: missing or "
                            "unknown schema header (want {\"schema\": %d, "
                            "\"kind\": \"autopilot_decisions\"})",
                            path, JOURNAL_SCHEMA)
                    continue
                f.seek(0, os.SEEK_END)
                f.seek(max(0, f.tell() - 65536))
                lines = f.read().decode("utf-8", "replace").splitlines()
        except OSError:
            continue
        for line in reversed(lines):
            try:
                doc = json.loads(line)
            except ValueError:
                continue
            if isinstance(doc, dict) and doc.get("action"):
                return {"t": doc.get("t"), "rule": doc.get("rule"),
                        "outcome": doc.get("outcome"), **doc["action"]}
    return None


# ---------------------------------------------------------------------------
# the merge
# ---------------------------------------------------------------------------

def merge_snapshots(snaps: dict[tuple[str, int], dict], *,
                    registry: MetricsRegistry | None = None,
                    on_conflict: str = "raise") -> tuple[MetricsRegistry,
                                                         list[str]]:
    """Merge per-rank registry snapshots into one fleet registry.

    ``snaps`` maps ``(role, rank)`` to that rank's ``/metrics.json``
    document.  Merge rules (the federation contract):

    * **counters** sum across ranks under their original labels (fleet
      totals: ops, bytes, samples);
    * **histograms** merge bucket-wise — identical boundary ladders sum
      per-bucket; a mismatched ladder raises :class:`FleetMergeError`
      (``on_conflict="raise"``) or drops that rank's family and records
      it in the returned conflict list (``"drop"``, what the live
      scraper does — loudly, via log + meta-counter, never by summing
      misaligned buckets);
    * **gauges** keep per-rank identity: ``role``/``rank`` labels are
      prepended (original labels named ``role``/``rank`` are renamed
      ``exported_role``/``exported_rank``), because summing a gauge
      (a rate, an age, an up-flag) across ranks destroys exactly the
      per-rank signal a fleet view exists to show.

    A family whose TYPE or label names differ across ranks conflicts as
    a whole (same policy as buckets).  Ranks merge in sorted order, so
    first-seen shape wins and the outcome is deterministic.
    """
    if on_conflict not in ("raise", "drop"):
        raise ValueError(f"on_conflict must be raise|drop, got {on_conflict!r}")
    reg = registry if registry is not None else MetricsRegistry()
    conflicts: list[str] = []
    # first-seen shape per family: (kind, labelnames, bounds|None)
    shapes: dict[str, tuple] = {}

    def _conflict(rank_key, name, why):
        msg = (f"fleet merge: {name!r} from {rank_key[0]}-{rank_key[1]} "
               f"conflicts with the first-seen shape ({why})")
        if on_conflict == "raise":
            raise FleetMergeError(msg)
        log.error("%s — dropping this rank's family, NOT summing it", msg)
        conflicts.append(f"{rank_key[0]}-{rank_key[1]}:{name}")

    for rank_key in sorted(snaps):
        role, rank = rank_key
        for name, fam in snaps[rank_key].items():
            kind = fam.get("type", "gauge")
            series = fam.get("series", [])
            if not series:
                continue  # no children yet: label names unknowable
            labelnames = tuple(series[0]["labels"])
            bounds = None
            if kind == "histogram":
                bounds = _hist_parts(series[0])[0]
            seen = shapes.get(name)
            if seen is None:
                shapes[name] = (kind, labelnames, bounds)
            elif seen[0] != kind or seen[1] != labelnames:
                _conflict(rank_key, name,
                          f"type/labels {kind}/{labelnames} vs "
                          f"{seen[0]}/{seen[1]}")
                continue
            elif kind == "histogram" and seen[2] != bounds:
                _conflict(rank_key, name,
                          f"bucket boundaries {bounds} vs {seen[2]}")
                continue

            help_ = fam.get("help", "")
            if kind == "counter":
                out = reg.counter(name, help_, labelnames)
                for s in series:
                    out.labels(**s["labels"]).inc(float(s["value"]))
            elif kind == "histogram":
                out = reg.histogram(name, help_, labelnames, buckets=bounds)
                for s in series:
                    b, counts, total = _hist_parts(s)
                    if b != bounds:
                        _conflict(rank_key, name,
                                  f"bucket boundaries {b} vs {bounds}")
                        continue
                    child = out.labels(**s["labels"])
                    # merge bucket-wise into the child's internal counts
                    # (same package; a public "add counts" API would only
                    # exist for this one caller)
                    with child._lock:
                        for i, c in enumerate(counts):
                            child._counts[i] += c
                        child._sum += float(s["sum"])
                        child._count += total
            else:  # gauge (and any future untyped): per-rank identity
                renamed = tuple(
                    f"exported_{n}" if n in ("role", "rank") else n
                    for n in labelnames
                )
                out = reg.gauge(name, help_, ("role", "rank") + renamed)
                for s in series:
                    labels = {"role": role, "rank": str(rank)}
                    labels.update(
                        (f"exported_{k}" if k in ("role", "rank") else k, v)
                        for k, v in s["labels"].items()
                    )
                    out.labels(**labels).set(float(s["value"]))
    return reg, conflicts


# ---------------------------------------------------------------------------
# derived alerts
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class AlertThresholds:
    """Thresholds behind the ``distlr_alert_*`` gauges.  Each gauge
    carries its threshold as a label, so a scrape is self-describing."""

    #: barrier-wait p99 fires above this multiple of the median step time
    #: (a healthy BSP barrier is ~one peer's step; a straggler is many).
    barrier_wait_ratio: float = 2.0
    #: minimum barrier_wait observations before the stall alert may fire:
    #: every run records a couple of one-time startup/exit rendezvous
    #: spans whose wait is legitimately long (peers still parsing shards)
    #: — two samples of startup skew are not a straggler.
    barrier_min_count: int = 8
    #: PS push error+timeout rate (errors / total push-family ops).
    push_error_rate: float = 0.01
    #: seconds since a rank's last successful scrape before it alerts.
    scrape_stale_s: float = 10.0
    #: async weight age fires above this multiple of the median step time
    #: (Hogwild self-staleness is ~1 in-flight step; 10x means a worker
    #: is computing on ancient weights).
    weight_age_ratio: float = 10.0
    #: fleet retry fraction — the share of KV op ATTEMPTS that are
    #: retry re-issues (retries / total attempts; failed attempts count
    #: in the denominator, so the ratio is bounded [0, 1) and rises
    #: toward 1 as every op needs more tries).  Above this,
    #: distlr_alert_ps_retry_rate fires — the "network is degraded but
    #: the retry layer is absorbing it" signal; it alerts BEFORE the
    #: error-rate alert (retries precede failures).
    retry_rate: float = 0.05
    #: shadow-scoring PSI (distlr_tenant_shadow_psi) above which
    #: distlr_alert_shadow_psi fires PER (tenant, candidate) series —
    #: the one alert family ATTRIBUTABLE to a specific model version,
    #: which is what lets `launch rollout` gate a candidate's ramp on
    #: the candidate's OWN evidence instead of any fleet alert (the
    #: scoped-SLO-gating contract; see serve.rollout.attributable).
    #: Same default as the drift detector's PSI threshold.
    shadow_psi: float = 0.25

    @classmethod
    def resolve(cls, path: str | None = None, **overrides) -> "AlertThresholds":
        """Effective thresholds for one run: dataclass defaults, overlaid
        by a JSON thresholds file, overlaid by non-``None`` explicit
        overrides (the ``launch obs-agg`` CLI flags).  Unknown keys —
        in the file or the overrides — raise: a typo must not silently
        leave a default in force."""
        names = {f.name for f in dataclasses.fields(cls)}
        kw: dict = {}
        if path:
            with open(path) as f:
                doc = json.load(f)
            if not isinstance(doc, dict):
                raise ValueError(
                    f"thresholds file {path} must hold a JSON object")
            unknown = sorted(set(doc) - names)
            if unknown:
                raise ValueError(
                    f"unknown threshold(s) {unknown} in {path}; "
                    f"known: {sorted(names)}")
            kw.update(doc)
        for k, v in overrides.items():
            if k not in names:
                raise ValueError(f"unknown threshold override {k!r}; "
                                 f"known: {sorted(names)}")
            if v is not None:
                kw[k] = v
        for k, v in list(kw.items()):
            # values must be numbers NOW, not when evaluate_alerts
            # formats a threshold label mid-cycle (where the daemon's
            # bad-cycle guard would swallow the crash every scrape and
            # the alert gauges would silently never publish)
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                raise ValueError(
                    f"threshold {k!r} must be a number, got {v!r}")
            if k == "barrier_min_count":
                if v != int(v):
                    # truncating 8.7 -> 8 would label an effective value
                    # the operator never wrote
                    raise ValueError(
                        f"threshold {k!r} must be an integer, got {v!r}")
                kw[k] = int(v)
            else:
                kw[k] = float(v)
        return cls(**kw)


def _merged_hist_child(reg: MetricsRegistry, name: str,
                       prefer: dict | None = None, *,
                       strict: bool = False):
    """A histogram child to take percentiles from: the labeled child
    matching ``prefer`` if it has observations, else (non-``strict``
    only) the busiest child.  ``strict`` is for label-selective reads
    like the barrier-wait phase, where falling back to a DIFFERENT
    label's series would alert on the wrong signal."""
    fam = reg.get(name)
    if fam is None or fam.kind != "histogram":
        return None
    children = fam.children()
    if not children:
        return None
    if prefer:
        want = tuple(prefer.get(n, None) for n in fam.labelnames)
        for values, child in children:
            if values == want and child.count:
                return child
        if strict:
            return None
    best = max(children, key=lambda vc: vc[1].count)[1]
    return best if best.count else None


def evaluate_alerts(reg: MetricsRegistry, *, thresholds: AlertThresholds,
                    rank_ages: dict[tuple[str, int], float] | None = None,
                    ) -> list[dict]:
    """Compute the ``distlr_alert_*`` 0/1 gauges (+ their
    ``distlr_fleet_*`` input-value gauges) inside the merged registry.

    Returns the structured alert list ``/fleet.json`` carries.  All six
    alert families are always declared — a scrape can tell "not firing"
    from "aggregator doesn't compute this".
    """
    t = thresholds
    alerts: list[dict] = []

    def emit(gauge, labels: dict, firing: bool, value, threshold):
        gauge.labels(**labels).set(1.0 if firing else 0.0)
        # non-finite values (a never-scraped rank's inf age) must not
        # reach json.dumps: Python would emit the bare token Infinity,
        # which is not JSON — every non-Python /fleet.json consumer
        # would reject the scrape exactly when a rank is down
        if value is not None and not math.isfinite(value):
            value = None
        alerts.append({"name": gauge.name, "labels": dict(labels),
                       "firing": bool(firing),
                       "value": None if value is None else round(value, 6),
                       "threshold": threshold})

    step = _merged_hist_child(reg, "distlr_train_step_seconds",
                              prefer={"loop": "ps"})
    step_p50 = step.percentile(0.5) if step is not None else None
    if step_p50 is not None:
        reg.gauge("distlr_fleet_step_seconds_p50",
                  "fleet median training step time (alert denominator)",
                  ).set(step_p50)

    # 1. barrier-wait p99 vs step time — the straggler alert.
    bw = _merged_hist_child(reg, "distlr_phase_seconds",
                            prefer={"phase": "barrier_wait"}, strict=True)
    bw_p99 = bw.percentile(0.99) if bw is not None else None
    if bw_p99 is not None:
        reg.gauge("distlr_fleet_barrier_wait_p99_seconds",
                  "fleet p99 barrier-wait phase time").set(bw_p99)
    g = reg.gauge("distlr_alert_barrier_wait_stall",
                  "1 while barrier-wait p99 exceeds threshold x median "
                  "step time (a straggler is holding the BSP round)",
                  ("threshold",))
    firing = (bw_p99 is not None and step_p50 is not None and step_p50 > 0
              and bw.count >= t.barrier_min_count
              and bw_p99 > t.barrier_wait_ratio * step_p50)
    emit(g, {"threshold": f"{t.barrier_wait_ratio:g}x_step_p50"},
         firing, bw_p99, t.barrier_wait_ratio)

    # 2. PS push error rate, from the merged op-outcome counters.
    ops = reg.get("distlr_ps_client_ops_total")
    total = bad = 0.0
    if ops is not None and ops.labelnames == ("op", "status"):
        for (op, status), child in ops.children():
            if op in _PUSH_OPS:
                total += child.value
                if status in ("error", "timeout"):
                    bad += child.value
    rate = (bad / total) if total else 0.0
    reg.gauge("distlr_fleet_push_error_rate",
              "fleet PS push error+timeout fraction").set(rate)
    g = reg.gauge("distlr_alert_ps_push_errors",
                  "1 while the fleet's PS push error+timeout rate "
                  "exceeds the threshold label", ("threshold",))
    emit(g, {"threshold": f"{t.push_error_rate:g}"},
         total > 0 and rate > t.push_error_rate, rate, t.push_error_rate)

    # 3. scrape staleness, per rank (rank_ages: seconds since last good
    # scrape; inf = never scraped).
    g = reg.gauge("distlr_alert_scrape_stale",
                  "1 while this rank's last successful scrape is older "
                  "than the threshold label (rank wedged or down)",
                  ("role", "rank", "threshold"))
    for (role, rank), age in sorted((rank_ages or {}).items()):
        emit(g, {"role": role, "rank": str(rank),
                 "threshold": f"{t.scrape_stale_s:g}s"},
             age > t.scrape_stale_s, age, t.scrape_stale_s)

    # 4. async weight age vs step time, per rank (merged gauge carries
    # role/rank + the worker's own rank as exported_rank).
    g = reg.gauge("distlr_alert_weight_age",
                  "1 while a rank's async weight age exceeds threshold x "
                  "median step time (worker riding ancient weights)",
                  ("role", "rank", "threshold"))
    stale = reg.get("distlr_train_staleness_seconds")
    if stale is not None and "role" in stale.labelnames:
        per_rank: dict[tuple[str, str], float] = {}
        idx_role = stale.labelnames.index("role")
        idx_rank = stale.labelnames.index("rank")
        for values, child in stale.children():
            key = (values[idx_role], values[idx_rank])
            per_rank[key] = max(per_rank.get(key, 0.0), child.value)
        for (role, rank), age in sorted(per_rank.items()):
            firing = (step_p50 is not None and step_p50 > 0
                      and age > t.weight_age_ratio * step_p50)
            emit(g, {"role": role, "rank": rank,
                     "threshold": f"{t.weight_age_ratio:g}x_step_p50"},
                 firing, age, t.weight_age_ratio)

    # 5. PS retry rate — the resilience layer's "absorbing faults"
    # signal: in-place retries per client op.  Fires while the network
    # is degraded even when every op ultimately SUCCEEDS, i.e. before
    # (and independently of) the push error-rate alert.
    retries = _fam_sum(reg, "distlr_ps_retries_total")
    # denominator = op ATTEMPTS (every issue, including failed tries,
    # lands in distlr_ps_client_ops_total): the ratio is the share of
    # attempts that were re-issues, bounded [0, 1)
    ops_total = _fam_sum(reg, "distlr_ps_client_ops_total")
    retry_rate = (retries / ops_total) if ops_total else 0.0
    reg.gauge("distlr_fleet_ps_retry_rate",
              "fleet in-place KV retry fraction (retry re-issues / "
              "total op attempts)").set(retry_rate)
    g = reg.gauge("distlr_alert_ps_retry_rate",
                  "1 while the fleet's in-place KV retry fraction exceeds "
                  "the threshold label (transient faults being absorbed "
                  "at volume)", ("threshold",))
    emit(g, {"threshold": f"{t.retry_rate:g}"},
         ops_total > 0 and retry_rate > t.retry_rate,
         retry_rate, t.retry_rate)

    # 6. supervisor gave up on a server rank — a dead-and-abandoned
    # range: every key it owned is frozen until a human intervenes.
    # Threshold is structurally 0 (any give-up is an outage), labeled
    # like the other alerts so the scrape stays self-describing.
    gave_up = _fam_sum(reg, "distlr_ps_supervisor_events_total",
                       {"event": "gave-up"})
    g = reg.gauge("distlr_alert_ps_gave_up",
                  "1 while the server supervisor has abandoned a rank "
                  "(respawn budget exhausted — that key range is frozen)",
                  ("threshold",))
    emit(g, {"threshold": "0"}, gave_up > 0, gave_up, 0.0)

    # 7. shadow-scoring PSI per (tenant, candidate) — the one alert
    # family ATTRIBUTABLE to a model version: a shadow-mirrored
    # candidate whose score distribution diverges from its primary past
    # the threshold fires ITS OWN series, and a candidate-scoped ramp
    # (`launch rollout`'s default) rolls back on exactly this evidence
    # — never on an alert the primary or another tenant caused.
    g = reg.gauge("distlr_alert_shadow_psi",
                  "1 while a shadow-scored candidate's score "
                  "distribution diverges from its primary's (PSI above "
                  "the threshold label) — candidate-attributed, the "
                  "scoped rollout gate's input",
                  ("tenant", "candidate", "threshold"))
    psi_fam = reg.get("distlr_tenant_shadow_psi")
    if psi_fam is not None and psi_fam.kind == "gauge":
        names = psi_fam.labelnames
        if "tenant" in names and "candidate" in names:
            it, ic = names.index("tenant"), names.index("candidate")
            for values, child in sorted(psi_fam.children()):
                emit(g, {"tenant": values[it], "candidate": values[ic],
                         "threshold": f"{t.shadow_psi:g}"},
                     child.value > t.shadow_psi, child.value, t.shadow_psi)
    return alerts


def _fam_sum(reg: MetricsRegistry, name: str,
             where: dict | None = None) -> float:
    """Sum of a live merged family's child values, optionally filtered
    by a label subset — the in-registry twin of :func:`_snap_sum`."""
    fam = reg.get(name)
    if fam is None:
        return 0.0
    total = 0.0
    for values, child in fam.children():
        labels = dict(zip(fam.labelnames, values))
        if where and any(labels.get(k) != v for k, v in where.items()):
            continue
        total += child.value
    return total


# ---------------------------------------------------------------------------
# the scraper
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class _RankState:
    role: str
    rank: int
    url: str | None = None          # HTTP source
    path: str | None = None         # file-snapshot source
    ok_scrapes: int = 0
    failed_scrapes: int = 0
    last_ok: float | None = None    # monotonic
    last_error: str = ""
    up: bool = False
    snapshot: dict | None = None


#: fleet scrapes kept per history segment before rotation (two segments
#: survive: ~2x this many scrapes of incident context on disk)
HISTORY_MAX_LINES = 2000


class FleetScraper:
    """Polls every discovered rank endpoint and maintains the merged
    fleet registry + the structured ``/fleet.json`` summary.

    Every scrape also appends its ``/fleet.json`` document to a bounded
    ``<first run_dir>/history.jsonl`` (one rotation kept), so ``launch
    top --replay`` can scrub a past incident offline — the metrics-
    timeline complement of the flight recorder's span rings.

    Duck-types the exporter's registry protocol (``prometheus_text()``
    / ``snapshot()``), so a :class:`distlr_tpu.obs.MetricsServer` can
    serve the LATEST merged view directly: ``MetricsServer(registry=
    scraper, extra_json={"/fleet.json": scraper.fleet_json})``.
    """

    def __init__(self, run_dir, *, interval_s: float = 2.0,
                 stale_after_s: float = 10.0, timeout_s: float = 2.0,
                 thresholds: AlertThresholds | None = None,
                 history: bool = True,
                 history_max_lines: int | None = None,
                 slo_spec=None, slo_rules=None,
                 tsdb_raw_points: int = 512,
                 tsdb_rollup_retention_s: float = 3600.0,
                 incidents: bool = True,
                 incident_window_s: float | None = None,
                 incident_settle_s: float | None = None,
                 incident_max: int = 32):
        if interval_s <= 0:
            raise ValueError(f"interval_s must be positive, got {interval_s}")
        if history_max_lines is None:
            # resolved at call time, not def time: tests (and embedders)
            # override the module-level default
            history_max_lines = HISTORY_MAX_LINES
        if history_max_lines < 1:
            raise ValueError("history_max_lines must be >= 1, got "
                             f"{history_max_lines}")
        # Aggregation of aggregators: several run dirs (a list, or one
        # os.pathsep-joined string — the repeatable `--obs-run-dir` CLI
        # form) federate into ONE scrape, so the trainer fleet and the
        # serving fleet read as one system.  Ranks are keyed (role, rank)
        # across ALL dirs; a collision keeps the first dir's endpoint and
        # warns — give fleets distinct roles/ranks.
        if isinstance(run_dir, str):
            self.run_dirs = [d for d in run_dir.split(os.pathsep) if d]
        else:
            self.run_dirs = list(run_dir)
        if not self.run_dirs:
            raise ValueError("FleetScraper needs at least one run dir")
        self.run_dir = os.pathsep.join(self.run_dirs)
        self.interval_s = float(interval_s)
        self.stale_after_s = float(stale_after_s)
        self.timeout_s = float(timeout_s)
        self.thresholds = thresholds or AlertThresholds(
            scrape_stale_s=stale_after_s)
        self._states: dict[tuple[str, int], _RankState] = {}
        self._conflicts: dict[str, int] = {}
        self._collision_warned: set[tuple[str, int]] = set()
        #: alert instances firing at the last scrape — the flight
        #: recorder triggers on the not-firing -> firing EDGE only, so a
        #: persistently-red fleet dumps once per incident, not per cycle
        self._alerts_firing: set[str] = set()
        self._lock = threading.Lock()
        self._merged = MetricsRegistry()
        self._fleet: dict = {"updated": None, "run_dir": run_dir,
                             "ranks": [], "alerts": [], "totals": {}}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._pool: concurrent.futures.ThreadPoolExecutor | None = None
        self.scrapes = 0
        self.history_path = (os.path.join(self.run_dirs[0], "history.jsonl")
                             if history else None)
        self.history_max_lines = int(history_max_lines)
        self._history_lines = self._count_history_lines()
        # the embedded time-series store (ISSUE 17): every scrape's
        # fleet doc + merged snapshot lands here; recording rules and
        # the SLO engine evaluate over it each tick.  history.jsonl
        # stays the on-disk raw tier (same file, `top --replay` input).
        self.tsdb = tsdb_mod.FleetTSDB(
            raw_points=tsdb_raw_points,
            rollup_retention_s=tsdb_rollup_retention_s)
        self.rules = tsdb_mod.default_rules() + list(slo_rules or [])
        self.slo_engine = (slo_mod.SLOEngine(slo_spec)
                           if slo_spec else None)
        # the incident engine (ISSUE 18): every alert edge that fires
        # the flight recorder also queues a bundle, assembled one
        # settle window later (so the PR 8/9 dumps and bursts have
        # landed) on this same scrape thread
        self.incidents_enabled = bool(incidents)
        self.incident_window_s = float(
            incident_window_s if incident_window_s is not None
            else incident_mod.WINDOW_S)
        self.incident_settle_s = float(
            incident_settle_s if incident_settle_s is not None
            else incident_mod.SETTLE_S)
        self.incident_max = int(incident_max)
        self._pending_incidents: list[dict] = []
        self._last_incident_seq = incident_mod.latest_seq(self.run_dirs[0])

    # -- exporter protocol (what MetricsServer calls) ---------------------
    @property
    def merged(self) -> MetricsRegistry:
        with self._lock:
            return self._merged

    def prometheus_text(self) -> str:
        return self.merged.prometheus_text()

    def snapshot(self) -> dict:
        return self.merged.snapshot()

    def fleet_json(self) -> dict:
        with self._lock:
            return self._fleet

    # -- one scrape cycle -------------------------------------------------
    def _fetch(self, st: _RankState) -> None:
        try:
            if st.url is not None:
                with urllib.request.urlopen(st.url + "/metrics.json",
                                            timeout=self.timeout_s) as r:
                    st.snapshot = json.load(r)
            else:
                with open(st.path) as f:
                    st.snapshot = json.load(f)
            st.up = True
            st.ok_scrapes += 1
            st.last_ok = time.monotonic()
            st.last_error = ""
        except Exception as e:  # noqa: BLE001 — any failure = rank not up
            st.up = False
            st.failed_scrapes += 1
            st.last_error = f"{type(e).__name__}: {e}"

    def scrape_once(self) -> MetricsRegistry:
        """Discover + scrape every rank, rebuild the merged registry and
        the /fleet.json summary, and atomically swap them in."""
        targets: dict[tuple[str, int], tuple[str | None, str | None]] = {}
        for d in self.run_dirs:
            for ep in discover_endpoints(d):
                if ep["role"] == "obs-agg":
                    continue  # never scrape ourselves back into the merge
                key = (ep["role"], ep["rank"])
                url = f"http://{ep['host']}:{ep['port']}"
                if key in targets and targets[key][0] not in (None, url):
                    if key not in self._collision_warned:
                        self._collision_warned.add(key)
                        log.warning(
                            "fleet rank %s-%s published from more than one "
                            "run dir; keeping the first dir's endpoint — "
                            "give each fleet distinct roles/ranks",
                            *key)
                    continue
                targets[key] = (url, None)
            for sf in discover_snapshot_files(d):
                targets.setdefault((sf["role"], sf["rank"]),
                                   (None, sf["path"]))

        for key, (url, path) in targets.items():
            st = self._states.get(key)
            if st is None:
                st = self._states[key] = _RankState(key[0], key[1])
            st.url, st.path = url, path
        if targets:
            # Concurrent fetch: one wedged (accepting-but-silent) rank
            # costs timeout_s; fetched serially, N wedged ranks would
            # stretch the cycle to N*timeout_s — blowing past interval_s
            # and aging HEALTHY ranks' scrapes into false stale alerts.
            # One pool for the scraper's lifetime (stop() retires it) —
            # not per cycle, which would churn 16 OS threads every 2 s.
            if self._pool is None:
                self._pool = concurrent.futures.ThreadPoolExecutor(
                    max_workers=16, thread_name_prefix="distlr-fleet-fetch")
            list(self._pool.map(self._fetch,
                                [self._states[k] for k in targets]))
        for key in list(self._states):
            if key not in targets:
                # endpoint file gone (run dir cleaned): forget the rank
                del self._states[key]

        now_mono = time.monotonic()
        rank_ages = {
            k: (max(0.0, now_mono - st.last_ok) if st.last_ok is not None
                else float("inf"))
            for k, st in self._states.items()
        }
        # Merge up AND stale ranks (stale = missed the latest scrape but
        # answered within stale_after): a single timed-out scrape must
        # not subtract a rank's whole counter contribution from the
        # fleet totals for one cycle — Prometheus rate()/increase() over
        # the merged scrape would read the dip + recovery as a counter
        # reset and report a spurious spike.  Only DOWN ranks drop out.
        merge_snaps = {
            k: st.snapshot for k, st in self._states.items()
            if st.snapshot is not None
            and self._rank_state_name(st, rank_ages[k]) != "down"
        }
        reg, conflicts = merge_snapshots(merge_snaps, on_conflict="drop")
        for c in conflicts:
            self._conflicts[c] = self._conflicts.get(c, 0) + 1
        self._write_meta_series(reg, rank_ages)
        alerts = evaluate_alerts(reg, thresholds=self.thresholds,
                                 rank_ages=rank_ages)
        fleet = self._build_fleet_json(rank_ages, alerts)
        # Feed the embedded tsdb, evaluate recording rules, then the
        # SLO engine.  Burn alerts append onto the SAME alerts list the
        # fleet doc carries, so the flight-recorder edge trigger below
        # and every fleet.json consumer (rollout gate, autopilot, top)
        # inherit them with zero plumbing changes.
        self.tsdb.ingest(fleet, reg.snapshot())
        now_t = self.tsdb.latest_time()
        if now_t is not None:
            for rule in self.rules:
                rule.evaluate(self.tsdb, now_t)
            if self.slo_engine is not None:
                fleet["slo"] = self.slo_engine.evaluate(
                    self.tsdb, reg, now_t, alerts)
        self._write_tsdb_series(reg)
        self._maybe_trigger_flightrec(alerts)
        self._maybe_assemble_incidents(fleet)
        self._append_history(fleet)
        with self._lock:
            self._merged = reg
            self._fleet = fleet
        self.scrapes += 1
        return reg

    # -- scrape history (the `launch top --replay` input) -----------------
    def _count_history_lines(self) -> int:
        if self.history_path is None:
            return 0
        try:
            with open(self.history_path) as f:
                return sum(1 for _ in f)
        except OSError:
            return 0

    def _append_history(self, fleet: dict) -> None:
        if self.history_path is None:
            return
        try:
            if self._history_lines >= self.history_max_lines:
                # bounded: one rotation kept, like the feedback spool's
                # journal segments — an always-on aggregator must never
                # grow a run dir without limit.  The overwritten .1
                # segment's lines are counted into the tsdb's drop
                # counter (`distlr_tsdb_points_dropped_total{tier=
                # history}`) — eviction is loud, never silent.
                try:
                    with open(self.history_path + ".1") as f:
                        lost = sum(1 for _ in f)
                except OSError:
                    lost = 0
                os.replace(self.history_path, self.history_path + ".1")
                self._history_lines = 0
                self.tsdb.count_dropped("history", lost)
            os.makedirs(os.path.dirname(self.history_path), exist_ok=True)
            with open(self.history_path, "a") as f:
                f.write(json.dumps(fleet) + "\n")
            self._history_lines += 1
        except OSError:
            pass  # history is an extra; a full disk must not stop scraping

    def _maybe_trigger_flightrec(self, alerts: list[dict]) -> None:
        """Drop the flight-recorder trigger into every run dir when any
        ``distlr_alert_*`` instance TRANSITIONS to firing: each process
        configured on the dir dumps its ring of the seconds *before*
        the alert (:mod:`distlr_tpu.obs.dtrace`) — exactly the context
        a sampled-only journal would have discarded."""
        from distlr_tpu.obs import dtrace  # noqa: PLC0415  (stdlib-only)

        now_firing = {
            a["name"] + json.dumps(a.get("labels", {}), sort_keys=True)
            for a in alerts if a.get("firing")
        }
        new = now_firing - self._alerts_firing
        self._alerts_firing = now_firing
        if not new:
            return
        reason = ",".join(sorted({k.split("{", 1)[0] for k in new}))
        log.warning("alert(s) newly firing (%s); triggering flight-"
                    "recorder dumps", reason)
        per_dir_seqs: list[int | None] = []
        for d in self.run_dirs:
            try:
                dtrace.trigger(d, alert=reason)
            except OSError as e:
                log.warning("flight-recorder trigger in %s failed: %s",
                            d, e)
            seq = None
            try:
                with open(os.path.join(d, "flightrec",
                                       dtrace.TRIGGER_NAME)) as f:
                    seq = int(json.load(f).get("seq", 0))
            except (OSError, ValueError):
                pass
            per_dir_seqs.append(seq)
        if not self.incidents_enabled:
            return
        # queue the incident bundle for this edge; assembled one settle
        # window later (see _maybe_assemble_incidents) so the flight
        # dumps and profiler bursts stamped with these seqs have landed
        # on disk.  The EDGE gate above is the exactly-one contract: a
        # persistently-firing alert queues once, not once per cycle.
        seq = next((s for s in per_dir_seqs if s is not None), 0)
        self._pending_incidents.append({
            "seq": seq,
            "per_dir_seqs": per_dir_seqs,
            "reason": reason,
            "detected_ts": time.time(),
            "alerts": [dict(a) for a in alerts if a.get("firing")],
            "due": time.monotonic() + self.incident_settle_s,
        })

    def _maybe_assemble_incidents(self, fleet: dict) -> None:
        """Assemble queued incident bundles whose settle window has
        elapsed, enforce retention, and stamp the open-incident seq
        into the fleet doc (the `launch top` ``inc`` column)."""
        if not self.incidents_enabled:
            return
        now = time.monotonic()
        due = [p for p in self._pending_incidents if p["due"] <= now]
        if due:
            self._pending_incidents = [
                p for p in self._pending_incidents if p["due"] > now]
        for p in due:
            try:
                out = incident_mod.assemble(
                    self.run_dirs, seq=p["seq"], reason=p["reason"],
                    detected_ts=p["detected_ts"], alerts=p["alerts"],
                    slo=fleet.get("slo"), per_dir_seqs=p["per_dir_seqs"],
                    window_s=self.incident_window_s,
                    settle_s=self.incident_settle_s, tsdb=self.tsdb)
                if out is not None:
                    self._last_incident_seq = p["seq"]
            except Exception:  # a bad bundle must not stop scraping
                log.exception("incident %s bundle assembly failed",
                              p["seq"])
            incident_mod.prune(self.run_dirs[0], self.incident_max)
        open_seq = None
        if self._pending_incidents:
            open_seq = self._pending_incidents[-1]["seq"]
        elif self._alerts_firing:
            open_seq = self._last_incident_seq
        info = {"open": open_seq, "last": self._last_incident_seq,
                "pending": len(self._pending_incidents)}
        fleet["incident"] = info
        if open_seq is not None:
            for row in fleet.get("ranks", []):
                row["incident_open"] = open_seq

    def _write_tsdb_series(self, reg: MetricsRegistry) -> None:
        """Export the store's own health (a fresh merged registry is
        rebuilt every scrape, so cumulative ``.inc(total)`` yields the
        correct counter values — same pattern as the scrape totals)."""
        st = self.tsdb.stats()
        reg.gauge("distlr_tsdb_series",
                  "live (series, labels) pairs in the embedded fleet "
                  "time-series store").set(st["series"])
        reg.counter("distlr_tsdb_frames_total",
                    "scrape frames ingested into the embedded "
                    "time-series store").inc(st["frames"])
        reg.counter("distlr_tsdb_points_total",
                    "points ingested into the embedded time-series "
                    "store across all series").inc(st["points"])
        drop_c = reg.counter(
            "distlr_tsdb_points_dropped_total",
            "points evicted from a bounded tier (raw ring, rollup "
            "retention, on-disk history rotation) — loud, never "
            "silently truncated", ("tier",))
        for tier, n in sorted(st["dropped"].items()):
            drop_c.labels(tier=tier).inc(n)

    def query_endpoint(self, params: dict) -> dict:
        """The ``/query?expr=...&window=...`` route (`MetricsServer`
        ``extra_query``): evaluate one tsdb expression over a trailing
        window.  ValueError (bad expr / bad window) surfaces as a 400
        JSON error body."""
        expr = params.get("expr")
        if not expr:
            raise ValueError("missing required query param 'expr'")
        window_s = float(params.get("window", 60.0))
        if window_s <= 0:
            raise ValueError(f"window must be positive, got {window_s}")
        return {
            "expr": expr,
            "window_s": window_s,
            "t": self.tsdb.latest_time(),
            "value": self.tsdb.query(expr, window_s=window_s),
        }

    def _rank_state_name(self, st: _RankState, age: float) -> str:
        if st.up:
            return "up"
        return "stale" if age <= self.stale_after_s else "down"

    def _write_meta_series(self, reg: MetricsRegistry, rank_ages) -> None:
        up_g = reg.gauge("distlr_fleet_scrape_up",
                         "1 when this rank answered the latest scrape",
                         ("role", "rank"))
        stale_g = reg.gauge(
            "distlr_fleet_scrape_stale",
            "1 when this rank missed the latest scrape but was up within "
            "stale_after (0 for both healthy and fully-down ranks)",
            ("role", "rank"))
        age_g = reg.gauge("distlr_fleet_scrape_age_seconds",
                          "seconds since this rank's last successful "
                          "scrape (-1 = never scraped)", ("role", "rank"))
        tot_c = reg.counter("distlr_fleet_scrapes_total",
                            "scrape attempts by outcome",
                            ("role", "rank", "status"))
        counts = {"up": 0, "stale": 0, "down": 0}
        for key, st in sorted(self._states.items()):
            role, rank = key
            age = rank_ages[key]
            state = self._rank_state_name(st, age)
            counts[state] += 1
            up_g.labels(role=role, rank=rank).set(1.0 if state == "up" else 0.0)
            stale_g.labels(role=role, rank=rank).set(
                1.0 if state == "stale" else 0.0)
            age_g.labels(role=role, rank=rank).set(
                -1.0 if age == float("inf") else age)
            tot_c.labels(role=role, rank=rank, status="ok").inc(st.ok_scrapes)
            tot_c.labels(role=role, rank=rank,
                         status="error").inc(st.failed_scrapes)
        ranks_g = reg.gauge("distlr_fleet_ranks",
                            "discovered ranks by scrape state", ("state",))
        for state, n in counts.items():
            ranks_g.labels(state=state).set(n)
        if self._conflicts:
            conf_c = reg.counter(
                "distlr_fleet_merge_conflicts_total",
                "per-rank families dropped from the merge (shape/bucket "
                "mismatch — rejected, never silently summed)", ("family",))
            for fam, n in sorted(self._conflicts.items()):
                conf_c.labels(family=fam).inc(n)

    def _build_fleet_json(self, rank_ages, alerts) -> dict:
        ranks = []
        tot_rate = 0.0
        for key, st in sorted(self._states.items()):
            age = rank_ages[key]
            row = {
                "role": st.role, "rank": st.rank,
                "source": st.url or st.path,
                "state": self._rank_state_name(st, age),
                "age_s": None if age == float("inf") else round(age, 3),
                "last_error": st.last_error,
            }
            snap = st.snapshot
            if snap is not None:
                rate = _snap_sum(snap, "distlr_train_samples_per_second")
                if st.up:
                    tot_rate += rate
                row.update({
                    "steps": int(_snap_sum(snap, "distlr_train_steps_total")),
                    "samples_per_s": round(rate, 1),
                    "staleness_s": _snap_max(
                        snap, "distlr_train_staleness_seconds"),
                })
                for label, name, where in (
                    ("step", "distlr_train_step_seconds", None),
                    ("pull", "distlr_ps_client_op_seconds", {"op": "pull"}),
                    ("push", "distlr_ps_client_op_seconds",
                     {"op": "push_pull"}),
                ):
                    p = _snap_hist_percentiles(snap, name, (0.5, 0.99), where)
                    if p is None and label == "push":
                        p = _snap_hist_percentiles(snap, name, (0.5, 0.99),
                                                   {"op": "push"})
                    if p is not None:
                        row[f"{label}_p50_ms"] = round(p[0] * 1e3, 3)
                        row[f"{label}_p99_ms"] = round(p[1] * 1e3, 3)
                p = _snap_hist_percentiles(
                    snap, "distlr_train_staleness_pushes", (0.5, 0.99))
                if p is not None:
                    row["staleness_pushes_p50"] = round(p[0], 1)
                    row["staleness_pushes_p99"] = round(p[1], 1)
                # cumulative request/push counters: `launch top` derives
                # its windowed rates (req/s, push/s over the last N
                # scrapes) from successive values of these
                if snap.get("distlr_serve_requests_total") is not None:
                    row["requests"] = int(
                        _snap_sum(snap, "distlr_serve_requests_total"))
                if snap.get("distlr_ps_client_ops_total") is not None:
                    row["pushes"] = int(
                        _snap_sum(snap, "distlr_ps_client_ops_total",
                                  {"op": "push", "status": "ok"})
                        + _snap_sum(snap, "distlr_ps_client_ops_total",
                                    {"op": "push_pull", "status": "ok"}))
                # JAX runtime introspection (obs.jaxrt): recompile count
                # and live device-buffer footprint per engine/trainer
                # rank — `launch top` renders these next to the rates
                if snap.get("distlr_jax_compiles_total") is not None:
                    row["jax_compiles"] = int(
                        _snap_sum(snap, "distlr_jax_compiles_total"))
                if snap.get("distlr_jax_device_buffer_bytes") is not None:
                    b = _snap_max(snap, "distlr_jax_device_buffer_bytes")
                    if b is not None:
                        row["device_mb"] = round(b / 1e6, 2)
                # feedback-loop ranks: joined-label and drift signals
                if snap.get("distlr_feedback_joined_total") is not None:
                    row["feedback_joined"] = int(
                        _snap_sum(snap, "distlr_feedback_joined_total"))
                if snap.get("distlr_feedback_score_psi") is not None:
                    row["score_psi"] = _snap_max(
                        snap, "distlr_feedback_score_psi")
                if snap.get("distlr_feedback_shard_lag") is not None:
                    # pending unclaimed feedback shards — the autopilot's
                    # worker-band signal (deterministic, unlike the
                    # cumulative latency percentiles)
                    lag = _snap_max(snap, "distlr_feedback_shard_lag")
                    if lag is not None:
                        row["shard_lag"] = lag
                # autopilot ranks (`launch autopilot`, ISSUE 16): the
                # control loop's own telemetry rolls through fleet.json
                # so `launch top` shows who is steering the fleet
                if snap.get("distlr_autopilot_ticks_total") is not None:
                    row["autopilot_ticks"] = int(
                        _snap_sum(snap, "distlr_autopilot_ticks_total"))
                    row["autopilot_actions"] = int(
                        _snap_sum(snap, "distlr_autopilot_actions_total"))
                    row["autopilot_errors"] = int(
                        _snap_sum(snap, "distlr_autopilot_errors_total"))
                    row["autopilot_rollbacks"] = int(_snap_sum(
                        snap, "distlr_autopilot_rollbacks_total"))
                    row["autopilot_holding"] = int(
                        _snap_sum(snap, "distlr_autopilot_holding"))
                    last = _read_autopilot_last_action(self.run_dirs)
                    if last is not None:
                        row["autopilot_last_action"] = last
                # multi-tenant serving ranks (ISSUE 10): hosted-model
                # count, per-tenant quota sheds, and the live shadow PSI
                # (the canary ramp's promote/rollback evidence) roll
                # through fleet.json into `launch top`
                if snap.get("distlr_tenant_models") is not None:
                    # the router's purpose-built registration gauge —
                    # counting distinct request labels instead would
                    # under-report versions that took no traffic yet
                    # (exactly the pre-ramp window an operator checks)
                    m = _snap_max(snap, "distlr_tenant_models")
                    if m is not None:
                        row["models"] = int(m)
                if snap.get("distlr_tenant_shed_total") is not None:
                    row["tenant_shed"] = int(
                        _snap_sum(snap, "distlr_tenant_shed_total"))
                if snap.get("distlr_tenant_shadow_psi") is not None:
                    row["shadow_psi"] = _snap_max(
                        snap, "distlr_tenant_shadow_psi")
                if snap.get("distlr_rollout_weight") is not None:
                    row["rollout_weight"] = _snap_max(
                        snap, "distlr_rollout_weight")
                # structured-log signal (ISSUE 18): cumulative ERROR
                # records (tsdb ingests it per-rank, feeding the
                # fleet:log_error_rate recording rule) and the windowed
                # per-rank ERROR rate read back from the store — one
                # frame behind, like autopilot_last_action
                if snap.get("distlr_log_records_total") is not None:
                    row["log_errors_total"] = int(_snap_sum(
                        snap, "distlr_log_records_total",
                        {"level": "error"}))
                    r = self.tsdb.query(
                        "rate(log_errors_total"
                        f"{{role={st.role},rank={st.rank}}})",
                        window_s=30.0)
                    if r is not None:
                        row["log_errors"] = round(r, 3)
                # routing-tier ranks (`launch route`): surface the
                # admission/health signals next to the trainer rows
                if snap.get("distlr_route_requests_total") is not None:
                    row["route_requests"] = int(
                        _snap_sum(snap, "distlr_route_requests_total"))
                    row["route_shed"] = int(
                        _snap_sum(snap, "distlr_route_shed_total"))
                    row["replicas_up"] = int(
                        _snap_sum(snap, "distlr_route_replica_up"))
                    # end-to-end serve latency as the client sees it
                    # (admission -> reply, retries included): `launch
                    # top` renders these next to the windowed req/s
                    p = _snap_hist_percentiles(
                        snap, "distlr_route_request_seconds", (0.5, 0.99))
                    if p is not None:
                        row["route_p50_ms"] = round(p[0] * 1e3, 3)
                        row["route_p99_ms"] = round(p[1] * 1e3, 3)
            ranks.append(row)
        states = [r["state"] for r in ranks]
        return {
            "updated": time.time(),
            "run_dir": self.run_dir,
            "interval_s": self.interval_s,
            "scrapes": self.scrapes + 1,
            "ranks": ranks,
            "alerts": alerts,
            "totals": {
                "ranks": len(ranks),
                "up": states.count("up"),
                "stale": states.count("stale"),
                "down": states.count("down"),
                "samples_per_s": round(tot_rate, 1),
            },
        }

    # -- lifecycle --------------------------------------------------------
    def run_forever(self) -> None:
        """Foreground scrape loop (``launch obs-agg``); returns when
        :meth:`stop` is called from another thread or on interrupt."""
        while not self._stop.is_set():
            t0 = time.monotonic()
            try:
                self.scrape_once()
            except Exception:  # a bad cycle must not kill the aggregator
                log.exception("fleet scrape cycle failed; retrying")
            elapsed = time.monotonic() - t0
            self._stop.wait(max(0.05, self.interval_s - elapsed))

    def start(self) -> "FleetScraper":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self.run_forever, daemon=True, name="distlr-fleet-scraper")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.timeout_s + self.interval_s)
            self._thread = None
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
