"""Fleet-wide distributed tracing — follow one request across every
process (ISSUE 8).

PR 2's :class:`~distlr_tpu.obs.tracing.PhaseTracer` answers "where did
THIS process spend its time"; this module answers the question that
stops at process boundaries: *where did this request/push/label spend
its time across the router, the engine replica, the PS client, the
native KV server, and the feedback loop?*  Dapper-style: a
:class:`TraceContext` (trace_id, span_id, sampled flag) propagates
through every hop —

* **serve line protocol** — additively, like STATS/LABEL: the router
  mints a context per scoring request and forwards
  ``TRACE <tid>/<sid> <line>``; replicas (and nested routers) join it.
* **KV wire** — additively, like vals_per_key and the codec bits: a
  negotiated flag bit + 16-byte trailer (``kv_protocol.h kTraced``)
  stamps ops, and ``distlr_kv_server --trace_journal`` logs per-handler
  spans joined to the client's op span.  Pre-trace servers never
  advertise the capability, so mixed fleets degrade to client-only
  spans, and a zero sample rate leaves the wire byte-identical.
* **feedback loop** — the spool entry remembers its request's context,
  the LABEL join continues it, shard sidecar files carry it to the
  online trainer, and the trainer's flush push stamps it back onto the
  KV wire — one timeline from score to FTRL apply to hot reload.

Two sinks per process:

* **span journal** — sampled spans append (bounded) to
  ``<obs_run_dir>/spans/<role>-<rank>.jsonl``; ``launch trace-agg``
  merges every rank's journal (Python and native, one schema) into a
  single Chrome/Perfetto trace, aligning cross-host clocks with the
  kHello clock probe and interleaving chaos-proxy events on the
  affected link's track.
* **flight recorder** — a bounded in-memory ring of recent spans
  (SAMPLED OR NOT) plus structured events.  When any ``distlr_alert_*``
  gauge fires, the aggregator drops a trigger file into
  ``<run_dir>/flightrec/`` and every process dumps its ring — the
  postmortem captures the seconds *before* the alert, which a
  sampled-only journal would have discarded.

Deterministic sampling: the decision is a pure hash of the trace id, so
every process that sees a context agrees on it without coordination.
Stdlib-only and jax-free, like the rest of ``obs``.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import random
import threading
import time
from collections import deque

from distlr_tpu.obs.registry import get_registry
from distlr_tpu.utils.logging import get_logger

log = get_logger(__name__)

_reg = get_registry()
_SPANS = _reg.counter(
    "distlr_trace_spans_total",
    "distributed-trace spans recorded, by journal destination "
    "(sampled -> span journal + flight ring; unsampled -> ring only)",
    labelnames=("sampled",),
)
# children resolved once: .labels() takes the registry lock, and the
# ring path runs per request even at sample 0
_SPANS_SAMPLED = _SPANS.labels(sampled="true")
_SPANS_UNSAMPLED = _SPANS.labels(sampled="false")
_JOURNAL_DROPPED = _reg.counter(
    "distlr_trace_journal_dropped_total",
    "sampled spans dropped after the per-process span-journal cap",
)
_FLIGHT_DUMPS = _reg.counter(
    "distlr_trace_flightrec_dumps_total",
    "flight-recorder ring dumps (alert-triggered or on demand)",
)

#: per-process span-journal entry cap (the native server uses the same
#: figure; a runaway sampled stream bounds disk, loudly)
MAX_JOURNAL_SPANS = 200_000

#: thread ident -> stack of active span NAMES, readable from OTHER
#: threads (a threading.local cannot be) — what lets the sampling
#: profiler (distlr_tpu.obs.profile) tag each stack sample with the
#: innermost dtrace span running on the sampled thread, so flamegraphs
#: split by serve.request vs train.step vs feedback.*.  Mutated only by
#: the owning thread (list append/pop are atomic under the GIL); readers
#: tolerate the race.
_ACTIVE_NAMES: dict[int, list] = {}

#: callables merged into every flight-recorder dump document —
#: ``fn(reason, seq) -> dict`` — so sibling subsystems (the continuous
#: profiler) can cross-reference their own incident artifacts from the
#: flight dump without dtrace importing them.
_FLIGHT_INFO: list = []


def active_span_name(tid: int) -> str | None:
    """Innermost active span name on thread ``tid`` (None when that
    thread is outside every span).  Racy by design — a profiler reading
    a thread mid-pop may see a just-closed span; one sample of drift is
    noise at any sane sampling rate."""
    try:
        return _ACTIVE_NAMES[tid][-1]
    except (KeyError, IndexError):
        return None


def register_flight_info(fn) -> None:
    """Register a provider whose dict is merged into every flight dump
    (idempotent per function object)."""
    if fn not in _FLIGHT_INFO:
        _FLIGHT_INFO.append(fn)


def unregister_flight_info(fn) -> None:
    with contextlib.suppress(ValueError):
        _FLIGHT_INFO.remove(fn)
#: flight-recorder ring capacity (spans + events kept per process)
FLIGHT_CAPACITY = 4096
#: flight-recorder trigger filename inside <run_dir>/flightrec/
TRIGGER_NAME = "TRIGGER.json"


def _hex(v: int | None) -> str | None:
    return None if v is None else f"{v:016x}"


class TraceContext:
    """One hop's view of a distributed trace: which trace, which span
    is current, and whether the trace is sampled (journal + propagate)
    or ring-only."""

    __slots__ = ("trace_id", "span_id", "sampled")

    def __init__(self, trace_id: int, span_id: int, sampled: bool):
        self.trace_id = int(trace_id)
        self.span_id = int(span_id)
        self.sampled = bool(sampled)

    def token(self) -> str:
        """Wire form of this context (the ``TRACE <token>`` prefix)."""
        return f"{self.trace_id:016x}/{self.span_id:016x}"

    def __repr__(self):  # debugging/test output
        return (f"TraceContext({self.token()}, "
                f"sampled={self.sampled})")


def parse_token(token: str) -> TraceContext:
    """Inverse of :meth:`TraceContext.token`.  A propagated context is
    by definition sampled (unsampled traces never cross the wire)."""
    tid, _, sid = token.partition("/")
    try:
        return TraceContext(int(tid, 16), int(sid, 16), True)
    except ValueError as e:
        raise ValueError(f"malformed trace token {token!r}") from e


def is_sampled(trace_id: int, rate: float) -> bool:
    """Deterministic sampling decision: a pure hash of the trace id, so
    every process agrees without coordination — the per-run sampler."""
    if rate <= 0.0:
        return False
    if rate >= 1.0:
        return True
    h = hashlib.blake2b(int(trace_id).to_bytes(8, "little"),
                        digest_size=8).digest()
    return int.from_bytes(h, "little") / 2.0 ** 64 < rate


class Span:
    """Handle yielded by :func:`span` while the block runs."""

    __slots__ = ("name", "ctx", "tags", "t0_wall", "t0_perf")

    def __init__(self, name: str, ctx: TraceContext, tags: dict | None):
        self.name = name
        self.ctx = ctx          # the CHILD context (this span's identity)
        self.tags = tags
        self.t0_wall = time.time()
        self.t0_perf = time.perf_counter()

    @property
    def span_id(self) -> int:
        return self.ctx.span_id


class _Tracer:
    """Per-process tracing state: config, thread-local context stack,
    span journal, flight ring, and the trigger watcher."""

    def __init__(self):
        self._lock = threading.Lock()
        self._tls = threading.local()
        self._rng = random.Random()
        self.configured = False
        self.sample = 0.0
        self.role = "proc"
        self.rank = 0
        self.run_dir: str | None = None
        self._journal_path: str | None = None
        self._journal_file = None
        self._journal_written = 0
        self._journal_unflushed = 0
        self._ring: deque = deque(maxlen=FLIGHT_CAPACITY)
        self._watcher: threading.Thread | None = None
        self._watch_stop = threading.Event()
        self._trigger_seq = -1
        self._atexit_installed = False

    # -- configuration -----------------------------------------------------
    def configure(self, run_dir: str | None, role: str, rank: int, *,
                  sample: float = 0.0,
                  flight_capacity: int = FLIGHT_CAPACITY) -> None:
        """Arm tracing for this process.  ``run_dir=None`` keeps the
        flight ring only (no journal, no trigger watcher).  Safe to call
        again (tests, multi-command processes): the journal re-targets
        and the watcher restarts."""
        if not 0.0 <= sample <= 1.0:
            raise ValueError(f"sample must be in [0, 1], got {sample}")
        self.stop_watcher()
        with self._lock:
            self.sample = float(sample)
            self.role, self.rank = str(role), int(rank)
            self.run_dir = run_dir
            if self._journal_file is not None:
                self._journal_file.close()
                self._journal_file = None
            self._journal_path = None
            self._journal_written = 0
            self._journal_unflushed = 0
            self._ring = deque(maxlen=int(flight_capacity))
            self._trigger_seq = self._read_trigger_seq()
            self.configured = True
            if run_dir:
                d = os.path.join(run_dir, "spans")
                os.makedirs(d, exist_ok=True)
                self._journal_path = os.path.join(
                    d, f"{self.role}-{self.rank}.jsonl")
        if run_dir:
            self._journal_line({
                "type": "meta", "role": self.role, "rank": self.rank,
                "pid": os.getpid(), "sample": self.sample,
            })
            self._watch_stop.clear()
            self._watcher = threading.Thread(
                target=self._watch_loop, daemon=True,
                name="distlr-flightrec-watch")
            self._watcher.start()
        if not self._atexit_installed:
            import atexit  # noqa: PLC0415

            atexit.register(self.flush)
            self._atexit_installed = True

    def stop_watcher(self) -> None:
        self._watch_stop.set()
        w = self._watcher
        if w is not None and w.is_alive():
            w.join(timeout=2.0)
        self._watcher = None

    def reset_for_tests(self) -> None:
        """Back to the unconfigured state (journal closed, ring empty)."""
        self.stop_watcher()
        with self._lock:
            if self._journal_file is not None:
                self._journal_file.close()
                self._journal_file = None
            self.configured = False
            self.sample = 0.0
            self.run_dir = None
            self._journal_path = None
            self._journal_written = 0
            self._journal_unflushed = 0
            self._ring.clear()
        self._tls = threading.local()
        _ACTIVE_NAMES.clear()
        _FLIGHT_INFO.clear()

    # -- context stack -----------------------------------------------------
    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def current(self) -> TraceContext | None:
        st = self._stack()
        return st[-1] if st else None

    @contextlib.contextmanager
    def use(self, ctx: TraceContext | None):
        """Install ``ctx`` as the thread's current context for the
        block (a no-op passthrough for ``None`` — callers never branch)."""
        if ctx is None:
            yield None
            return
        st = self._stack()
        st.append(ctx)
        try:
            yield ctx
        finally:
            st.pop()

    def new_trace(self) -> TraceContext | None:
        """Mint a root context (the router / front-end entry point).
        ``None`` until :meth:`configure` ran — unconfigured processes
        pay nothing."""
        if not self.configured:
            return None
        tid = self._rng.getrandbits(64) | 1
        return TraceContext(tid, 0, is_sampled(tid, self.sample))

    def current_ids(self) -> tuple[int, int] | None:
        """(trace_id, span_id) of the current SAMPLED context — what
        gets persisted into spool records and shard sidecars."""
        ctx = self.current()
        if ctx is None or not ctx.sampled:
            return None
        return (ctx.trace_id, ctx.span_id)

    def token(self) -> str | None:
        """Wire token of the current sampled context (``None``
        otherwise) — the serve-protocol ``TRACE`` prefix payload."""
        ctx = self.current()
        if ctx is None or not ctx.sampled:
            return None
        return ctx.token()

    # -- spans -------------------------------------------------------------
    @contextlib.contextmanager
    def span(self, name: str, tags: dict | None = None,
             ctx: TraceContext | None = None):
        """Record one span under ``ctx`` (default: the current
        context).  With no context at all the block runs untraced and
        the manager yields ``None`` — call sites never branch."""
        parent = ctx if ctx is not None else self.current()
        if parent is None:
            yield None
            return
        child = TraceContext(parent.trace_id,
                             self._rng.getrandbits(64) | 1, parent.sampled)
        sp = Span(name, child, tags)
        st = self._stack()
        st.append(child)
        tid = threading.get_ident()
        names = _ACTIVE_NAMES.setdefault(tid, [])
        names.append(name)
        try:
            yield sp
        finally:
            names.pop()
            if not names:
                _ACTIVE_NAMES.pop(tid, None)
            st.pop()
            self._record(sp, parent.span_id or None)

    def record_span(self, name: str, ctx: TraceContext, t0_wall: float,
                    dur_s: float, tags: dict | None = None) -> TraceContext:
        """Record a span retrospectively (measured by the caller) and
        return its child context — how the online trainer attributes one
        shard-consume interval to each trace it carried."""
        child = TraceContext(ctx.trace_id, self._rng.getrandbits(64) | 1,
                             ctx.sampled)
        rec = self._span_doc(name, child, ctx.span_id or None,
                             t0_wall, dur_s, tags)
        self._sink(rec, child.sampled)
        return child

    def _record(self, sp: Span, parent_id: int | None) -> None:
        dur = time.perf_counter() - sp.t0_perf
        if not sp.ctx.sampled:
            # ring-only span: keep a compact tuple and defer the doc
            # formatting to dump time — this path runs per REQUEST even
            # at sample 0, and the flight dump is rare
            self._ring.append((sp.name, sp.ctx.trace_id, sp.ctx.span_id,
                               parent_id, sp.t0_wall, dur, sp.tags))
            _SPANS_UNSAMPLED.inc()
            return
        rec = self._span_doc(sp.name, sp.ctx, parent_id, sp.t0_wall, dur,
                             sp.tags)
        self._sink(rec, True)

    def _span_doc(self, name, ctx, parent_id, t0_wall, dur_s, tags) -> dict:
        return {
            "type": "span",
            "name": name,
            "trace": _hex(ctx.trace_id),
            "span": _hex(ctx.span_id),
            "parent": _hex(parent_id),
            "ts": round(t0_wall * 1e6, 1),
            "dur": round(max(dur_s, 0.0) * 1e6, 1),
            "tid": threading.get_ident() & 0x7FFFFFFF,
            "args": dict(tags) if tags else {},
        }

    def _sink(self, rec: dict, sampled: bool) -> None:
        rec["sampled"] = bool(sampled)
        self._ring.append(rec)
        (_SPANS_SAMPLED if sampled else _SPANS_UNSAMPLED).inc()
        if sampled and self._journal_path is not None:
            self._journal_line({k: v for k, v in rec.items()
                                if k != "sampled"})

    def instant(self, name: str, tags: dict | None = None) -> None:
        """A zero-duration timeline marker, journaled unconditionally
        (the chaos proxy's fault events ride this so merged traces show
        'this retry was caused by fault #3' on the link's track)."""
        rec = {
            "type": "instant", "name": name,
            "ts": round(time.time() * 1e6, 1),
            "tid": threading.get_ident() & 0x7FFFFFFF,
            "args": dict(tags) if tags else {},
        }
        self._ring.append(rec)
        if self._journal_path is not None:
            self._journal_line(rec)

    def event(self, name: str, **tags) -> None:
        """Flight-ring-only structured event (never journaled): cheap
        breadcrumbs for the postmortem dump."""
        self._ring.append({
            "type": "event", "name": name,
            "ts": round(time.time() * 1e6, 1), "args": tags,
        })

    def record_clock(self, peer: str, offset_s: float) -> None:
        """Journal a measured clock offset toward ``peer`` (host:port):
        trace-agg shifts that peer's journal timestamps by it."""
        if self._journal_path is not None:
            self._journal_line({"type": "clock", "peer": peer,
                                "offset_s": round(float(offset_s), 6)})

    # -- journal I/O -------------------------------------------------------
    def _journal_line(self, doc: dict) -> None:
        with self._lock:
            if self._journal_path is None:
                return
            if doc.get("type") == "span":
                if self._journal_written >= MAX_JOURNAL_SPANS:
                    _JOURNAL_DROPPED.inc()
                    return
                self._journal_written += 1
            try:
                if self._journal_file is None:
                    self._journal_file = open(self._journal_path, "a")
                self._journal_file.write(json.dumps(doc) + "\n")
                # batched flush: a per-line flush cost full-sample runs
                # ~20% QPS; readers (trace-agg, tests) call flush()
                # first, atexit flushes the tail, and a torn final line
                # is skipped by the merge reader anyway
                self._journal_unflushed += 1
                if self._journal_unflushed >= 64:
                    self._journal_file.flush()
                    self._journal_unflushed = 0
            except OSError:
                pass  # tracing must never fail the traced work

    def flush(self) -> None:
        with self._lock:
            if self._journal_file is not None:
                with contextlib.suppress(OSError):
                    self._journal_file.flush()
                self._journal_unflushed = 0

    # -- flight recorder ---------------------------------------------------
    def _trigger_path(self) -> str | None:
        if not self.run_dir:
            return None
        return os.path.join(self.run_dir, "flightrec", TRIGGER_NAME)

    def _read_trigger_seq(self) -> int:
        path = self._trigger_path()
        if path is None:
            return -1
        try:
            with open(path) as f:
                return int(json.load(f).get("seq", -1))
        except (OSError, ValueError):
            return -1

    def _watch_loop(self) -> None:
        while not self._watch_stop.wait(0.25):
            path = self._trigger_path()
            if path is None:
                return
            try:
                with open(path) as f:
                    doc = json.load(f)
            except (OSError, ValueError):
                continue
            seq = int(doc.get("seq", -1))
            if seq > self._trigger_seq:
                self._trigger_seq = seq
                self.dump_flight(reason=str(doc.get("alert", "trigger")),
                                 seq=seq)

    @staticmethod
    def _ring_doc(rec) -> dict:
        """Ring entry -> dump schema (unsampled spans ride the ring as
        compact tuples; everything else is already a doc)."""
        if isinstance(rec, dict):
            return rec
        name, tid, sid, parent, ts, dur, tags = rec
        return {
            "type": "span", "name": name, "trace": _hex(tid),
            "span": _hex(sid), "parent": _hex(parent),
            "ts": round(ts * 1e6, 1), "dur": round(max(dur, 0.0) * 1e6, 1),
            "args": dict(tags) if tags else {}, "sampled": False,
        }

    def dump_flight(self, reason: str = "manual",
                    seq: int | None = None) -> str | None:
        """Write the ring to ``<run_dir>/flightrec/<role>-<rank>-<n>.json``
        — the seconds BEFORE now, sampled or not.  Returns the path
        (None without a run dir)."""
        if not self.run_dir:
            return None
        d = os.path.join(self.run_dir, "flightrec")
        os.makedirs(d, exist_ok=True)
        if seq is None:
            seq = self._trigger_seq + 1
        path = os.path.join(d, f"{self.role}-{self.rank}-{seq}.json")
        doc = {
            "role": self.role, "rank": self.rank, "pid": os.getpid(),
            "reason": reason, "dumped_at": time.time(),
            "spans": [self._ring_doc(r) for r in list(self._ring)],
        }
        for fn in list(_FLIGHT_INFO):
            # cross-references from sibling subsystems (e.g. the
            # continuous profiler names the incident's burst-window
            # journal) — a broken provider must not lose the dump
            try:
                doc.update(fn(reason, seq) or {})
            except Exception:  # noqa: BLE001
                log.exception("flight-info provider %r failed", fn)
        tmp = f"{path}.{os.getpid()}.tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, path)
        except OSError:
            return None
        _FLIGHT_DUMPS.inc()
        log.info("flight recorder dumped %d entries -> %s (%s)",
                 len(doc["spans"]), path, reason)
        return path


_TRACER = _Tracer()


# -- module-level API (what every instrumented call site imports) -----------

def configure(run_dir: str | None, role: str, rank: int, *,
              sample: float = 0.0) -> None:
    _TRACER.configure(run_dir, role, rank, sample=sample)


def is_configured() -> bool:
    return _TRACER.configured


def sample_rate() -> float:
    return _TRACER.sample


def new_trace() -> TraceContext | None:
    return _TRACER.new_trace()


def current() -> TraceContext | None:
    return _TRACER.current()


def current_ids() -> tuple[int, int] | None:
    return _TRACER.current_ids()


def token() -> str | None:
    return _TRACER.token()


def use(ctx: TraceContext | None):
    return _TRACER.use(ctx)


def span(name: str, tags: dict | None = None,
         ctx: TraceContext | None = None):
    return _TRACER.span(name, tags, ctx)


def record_span(name: str, ctx: TraceContext, t0_wall: float, dur_s: float,
                tags: dict | None = None) -> TraceContext:
    return _TRACER.record_span(name, ctx, t0_wall, dur_s, tags)


def instant(name: str, tags: dict | None = None) -> None:
    _TRACER.instant(name, tags)


def event(name: str, **tags) -> None:
    _TRACER.event(name, **tags)


def record_clock(peer: str, offset_s: float) -> None:
    _TRACER.record_clock(peer, offset_s)


def flush() -> None:
    _TRACER.flush()


def flight_dump(reason: str = "manual") -> str | None:
    return _TRACER.dump_flight(reason=reason)


def reset_for_tests() -> None:
    _TRACER.reset_for_tests()


def trigger(run_dir: str, alert: str = "manual") -> str:
    """Drop/refresh the flight-recorder trigger file under ``run_dir``:
    every process configured on that run dir dumps its ring within one
    watcher poll.  Called by the fleet aggregator when a
    ``distlr_alert_*`` gauge transitions to firing, and by
    ``launch flightrec`` on demand."""
    d = os.path.join(run_dir, "flightrec")
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, TRIGGER_NAME)
    seq = 0
    try:
        with open(path) as f:
            seq = int(json.load(f).get("seq", -1)) + 1
    except (OSError, ValueError):
        pass
    doc = {"seq": seq, "alert": str(alert), "ts": time.time()}
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
    return path


# ---------------------------------------------------------------------------
# trace-agg: merge per-rank span journals into one Chrome/Perfetto trace
# ---------------------------------------------------------------------------

def read_journal(path: str) -> list[dict]:
    """Tolerant JSONL journal reader (torn tail lines skipped) — shared
    by trace-agg here and the incident engine's artifact collectors."""
    return _read_journal(path)


def _read_journal(path: str) -> list[dict]:
    out = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue  # a line torn mid-write: skip, keep the rest
    except OSError:
        pass
    return out


def merge_run_dirs(run_dirs, *, align_clocks: bool = True) -> dict:
    """Merge every ``<run_dir>/spans/*.jsonl`` journal (Python AND
    native — one schema) into a single Chrome trace-event document.

    * each journal becomes one named process track
      (``process_name = <file stem>``);
    * spans become ``ph: "X"`` complete events carrying
      ``args.trace/span/parent`` so Perfetto queries can follow one
      trace id end to end;
    * ``instant`` records (the chaos proxy's fault events) become
      ``ph: "i"`` markers on their emitting process's track, with the
      faulted op's trace id in args when the frame carried one;
    * clock-skew alignment: ``clock`` records (the client's kHello
      probe) name a peer ``host:port`` and its measured offset; any
      journal whose ``meta.listen`` matches is shifted onto the
      observing client's clock.
    """
    if isinstance(run_dirs, str):
        run_dirs = [run_dirs]
    journals: list[tuple[str, list[dict]]] = []
    seen = set()
    for d in run_dirs:
        spans_dir = os.path.join(d, "spans")
        if not os.path.isdir(spans_dir):
            continue
        for name in sorted(os.listdir(spans_dir)):
            if not name.endswith(".jsonl"):
                continue
            path = os.path.join(spans_dir, name)
            stem = name[:-len(".jsonl")]
            key = stem
            n = 1
            while key in seen:  # same role-rank in two federated dirs
                n += 1
                key = f"{stem}#{n}"
            seen.add(key)
            journals.append((key, _read_journal(path)))

    # clock offsets observed by any client, keyed on the peer's port
    # (the meta.listen host may be 0.0.0.0 while the client dialed a
    # concrete address — the port is the stable join key on one host)
    offsets: dict[str, float] = {}
    if align_clocks:
        for _stem, recs in journals:
            for r in recs:
                if r.get("type") == "clock" and r.get("peer"):
                    port = str(r["peer"]).rpartition(":")[2]
                    offsets[port] = float(r.get("offset_s", 0.0))

    events: list[dict] = []
    n_spans = 0
    traces: set[str] = set()
    for pid, (stem, recs) in enumerate(journals, start=1):
        shift_us = 0.0
        for r in recs:
            if r.get("type") == "meta" and r.get("listen"):
                port = str(r["listen"]).rpartition(":")[2]
                if port in offsets:
                    # server journal: subtract its measured offset so
                    # its timestamps land on the client's clock
                    shift_us = -offsets[port] * 1e6
                break
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "args": {"name": stem}})
        for r in recs:
            kind = r.get("type")
            if kind == "span":
                args = dict(r.get("args") or {})
                args["trace"] = r.get("trace")
                args["span"] = r.get("span")
                args["parent"] = r.get("parent")
                events.append({
                    "name": r.get("name", "?"), "cat": "dtrace", "ph": "X",
                    "pid": pid, "tid": r.get("tid", 0),
                    "ts": round(float(r.get("ts", 0.0)) + shift_us, 1),
                    "dur": float(r.get("dur", 0.0)),
                    "args": args,
                })
                n_spans += 1
                if r.get("trace"):
                    traces.add(r["trace"])
            elif kind == "instant":
                events.append({
                    "name": r.get("name", "?"), "cat": "dtrace", "ph": "i",
                    "pid": pid, "tid": r.get("tid", 0),
                    "ts": round(float(r.get("ts", 0.0)) + shift_us, 1),
                    "s": "p",
                    "args": dict(r.get("args") or {}),
                })
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "producer": "distlr_tpu.obs.dtrace",
            "journals": [stem for stem, _ in journals],
            "spans": n_spans,
            "trace_ids": sorted(traces),
            "clock_offsets": offsets,
        },
    }


def write_merged_trace(run_dirs, out_path: str) -> dict:
    """Merge and write atomically; returns the document (its
    ``otherData`` carries span/trace counts for callers to report)."""
    doc = merge_run_dirs(run_dirs)
    os.makedirs(os.path.dirname(os.path.abspath(out_path)), exist_ok=True)
    tmp = f"{out_path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, out_path)
    return doc
