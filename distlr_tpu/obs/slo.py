"""SLO engine: error budgets and multi-window multi-burn-rate alerts
over the embedded fleet tsdb (:mod:`distlr_tpu.obs.tsdb`).

An SLO file (``launch obs-agg --slo-file slo.json``) declares
objectives over SLI expressions::

    {
      "clock_scale": 1.0,
      "slos": [
        {"name": "route_availability",
         "objective": 0.99,
         "window_s": 3600,
         "sli": {"kind": "ratio",
                 "bad": "increase(route_shed)",
                 "total": "increase(route_requests)"}},
        {"name": "route_p99",
         "objective": 0.95,
         "window_s": 3600,
         "sli": {"kind": "threshold",
                 "expr": "histogram_quantile(0.99, distlr_route_request_seconds)",
                 "op": "<=", "bound": 0.25}}
      ]
    }

Two SLI kinds:

* **ratio** — ``bad``/``total`` tsdb expressions evaluated per burn
  window; the bad fraction is their quotient (``None`` -> unknown when
  total is 0: no traffic is not compliance).
* **threshold** — ``expr`` compared against ``bound`` each scrape tick;
  the engine records a 0/1 ``slo:<name>:bad`` series into the tsdb and
  the bad fraction over any window is its ``avg_over_time``.

From the bad fraction the engine derives, each scrape tick:

* ``budget_remaining = 1 - bad_fraction(window_s) / (1 - objective)``
  — the fraction of the error budget left over the SLO window
  (negative = overspent), exported as
  ``distlr_slo_budget_remaining{slo}``;
* ``burn_rate(w) = bad_fraction(w) / (1 - objective)`` per burn
  window, exported as ``distlr_slo_burn_rate{slo,window}`` — 1.0 means
  burning exactly the budget over the SLO window, 14.4 means the whole
  budget gone in ~2% of it;
* **multi-window multi-burn-rate alerts** (Google SRE workbook ch. 5):
  a pair fires only when BOTH its short and long windows exceed the
  pair's factor — the long window guards against noise, the short one
  makes the alert reset quickly once the burn stops.  Defaults: fast =
  (5m, 1h) at 14.4x, slow = (30m, 6h) at 6x; ``clock_scale`` shrinks
  every window uniformly so compressed bench/e2e clocks keep the same
  math.

Alerts are emitted as ``distlr_alert_slo_burn{slo,window}`` through the
same alert list ``evaluate_alerts`` produces — the flight recorder,
profiler bursts, rollout gater, and autopilot rollback inherit
burn-rate triggering with zero changes to their plumbing.
"""

from __future__ import annotations

import json
import math

from distlr_tpu.obs import tsdb as tsdb_mod

#: default burn-rate window pairs: (label, short_s, long_s, factor) —
#: the SRE-workbook 5m/1h @ 14.4x and 30m/6h @ 6x pairs
DEFAULT_BURN_WINDOWS = (
    ("fast", 300.0, 3600.0, 14.4),
    ("slow", 1800.0, 21600.0, 6.0),
)

_OPS = {
    "<=": lambda v, b: v <= b,
    "<": lambda v, b: v < b,
    ">=": lambda v, b: v >= b,
    ">": lambda v, b: v > b,
}


class SLOSpecError(ValueError):
    """A malformed SLO file — raised loudly at load, never mid-scrape."""


def _req(obj: dict, key: str, where: str):
    if key not in obj:
        raise SLOSpecError(f"{where}: missing required key {key!r}")
    return obj[key]


class SLO:
    """One objective: name, target, SLO window, and an SLI."""

    def __init__(self, spec: dict, *, clock_scale: float = 1.0,
                 burn_windows=DEFAULT_BURN_WINDOWS):
        where = f"slo {spec.get('name', '?')!r}"
        self.name = str(_req(spec, "name", "slo"))
        if not self.name:
            raise SLOSpecError("slo: empty name")
        self.objective = float(_req(spec, "objective", where))
        if not 0.0 < self.objective < 1.0:
            raise SLOSpecError(
                f"{where}: objective must be in (0, 1), got "
                f"{self.objective}")
        self.window_s = float(_req(spec, "window_s", where)) * clock_scale
        if self.window_s <= 0:
            raise SLOSpecError(f"{where}: window_s must be positive")
        sli = _req(spec, "sli", where)
        if not isinstance(sli, dict):
            raise SLOSpecError(f"{where}: sli must be an object")
        self.kind = str(_req(sli, "kind", where))
        if self.kind == "ratio":
            self.bad_expr = str(_req(sli, "bad", where))
            self.total_expr = str(_req(sli, "total", where))
            self._check_expr(self.bad_expr, where)
            self._check_expr(self.total_expr, where)
        elif self.kind == "threshold":
            self.expr = str(_req(sli, "expr", where))
            self._check_expr(self.expr, where)
            self.bound = float(_req(sli, "bound", where))
            op = str(sli.get("op", "<="))
            if op not in _OPS:
                raise SLOSpecError(
                    f"{where}: op must be one of {sorted(_OPS)}, got "
                    f"{op!r}")
            self.op = op
        else:
            raise SLOSpecError(
                f"{where}: sli.kind must be 'ratio' or 'threshold', got "
                f"{self.kind!r}")
        labels = spec.get("labels") or {}
        if not isinstance(labels, dict):
            raise SLOSpecError(f"{where}: labels must be an object")
        # attribution labels (model/tenant/candidate/...) ride only on
        # the alert dicts in fleet.json — the gauge families keep fixed
        # labelnames
        self.labels = {str(k): str(v) for k, v in labels.items()}
        self.burn_windows = tuple(
            (str(lbl), float(short) * clock_scale,
             float(long) * clock_scale, float(factor))
            for lbl, short, long, factor in burn_windows)
        for lbl, short, long, factor in self.burn_windows:
            if not (0 < short < long):
                raise SLOSpecError(
                    f"{where}: burn window {lbl!r} needs "
                    f"0 < short < long, got ({short}, {long})")
            if factor <= 0:
                raise SLOSpecError(
                    f"{where}: burn window {lbl!r} factor must be "
                    f"positive, got {factor}")

    @staticmethod
    def _check_expr(expr: str, where: str) -> None:
        try:
            tsdb_mod.check_expr(expr)
        except ValueError as e:
            raise SLOSpecError(
                f"{where}: bad sli expression {expr!r}: {e}") from e

    # -- SLI ---------------------------------------------------------------
    def bad_series(self) -> str:
        return f"slo:{self.name}:bad"

    def observe(self, db: tsdb_mod.FleetTSDB, now: float) -> None:
        """Per-tick bookkeeping: threshold SLIs record their 0/1 bad
        sample so windowed bad fractions are just ``avg_over_time``."""
        if self.kind != "threshold":
            return
        v = db.query(self.expr, window_s=min(self.window_s, 60.0), now=now)
        if v is None:
            return          # no data is unknown, not good and not bad
        good = _OPS[self.op](v, self.bound)
        db.record(self.bad_series(), None, now, 0.0 if good else 1.0)

    def bad_fraction(self, db: tsdb_mod.FleetTSDB, window_s: float,
                     now: float) -> float | None:
        if self.kind == "threshold":
            frac = db.query(f"avg_over_time({self.bad_series()})",
                            window_s=window_s, now=now)
        else:
            bad = db.query(self.bad_expr, window_s=window_s, now=now)
            total = db.query(self.total_expr, window_s=window_s, now=now)
            if bad is None or total is None or total <= 0:
                return None
            frac = bad / total
        if frac is None:
            return None
        return min(1.0, max(0.0, frac))

    # -- budget math -------------------------------------------------------
    def burn_rate(self, db: tsdb_mod.FleetTSDB, window_s: float,
                  now: float) -> float | None:
        frac = self.bad_fraction(db, window_s, now)
        if frac is None:
            return None
        return frac / (1.0 - self.objective)

    def budget_remaining(self, db: tsdb_mod.FleetTSDB,
                         now: float) -> float | None:
        burn = self.burn_rate(db, self.window_s, now)
        if burn is None:
            return None
        return 1.0 - burn


def load_slo_spec(doc: dict) -> list[SLO]:
    """Compile a parsed SLO file into objectives (raises
    :class:`SLOSpecError` on any malformed entry)."""
    if not isinstance(doc, dict):
        raise SLOSpecError("slo file: top level must be an object")
    clock_scale = float(doc.get("clock_scale", 1.0))
    if clock_scale <= 0:
        raise SLOSpecError(
            f"slo file: clock_scale must be positive, got {clock_scale}")
    raw_windows = doc.get("burn_windows")
    if raw_windows is not None:
        if not isinstance(raw_windows, list) or not raw_windows:
            raise SLOSpecError("slo file: burn_windows must be a "
                               "non-empty list")
        windows = tuple(
            (str(_req(w, "name", "burn_window")),
             float(_req(w, "short_s", "burn_window")),
             float(_req(w, "long_s", "burn_window")),
             float(_req(w, "factor", "burn_window")))
            for w in raw_windows)
    else:
        windows = DEFAULT_BURN_WINDOWS
    slos_doc = doc.get("slos")
    if not isinstance(slos_doc, list) or not slos_doc:
        raise SLOSpecError("slo file: 'slos' must be a non-empty list")
    slos = [SLO(s, clock_scale=clock_scale, burn_windows=windows)
            for s in slos_doc]
    names = [s.name for s in slos]
    if len(set(names)) != len(names):
        raise SLOSpecError(f"slo file: duplicate slo names in {names}")
    return slos


def load_slo_file(path: str) -> tuple[list[SLO], list[tsdb_mod.RecordingRule]]:
    """Parse + compile an SLO file; also returns any extra recording
    rules it declares (``"rules": [{"name", "expr", "window_s"}]``)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except OSError as e:
        raise SLOSpecError(f"cannot read slo file {path}: {e}") from e
    except ValueError as e:
        raise SLOSpecError(f"slo file {path} is not valid JSON: {e}") from e
    slos = load_slo_spec(doc)
    rules = []
    for r in doc.get("rules") or []:
        try:
            rules.append(tsdb_mod.RecordingRule(
                _req(r, "name", "rule"), _req(r, "expr", "rule"),
                float(r.get("window_s", 30.0))))
        except ValueError as e:
            raise SLOSpecError(f"slo file {path}: bad rule: {e}") from e
    return slos, rules


class SLOEngine:
    """Evaluates every objective each scrape tick: writes the budget /
    burn gauges into the merged registry, appends burn alerts onto the
    scrape's alert list (same dict shape ``evaluate_alerts`` emits, so
    every downstream consumer — flight recorder, rollout gate,
    autopilot — inherits them), and returns fleet.json summaries."""

    def __init__(self, slos: list[SLO]):
        self.slos = list(slos)
        # last firing state per (slo, window): a window with NO data
        # holds its previous state — a missed scrape neither pages nor
        # resolves (resolving on absence would flap the pager and
        # re-edge the flight recorder every stall)
        self._firing: dict[tuple[str, str], bool] = {}

    def evaluate(self, db: tsdb_mod.FleetTSDB, reg, now: float,
                 alerts: list) -> list[dict]:
        budget_g = reg.gauge(
            "distlr_slo_budget_remaining",
            "Fraction of the SLO window's error budget remaining "
            "(1 = untouched, 0 = exhausted, negative = overspent; "
            "NaN = no data yet)", ("slo",))
        burn_g = reg.gauge(
            "distlr_slo_burn_rate",
            "Error-budget burn rate over each alerting window "
            "(1 = burning exactly the budget; NaN = no data yet)",
            ("slo", "window"))
        alert_g = reg.gauge(
            "distlr_alert_slo_burn",
            "1 while an SLO burn-rate window pair (short AND long over "
            "its factor) is firing", ("slo", "window", "threshold"))
        summaries = []
        for slo in self.slos:
            slo.observe(db, now)
            budget = slo.budget_remaining(db, now)
            budget_g.labels(slo=slo.name).set(
                budget if budget is not None else math.nan)
            summary = {
                "name": slo.name,
                "objective": slo.objective,
                "window_s": slo.window_s,
                "budget_remaining": budget,
                "burn": {},
            }
            for lbl, short_s, long_s, factor in slo.burn_windows:
                short = slo.burn_rate(db, short_s, now)
                long = slo.burn_rate(db, long_s, now)
                burn_g.labels(slo=slo.name, window=lbl).set(
                    long if long is not None else math.nan)
                if short is None or long is None:
                    # no data: hold the previous state (see __init__)
                    firing = self._firing.get((slo.name, lbl), False)
                else:
                    firing = short >= factor and long >= factor
                self._firing[(slo.name, lbl)] = firing
                alert_g.labels(slo=slo.name, window=lbl,
                               threshold=f"{factor:g}").set(
                    1.0 if firing else 0.0)
                labels = {"slo": slo.name, "window": lbl, **slo.labels}
                alerts.append({
                    "name": "distlr_alert_slo_burn",
                    "labels": labels,
                    "firing": firing,
                    "value": (round(long, 6)
                              if long is not None and math.isfinite(long)
                              else None),
                    "threshold": factor,
                })
                summary["burn"][lbl] = {
                    "short": short, "long": long, "factor": factor,
                    "firing": firing,
                }
            summaries.append(summary)
        return summaries
