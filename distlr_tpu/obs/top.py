"""``launch top`` — a live ANSI terminal dashboard over the fleet scrape.

Renders the aggregator's ``/fleet.json`` (per-rank step rate, pull/push
p50/p99, staleness in seconds AND pushes-behind, firing alerts) the way
``top`` renders processes: one frame per poll, in-place.  Pure text in,
pure text out — :func:`render_fleet` takes the parsed summary and
returns the frame, so tests assert on content without a terminal.
"""

from __future__ import annotations

import collections
import json
import sys
import time
import urllib.request

from distlr_tpu.obs.tsdb import delta_rate

_RESET = "\x1b[0m"
_BOLD = "\x1b[1m"
_DIM = "\x1b[2m"
_RED = "\x1b[31m"
_GREEN = "\x1b[32m"
_YELLOW = "\x1b[33m"
#: Home + clear-to-end: repaint without the flicker of a full 2J clear.
CLEAR = "\x1b[H\x1b[J"

_STATE_COLOR = {"up": _GREEN, "stale": _YELLOW, "down": _RED}

_COLUMNS = (
    ("role", 9), ("rank", 4), ("state", 6), ("steps", 8),
    ("samples/s", 10), ("req/s", 8), ("push/s", 8), ("e2e p50/p99", 13),
    ("step p50", 9), ("pull p50/p99", 13), ("push p50/p99", 13),
    ("stale s", 8), ("stale pushes", 13), ("compiles", 8), ("dev MB", 8),
    ("mdl", 4), ("t-shed", 7), ("sh-psi", 7), ("lag", 5), ("autopilot", 14),
    ("err/s", 6), ("inc", 5),
)


class RateTracker:
    """Windowed rates from successive ``/fleet.json`` polls: the frame's
    cumulative counters (serve/route requests, ok gradient pushes) are
    differenced against the OLDEST frame in a bounded history — so the
    dashboard shows requests/s and pushes/s over the last N scrapes next
    to the cumulative columns, not a lifetime average that flattens
    every burst."""

    def __init__(self, window: int = 10):
        if window < 2:
            raise ValueError(f"window must be >= 2 frames, got {window}")
        self._hist: collections.deque = collections.deque(maxlen=window)

    @staticmethod
    def _counters(fleet: dict) -> dict:
        cur = {}
        for r in fleet.get("ranks", []):
            req = r.get("requests")
            if req is None:
                req = r.get("route_requests")
            cur[(r.get("role"), r.get("rank"))] = (req, r.get("pushes"))
        return cur

    def update(self, fleet: dict) -> dict:
        """Feed one frame; returns ``{(role, rank): {"req_s", "push_s"}}``
        (values None where the rank exports no such counter)."""
        ts = fleet.get("updated")
        if ts is None:
            return {}
        if self._hist and self._hist[-1][0] == ts:
            # the aggregator hasn't rescraped since our last poll: a
            # duplicate frame would shrink the window without adding data
            pass
        else:
            self._hist.append((ts, self._counters(fleet)))
        if len(self._hist) < 2:
            return {}
        t0, old = self._hist[0]
        t1, new = self._hist[-1]
        if t1 - t0 <= 0:
            return {}
        rates = {}
        for key, (req1, push1) in new.items():
            req0, push0 = old.get(key, (None, None))
            rates[key] = {
                "req_s": delta_rate(t0, req0, t1, req1),
                "push_s": delta_rate(t0, push0, t1, push1),
            }
        return rates


def _c(text: str, code: str, color: bool) -> str:
    return f"{code}{text}{_RESET}" if color else text


def _ms(v) -> str:
    if v is None:
        return "-"
    return f"{v:.2f}" if v < 100 else f"{v:.0f}"


def _pair(p50, p99) -> str:
    if p50 is None and p99 is None:
        return "-"
    return f"{_ms(p50)}/{_ms(p99)}"


def _num(v, fmt="{:.1f}") -> str:
    return "-" if v is None else fmt.format(v)


def _autopilot(r: dict) -> str:
    """The controller rank's cell: actions/rollbacks, the last action
    (``eng+3`` = engine scaled up to 3), and whether it is holding."""
    if r.get("autopilot_ticks") is None:
        return "-"
    cell = (f"{r.get('autopilot_actions', 0)}a/"
            f"{r.get('autopilot_rollbacks', 0)}r")
    last = r.get("autopilot_last_action")
    if last:
        sign = "+" if last.get("direction") == "up" else "-"
        cell += f" {str(last.get('actuator', '?'))[:3]}{sign}{last.get('to')}"
    if r.get("autopilot_holding"):
        cell += " hold"
    return cell


def _rank_cells(r: dict, rates: dict | None = None) -> list[str]:
    rr = (rates or {}).get((r.get("role"), r.get("rank")), {})
    return [
        str(r.get("role", "?")), str(r.get("rank", "?")),
        str(r.get("state", "?")),
        _num(r.get("steps"), "{:d}"), _num(r.get("samples_per_s")),
        _num(rr.get("req_s")), _num(rr.get("push_s")),
        # e2e serve latency: the routing tier's admission-to-reply
        # histogram (the number a user-facing SLO is stated against)
        _pair(r.get("route_p50_ms"), r.get("route_p99_ms")),
        _ms(r.get("step_p50_ms")),
        _pair(r.get("pull_p50_ms"), r.get("pull_p99_ms")),
        _pair(r.get("push_p50_ms"), r.get("push_p99_ms")),
        _num(r.get("staleness_s"), "{:.3f}"),
        _pair(r.get("staleness_pushes_p50"), r.get("staleness_pushes_p99")),
        # JAX runtime introspection: recompile count + live device-
        # buffer footprint (engine/trainer ranks; '-' for jax-free roles)
        _num(r.get("jax_compiles"), "{:d}"),
        _num(r.get("device_mb")),
        # multi-tenant serving: hosted model count, per-tenant quota
        # sheds, and the worst live shadow PSI (routing-tier ranks)
        _num(r.get("models"), "{:d}"),
        _num(r.get("tenant_shed"), "{:d}"),
        _num(r.get("shadow_psi"), "{:.3f}"),
        # feedback backlog (pending unclaimed shards) + the autopilot
        # rank's control-loop telemetry (actions, rollbacks, last move)
        _num(r.get("shard_lag"), "{:.0f}"),
        _autopilot(r),
        # structured-log ERROR rate (tsdb-windowed) + the open-incident
        # seq the aggregator stamps while an alert edge's bundle is
        # settling or its alert is still firing
        _num(r.get("log_errors"), "{:.2f}"),
        ("-" if r.get("incident_open") is None
         else f"{int(r['incident_open']):04d}"),
    ]


def render_fleet(fleet: dict, *, color: bool = True,
                 clear: bool = False, rates: dict | None = None) -> str:
    """One dashboard frame from a parsed ``/fleet.json`` document.
    ``rates``: a :class:`RateTracker.update` result — windowed req/s
    and push/s per rank (``-`` without history)."""
    lines: list[str] = []
    tot = fleet.get("totals", {})
    updated = fleet.get("updated")
    if fleet.get("virtual"):
        # a fleetsim-emitted frame: "updated" is the simulator's
        # virtual clock, meaningless against time.time() — scrub by
        # simulated offset instead (rate windows already difference
        # successive "updated" stamps, so they are virtual-safe as-is)
        age = (f"t=+{updated:.1f}s (virtual clock)" if updated is not None
               else "never")
    elif updated:
        age = f"{max(0.0, time.time() - updated):.1f}s ago"
    else:
        age = "never"
    head = (f"distlr fleet top — {fleet.get('run_dir', '?')} — "
            f"{tot.get('up', 0)}/{tot.get('ranks', 0)} up — "
            f"{tot.get('samples_per_s', 0):,.0f} samples/s — updated {age}")
    lines.append(_c(head, _BOLD, color))

    firing = [a for a in fleet.get("alerts", []) if a.get("firing")]
    if firing:
        for a in firing:
            labels = ",".join(f"{k}={v}" for k, v in a.get("labels", {}).items())
            val = a.get("value")
            lines.append(_c(
                f"ALERT {a['name']}{{{labels}}}"
                + (f" value={val}" if val is not None else ""),
                _RED + _BOLD, color))
    else:
        lines.append(_c("alerts: none firing", _DIM, color))
    # SLO error budgets (aggregators running with --slo-file publish a
    # "slo" summary in fleet.json; frames without one render unchanged)
    for s in fleet.get("slo") or []:
        budget = s.get("budget_remaining")
        cell = "budget ?" if budget is None else f"budget {budget:7.1%}"
        burns = []
        for lbl, b in sorted((s.get("burn") or {}).items()):
            long = b.get("long")
            burns.append(f"{lbl} {'-' if long is None else f'{long:.2f}x'}"
                         + (" FIRING" if b.get("firing") else ""))
        line = f"SLO {s.get('name', '?')}: {cell}  " + "  ".join(burns)
        exhausted = budget is not None and budget <= 0
        firing = any(b.get("firing") for b in (s.get("burn") or {}).values())
        code = _RED + _BOLD if (exhausted or firing) else _DIM
        lines.append(_c(line, code, color))
    lines.append("")

    header = "  ".join(name.ljust(w) for name, w in _COLUMNS)
    lines.append(_c(header, _BOLD, color))
    for r in fleet.get("ranks", []):
        cells = _rank_cells(r, rates)
        row = "  ".join(c.ljust(w) for c, (_, w) in zip(cells, _COLUMNS))
        state_color = _STATE_COLOR.get(r.get("state"), "")
        lines.append(_c(row, state_color, color) if state_color else row)
    if not fleet.get("ranks"):
        lines.append(_c("  (no ranks discovered yet — are processes "
                        "running with --obs-run-dir?)", _DIM, color))
    body = "\n".join(lines) + "\n"
    return (CLEAR + body) if clear else body


def run_top_replay(path: str, *, interval: float = 0.0,
                   color: bool | None = None, out=None,
                   rate_window: int = 10) -> int:
    """Offline incident scrubbing (``launch top --replay``): render a
    banked scrape history (``<run_dir>/history.jsonl``, one
    ``/fleet.json`` document per line, written by the aggregator every
    cycle) frame by frame.  Windowed req/s / push/s columns derive from
    the REPLAYED timestamps, so rates read as they did live.  Returns a
    shell-style exit code."""
    out = out or sys.stdout
    if color is None:
        color = bool(getattr(out, "isatty", lambda: False)())
    tracker = RateTracker(window=max(2, rate_window))
    n = 0
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    fleet = json.loads(line)
                except ValueError:
                    continue  # torn tail line: skip, keep scrubbing
                if n and interval > 0:
                    time.sleep(interval)
                frame = render_fleet(fleet, color=color, clear=color,
                                     rates=tracker.update(fleet))
                out.write(frame)
                out.flush()
                n += 1
    except OSError as e:
        out.write(f"cannot replay {path}: {e}\n")
        return 1
    except KeyboardInterrupt:
        if color:
            out.write(_RESET + "\n")
        return 130
    if n == 0:
        out.write(f"no frames in {path} — did the aggregator run with "
                  "history enabled?\n")
        return 1
    out.write(f"replayed {n} frames from {path}\n")
    return 0


def run_top(url: str, *, interval: float = 1.0,
            iterations: int | None = None, color: bool | None = None,
            timeout_s: float = 2.0, out=None, rate_window: int = 10) -> int:
    """Poll ``<url>/fleet.json`` and repaint until interrupted (or for
    ``iterations`` frames — what scripts and tests use).  Returns a
    shell-style exit code.  ``rate_window``: frames of history behind
    the windowed req/s / push/s columns."""
    out = out or sys.stdout
    if color is None:
        color = bool(getattr(out, "isatty", lambda: False)())
    tracker = RateTracker(window=max(2, rate_window))
    n = 0
    try:
        while iterations is None or n < iterations:
            if n:
                time.sleep(interval)
            try:
                with urllib.request.urlopen(url + "/fleet.json",
                                            timeout=timeout_s) as r:
                    fleet = json.load(r)
                frame = render_fleet(fleet, color=color, clear=color,
                                     rates=tracker.update(fleet))
            except Exception as e:  # noqa: BLE001 — show, keep polling
                frame = (CLEAR if color else "") + \
                    f"fleet aggregator unreachable at {url}: {e}\n"
            out.write(frame)
            out.flush()
            n += 1
    except KeyboardInterrupt:
        if color:
            out.write(_RESET + "\n")
        return 130
    return 0
