"""``launch top`` — a live ANSI terminal dashboard over the fleet scrape.

Renders the aggregator's ``/fleet.json`` (per-rank step rate, pull/push
p50/p99, staleness in seconds AND pushes-behind, firing alerts) the way
``top`` renders processes: one frame per poll, in-place.  Pure text in,
pure text out — :func:`render_fleet` takes the parsed summary and
returns the frame, so tests assert on content without a terminal.
"""

from __future__ import annotations

import json
import sys
import time
import urllib.request

_RESET = "\x1b[0m"
_BOLD = "\x1b[1m"
_DIM = "\x1b[2m"
_RED = "\x1b[31m"
_GREEN = "\x1b[32m"
_YELLOW = "\x1b[33m"
#: Home + clear-to-end: repaint without the flicker of a full 2J clear.
CLEAR = "\x1b[H\x1b[J"

_STATE_COLOR = {"up": _GREEN, "stale": _YELLOW, "down": _RED}

_COLUMNS = (
    ("role", 9), ("rank", 4), ("state", 6), ("steps", 8),
    ("samples/s", 10), ("step p50", 9), ("pull p50/p99", 13),
    ("push p50/p99", 13), ("stale s", 8), ("stale pushes", 13),
)


def _c(text: str, code: str, color: bool) -> str:
    return f"{code}{text}{_RESET}" if color else text


def _ms(v) -> str:
    if v is None:
        return "-"
    return f"{v:.2f}" if v < 100 else f"{v:.0f}"


def _pair(p50, p99) -> str:
    if p50 is None and p99 is None:
        return "-"
    return f"{_ms(p50)}/{_ms(p99)}"


def _num(v, fmt="{:.1f}") -> str:
    return "-" if v is None else fmt.format(v)


def _rank_cells(r: dict) -> list[str]:
    return [
        str(r.get("role", "?")), str(r.get("rank", "?")),
        str(r.get("state", "?")),
        _num(r.get("steps"), "{:d}"), _num(r.get("samples_per_s")),
        _ms(r.get("step_p50_ms")),
        _pair(r.get("pull_p50_ms"), r.get("pull_p99_ms")),
        _pair(r.get("push_p50_ms"), r.get("push_p99_ms")),
        _num(r.get("staleness_s"), "{:.3f}"),
        _pair(r.get("staleness_pushes_p50"), r.get("staleness_pushes_p99")),
    ]


def render_fleet(fleet: dict, *, color: bool = True,
                 clear: bool = False) -> str:
    """One dashboard frame from a parsed ``/fleet.json`` document."""
    lines: list[str] = []
    tot = fleet.get("totals", {})
    updated = fleet.get("updated")
    age = f"{max(0.0, time.time() - updated):.1f}s ago" if updated else "never"
    head = (f"distlr fleet top — {fleet.get('run_dir', '?')} — "
            f"{tot.get('up', 0)}/{tot.get('ranks', 0)} up — "
            f"{tot.get('samples_per_s', 0):,.0f} samples/s — updated {age}")
    lines.append(_c(head, _BOLD, color))

    firing = [a for a in fleet.get("alerts", []) if a.get("firing")]
    if firing:
        for a in firing:
            labels = ",".join(f"{k}={v}" for k, v in a.get("labels", {}).items())
            val = a.get("value")
            lines.append(_c(
                f"ALERT {a['name']}{{{labels}}}"
                + (f" value={val}" if val is not None else ""),
                _RED + _BOLD, color))
    else:
        lines.append(_c("alerts: none firing", _DIM, color))
    lines.append("")

    header = "  ".join(name.ljust(w) for name, w in _COLUMNS)
    lines.append(_c(header, _BOLD, color))
    for r in fleet.get("ranks", []):
        cells = _rank_cells(r)
        row = "  ".join(c.ljust(w) for c, (_, w) in zip(cells, _COLUMNS))
        state_color = _STATE_COLOR.get(r.get("state"), "")
        lines.append(_c(row, state_color, color) if state_color else row)
    if not fleet.get("ranks"):
        lines.append(_c("  (no ranks discovered yet — are processes "
                        "running with --obs-run-dir?)", _DIM, color))
    body = "\n".join(lines) + "\n"
    return (CLEAR + body) if clear else body


def run_top(url: str, *, interval: float = 1.0,
            iterations: int | None = None, color: bool | None = None,
            timeout_s: float = 2.0, out=None) -> int:
    """Poll ``<url>/fleet.json`` and repaint until interrupted (or for
    ``iterations`` frames — what scripts and tests use).  Returns a
    shell-style exit code."""
    out = out or sys.stdout
    if color is None:
        color = bool(getattr(out, "isatty", lambda: False)())
    n = 0
    try:
        while iterations is None or n < iterations:
            if n:
                time.sleep(interval)
            try:
                with urllib.request.urlopen(url + "/fleet.json",
                                            timeout=timeout_s) as r:
                    fleet = json.load(r)
                frame = render_fleet(fleet, color=color, clear=color)
            except Exception as e:  # noqa: BLE001 — show, keep polling
                frame = (CLEAR if color else "") + \
                    f"fleet aggregator unreachable at {url}: {e}\n"
            out.write(frame)
            out.flush()
            n += 1
    except KeyboardInterrupt:
        if color:
            out.write(_RESET + "\n")
        return 130
    return 0
