"""ONE traffic model for the serving tier — shared math, two drivers.

``benchmarks/loadgen.py`` (real sockets against a ``launch route``
front-end) and :mod:`distlr_tpu.analysis.fleetsim` (simulated arrivals
against modeled engines) must stress the control plane with the SAME
offered-load shape, or a policy tuned against one lies about the
other.  Everything here is pure, seeded, stdlib-only arithmetic:

* the **diurnal curve** (:func:`qps_at`) and its open-loop send
  :func:`schedule` — raised cosine from ``base_qps`` to ``peak_qps``
  over ``period_s``, integrated at fixed ``dt`` so the offsets are a
  deterministic function of the four numbers alone;
* **Zipf-skewed popularity** (:class:`ZipfSampler`) — key/feature ids
  drawn ``P(k) ∝ 1/(k+1)^alpha`` via inverse-CDF on a caller-owned
  ``random.Random``, plus :meth:`ZipfSampler.mass` so fleetsim can ask
  "how much of the hot set lands in key range [lo, hi)" without
  sampling at all (the reshard-convergence check);
* **per-tenant mixes** (:func:`parse_tenant_mix` /
  :func:`split_by_mix`) — ``"v1=0.8,v2=0.2"`` specs normalized and
  apportioned by largest remainder, so W senders split across models
  the same way every run;
* a **replayable label-delay distribution** (:class:`LabelDelay`) —
  lognormal parameterized by its own p50/p95 (the two numbers an
  operator actually knows about a feedback pipeline), sampled from a
  caller-owned seeded RNG.

No numpy, no jax: fleetsim imports this on the analysis path where
heavyweight deps are banned, and loadgen keeps its numpy payload
generation on its own side.
"""

from __future__ import annotations

import bisect
import math

__all__ = [
    "LabelDelay",
    "ZipfSampler",
    "parse_tenant_mix",
    "qps_at",
    "schedule",
    "split_by_mix",
]


def qps_at(t: float, base_qps: float, peak_qps: float,
           period_s: float) -> float:
    """The diurnal curve: raised cosine, base at t=0 and t=period, peak
    at t=period/2."""
    phase = (t % period_s) / period_s
    return base_qps + (peak_qps - base_qps) * 0.5 * (1.0 - math.cos(
        2.0 * math.pi * phase))


def schedule(duration_s: float, base_qps: float, peak_qps: float,
             period_s: float, *, dt: float = 0.001) -> list[float]:
    """Deterministic send offsets: integrate the curve in ``dt`` steps
    and emit a send time each time the cumulative expectation crosses
    the next integer."""
    times: list[float] = []
    acc = 0.0
    t = 0.0
    while t < duration_s:
        acc += qps_at(t, base_qps, peak_qps, period_s) * dt
        while acc >= 1.0:
            acc -= 1.0
            times.append(t)
        t += dt
    return times


class ZipfSampler:
    """Zipf-skewed ids over ``[0, n)``: ``P(k) ∝ 1/(k+1)^alpha``.

    ``alpha=0`` degrades to uniform (every existing call site keeps its
    old distribution by default).  Sampling is inverse-CDF bisection on
    ``rng.random()`` — the caller owns the ``random.Random``, so one
    seed makes the whole traffic tape replayable."""

    def __init__(self, n: int, alpha: float = 1.1):
        if n < 1:
            raise ValueError(f"need n >= 1 ids, got {n}")
        if alpha < 0:
            raise ValueError(f"alpha must be >= 0, got {alpha}")
        self.n = int(n)
        self.alpha = float(alpha)
        weights = [1.0 / float(k + 1) ** self.alpha for k in range(self.n)]
        total = sum(weights)
        self._cdf: list[float] = []
        acc = 0.0
        for w in weights:
            acc += w / total
            self._cdf.append(acc)
        self._cdf[-1] = 1.0  # guard the float tail

    def sample(self, rng) -> int:
        return bisect.bisect_left(self._cdf, rng.random())

    def mass(self, lo: int, hi: int) -> float:
        """Probability mass of ids in ``[lo, hi)`` — the expected load
        share of a key range under this popularity, closed-form."""
        lo = max(0, min(self.n, int(lo)))
        hi = max(0, min(self.n, int(hi)))
        if hi <= lo:
            return 0.0
        upper = self._cdf[hi - 1]
        lower = self._cdf[lo - 1] if lo > 0 else 0.0
        return upper - lower


def parse_tenant_mix(spec) -> dict[str, float]:
    """``"v1=0.8,v2=0.2"`` (or a ready mapping) -> normalized weights.
    Rejects empty specs, non-positive weights, and duplicates loudly —
    a silently-dropped tenant is a traffic model lying about the
    fleet."""
    if isinstance(spec, dict):
        items = [(str(k), v) for k, v in spec.items()]
    else:
        items = []
        seen: set[str] = set()
        for part in str(spec).split(","):
            part = part.strip()
            if not part:
                continue
            name, eq, raw = part.partition("=")
            name = name.strip()
            if not name or not eq:
                raise ValueError(
                    f"tenant mix entry {part!r}: need model=weight")
            if name in seen:
                raise ValueError(f"tenant mix names {name!r} twice")
            seen.add(name)
            items.append((name, raw.strip()))
    if not items:
        raise ValueError(f"empty tenant mix spec {spec!r}")
    mix: dict[str, float] = {}
    for name, raw in items:
        try:
            w = float(raw)
        except (TypeError, ValueError):
            raise ValueError(
                f"tenant mix weight for {name!r} must be a number, "
                f"got {raw!r}") from None
        if w <= 0 or not math.isfinite(w):
            raise ValueError(
                f"tenant mix weight for {name!r} must be positive and "
                f"finite, got {w}")
        mix[name] = w
    total = sum(mix.values())
    return {name: w / total for name, w in mix.items()}


def split_by_mix(count: int, mix: dict[str, float]) -> dict[str, int]:
    """Apportion ``count`` identical senders across the mix by largest
    remainder (Hamilton's method): deterministic, sums to ``count``,
    and every tenant with positive weight gets at least the floor of
    its share."""
    if count < 0:
        raise ValueError(f"count must be >= 0, got {count}")
    total = sum(mix.values())
    quotas = [(name, count * w / total) for name, w in mix.items()]
    out = {name: int(q) for name, q in quotas}
    rem = count - sum(out.values())
    by_frac = sorted(quotas, key=lambda nq: (-(nq[1] - int(nq[1])), nq[0]))
    for name, _q in by_frac[:rem]:
        out[name] += 1
    return out


class LabelDelay:
    """Replayable label-arrival delays: lognormal pinned by its own
    p50/p95 (``sigma = ln(p95/p50) / z95``), sampled off a caller-owned
    seeded RNG — the shape feedback pipelines actually show (most
    labels arrive fast, a heavy tail straggles past the join window)."""

    _Z95 = 1.6448536269514722  # Phi^-1(0.95)

    def __init__(self, p50_s: float, p95_s: float):
        if p50_s <= 0 or p95_s < p50_s:
            raise ValueError(
                f"need 0 < p50_s <= p95_s, got {p50_s}/{p95_s}")
        self.p50_s = float(p50_s)
        self.p95_s = float(p95_s)
        self._mu = math.log(self.p50_s)
        self._sigma = (math.log(self.p95_s) - self._mu) / self._Z95

    def sample(self, rng) -> float:
        if self._sigma == 0.0:
            return self.p50_s
        return rng.lognormvariate(self._mu, self._sigma)
