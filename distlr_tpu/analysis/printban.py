"""print-ban lint: structured-log coverage can't silently regress.

ISSUE 18 makes log records a first-class fleet signal — journaled,
trace-stamped, deduped, federated into incident bundles.  That only
stays true if daemon code keeps logging through the
``utils/logging.get_logger`` path (which the journal tee shadows);
a bare ``print(`` or ``sys.stderr.write`` in a serving or training
module is a narrative line the incident engine can never collect.

This pass AST-scans every module under ``distlr_tpu/`` for ``print(``
calls and ``sys.stderr.write`` calls and flags them.  Legitimate
terminal output — the launch CLI's scriptable stdout contracts
(``METRICS``/``SERVING``/...), the lint runners' own reports, the
reference-format eval line — lives in the audited baseline
``analysis/printban_baseline.toml``: same grammar and hygiene rules as
the concurrency baseline (a justification is REQUIRED; a stale entry is
itself a finding), with keys at function granularity
(``print:<module>:<function>``) and a trailing ``*`` glob so one entry
can cover a CLI module.
"""

from __future__ import annotations

import ast
import os

from distlr_tpu.analysis.report import Finding, rel, repo_root


def baseline_path() -> str:
    return os.path.join(repo_root(), "distlr_tpu", "analysis",
                        "printban_baseline.toml")


def _is_stderr_write(node: ast.Call) -> bool:
    # sys.stderr.write(...) — the attribute chain, not a variable that
    # happens to hold the stream (the lint is syntactic, like the
    # concurrency registry)
    f = node.func
    return (isinstance(f, ast.Attribute) and f.attr == "write"
            and isinstance(f.value, ast.Attribute)
            and f.value.attr == "stderr"
            and isinstance(f.value.value, ast.Name)
            and f.value.value.id == "sys")


def _scan_file(path: str) -> dict[str, list[tuple[str, int]]]:
    """``{finding key: [(file, line), ...]}`` for one module."""
    try:
        with open(path) as f:
            tree = ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError):
        return {}
    prel = rel(path)
    hits: dict[str, list[tuple[str, int]]] = {}

    def visit(node: ast.AST, func: str) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            func = node.name
        if isinstance(node, ast.Call):
            kind = None
            if isinstance(node.func, ast.Name) and node.func.id == "print":
                kind = "print"
            elif _is_stderr_write(node):
                kind = "stderr-write"
            if kind is not None:
                key = f"{kind}:{prel}:{func}"
                hits.setdefault(key, []).append((prel, node.lineno))
        for child in ast.iter_child_nodes(node):
            visit(child, func)

    visit(tree, "<module>")
    return hits


def collect() -> dict[str, list[tuple[str, int]]]:
    root = os.path.join(repo_root(), "distlr_tpu")
    hits: dict[str, list[tuple[str, int]]] = {}
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in sorted(filenames):
            if name.endswith(".py"):
                hits.update(_scan_file(os.path.join(dirpath, name)))
    return hits


# -- the audited baseline (concurrency-baseline grammar, two fields) -------

def _load_baseline() -> tuple[list[tuple[str, str, int]], list[Finding]]:
    """``[(key, justification, line)]`` + hygiene findings.  Subset
    grammar shared with the concurrency baseline: ``[[suppress]]``
    blocks of quoted ``key``/``justification`` pairs, full-line
    comments, blank lines."""
    path = baseline_path()
    if not os.path.exists(path):
        return [], []
    prel = rel(path)
    entries: list[tuple[str, str, int]] = []
    problems: list[Finding] = []
    cur: dict[str, tuple[str, int]] | None = None

    def flush(at_line: int) -> None:
        nonlocal cur
        if cur is None:
            return
        key = cur.get("key")
        just = cur.get("justification")
        if key is None:
            problems.append(Finding(
                "printban", f"baseline-no-key:{at_line}",
                "[[suppress]] entry has no key", ((prel, at_line),)))
        elif just is None or not just[0].strip():
            problems.append(Finding(
                "printban", f"baseline-no-justification:{key[0]}",
                f"baseline entry {key[0]!r} carries no justification — "
                "every allowlisted print must say WHY it is terminal "
                "output and not a log record", ((prel, key[1]),)))
        else:
            entries.append((key[0], just[0], key[1]))
        cur = None

    i = 0
    with open(path) as f:
        for i, raw in enumerate(f, start=1):
            line = "" if raw.strip().startswith("#") else raw.strip()
            if not line:
                continue
            if line == "[[suppress]]":
                flush(i)
                cur = {}
                continue
            if "=" in line and cur is not None:
                name, _, val = line.partition("=")
                val = val.strip()
                if len(val) < 2 or val[0] not in "\"'" or val[-1] != val[0]:
                    problems.append(Finding(
                        "printban", f"baseline-parse:{i}",
                        f"baseline values must be quoted strings, got "
                        f"{val!r}", ((prel, i),)))
                else:
                    cur[name.strip()] = (val[1:-1], i)
                continue
            problems.append(Finding(
                "printban", f"baseline-parse:{i}",
                f"unparseable baseline line {line!r}", ((prel, i),)))
    flush(i + 1)
    return entries, problems


def _matches(entry_key: str, finding_key: str) -> bool:
    if entry_key.endswith("*"):
        return finding_key.startswith(entry_key[:-1])
    return finding_key == entry_key


def check() -> list[Finding]:
    hits = collect()
    entries, problems = _load_baseline()
    findings: list[Finding] = list(problems)
    used: set[int] = set()
    for key in sorted(hits):
        idxs = [i for i, (ek, _j, _ln) in enumerate(entries)
                if _matches(ek, key)]
        if idxs:
            used.update(idxs)
            continue
        kind = key.split(":", 1)[0]
        what = ("bare print(" if kind == "print"
                else "sys.stderr.write(")
        findings.append(Finding(
            "printban", key,
            f"{what}...) outside the CLI-output allowlist — daemon "
            "narrative must go through utils/logging.get_logger so the "
            "structured-log journal (and incident bundles) see it; if "
            "this IS terminal output, allowlist it in "
            "printban_baseline.toml with a justification",
            tuple(hits[key])))
    prel = rel(baseline_path())
    for i, (ek, _j, ln) in enumerate(entries):
        if i not in used:
            findings.append(Finding(
                "printban", f"baseline-stale:{ek}",
                f"baseline entry {ek!r} matches no current print site — "
                "the output it allowlisted is gone; delete the entry",
                ((prel, ln),)))
    return findings
