"""Concurrency lint: shared-state discipline + lock-order cycles.

The repo's post-review history is a catalog of races that only human
eyes caught (the barrier double-vote, ``JitCacheProbe.tick``'s
read-modify-write, the joiner's spool/pending races) — and in a
Hogwild-style system some races are INTENTIONAL, which is exactly why
the accidental ones must be machine-distinguishable.  Two AST passes
over ``distlr_tpu/`` (static — nothing is imported):

**Shared-state registry.**  For every class that provably crosses
threads (spawns ``threading.Thread``, subclasses ``Thread`` or a
``socketserver`` server, or owns a lock — owning a lock is a
self-declaration of cross-thread sharing), find attributes written
under a ``with self.<lock>:`` in one method but read or written
lock-free in another.  ``__init__`` is exempt (construction
happens-before thread start).

**Lock-order graph.**  Every lock the package creates is a node; an
edge ``A -> B`` means some code path acquires B while holding A —
through direct ``with`` nesting, same-class method calls (one level of
closure), or calls through attributes whose class is statically known
(``self.group = ServerGroup(...)`` or an annotated ctor parameter).  A
cycle in this graph is a deadlock waiting for the right interleaving.

Intentional findings live in ``analysis/concurrency_baseline.toml``;
every entry REQUIRES a one-line justification, and a finding not in the
baseline fails the build.  Stale baseline entries (matching nothing)
fail too — suppressions must never outlive their race.
"""

from __future__ import annotations

import ast
import dataclasses
import os

from distlr_tpu.analysis.report import Finding, repo_root

#: names that create a lock when assigned to an attribute / module global
_LOCK_FACTORIES = {"Lock", "RLock", "Condition"}
#: base-class names marking a class as thread-crossing by construction
_THREADED_BASES = {"Thread", "ThreadingTCPServer", "ThreadingMixIn",
                   "StreamRequestHandler", "BaseRequestHandler"}


@dataclasses.dataclass
class Access:
    attr: str
    line: int
    held: frozenset[str]  # lock attrs held at this point
    kind: str             # "read" | "write"


@dataclasses.dataclass
class MethodInfo:
    name: str
    line: int
    accesses: list[Access] = dataclasses.field(default_factory=list)
    #: (lock_node, line, locks_held_at_acquire)
    acquires: list[tuple[str, int, frozenset[str]]] = \
        dataclasses.field(default_factory=list)
    #: same-class methods this one calls: (name, line, held)
    self_calls: list[tuple[str, int, frozenset[str]]] = \
        dataclasses.field(default_factory=list)
    #: calls through typed attributes: (attr, method, line, held)
    attr_calls: list[tuple[str, str, int, frozenset[str]]] = \
        dataclasses.field(default_factory=list)


@dataclasses.dataclass
class ClassInfo:
    module: str   # repo-relative path
    name: str
    line: int
    lock_attrs: dict[str, int] = dataclasses.field(default_factory=dict)
    spawns_threads: bool = False
    threaded_base: bool = False
    methods: dict[str, MethodInfo] = dataclasses.field(default_factory=dict)
    #: self.<attr> -> class NAME it holds (ctor construction or
    #: annotated ctor param)
    attr_types: dict[str, str] = dataclasses.field(default_factory=dict)

    @property
    def crosses_threads(self) -> bool:
        return bool(self.spawns_threads or self.threaded_base
                    or self.lock_attrs)


def _iter_py(pkg_dir: str):
    for dirpath, dirnames, filenames in os.walk(pkg_dir):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def _is_lock_factory(node: ast.AST) -> bool:
    """``threading.Lock()`` / ``Lock()`` / ``threading.RLock()`` ..."""
    if not isinstance(node, ast.Call):
        return False
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr in _LOCK_FACTORIES
    if isinstance(fn, ast.Name):
        return fn.id in _LOCK_FACTORIES
    return False


def _self_attr(node: ast.AST) -> str | None:
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


class _MethodVisitor(ast.NodeVisitor):
    """Walk one method body tracking the set of self-locks held."""

    def __init__(self, info: MethodInfo, lock_attrs: set[str]):
        self.info = info
        self.locks = lock_attrs
        self.held: list[str] = []

    def _frozen(self) -> frozenset[str]:
        return frozenset(self.held)

    def visit_With(self, node: ast.With) -> None:
        acquired: list[str] = []
        for item in node.items:
            attr = _self_attr(item.context_expr)
            if attr is not None and attr in self.locks:
                self.info.acquires.append(
                    (attr, node.lineno, self._frozen()))
                acquired.append(attr)
                self.held.append(attr)
            else:
                self.visit(item.context_expr)
        for stmt in node.body:
            self.visit(stmt)
        for _ in acquired:
            self.held.pop()

    def visit_Attribute(self, node: ast.Attribute) -> None:
        attr = _self_attr(node)
        if attr is not None and attr not in self.locks:
            kind = ("write" if isinstance(node.ctx, (ast.Store, ast.Del))
                    else "read")
            self.info.accesses.append(
                Access(attr, node.lineno, self._frozen(), kind))
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        # self.x += 1 parses the target as a Load-ctx read in some
        # branches; record the read-modify-write explicitly as a write
        attr = _self_attr(node.target)
        if attr is not None and attr not in self.locks:
            self.info.accesses.append(
                Access(attr, node.lineno, self._frozen(), "write"))
        self.visit(node.value)

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        if isinstance(fn, ast.Attribute):
            # self.m(...)
            if isinstance(fn.value, ast.Name) and fn.value.id == "self":
                self.info.self_calls.append(
                    (fn.attr, node.lineno, self._frozen()))
            # self.attr.m(...)
            inner = _self_attr(fn.value)
            if inner is not None:
                self.info.attr_calls.append(
                    (inner, fn.attr, node.lineno, self._frozen()))
        self.generic_visit(node)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        # nested closures (thread bodies defined inline) run on OTHER
        # threads: whatever locks the spawner holds are NOT held there
        saved, self.held = self.held, []
        for stmt in node.body:
            self.visit(stmt)
        self.held = saved

    visit_AsyncFunctionDef = visit_FunctionDef


def _collect_class(cls_node: ast.ClassDef, module: str) -> ClassInfo:
    info = ClassInfo(module=module, name=cls_node.name, line=cls_node.lineno)
    for base in cls_node.bases:
        name = base.attr if isinstance(base, ast.Attribute) else (
            base.id if isinstance(base, ast.Name) else "")
        if name in _THREADED_BASES:
            info.threaded_base = True
    # pass 1: lock attrs + attribute types from every method (locks are
    # overwhelmingly bound in __init__, but start()/reset styles exist)
    for item in cls_node.body:
        if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        ann: dict[str, str] = {}
        for arg in item.args.args + item.args.kwonlyargs:
            a = arg.annotation
            if isinstance(a, ast.Name):
                ann[arg.arg] = a.id
            elif (isinstance(a, ast.Constant) and isinstance(a.value, str)):
                ann[arg.arg] = a.value.strip('"').split(".")[-1]
        for node in ast.walk(item):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                attr = _self_attr(node.targets[0])
                if attr is None:
                    continue
                if _is_lock_factory(node.value):
                    info.lock_attrs.setdefault(attr, node.lineno)
                elif (isinstance(node.value, ast.Call)
                      and isinstance(node.value.func, ast.Name)):
                    info.attr_types.setdefault(attr, node.value.func.id)
                elif (isinstance(node.value, ast.Name)
                      and node.value.id in ann):
                    info.attr_types.setdefault(attr, ann[node.value.id])
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "Thread"):
                info.spawns_threads = True
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "Thread"):
                info.spawns_threads = True
    # pass 2: per-method access/acquire walk
    locks = set(info.lock_attrs)
    for item in cls_node.body:
        if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        m = MethodInfo(name=item.name, line=item.lineno)
        v = _MethodVisitor(m, locks)
        if item.name.endswith("_locked"):
            # repo convention: a *_locked method asserts its caller
            # already holds the class lock — its accesses are guarded,
            # and flagging them would punish exactly the discipline the
            # lint wants to encourage
            v.held.append("<caller-held>")
        for stmt in item.body:
            v.visit(stmt)
        info.methods[item.name] = m
    return info


def collect_classes(pkg_dir: str | None = None) -> list[ClassInfo]:
    pkg_dir = pkg_dir or os.path.join(repo_root(), "distlr_tpu")
    root = os.path.dirname(pkg_dir)
    out: list[ClassInfo] = []
    for path in _iter_py(pkg_dir):
        with open(path) as f:
            try:
                tree = ast.parse(f.read(), filename=path)
            except SyntaxError:
                continue
        module = os.path.relpath(path, root)
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                out.append(_collect_class(node, module))
    return out


# ---------------------------------------------------------------------------
# finding generators
# ---------------------------------------------------------------------------


def shared_state_findings(classes: list[ClassInfo]) -> list[Finding]:
    """Attributes written under a lock in one method but accessed
    lock-free in another, on thread-crossing classes."""
    out: list[Finding] = []
    for cls in classes:
        if not cls.crosses_threads or not cls.lock_attrs:
            continue
        guarded: dict[str, tuple[str, int]] = {}  # attr -> (method, line)
        for m in cls.methods.values():
            if m.name == "__init__":
                continue
            for a in m.accesses:
                if a.kind == "write" and a.held and a.attr not in guarded:
                    guarded[a.attr] = (m.name, a.line)
        for attr, (gm, gline) in sorted(guarded.items()):
            for m in cls.methods.values():
                if m.name == "__init__":
                    continue
                bare = [a for a in m.accesses
                        if a.attr == attr and not a.held]
                if not bare:
                    continue
                kind = ("write" if any(a.kind == "write" for a in bare)
                        else "read")
                a0 = min(bare, key=lambda a: a.line)
                out.append(Finding(
                    "concurrency",
                    f"unlocked-{kind}:{cls.module}:{cls.name}.{attr}"
                    f":{m.name}",
                    f"{cls.name}.{attr} is written under a lock in "
                    f"{gm}() but {kind.replace('write', 'written')}"
                    f"{'' if kind == 'write' else ''} lock-free in "
                    f"{m.name}() — either take the lock, or baseline it "
                    "with a justification if the race is intentional",
                    ((cls.module, a0.line), (cls.module, gline))))
    # dedupe: one finding per (class, attr, method, kind)
    seen: set[str] = set()
    uniq = []
    for f in out:
        if f.key not in seen:
            seen.add(f.key)
            uniq.append(f)
    return uniq


def _acquired_closure(cls: ClassInfo) -> dict[str, set[tuple[str, int]]]:
    """Per method: self-locks it may acquire, directly or through ONE
    level of same-class calls -> {(lock_attr, line)}."""
    direct: dict[str, set[tuple[str, int]]] = {}
    for name, m in cls.methods.items():
        direct[name] = {(lk, ln) for lk, ln, _held in m.acquires}
    closed: dict[str, set[tuple[str, int]]] = {}
    for name, m in cls.methods.items():
        s = set(direct[name])
        for callee, ln, _held in m.self_calls:
            for lk, _ln2 in direct.get(callee, ()):
                s.add((lk, ln))
        closed[name] = s
    return closed


def lock_order_findings(classes: list[ClassInfo]) -> list[Finding]:
    """Build the cross-module lock-acquisition-order graph and report
    every cycle (a deadlock needs only the right interleaving)."""
    by_name = {c.name: c for c in classes}
    #: per-class acquisition closures, memoized — the typed-attribute
    #: branch below needs the TARGET class's closure per call site, and
    #: recomputing it there was O(call sites x methods)
    closures = {c.name: _acquired_closure(c) for c in classes}
    #: edge (holder_node, acquired_node) -> (module, line)
    edges: dict[tuple[str, str], tuple[str, int]] = {}

    def node(cls: ClassInfo, attr: str) -> str:
        return f"{cls.name}.{attr}"

    for cls in classes:
        closure = closures[cls.name]
        for m in cls.methods.values():
            # direct nesting + nested-through-self-calls; the
            # "<caller-held>" pseudo-token of *_locked methods never
            # names a real lock and takes no part in the order graph
            for lk, ln, held in m.acquires:
                for h in held:
                    if h.startswith("<"):
                        continue
                    edges.setdefault((node(cls, h), node(cls, lk)),
                                     (cls.module, ln))
            for callee, ln, held in m.self_calls:
                if not held:
                    continue
                for lk, _ln2 in closure.get(callee, ()):
                    for h in held:
                        if lk != h and not h.startswith("<"):
                            edges.setdefault(
                                (node(cls, h), node(cls, lk)),
                                (cls.module, ln))
            # calls through statically-typed attributes
            for attr, meth, ln, held in m.attr_calls:
                if not held:
                    continue
                tgt = by_name.get(cls.attr_types.get(attr, ""))
                if tgt is None:
                    continue
                for lk, _ln2 in closures[tgt.name].get(meth, ()):
                    for h in held:
                        if h.startswith("<"):
                            continue
                        edges.setdefault(
                            (node(cls, h), node(tgt, lk)),
                            (cls.module, ln))

    # cycle detection (DFS, reporting each strongly-connected loop once)
    graph: dict[str, set[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
    out: list[Finding] = []
    reported: set[frozenset[str]] = set()

    def dfs(start: str, cur: str, path: list[str]) -> None:
        for nxt in sorted(graph.get(cur, ())):
            if nxt == start and len(path) > 1:
                key = frozenset(path)
                if key in reported:
                    continue
                reported.add(key)
                cycle = path + [start]
                locs = tuple(
                    edges[(cycle[i], cycle[i + 1])]
                    for i in range(len(cycle) - 1)
                    if (cycle[i], cycle[i + 1]) in edges)
                out.append(Finding(
                    "concurrency",
                    "lock-cycle:" + "->".join(sorted(path)),
                    "lock-acquisition-order cycle "
                    + " -> ".join(cycle)
                    + " — two threads entering from different ends "
                    "deadlock; impose a global order or baseline with "
                    "a justification",
                    locs))
            elif nxt not in path and nxt > start:
                # only walk nodes > start so each cycle is found from
                # its smallest node exactly once
                dfs(start, nxt, path + [nxt])

    for n in sorted(graph):
        dfs(n, n, [n])
    return out


def check(pkg_dir: str | None = None,
          baseline_path: str | None = None) -> list[Finding]:
    """Run both concurrency passes, apply the audited baseline, and
    return the unsuppressed findings plus any baseline hygiene problems
    (missing justification, stale entry)."""
    from distlr_tpu.analysis.baseline import (
        apply_baseline,
        load_baseline,
        scenario_crossref,
    )

    classes = collect_classes(pkg_dir)
    findings = shared_state_findings(classes) + lock_order_findings(classes)
    entries, problems = load_baseline(baseline_path)
    kept, stale = apply_baseline(findings, entries)
    for f in stale:
        kept.append(f)
    return kept + problems + scenario_crossref(entries)
