"""Audited suppression baseline for the concurrency lint.

``analysis/concurrency_baseline.toml`` names every finding the repo
accepts ON PURPOSE — the Hogwild-intentional races and the
swap-whole-object publication patterns — and each entry REQUIRES a
one-line justification.  Hygiene is enforced both ways: an entry with
no justification is itself a finding, and a STALE entry (matching no
current finding) is too, so a suppression can never outlive the race it
was written for.

The file is parsed by the tiny TOML-subset reader below (this
container's Python predates ``tomllib`` and nothing may be pip
installed): ``[[suppress]]`` table arrays of ``key = "..."`` /
``justification = "..."`` / ``schedcheck_scenario = "..."`` string
triples (all three REQUIRED since ISSUE 15 — the scenario names the
:mod:`distlr_tpu.analysis.schedcheck` scenario exercising the race, or
``"-"`` for classes schedcheck cannot run), comments, and blank lines
— which is the entire grammar the baseline needs.  A trailing ``*`` in
a key glob-matches, so one entry can cover every method of one
attribute.
"""

from __future__ import annotations

import dataclasses
import os
import re

from distlr_tpu.analysis.report import Finding, rel, repo_root


@dataclasses.dataclass(frozen=True)
class Entry:
    key: str
    justification: str
    line: int
    #: the ISSUE-15 cross-reference: the schedcheck scenario that
    #: exercises this intentional race under controlled interleavings,
    #: or ``"-"`` for classes schedcheck cannot run (jax-holding,
    #: process-spawning) — an explicit, audited statement either way
    scenario: str | None = None
    scenario_line: int = 0

    def matches(self, finding_key: str) -> bool:
        if self.key.endswith("*"):
            return finding_key.startswith(self.key[:-1])
        return finding_key == self.key


def default_path() -> str:
    return os.path.join(repo_root(), "distlr_tpu", "analysis",
                        "concurrency_baseline.toml")


def _parse_string(raw: str, path: str, line: int) -> str:
    raw = raw.strip()
    if len(raw) < 2 or raw[0] not in "\"'" or raw[-1] != raw[0]:
        raise ValueError(
            f"{path}:{line}: baseline values must be quoted strings, "
            f"got {raw!r}")
    return raw[1:-1]


def load_baseline(path: str | None = None
                  ) -> tuple[list[Entry], list[Finding]]:
    """Parse the baseline; returns ``(entries, hygiene_problems)``.
    A missing file is an empty baseline (the passes then accept zero
    findings — the state a fully clean tree earns)."""
    path = path or default_path()
    if not os.path.exists(path):
        return [], []
    prel = rel(path) if os.path.isabs(path) else path
    entries: list[Entry] = []
    problems: list[Finding] = []
    cur: dict[str, tuple[str, int]] | None = None

    def flush(at_line: int) -> None:
        nonlocal cur
        if cur is None:
            return
        key = cur.get("key")
        just = cur.get("justification")
        scen = cur.get("schedcheck_scenario")
        if key is None:
            problems.append(Finding(
                "concurrency", f"baseline-no-key:{at_line}",
                "[[suppress]] entry has no key", ((prel, at_line),)))
        elif just is None or not just[0].strip():
            problems.append(Finding(
                "concurrency", f"baseline-no-justification:{key[0]}",
                f"baseline entry {key[0]!r} carries no justification — "
                "every suppression must say WHY the race is intentional",
                ((prel, key[1]),)))
        elif scen is None or not scen[0].strip():
            # ISSUE 15: every intentional race names the schedcheck
            # scenario that exercises it (or "-" with the class's
            # reason schedcheck cannot run it) — suppressions must be
            # tied to the machinery that would catch them going wrong
            problems.append(Finding(
                "concurrency", f"baseline-no-scenario:{key[0]}",
                f"baseline entry {key[0]!r} names no "
                "schedcheck_scenario — point it at the scenario that "
                "exercises this class under controlled interleavings, "
                "or '-' if the class cannot run under schedcheck "
                "(say why in the justification)",
                ((prel, key[1]),)))
        else:
            entries.append(Entry(key[0], just[0], key[1],
                                 scenario=scen[0], scenario_line=scen[1]))
        cur = None

    i = 0
    with open(path) as f:
        for i, raw in enumerate(f, start=1):
            # FULL-LINE comments only: a '#' inside a quoted
            # justification ("see ISSUE #13") is content, and splitting
            # on it would truncate the string mid-quote
            line = "" if raw.strip().startswith("#") else raw.strip()
            if not line:
                continue
            if line == "[[suppress]]":
                flush(i)
                cur = {}
                continue
            if "=" in line and cur is not None:
                name, _, val = line.partition("=")
                try:
                    cur[name.strip()] = (_parse_string(val, prel, i), i)
                except ValueError as e:
                    problems.append(Finding(
                        "concurrency", f"baseline-parse:{i}", str(e),
                        ((prel, i),)))
                continue
            problems.append(Finding(
                "concurrency", f"baseline-parse:{i}",
                f"unparseable baseline line {line!r} (the subset "
                "grammar is [[suppress]] + quoted key/justification)",
                ((prel, i),)))
    flush(i + 1)
    return entries, problems


def apply_baseline(findings: list[Finding], entries: list[Entry]
                   ) -> tuple[list[Finding], list[Finding]]:
    """Split findings by the baseline: returns ``(unsuppressed,
    stale-entry findings)``."""
    used: set[int] = set()
    kept: list[Finding] = []
    for f in findings:
        # EVERY matching entry counts as used, not just the first: a
        # broad glob listed before a narrower overlapping entry must not
        # make the narrow one read as "stale" — that would fail a tree
        # whose races are all audited, with a message claiming a live
        # race is gone.
        hits = [idx for idx, e in enumerate(entries) if e.matches(f.key)]
        if not hits:
            kept.append(f)
        else:
            used.update(hits)
    prel = rel(default_path())
    stale = [
        Finding("concurrency", f"baseline-stale:{e.key}",
                f"baseline entry {e.key!r} matches no current finding — "
                "the race it suppressed is gone; delete the entry",
                ((prel, e.line),))
        for idx, e in enumerate(entries) if idx not in used
    ]
    return kept, stale


_CLASS_RE = re.compile(r"^[a-z-]+:(?P<mod>[\w/.]+\.py):(?P<cls>\w+)")


def _entry_class(key: str) -> str | None:
    """``unlocked-read:path/mod.py:Class.attr[:method]`` ->
    ``path/mod.py:Class`` (None for keys not in that shape)."""
    m = _CLASS_RE.match(key)
    return f"{m.group('mod')}:{m.group('cls')}" if m else None


def scenario_crossref(entries: list[Entry]) -> list[Finding]:
    """Validate each entry's ``schedcheck_scenario`` against the live
    scenario registry — the PR-13 staleness rule applied to the
    ISSUE-15 cross-reference.  ``"-"`` is the audited opt-out; a named
    scenario must exist AND declare the entry's class among the
    classes it exercises (a renamed/deleted scenario, or one that
    stopped covering the class, fails loudly instead of silently
    un-verifying the race)."""
    from distlr_tpu.analysis.schedcheck import scenarios as sched_scenarios

    prel = rel(default_path())
    out: list[Finding] = []
    for e in entries:
        if e.scenario is None or e.scenario == "-":
            continue
        s = sched_scenarios.SCENARIOS.get(e.scenario)
        if s is None:
            out.append(Finding(
                "concurrency", f"baseline-stale-scenario:{e.key}",
                f"baseline entry {e.key!r} names schedcheck scenario "
                f"{e.scenario!r}, which does not exist (have: "
                f"{', '.join(sched_scenarios.names())}) — the "
                "cross-reference went stale",
                ((prel, e.scenario_line or e.line),)))
            continue
        cls = _entry_class(e.key)
        if cls is not None and cls not in s.classes:
            out.append(Finding(
                "concurrency", f"baseline-scenario-mismatch:{e.key}",
                f"baseline entry {e.key!r} names scenario "
                f"{e.scenario!r}, but that scenario does not exercise "
                f"{cls} (it covers: {', '.join(s.classes)})",
                ((prel, e.scenario_line or e.line),)))
    return out
