"""Shared finding type + helpers for the distlr-lint passes."""

from __future__ import annotations

import dataclasses
import os


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint problem.

    ``key`` is the STABLE identity a baseline suppression matches on —
    pass-specific, never containing line numbers (a suppression must
    survive unrelated edits above the finding).  ``where`` carries the
    human-facing ``file:line`` location(s); for cross-file findings
    (wire parity) both sides are listed.
    """

    #: which pass produced it ("wire", "concurrency", "config", "metrics")
    pass_name: str
    #: stable suppression identity, e.g.
    #: "unlocked-write:distlr_tpu/ps/server.py:ServerGroup.ports"
    key: str
    #: human-readable problem statement
    message: str
    #: ("file", line) locations, repo-relative — rendered as file:line
    locations: tuple[tuple[str, int], ...] = ()

    def render(self) -> str:
        locs = " ".join(f"{f}:{ln}" for f, ln in self.locations)
        return f"[{self.pass_name}] {self.key}: {self.message}" + (
            f"  ({locs})" if locs else "")


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def rel(path: str) -> str:
    return os.path.relpath(path, repo_root())
