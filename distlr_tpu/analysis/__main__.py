"""distlr-lint runner: ``python -m distlr_tpu.analysis`` / ``make lint``.

Runs every pass (wire parity, concurrency, config/CLI/docs parity, the
folded-in metrics-doc lint, the protocol model-checking pass, the
schedcheck interleaving pass, and the fleetsim scenario pass), prints
findings as
``[pass] key: message (file:line ...)``, and exits non-zero when any
survive the audited baselines — the single static-analysis entry point
tier-1 enforces through ``tests/test_analysis.py``.

    python -m distlr_tpu.analysis                # all passes
    python -m distlr_tpu.analysis --only wire    # one pass in isolation
    python -m distlr_tpu.analysis --list-passes  # what exists
    python -m distlr_tpu.analysis --write-docs   # regenerate
                                                 # docs/CONFIG.md +
                                                 # docs/METRICS.md
"""

from __future__ import annotations

import argparse
import sys

from distlr_tpu.analysis.report import Finding

PASSES = ("wire", "concurrency", "config", "metrics", "printban",
          "protocol", "sched", "fleetsim")

#: one-line summaries for --list-passes (kept here, not in the pass
#: modules, so listing passes never imports them)
PASS_SUMMARIES = {
    "wire": "kv_protocol.h <-> ps/wire.py mirror parity "
            "(analysis/wire_parity.py)",
    "concurrency": "shared-state registry + lock-order cycles + "
                   "audited baseline (analysis/concurrency.py)",
    "config": "Config <-> launch CLI <-> docs/CONFIG.md parity "
              "(analysis/config_doc.py)",
    "metrics": "metric-series <-> docs/METRICS.md drift "
               "(obs/metrics_doc.py)",
    "printban": "bare print()/sys.stderr.write outside the audited "
                "CLI-output allowlist (analysis/printban.py)",
    "protocol": "KV state-machine model checking + mutants + trace "
                "conformance (analysis/protocol/)",
    "sched": "deterministic-interleaving execution of the real fleet "
             "classes + mutants (analysis/schedcheck/)",
    "fleetsim": "discrete-event fleet scenarios property-testing the "
                "control plane + policy mutants (analysis/fleetsim/)",
}


def run_pass(name: str) -> list[Finding]:
    if name == "wire":
        from distlr_tpu.analysis import wire_parity
        return wire_parity.check()
    if name == "concurrency":
        from distlr_tpu.analysis import concurrency
        return concurrency.check()
    if name == "config":
        from distlr_tpu.analysis import config_doc
        return config_doc.check()
    if name == "printban":
        # ISSUE 18: structured-log coverage can't silently regress —
        # daemon narrative must flow through get_logger (where the
        # journal tee sees it), not bare prints
        from distlr_tpu.analysis import printban
        return printban.check()
    if name == "protocol":
        # ISSUE 14: bounded exhaustive search of the KV state machine,
        # mutant rediscovery, and fixture trace conformance — the
        # semantic pass next to the four syntactic ones (full-depth:
        # `make verify-protocol`)
        from distlr_tpu.analysis.protocol import lint
        return lint.check()
    if name == "sched":
        # ISSUE 15: the real Python classes under controlled
        # interleavings — scenario DFS/fuzz + the two historical-race
        # mutants (full-depth: `make verify-sched-full`)
        from distlr_tpu.analysis.schedcheck import lint
        return lint.check()
    if name == "fleetsim":
        # ISSUE 19: thousand-rank fleet scenarios driving the REAL
        # autopilot/balance/reshard/SLO policies on a seeded event
        # loop — pinned digests + the three policy-bug mutants
        # (full-depth: `make verify-fleetsim-full`)
        from distlr_tpu.analysis.fleetsim import lint
        return lint.check()
    if name == "metrics":
        # the PR-8 lint, folded under this runner (its module keeps its
        # own __main__ for the doc generator; tests/test_metrics_doc.py
        # keeps tier-1 coverage unchanged)
        from distlr_tpu.obs import metrics_doc
        return [Finding("metrics", f"metrics-drift:{i}", p)
                for i, p in enumerate(metrics_doc.check())]
    raise ValueError(f"unknown pass {name!r} (choose from {PASSES})")


def run(passes=PASSES) -> list[Finding]:
    findings: list[Finding] = []
    for name in passes:
        findings.extend(run_pass(name))
    return findings


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m distlr_tpu.analysis",
        description="distlr-lint: wire parity, concurrency, "
                    "config/docs parity, metrics doc, protocol model "
                    "checking, schedcheck interleavings, fleetsim "
                    "scenarios")
    ap.add_argument("--pass", dest="passes", action="append",
                    choices=PASSES,
                    help="run only this pass (repeatable; default all)")
    ap.add_argument("--only", dest="passes", action="append",
                    choices=PASSES, metavar="PASS",
                    help="alias of --pass: run one pass in isolation "
                    "(the now-eight-pass runner takes a while end to "
                    "end; see --list-passes)")
    ap.add_argument("--list-passes", action="store_true",
                    help="list the passes with one-line summaries, "
                    "then exit")
    ap.add_argument("--write-docs", action="store_true",
                    help="regenerate docs/CONFIG.md and docs/METRICS.md "
                    "from the sources, then exit")
    args = ap.parse_args(argv)
    if args.list_passes:
        for name in PASSES:
            print(f"{name}: {PASS_SUMMARIES[name]}")
        return 0
    if args.write_docs:
        from distlr_tpu.analysis import config_doc
        from distlr_tpu.obs import metrics_doc
        print(f"wrote {config_doc.write_doc()}")
        metrics_doc.main([])
        return 0
    passes = tuple(args.passes) if args.passes else PASSES
    findings = run(passes)
    for f in findings:
        print(f.render(), file=sys.stderr)
    if findings:
        print(f"distlr-lint: {len(findings)} finding(s) across "
              f"{len(passes)} pass(es)", file=sys.stderr)
        return 1
    print(f"distlr-lint: clean ({', '.join(passes)})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
