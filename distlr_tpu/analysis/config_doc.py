"""Config <-> CLI <-> docs parity lint + ``docs/CONFIG.md`` generator.

The same bidirectional style as the metrics-doc lint (ISSUE 8), applied
to the configuration surface: every :class:`distlr_tpu.config.Config`
field must be reachable from the ``launch`` CLI (an ``add_argument``
whose dest is the field, an audited alias, or an audited NO_FLAG entry
saying WHY not) and documented in the generated ``docs/CONFIG.md``; and
every doc row / audit entry must still correspond to a live field.
Everything is read statically (``ast`` — no jax, no argparse import).

Regenerate the doc after changing Config or the CLI::

    python -m distlr_tpu.analysis --write-docs
"""

from __future__ import annotations

import ast
import os
import re

from distlr_tpu.analysis.report import Finding, repo_root

#: Config field -> the launch flag DEST that carries it when they are
#: deliberately named differently (subcommand-scoped flags predating the
#: serve_*/route_* prefixes).  An alias naming a dead dest or a dead
#: field is itself a finding.
FLAG_ALIASES = {
    "serve_port": "port",
    "serve_host": "bind",
    "serve_max_wait_ms": "max_wait_ms",
    "serve_reload_interval_s": "reload_interval",
    "serve_hot_rows": "hot_rows",
    "serve_hot_min_coverage": "hot_min_coverage",
    "serve_hot_full_every": "hot_full_every",
    "serve_engine_idle_evict_s": "engine_idle_evict",
    "feedback_spool_dir": "feedback_spool",
    "feedback_shard_dir": "feedback_shards",
    "feedback_window_s": "feedback_window",
    "feedback_drift_block": "drift_block",
    "feedback_drift_threshold": "drift_threshold",
    "serve_model_id": "model_id",
    "route_quota": "quota",
    "route_port": "port",
    "route_host": "bind",
    "route_max_inflight": "max_inflight",
    "route_eject_after": "eject_after",
    "route_health_interval_s": "health_interval",
    "route_probe_backoff_s": "probe_backoff",
    "route_probe_backoff_max_s": "probe_backoff_max",
    "route_backend_timeout_s": "backend_timeout",
}

#: Config fields with deliberately NO CLI flag, each with the audit
#: reason (an entry for a field that gained a flag, or stopped
#: existing, is a finding).
NO_FLAG = {
    "sync_mode": "selected by the subcommand, not a flag: `launch sync` "
                 "is sync, `launch ps` is BSP, `launch ps --async` is "
                 "Hogwild",
    "l2_scale_by_batch": "per-quirk gate set via --compat-mode "
                         "(reference parity, SURVEY.md Q4); individual "
                         "flags would invite mixed quirk states the "
                         "parity suite never pins",
    "sync_last_gradient": "per-quirk gate set via --compat-mode (Q1)",
    "reference_rng_init": "per-quirk gate set via --compat-mode (Q2)",
    "wrap_final_batch": "per-quirk gate set via --compat-mode (Q5)",
    "dtype": "accumulation dtype is model-internal tuning pinned by the "
             "bench harness programmatically; the operational knob the "
             "CLI exposes is --feature-dtype",
    "compute_dtype": "matmul dtype, same class as dtype: bench-harness "
                     "tuning, not an operator knob",
    "mesh_shape": "derived from --num-workers x --feature-shards "
                  "(_config_from_args), never set directly",
    "ps_host": "reference env-var contract (DMLC_PS_ROOT_URI via "
               "Config.from_env); local launches use ephemeral ports "
               "and multi-host passes explicit --hosts",
    "ps_port": "reference env-var contract (DMLC_PS_ROOT_PORT), same "
               "as ps_host",
}


def config_path() -> str:
    return os.path.join(repo_root(), "distlr_tpu", "config.py")


def launch_path() -> str:
    return os.path.join(repo_root(), "distlr_tpu", "launch.py")


def doc_path() -> str:
    return os.path.join(repo_root(), "docs", "CONFIG.md")


# ---------------------------------------------------------------------------
# static extraction
# ---------------------------------------------------------------------------


def config_fields(path: str | None = None) -> dict[str, dict]:
    """Config dataclass fields -> {line, default, help} — the help text
    harvested from the comment block above (or inline with) the field,
    the way the dataclass is actually documented."""
    path = path or config_path()
    with open(path) as f:
        src = f.read()
    lines = src.splitlines()
    tree = ast.parse(src, filename=path)
    cls = next(n for n in tree.body
               if isinstance(n, ast.ClassDef) and n.name == "Config")
    out: dict[str, dict] = {}
    for node in cls.body:
        if not isinstance(node, ast.AnnAssign) or not isinstance(
                node.target, ast.Name):
            continue
        name = node.target.id
        default = ast.unparse(node.value) if node.value is not None else ""
        # inline comment, else the contiguous # block immediately above
        text = lines[node.lineno - 1]
        m = re.search(r"#\s?(.*)$", text)
        help_parts: list[str] = []
        if m and not text.lstrip().startswith("#"):
            help_parts.append(m.group(1).strip())
        i = node.lineno - 2
        block: list[str] = []
        while i >= 0:
            stripped = lines[i].strip()
            if stripped.startswith("#") and not stripped.startswith("# --"):
                block.append(stripped.lstrip("#").strip())
                i -= 1
            else:
                break
        help_parts = list(reversed(block)) + help_parts
        out[name] = {
            "line": node.lineno,
            "default": default,
            "help": " ".join(p for p in help_parts if p),
        }
    return out


def launch_dests(path: str | None = None) -> dict[str, dict]:
    """Every ``add_argument`` in launch.py -> dest: {flag, line}.  When
    several subcommands reuse one dest, the first flag wins (they are
    the same knob by construction)."""
    path = path or launch_path()
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    out: dict[str, dict] = {}
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "add_argument"):
            continue
        flags = [a.value for a in node.args
                 if isinstance(a, ast.Constant) and isinstance(a.value, str)
                 and a.value.startswith("--")]
        if not flags:
            continue
        dest = None
        for kw in node.keywords:
            if kw.arg == "dest" and isinstance(kw.value, ast.Constant):
                dest = kw.value.value
        if dest is None:
            dest = flags[0].lstrip("-").replace("-", "_")
        out.setdefault(dest, {"flag": flags[0], "line": node.lineno})
    return out


def documented_fields(text: str | None = None) -> dict[str, str]:
    """docs/CONFIG.md rows -> {field: flag-column-text}."""
    if text is None:
        try:
            with open(doc_path()) as f:
                text = f.read()
        except OSError:
            return {}
    rows = re.findall(r"^\| `([a-z0-9_]+)` \| ([^|]*) \|", text,
                      flags=re.MULTILINE)
    return {name: flag.strip() for name, flag in rows}


# ---------------------------------------------------------------------------
# doc generation
# ---------------------------------------------------------------------------


def _flag_for(field: str, dests: dict[str, dict]) -> str | None:
    if field in dests:
        return dests[field]["flag"]
    alias = FLAG_ALIASES.get(field)
    if alias is not None and alias in dests:
        return dests[alias]["flag"]
    return None


def generate() -> str:
    fields = config_fields()
    dests = launch_dests()
    lines = [
        "# Config reference",
        "",
        "Every `distlr_tpu.config.Config` field, its `launch` CLI flag,",
        "default, and meaning.  GENERATED — do not edit by hand:",
        "",
        "    python -m distlr_tpu.analysis --write-docs",
        "",
        "regenerates this file from the dataclass + the launch parser;",
        "the config-parity lint (`python -m distlr_tpu.analysis`, tier-1",
        "via tests/test_analysis.py) fails the build when field, flag,",
        "and doc drift in any direction.  Fields marked *(no flag)* are",
        "audited as CLI-less in `distlr_tpu/analysis/config_doc.py`",
        "(NO_FLAG), each with its reason.",
        "",
        "| field | flag | default | meaning |",
        "|---|---|---|---|",
    ]
    for name, meta in fields.items():
        flag = _flag_for(name, dests)
        if flag is None:
            flag_txt = "*(no flag)*"
        else:
            flag_txt = f"`{flag}`"
        help_txt = meta["help"].replace("|", "\\|")
        if name in NO_FLAG:
            help_txt = (help_txt + " — *no flag:* "
                        + NO_FLAG[name].replace("|", "\\|")).strip(" —")
        default = meta["default"].replace("|", "\\|")
        lines.append(
            f"| `{name}` | {flag_txt} | `{default}` | {help_txt} |")
    lines.append("")
    return "\n".join(lines)


def write_doc() -> str:
    path = doc_path()
    os.makedirs(os.path.dirname(path), exist_ok=True)
    text = generate()
    with open(path, "w") as f:
        f.write(text)
    return path


# ---------------------------------------------------------------------------
# the pass
# ---------------------------------------------------------------------------


def check() -> list[Finding]:
    fields = config_fields()
    dests = launch_dests()
    crel = os.path.relpath(config_path(), repo_root())
    lrel = os.path.relpath(launch_path(), repo_root())
    drel = os.path.relpath(doc_path(), repo_root())
    findings: list[Finding] = []

    # every field reaches the CLI, or carries an audited reason not to
    for name, meta in fields.items():
        if _flag_for(name, dests) is None and name not in NO_FLAG:
            findings.append(Finding(
                "config", f"config-no-flag:{name}",
                f"Config.{name} has no launch flag (no dest matches, no "
                "FLAG_ALIASES entry, no audited NO_FLAG reason)",
                ((crel, meta["line"]),)))

    # audit hygiene: aliases and NO_FLAG entries must stay live
    for field, dest in FLAG_ALIASES.items():
        if field not in fields:
            findings.append(Finding(
                "config", f"alias-stale-field:{field}",
                f"FLAG_ALIASES maps dead Config field {field!r}",
                ((crel, 1),)))
        elif dest not in dests:
            findings.append(Finding(
                "config", f"alias-stale-dest:{field}",
                f"FLAG_ALIASES maps {field!r} to dest {dest!r}, which no "
                "launch add_argument defines",
                ((lrel, 1),)))
    for field in NO_FLAG:
        if field not in fields:
            findings.append(Finding(
                "config", f"noflag-stale:{field}",
                f"NO_FLAG audits dead Config field {field!r}",
                ((crel, 1),)))
        elif field in dests:
            findings.append(Finding(
                "config", f"noflag-has-flag:{field}",
                f"NO_FLAG audits {field!r} as CLI-less but launch now "
                f"defines {dests[field]['flag']} — delete the entry",
                ((lrel, dests[field]["line"]),)))

    # doc sync, both directions (regenerate to fix)
    doc = documented_fields()
    if not doc:
        findings.append(Finding(
            "config", "config-doc-missing",
            "docs/CONFIG.md missing — run "
            "`python -m distlr_tpu.analysis --write-docs`",
            ((drel, 1),)))
        return findings
    for name, meta in fields.items():
        if name not in doc:
            findings.append(Finding(
                "config", f"undocumented-field:{name}",
                f"Config.{name} is missing from docs/CONFIG.md — "
                "regenerate it", ((crel, meta["line"]), (drel, 1))))
            continue
        flag = _flag_for(name, dests)
        want = f"`{flag}`" if flag else "*(no flag)*"
        if doc[name] != want:
            findings.append(Finding(
                "config", f"doc-flag-drift:{name}",
                f"docs/CONFIG.md lists {name} under {doc[name]!r} but "
                f"the CLI says {want!r} — regenerate",
                ((drel, 1),)))
    for name in doc:
        if name not in fields:
            findings.append(Finding(
                "config", f"stale-doc-row:{name}",
                f"docs/CONFIG.md documents {name} but Config has no such "
                "field — regenerate", ((drel, 1),)))
    return findings
