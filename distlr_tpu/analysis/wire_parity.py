"""Wire-parity lint: ``kv_protocol.h`` vs the Python protocol mirrors.

The bug class this kills is documented drift: the repo hand-mirrored
wire constants from ``ps/native/kv_protocol.h`` into Python (kStats
length pins, a third hand-rolled copy of the reply framing) and every
copy was one edit away from silently misframing the stream.  Since the
consolidation round, :mod:`distlr_tpu.ps.wire` is THE Python mirror and
every Python framing site imports it; this pass enforces the whole
arrangement statically (no imports — the header and the mirrors are
parsed, so the lint runs even where jax/numpy/native toolchains don't):

* every protocol constant in the header has a :mod:`~distlr_tpu.ps.wire`
  twin with the SAME value, and vice versa (one-sided constants fail
  with ``file:line`` on the side that has them);
* the ``static_assert``-ed frame sizes match the mirror's
  ``struct`` formats;
* ``STATS_FIELDS`` in :mod:`distlr_tpu.ps.client` tracks
  ``kStatsVals``/``kStatsValsV1`` in length and v1 order;
* ``CODEC_IDS`` in :mod:`distlr_tpu.compress.codecs` matches the
  header's ``Codec`` enum;
* no mirror site re-inlines a distinctive protocol value as a raw
  literal instead of naming it (the 4096 / 256 / magic class).
"""

from __future__ import annotations

import ast
import os
import re
import struct

from distlr_tpu.analysis.report import Finding, rel, repo_root

#: header constant -> distlr_tpu/ps/wire.py name.  ``sizeof(X)``
#: pseudo-constants come from the header's static_asserts.
HEADER_TO_WIRE = {
    "kMagic": "MAGIC",
    # enum class Op
    "kPush": "OP_PUSH",
    "kPull": "OP_PULL",
    "kBarrier": "OP_BARRIER",
    "kShutdown": "OP_SHUTDOWN",
    "kHello": "OP_HELLO",
    "kStats": "OP_STATS",
    "kPushPull": "OP_PUSH_PULL",
    "kEpoch": "OP_EPOCH",
    # enum Flags
    "kNone": "FLAG_NONE",
    "kResponse": "FLAG_RESPONSE",
    "kError": "FLAG_ERROR",
    "kInitPush": "FLAG_INIT_PUSH",
    "kForceInit": "FLAG_FORCE_INIT",
    "kCodecShift": "CODEC_SHIFT",
    "kCodecMask": "CODEC_MASK",
    "kOptState": "FLAG_OPT_STATE",
    "kTraced": "FLAG_TRACED",
    # enum Codec
    "kCodecNone": "CODEC_NONE",
    "kCodecInt8": "CODEC_INT8",
    "kCodecSign": "CODEC_SIGN",
    # constexpr values
    "kQuantBlock": "QUANT_BLOCK",
    "kStatsValsV1": "STATS_VALS_V1",
    "kStatsVals": "STATS_VALS",
    "kMaxValsPerKey": "MAX_VALS_PER_KEY",
    "kCapCodecInt8": "CAP_CODEC_INT8",
    "kCapCodecSign": "CAP_CODEC_SIGN",
    "kCapTrace": "CAP_TRACE",
    "kCapEpoch": "CAP_EPOCH",
    # static_assert-ed frame sizes
    "sizeof(MsgHeader)": "HEADER_SIZE",
    "sizeof(TraceFrame)": "TRACE_FRAME_SIZE",
}

#: wire.py integer constants with deliberately NO header twin, each with
#: the audit reason (the bidirectional check fails on unlisted extras)
WIRE_ONLY = {
    "AUX_MAX": "the u16 MsgHeader::aux width; the header types the "
               "field but names no constant for its ceiling",
}

#: header constant -> distlr_tpu/ps/store.py name.  Disk formats drift
#: exactly like wire formats drift: the durable-store constants the
#: native writer stamps into snapshot/WAL files are mirrored in
#: ps/store.py (NOT wire.py — they never cross a socket) and the same
#: bidirectional parity applies.
HEADER_TO_STORE = {
    "kStoreMagic": "STORE_MAGIC",
    "kStoreVersion": "STORE_VERSION",
    "kStoreHeaderSize": "STORE_HEADER_SIZE",
    "kStoreGenerations": "STORE_GENERATIONS",
    "kStoreFlagFtrl": "STORE_FLAG_FTRL",
    "kStoreFlagInitialized": "STORE_FLAG_INITIALIZED",
    "kWalMagic": "WAL_MAGIC",
    "kWalHeaderSize": "WAL_HEADER_SIZE",
    "kWalRecordHeaderSize": "WAL_RECORD_HEADER_SIZE",
}

#: store.py struct format -> the header-size constant it must pack to
STORE_STRUCT_SIZES = (
    ("SNAP_HEADER_STRUCT", "STORE_HEADER_SIZE"),
    ("WAL_SEGMENT_STRUCT", "WAL_HEADER_SIZE"),
    ("WAL_RECORD_STRUCT", "WAL_RECORD_HEADER_SIZE"),
)

#: the v1 kStats counter order the protocol comment fixes (the client's
#: STATS_FIELDS prefix must reproduce it exactly)
STATS_V1_ORDER = ("dim", "initialized", "pending_sync_pushes",
                  "barrier_waiters", "total_pushes", "total_pulls")

#: Python files that mirror wire framing (repo-relative) — the raw-
#: literal scan targets.  wire.py itself is the definition site.  The
#: protocol MODEL (analysis/protocol/, ISSUE 14) is a framing site like
#: any other: its op/flag/capability identities must come from wire.py,
#: so the executable spec can never drift from the header it verifies.
MIRROR_SITES = (
    "distlr_tpu/ps/client.py",
    "distlr_tpu/ps/membership.py",
    "distlr_tpu/ps/server.py",
    "distlr_tpu/compress/codecs.py",
    "distlr_tpu/chaos/proxy.py",
    "distlr_tpu/analysis/protocol/spec.py",
    "distlr_tpu/analysis/protocol/checker.py",
    "distlr_tpu/analysis/protocol/mutants.py",
    "distlr_tpu/analysis/protocol/conformance.py",
)

#: distinctive protocol values that must never appear as bare literals
#: in a mirror site (small ints like op codes and flag bits are too
#: collision-prone to scan for; these are unmistakable).  The store/WAL
#: magics are disk-format constants — named through ps/store.py.
_DISTINCTIVE = ("kMagic", "kQuantBlock", "kMaxValsPerKey",
                "kStoreMagic", "kWalMagic")


def header_path() -> str:
    return os.path.join(repo_root(), "distlr_tpu", "ps", "native",
                        "kv_protocol.h")


def wire_path() -> str:
    return os.path.join(repo_root(), "distlr_tpu", "ps", "wire.py")


# ---------------------------------------------------------------------------
# C header parsing
# ---------------------------------------------------------------------------

_INT_SUFFIX = re.compile(r"(?<=[0-9a-fA-Fx])(?:[uU]?[lL]{0,2}|[uU]?[lL][lL]?)\b")
_CONSTEXPR = re.compile(
    r"^\s*constexpr\s+[A-Za-z_][A-Za-z0-9_]*\s+(k[A-Za-z0-9_]+)\s*=\s*([^;]+);")
_ENUM_START = re.compile(r"^\s*enum\s+(class\s+)?([A-Za-z_]+)")
_ENUM_ENTRY = re.compile(r"^\s*(k[A-Za-z0-9_]+)\s*=\s*([^,}]+)\s*[,}]?")
_STATIC_ASSERT = re.compile(
    r"static_assert\s*\(\s*sizeof\s*\(\s*([A-Za-z_]+)\s*\)\s*==\s*(\d+)")


def _eval_cxx(expr: str, env: dict[str, int]) -> int:
    """Evaluate a C++ integer constant expression (literals with
    u/l suffixes, shifts, or-ed masks, references to earlier constants)
    using Python's own parser on the sanitized text."""
    text = _INT_SUFFIX.sub("", expr.strip())
    node = ast.parse(text, mode="eval").body
    return _eval_node(node, env, {})


def parse_header(path: str | None = None) -> dict[str, tuple[int, int]]:
    """Every protocol constant in the header -> ``(value, line)``:
    ``constexpr`` values, all enum entries, and the ``static_assert``-ed
    ``sizeof(Type)`` frame sizes (keyed ``"sizeof(Type)"``)."""
    path = path or header_path()
    out: dict[str, tuple[int, int]] = {}
    env: dict[str, int] = {}
    in_enum = False
    with open(path) as f:
        lines = f.readlines()
    for i, line in enumerate(lines, start=1):
        # strip // comments (the header is richly commented; a constant
        # mentioned in prose must not parse as a definition)
        code = line.split("//", 1)[0]
        if not code.strip():
            continue
        m = _STATIC_ASSERT.search(code)
        if m:
            out[f"sizeof({m.group(1)})"] = (int(m.group(2)), i)
            continue
        m = _CONSTEXPR.match(code)
        if m:
            try:
                val = _eval_cxx(m.group(2), env)
            except (ValueError, SyntaxError, KeyError):
                continue
            out[m.group(1)] = (val, i)
            env[m.group(1)] = val
            continue
        if _ENUM_START.match(code):
            in_enum = True
        if in_enum:
            m = _ENUM_ENTRY.match(code)
            if m:
                try:
                    val = _eval_cxx(m.group(2), env)
                except (ValueError, SyntaxError, KeyError):
                    continue
                out[m.group(1)] = (val, i)
                env[m.group(1)] = val
            if "}" in code:
                in_enum = False
    return out


# ---------------------------------------------------------------------------
# Python mirror parsing (static — modules are never imported)
# ---------------------------------------------------------------------------


def _eval_node(node: ast.AST, env: dict, modules: dict[str, dict]) -> int:
    """Tiny constant evaluator for mirror modules: int literals, binary
    arithmetic, names bound earlier in the module, and ``mod.NAME``
    attributes of an already-parsed mirror module."""
    if isinstance(node, ast.Constant) and isinstance(node.value, (int, str)):
        return node.value
    if isinstance(node, ast.Name) and node.id in env:
        return env[node.id]
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id in modules
            and node.attr in modules[node.value.id]):
        return modules[node.value.id][node.attr]
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        return -_eval_node(node.operand, env, modules)
    if isinstance(node, ast.BinOp):
        lhs = _eval_node(node.left, env, modules)
        rhs = _eval_node(node.right, env, modules)
        ops = {ast.LShift: lambda a, b: a << b,
               ast.RShift: lambda a, b: a >> b,
               ast.BitOr: lambda a, b: a | b,
               ast.BitAnd: lambda a, b: a & b,
               ast.Add: lambda a, b: a + b,
               ast.Sub: lambda a, b: a - b,
               ast.Mult: lambda a, b: a * b,
               ast.FloorDiv: lambda a, b: a // b}
        fn = ops.get(type(node.op))
        if fn is None:
            raise ValueError(f"unsupported operator {ast.dump(node.op)}")
        return fn(lhs, rhs)
    if (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)
            and node.func.attr == "Struct" and len(node.args) == 1
            and isinstance(node.args[0], ast.Constant)):
        # struct.Struct("<fmt>") -> its wire size (what parity cares about)
        return struct.calcsize(node.args[0].value)
    raise ValueError(f"unsupported expression {ast.dump(node)}")


def module_constants(path: str,
                     modules: dict[str, dict] | None = None
                     ) -> dict[str, tuple[object, int]]:
    """Module-level ``NAME = <const expr>`` bindings -> ``(value,
    line)``, resolved statically.  Tuples and dicts of constants are
    kept whole (STATS_FIELDS, CODEC_IDS); unevaluable assignments are
    skipped."""
    modules = modules or {}
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    out: dict[str, tuple[object, int]] = {}
    env: dict[str, object] = {}
    for node in tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt = node.targets[0]
        if not isinstance(tgt, ast.Name):
            continue
        try:
            if isinstance(node.value, (ast.Tuple, ast.List)):
                val: object = tuple(_eval_node(el, env, modules)
                                    for el in node.value.elts)
            elif isinstance(node.value, ast.Dict):
                val = {_eval_node(k, env, modules):
                       _eval_node(v, env, modules)
                       for k, v in zip(node.value.keys, node.value.values)}
            else:
                val = _eval_node(node.value, env, modules)
        except (ValueError, KeyError, struct.error):
            continue
        out[tgt.id] = (val, node.lineno)
        env[tgt.id] = val
    return out


def _import_aliases(path: str, target_module: str) -> set[str]:
    """Local names under which ``target_module`` is visible in a file
    (``from distlr_tpu.ps import wire`` -> {"wire"})."""
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    names: set[str] = set()
    short = target_module.rsplit(".", 1)[-1]
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == target_module:
                    names.add(a.asname or a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            for a in node.names:
                if f"{mod}.{a.name}" == target_module or (
                        mod == target_module.rsplit(".", 1)[0]
                        and a.name == short):
                    names.add(a.asname or a.name)
    return names


# ---------------------------------------------------------------------------
# the pass
# ---------------------------------------------------------------------------


def check(root: str | None = None,
          header: str | None = None) -> list[Finding]:
    """Run the wire-parity pass; returns findings ([] = parity holds).

    ``root``/``header`` exist for the self-test fixtures: the pass can
    be pointed at a seeded tree to prove it actually fails on a
    mismatch.
    """
    root = root or repo_root()
    hpath = header or os.path.join(root, "distlr_tpu", "ps", "native",
                                   "kv_protocol.h")
    wpath = os.path.join(root, "distlr_tpu", "ps", "wire.py")
    findings: list[Finding] = []
    hdr = parse_header(hpath)
    wire_vals = module_constants(wpath)
    hrel, wrel = rel(hpath) if root == repo_root() else hpath, \
        rel(wpath) if root == repo_root() else wpath

    # direction 1: every header constant has a wire twin of equal value
    # (durable-store constants route to ps/store.py — see
    # _check_store_format — and are skipped here)
    for hname, (hval, hline) in sorted(hdr.items()):
        if hname in HEADER_TO_STORE:
            continue
        wname = HEADER_TO_WIRE.get(hname)
        if wname is None:
            findings.append(Finding(
                "wire", f"header-only:{hname}",
                f"{hname} = {hval} exists in the header but has no "
                "distlr_tpu/ps/wire.py mirror (add it and extend "
                "HEADER_TO_WIRE)",
                ((hrel, hline),)))
            continue
        if wname not in wire_vals:
            findings.append(Finding(
                "wire", f"missing-mirror:{wname}",
                f"header {hname} = {hval} should mirror as wire.{wname}, "
                "which does not exist",
                ((hrel, hline), (wrel, 1))))
            continue
        wval, wline = wire_vals[wname]
        if wval != hval:
            findings.append(Finding(
                "wire", f"value-mismatch:{hname}",
                f"{hname} = {hval} in the header but wire.{wname} = "
                f"{wval} — the mirrors drifted",
                ((hrel, hline), (wrel, wline))))

    # direction 2: every wire int constant is either a mirror or audited
    mirrored = set(HEADER_TO_WIRE.values())
    for wname, (wval, wline) in sorted(wire_vals.items()):
        if not isinstance(wval, int) or wname.startswith("_"):
            continue
        if wname.endswith("_STRUCT"):
            continue  # struct objects; covered by the struct-size check
        if wname in mirrored or wname in WIRE_ONLY:
            continue
        findings.append(Finding(
            "wire", f"wire-only:{wname}",
            f"wire.{wname} = {wval} has no header twin and no WIRE_ONLY "
            "audit entry — either the header lost a constant or this "
            "needs an audited justification",
            ((wrel, wline),)))

    # struct formats must match the static_assert-ed sizes
    for sname, fname in (("HEADER_STRUCT", "HEADER_SIZE"),
                         ("TRACE_FRAME_STRUCT", "TRACE_FRAME_SIZE")):
        if sname in wire_vals and fname in wire_vals:
            sval, sline = wire_vals[sname]
            if sval != wire_vals[fname][0]:
                findings.append(Finding(
                    "wire", f"struct-size:{sname}",
                    f"wire.{sname} packs {sval} bytes but "
                    f"{fname} = {wire_vals[fname][0]}",
                    ((wrel, sline),)))

    findings += _check_store_format(root, hdr, hrel)
    findings += _check_stats_fields(root, hdr, hrel)
    findings += _check_codec_ids(root, hdr, hrel)
    findings += _check_raw_literals(root, hdr, hrel)
    return findings


def _check_store_format(root: str, hdr: dict, hrel: str) -> list[Finding]:
    """ps/store.py must mirror the header's durable-store constants
    exactly, in both directions, and its struct formats must pack to
    the header's pinned sizes — a disk-format edit that touches only
    one side fails the lint before it can strand snapshots."""
    spath = os.path.join(root, "distlr_tpu", "ps", "store.py")
    srel = rel(spath) if root == repo_root() else spath
    if not os.path.exists(spath):
        if any(h in hdr for h in HEADER_TO_STORE):
            return [Finding(
                "wire", "store-mirror-missing",
                "the header defines durable-store constants but "
                "distlr_tpu/ps/store.py does not exist", ((hrel, 1),))]
        return []
    store_vals = module_constants(spath)
    out: list[Finding] = []

    # direction 1: every header store constant has a store.py twin
    for hname, sname in sorted(HEADER_TO_STORE.items()):
        if hname not in hdr:
            out.append(Finding(
                "wire", f"store-header-lost:{hname}",
                f"HEADER_TO_STORE maps {hname} but the header no longer "
                "defines it", ((hrel, 1),)))
            continue
        hval, hline = hdr[hname]
        if sname not in store_vals:
            out.append(Finding(
                "wire", f"store-missing-mirror:{sname}",
                f"header {hname} = {hval} should mirror as "
                f"store.{sname}, which does not exist",
                ((hrel, hline), (srel, 1))))
            continue
        sval, sline = store_vals[sname]
        if sval != hval:
            out.append(Finding(
                "wire", f"store-value-mismatch:{hname}",
                f"{hname} = {hval} in the header but store.{sname} = "
                f"{sval} — the disk-format mirrors drifted",
                ((hrel, hline), (srel, sline))))

    # direction 2: every store.py int constant is a mirror (no
    # unaudited disk-format constants on the Python side)
    mirrored = set(HEADER_TO_STORE.values())
    for sname, (sval, sline) in sorted(store_vals.items()):
        if not isinstance(sval, int) or sname.startswith("_"):
            continue
        if sname.endswith("_STRUCT"):
            continue  # struct objects; covered by the size check below
        if sname in mirrored:
            continue
        out.append(Finding(
            "wire", f"store-only:{sname}",
            f"store.{sname} = {sval} has no kv_protocol.h twin — either "
            "the header lost a durable-store constant or HEADER_TO_STORE "
            "needs the new mapping", ((srel, sline),)))

    # struct formats must pack to the header-pinned sizes
    for stname, szname in STORE_STRUCT_SIZES:
        if stname in store_vals and szname in store_vals:
            stval, stline = store_vals[stname]
            if stval != store_vals[szname][0]:
                out.append(Finding(
                    "wire", f"store-struct-size:{stname}",
                    f"store.{stname} packs {stval} bytes but "
                    f"{szname} = {store_vals[szname][0]}",
                    ((srel, stline),)))
    return out


def _check_stats_fields(root: str, hdr: dict, hrel: str) -> list[Finding]:
    """STATS_FIELDS in ps/client.py must track kStatsVals in length and
    reproduce the protocol's v1 counter order as its prefix."""
    cpath = os.path.join(root, "distlr_tpu", "ps", "client.py")
    if not os.path.exists(cpath):
        return []
    crel = rel(cpath) if root == repo_root() else cpath
    consts = module_constants(cpath)
    out: list[Finding] = []
    if "STATS_FIELDS" not in consts:
        return [Finding("wire", "stats-fields-missing",
                        "ps/client.py no longer defines a statically "
                        "readable STATS_FIELDS tuple", ((crel, 1),))]
    fields, line = consts["STATS_FIELDS"]
    n_hdr, hline = hdr.get("kStatsVals", (None, 1))
    v1_hdr, v1line = hdr.get("kStatsValsV1", (None, 1))
    if n_hdr is not None and len(fields) != n_hdr:
        out.append(Finding(
            "wire", "stats-fields-length",
            f"STATS_FIELDS names {len(fields)} counters but the header "
            f"pins kStatsVals = {n_hdr} — extend BOTH sides together",
            ((crel, line), (hrel, hline))))
    if v1_hdr is not None and fields[:v1_hdr] != STATS_V1_ORDER[:v1_hdr]:
        out.append(Finding(
            "wire", "stats-fields-v1-order",
            f"STATS_FIELDS v1 prefix {fields[:v1_hdr]} != the protocol "
            f"order {STATS_V1_ORDER[:v1_hdr]} (kStatsValsV1 = {v1_hdr}; "
            "old servers reply exactly these, in exactly this order)",
            ((crel, line), (hrel, v1line))))
    return out


def _check_codec_ids(root: str, hdr: dict, hrel: str) -> list[Finding]:
    """CODEC_IDS in compress/codecs.py must match the Codec enum."""
    cpath = os.path.join(root, "distlr_tpu", "compress", "codecs.py")
    if not os.path.exists(cpath):
        return []
    crel = rel(cpath) if root == repo_root() else cpath
    wpath = os.path.join(root, "distlr_tpu", "ps", "wire.py")
    wire_env = {n: v for n, (v, _ln) in module_constants(wpath).items()
                if isinstance(v, int)}
    aliases = _import_aliases(cpath, "distlr_tpu.ps.wire")
    consts = module_constants(cpath, {a: wire_env for a in aliases})
    if "CODEC_IDS" not in consts:
        return [Finding("wire", "codec-ids-missing",
                        "compress/codecs.py no longer defines a "
                        "statically readable CODEC_IDS dict",
                        ((crel, 1),))]
    ids, line = consts["CODEC_IDS"]
    expected = {"none": hdr.get("kCodecNone", (0, 0))[0],
                "int8": hdr.get("kCodecInt8", (1, 0))[0],
                "signsgd": hdr.get("kCodecSign", (2, 0))[0]}
    if ids != expected:
        return [Finding(
            "wire", "codec-ids-mismatch",
            f"CODEC_IDS = {ids} but the header's Codec enum says "
            f"{expected}", ((crel, line), (hrel, 1)))]
    return []


def _check_raw_literals(root: str, hdr: dict, hrel: str) -> list[Finding]:
    """No mirror site may re-inline a distinctive protocol value as a
    bare literal — name it through distlr_tpu.ps.wire instead."""
    distinctive = {hdr[n][0]: n for n in _DISTINCTIVE if n in hdr}
    out: list[Finding] = []
    for site in MIRROR_SITES:
        path = os.path.join(root, site)
        if not os.path.exists(path):
            continue
        srel = rel(path) if root == repo_root() else path
        with open(path) as f:
            tree = ast.parse(f.read(), filename=path)
        for node in ast.walk(tree):
            if (isinstance(node, ast.Constant)
                    and isinstance(node.value, int)
                    and not isinstance(node.value, bool)
                    and node.value in distinctive):
                cname = distinctive[node.value]
                if cname in HEADER_TO_STORE:
                    named = f"store.{HEADER_TO_STORE[cname]}"
                else:
                    named = f"wire.{HEADER_TO_WIRE.get(cname, '?')}"
                out.append(Finding(
                    "wire",
                    f"raw-literal:{site}:{cname}",
                    f"protocol value {node.value} ({cname}) appears as "
                    f"a raw literal — use the named "
                    f"{named} mirror",
                    ((srel, node.lineno), (hrel, hdr[cname][1]))))
    return out
