"""Modeled fleet components + the real-policy composition seams.

Everything a process or socket owns in production is a fluid model
here — engines serve ``capacity_qps``, workers push gradients at a
rate, PS migrations take ``moved_keys / migrate_rate`` seconds — but
every DECISION is made by the real policy code:

* :class:`SimReplica` carries exactly the health fields of
  ``serve.router._Replica`` and is driven through
  :mod:`distlr_tpu.serve.balance` (selection order, ejection verdicts,
  probe backoff) — the router's policy, not a lookalike;
* :class:`SimActuators` duck-types
  :class:`~distlr_tpu.autopilot.actuators.Actuators` for a REAL
  :class:`~distlr_tpu.autopilot.daemon.AutopilotDaemon` (which brings
  its own sensor reduction and rate windows), raising the same
  ``ActuatorError`` when the standby pool runs dry;
* PS resizes go through the real
  :func:`~distlr_tpu.ps.server.plan_reshard`;
* the per-tick ``fleet.json`` document uses the same field names
  obs-agg federates (``route_requests``, ``route_shed``,
  ``staleness_pushes_p99``, ``shard_lag``, ...), so the daemon — and
  ``launch top --replay`` — cannot tell it is simulated.  Frames carry
  ``"virtual": true``; the dashboard renders the simulated clock
  instead of wall-clock age.

Request accounting per tick: offered load (the shared
:mod:`distlr_tpu.traffic` curve) spreads over in-rotation replicas;
requests landing on a replica inside a scripted fault window FAIL and
retry onto serving replicas (a retry, not an error) — with nowhere to
retry they are ERRORS (failed ACCEPTED requests, the thing the
``zero_failed_accepted`` property forbids outside fault windows).
Demand beyond serving capacity is SHED — explicit admission control,
never an error.  Overload alone never hard-fails an engine; only
scripted fault windows do.
"""

from __future__ import annotations

import dataclasses
import json

from distlr_tpu.analysis.fleetsim.events import EventLoop
from distlr_tpu.autopilot.actuators import ActuatorError
from distlr_tpu.autopilot.daemon import AutopilotDaemon
from distlr_tpu.autopilot.policy import PolicyConfig, PolicyEngine
from distlr_tpu.obs.registry import MetricsRegistry
from distlr_tpu.obs.slo import SLOEngine, load_slo_spec
from distlr_tpu.obs.tsdb import FleetTSDB
from distlr_tpu.ps.server import plan_reshard
from distlr_tpu.serve import balance
from distlr_tpu.traffic import qps_at

__all__ = ["FleetParams", "SimFleet", "SimPS", "SimReplica", "SimRouter",
           "SimActuators", "SimWorkers"]


def _r(v: float) -> float:
    """Canonical float for logs and fleet docs (6 decimals — formatting
    drift would break the byte-identity pin)."""
    return round(float(v), 6)


class SimReplica:
    """One modeled engine, shaped as the router's ``_Replica`` duck so
    :mod:`distlr_tpu.serve.balance` drives it unmodified."""

    def __init__(self, name: str, capacity_qps: float, now: float):
        self.name = name
        self.capacity_qps = float(capacity_qps)
        # -- the balance.* health-field contract --
        self.healthy = True
        self.consecutive_errors = 0
        self.inflight = 0
        self.requests = 0
        self.errors = 0
        self.ejections = 0
        self.reinstates = 0
        self.backoff_s = 0.0
        self.next_probe_at = 0.0
        self.last_ok = now
        self.last_probe = now
        self.models = {"m"}
        # -- model state --
        self.fail_until = 0.0       # scripted fault window end
        self.capacity_factor = 1.0  # slow-burn degradation knob
        self.in_service = True      # spun up (standby pool adds delay)
        self.retired = False
        self.floor_warned = False

    def failing(self, now: float) -> bool:
        return now < self.fail_until

    def capacity(self) -> float:
        return self.capacity_qps * self.capacity_factor


class SimRouter:
    """The routing tier: real balance policy over modeled replicas."""

    def __init__(self, loop: EventLoop, replicas: list[SimReplica], *,
                 eject_after: int = 3, probe_backoff_s: float = 2.0,
                 probe_backoff_max_s: float = 30.0,
                 health_interval_s: float = 2.0, base_ms: float = 5.0):
        self.loop = loop
        self.replicas = replicas
        self.eject_after = int(eject_after)
        self.probe_backoff_s = float(probe_backoff_s)
        self.probe_backoff_max_s = float(probe_backoff_max_s)
        self.health_interval_s = float(health_interval_s)
        self.base_ms = float(base_ms)
        self._rr = -1
        self.requests_total = 0.0   # accepted (cumulative)
        self.shed_total = 0.0
        self.errors_total = 0.0     # failed ACCEPTED requests
        self.retries_total = 0.0
        self.suppressed_total = 0   # floor-suppressed ejections
        self.p99_ms = self.base_ms
        #: (t, errors) deltas, for the zero_failed_accepted property
        self.error_ticks: list[tuple[float, float]] = []

    # -- membership --------------------------------------------------------
    def pool(self) -> list[SimReplica]:
        return [r for r in self.replicas if not r.retired and r.in_service]

    def in_rotation(self) -> list[SimReplica]:
        return [r for r in self.pool() if r.healthy]

    def _pools_for(self, rep: SimReplica) -> list[list[SimReplica]]:
        return [self.pool() for _m in sorted(rep.models)]

    # -- one traffic tick --------------------------------------------------
    def tick(self, dt: float, offered_qps: float) -> None:
        now = self.loop.now
        demand = offered_qps * dt
        rot = self.in_rotation()
        if not rot:
            # nothing in rotation: accepted-at-admission requests have
            # nowhere to go — hard errors, the outage fleetsim's
            # cascade scenario pins
            self.requests_total += demand
            self.errors_total += demand
            if demand > 0:
                self.error_ticks.append((now, _r(demand)))
                self.loop.log("route_errors", n=_r(demand), reason="no_replica")
            return
        ordered, self._rr = balance.order_candidates(rot, self._rr)
        share = demand / len(ordered)
        serving: list[SimReplica] = []
        failed_demand = 0.0
        for rep in ordered:
            if rep.failing(now):
                failed_demand += share
                # each failed exchange counts toward ejection; one
                # tick's worth is capped at the threshold (the streak
                # is what matters, not the raw request count)
                for _ in range(min(max(int(share), 1), self.eject_after)):
                    balance.note_failure(rep)
                verdict = balance.eject_verdict(
                    rep, self._pools_for(rep), self.eject_after)
                if verdict == "eject":
                    balance.eject(rep, now, self.probe_backoff_s)
                    self.loop.log("eject", replica=rep.name,
                                  errors=rep.consecutive_errors)
                elif verdict == "floor" and not rep.floor_warned:
                    rep.floor_warned = True
                    self.suppressed_total += 1
                    self.loop.log("eject_suppressed", replica=rep.name)
            else:
                serving.append(rep)
        cap = sum(r.capacity() for r in serving) * dt
        if serving:
            self.retries_total += failed_demand
            demand_on_serving = demand
            errors = 0.0
        else:
            demand_on_serving = demand - failed_demand
            errors = failed_demand
        served = min(demand_on_serving, cap)
        shed = max(0.0, demand_on_serving - served)
        self.requests_total += demand
        self.shed_total += shed
        self.errors_total += errors
        if errors > 0:
            self.error_ticks.append((now, _r(errors)))
            self.loop.log("route_errors", n=_r(errors), reason="all_failing")
        util = served / cap if cap > 0 else 1.0
        self.p99_ms = self.base_ms * (1.0 + 4.0 * util ** 3)
        for rep in serving:
            balance.note_success(rep, now)
            rep.floor_warned = False
            rep.inflight = int(util * 4)
            rep.requests += 1

    # -- health probes -----------------------------------------------------
    def probe_tick(self) -> None:
        now = self.loop.now
        for rep in self.pool():
            if not balance.probe_due(rep, now, self.health_interval_s,
                                     self.probe_backoff_s):
                continue
            outcome = balance.probe_result(
                rep, not rep.failing(now), now,
                probe_backoff_s=self.probe_backoff_s,
                probe_backoff_max_s=self.probe_backoff_max_s,
                eject_after=self.eject_after,
                pools=self._pools_for(rep))
            if outcome in ("reinstated", "ejected"):
                self.loop.log(f"probe_{outcome}", replica=rep.name)


class SimPS:
    """The KV server group: real :func:`plan_reshard` arithmetic, a
    fluid migration clock."""

    def __init__(self, dim: int, num: int, *,
                 migrate_keys_per_s: float = 200_000.0):
        self.dim = int(dim)
        self.num = int(num)
        self.ranges = [(self.dim * r // self.num,
                        self.dim * (r + 1) // self.num)
                       for r in range(self.num)]
        self.migrate_keys_per_s = float(migrate_keys_per_s)
        self.busy_until = 0.0
        self.resizes = 0
        self.moved_keys_total = 0

    def busy(self, now: float) -> bool:
        return now < self.busy_until

    def start_resize(self, to: int, loop: EventLoop):
        """Plan with the REAL planner, hold ``ps_busy`` for the modeled
        migration, commit at its end.  Returns the plan."""
        plan = plan_reshard(self.dim, self.ranges, to,
                            alive=[True] * self.num)
        dur = max(0.5, plan.moved_keys / self.migrate_keys_per_s)
        self.busy_until = loop.now + dur
        self.resizes += 1
        self.moved_keys_total += plan.moved_keys
        loop.log("ps_resize", frm=self.num, to=plan.new_num_servers,
                 moved_keys=plan.moved_keys, reuse=len(plan.reuse),
                 spawn=len(plan.spawn), retire=len(plan.retire),
                 dur=_r(dur))

        def commit(p=plan):
            self.num = p.new_num_servers
            self.ranges = list(p.new_ranges)
            loop.log("ps_resize_done", num=self.num)

        loop.at(self.busy_until, commit)
        return plan


class SimWorkers:
    """The training-worker population (pushes) + the feedback drain."""

    def __init__(self, total: int, *, push_rate_per_worker: float = 2.0,
                 staleness_k: float = 0.5):
        self.total = int(total)
        self.joined = int(total)
        self.push_rate_per_worker = float(push_rate_per_worker)
        self.staleness_k = float(staleness_k)
        self.pushes_total = 0.0
        self.rejoin_events = 0

    def push_rate(self) -> float:
        return self.joined * self.push_rate_per_worker

    def staleness(self, ps_num: int) -> float:
        # async staleness grows with the worker:server ratio
        # (FASGD, arXiv:1508.05711)
        return self.staleness_k * self.joined / max(1, ps_num)


class SimActuators:
    """The Actuators duck the real daemon applies decisions through."""

    def __init__(self, fleet: "SimFleet", *, standby_engines: int = 4,
                 spinup_s: float = 2.0):
        self.fleet = fleet
        self.standby_engines = int(standby_engines)
        self.spinup_s = float(spinup_s)
        self._engine_seq = 0

    def current(self) -> dict:
        f = self.fleet
        return {"ps": f.ps.num,
                "engine": len(f.router.pool()),
                "worker": f.drain_workers,
                "ps_busy": f.ps.busy(f.loop.now)}

    def apply(self, actuator: str, to_count: int) -> str:
        f = self.fleet
        if actuator == "engine":
            cur = len(f.router.pool())
            if to_count > cur:
                if self.standby_engines <= 0:
                    raise ActuatorError(
                        "standby pool exhausted: no engine to add")
                self.standby_engines -= 1
                f.add_engine(spinup_s=self.spinup_s)
            elif to_count < cur:
                f.retire_engine()
                self.standby_engines += 1
            return f"set engine={to_count}"
        if actuator == "ps":
            f.ps.start_resize(to_count, f.loop)
            return f"set ps={to_count}"
        if actuator == "worker":
            f.drain_workers = int(to_count)
            return f"set worker={to_count}"
        raise ActuatorError(f"unknown actuator {actuator!r}")

    def close(self) -> None:
        pass


#: the default SLO spec fleetsim evaluates (the PR-17 engine, windows
#: shrunk onto the simulated clock): route availability as a
#: shed/requests ratio
def default_slo_spec(*, objective: float = 0.95,
                     window_s: float = 3600.0) -> dict:
    return {
        "slos": [{
            "name": "route-availability",
            "objective": objective,
            "window_s": window_s,
            "sli": {"kind": "ratio",
                    "bad": "increase(route_shed)",
                    "total": "increase(route_requests)"},
        }],
        "burn_windows": [
            {"name": "fast", "short_s": 10.0, "long_s": 20.0, "factor": 2.0},
        ],
    }


@dataclasses.dataclass
class FleetParams:
    """One scenario's fleet shape + traffic (see scenarios.py)."""

    engines: int = 4
    engine_capacity_qps: float = 25.0
    workers: int = 4
    ps: int = 2
    ps_dim: int = 1 << 14
    drain_workers: int = 2
    standby_engines: int = 4
    tick_s: float = 0.5
    control_interval_s: float = 2.0
    base_qps: float = 40.0
    peak_qps: float = 80.0
    period_s: float = 120.0
    duration_s: float = 240.0
    shard_inflow_rate: float = 4.0
    claim_rate_per_worker: float = 2.0
    eject_after: int = 3
    autopilot: bool = True
    slo: bool = True
    slo_objective: float = 0.95
    policy: PolicyConfig | None = None


class SimFleet:
    """The composition root: modeled components + real control plane,
    stepped by the event loop."""

    def __init__(self, loop: EventLoop, params: FleetParams,
                 scenario: str = "fleet"):
        self.loop = loop
        self.p = params
        self.scenario = scenario
        now = loop.now
        self._engine_seq = params.engines
        self.router = SimRouter(
            loop,
            [SimReplica(f"e{i}", params.engine_capacity_qps, now)
             for i in range(params.engines)],
            eject_after=params.eject_after)
        self.ps = SimPS(params.ps_dim, params.ps)
        self.workers = SimWorkers(params.workers)
        self.drain_workers = int(params.drain_workers)
        self.shard_lag = 2.0
        self.offered_scale = 1.0
        #: scenario hooks (t -> rate); None = the built-in defaults
        self.shard_inflow = None
        self.claim_capacity = None
        # rank-second accounting (the rank_seconds property)
        self.rank_seconds = 0.0
        self.peak_ranks = 0
        # real observability plane on the virtual clock
        self.tsdb = FleetTSDB()
        self.registry = MetricsRegistry()
        self.slo_engine = SLOEngine(load_slo_spec(default_slo_spec(
            objective=params.slo_objective))) if params.slo else None
        self.slo_alerts: list[dict] = []
        self.slo_summaries: list[dict] = []
        self.latest_doc: dict = {"updated": 0.0, "ranks": []}
        self.history: list[dict] = []
        self.daemon: AutopilotDaemon | None = None
        self.decisions: list = []
        #: zero-arg callables run_scenario invokes after the run
        #: (tempdir removal for the real spool/joiner composition)
        self.cleanups: list = []
        if params.autopilot:
            self.daemon = AutopilotDaemon(
                PolicyEngine(params.policy or PolicyConfig()),
                SimActuators(self, standby_engines=params.standby_engines),
                fetch=lambda: self.latest_doc,
                alert_poll=self._firing_alert_names,
                clock=lambda: loop.now)

    # -- engine membership (actuator seam) ---------------------------------
    def add_engine(self, *, spinup_s: float = 2.0) -> SimReplica:
        rep = SimReplica(f"e{self._engine_seq}",
                         self.p.engine_capacity_qps, self.loop.now)
        self._engine_seq += 1
        rep.in_service = False
        self.router.replicas.append(rep)

        def up(r=rep):
            r.in_service = True
            self.loop.log("engine_up", replica=r.name)

        self.loop.after(spinup_s, up)
        return rep

    def retire_engine(self) -> None:
        pool = self.router.pool()
        if len(pool) <= 1:
            return
        rep = pool[-1]
        rep.retired = True
        self.loop.log("engine_retired", replica=rep.name)

    # -- faults (the chaos alphabet's delay/reset analogues) ---------------
    def degrade_all(self, until: float) -> None:
        for rep in self.router.pool():
            rep.fail_until = max(rep.fail_until, until)
        self.loop.log("fault", fault="brownout", until=_r(until))

    # -- observability -----------------------------------------------------
    def _firing_alert_names(self) -> list[str]:
        return [f"{a['name']}{{slo={a['labels'].get('slo', '?')}}}"
                for a in self.slo_alerts if a.get("firing")]

    def fleet_doc(self) -> dict:
        now = self.loop.now
        pool = self.router.pool()
        up = self.router.in_rotation()
        ranks = [{
            "role": "router", "rank": 0, "state": "up",
            "route_requests": _r(self.router.requests_total),
            "route_shed": _r(self.router.shed_total),
            "route_errors": _r(self.router.errors_total),
            "route_p99_ms": _r(self.router.p99_ms),
            "replicas_up": len(up),
        }, {
            "role": "trainer", "rank": 0, "state": "up",
            "pushes": _r(self.workers.pushes_total),
            "staleness_pushes_p99": _r(self.workers.staleness(self.ps.num)),
            "workers_joined": self.workers.joined,
        }, {
            "role": "joiner", "rank": 0, "state": "up",
            "shard_lag": _r(self.shard_lag),
        }]
        ranks += [{"role": "engine", "rank": i,
                   "state": "up" if (r.healthy and r.in_service) else "down",
                   "requests": r.requests, "errors": r.errors}
                  for i, r in enumerate(self.router.replicas)
                  if not r.retired]
        return {
            "updated": _r(now),
            "virtual": True,
            "run_dir": f"fleetsim:{self.scenario}",
            "totals": {"ranks": len(ranks),
                       "up": 3 + sum(1 for r in pool if r.healthy),
                       "samples_per_s": _r(self.offered(now))},
            "alerts": [a for a in self.slo_alerts if a.get("firing")],
            "slo": self.slo_summaries,
            "ranks": ranks,
        }

    def offered(self, t: float) -> float:
        return self.offered_scale * qps_at(
            t, self.p.base_qps, self.p.peak_qps, self.p.period_s)

    # -- the two periodic drivers ------------------------------------------
    def traffic_tick(self) -> None:
        now, dt = self.loop.now, self.p.tick_s
        self.router.tick(dt, self.offered(now))
        self.router.probe_tick()
        self.workers.pushes_total += self.workers.push_rate() * dt
        inflow = (self.shard_inflow(now) if self.shard_inflow
                  else self.p.shard_inflow_rate)
        claim = (self.claim_capacity(now) if self.claim_capacity
                 else self.drain_workers * self.p.claim_rate_per_worker)
        self.shard_lag = max(0.0, self.shard_lag + (inflow - claim) * dt)
        ranks = len(self.router.pool()) + self.ps.num + self.drain_workers
        self.rank_seconds += ranks * dt
        self.peak_ranks = max(self.peak_ranks, ranks)

    def control_tick(self) -> None:
        now = self.loop.now
        doc = self.fleet_doc()
        self.latest_doc = doc
        self.history.append(doc)
        self.tsdb.ingest(doc)
        if self.slo_engine is not None:
            alerts: list[dict] = []
            self.slo_summaries = self.slo_engine.evaluate(
                self.tsdb, self.registry, now, alerts)
            fired_before = {a["labels"].get("window")
                            for a in self.slo_alerts if a.get("firing")}
            self.slo_alerts = alerts
            for a in alerts:
                w = a["labels"].get("window")
                if a.get("firing") and w not in fired_before:
                    self.loop.log("slo_burn_firing", window=str(w))
        if self.daemon is not None:
            d = self.daemon.tick_once()
            self.decisions.append(d)
            if d.rule not in ("steady",):
                self.loop.log("autopilot", rule=d.rule,
                              action=d.action.to_doc() if d.action else None,
                              outcome=d.outcome)

    def schedule(self) -> None:
        """Install the periodic drivers through ``duration_s``."""
        self.loop.every(self.p.tick_s, self.traffic_tick,
                        until=self.p.duration_s)
        self.loop.every(self.p.control_interval_s, self.control_tick,
                        until=self.p.duration_s)

    # -- summary -----------------------------------------------------------
    def actions(self) -> list[dict]:
        return [json.loads(d.to_json())
                for d in self.decisions if d.action is not None]

    def summary(self) -> dict:
        return {
            "requests": _r(self.router.requests_total),
            "shed": _r(self.router.shed_total),
            "errors": _r(self.router.errors_total),
            "retries": _r(self.router.retries_total),
            "eject_suppressed": self.router.suppressed_total,
            "engines": len(self.router.pool()),
            "ps": self.ps.num,
            "ps_resizes": self.ps.resizes,
            "workers_joined": self.workers.joined,
            "rejoin_events": self.workers.rejoin_events,
            "shard_lag": _r(self.shard_lag),
            "rank_seconds": _r(self.rank_seconds),
            "peak_ranks": self.peak_ranks,
            "actions": len(self.actions()),
            "budget_remaining": (self.slo_summaries[0]["budget_remaining"]
                                 if self.slo_summaries else None),
        }
