"""Fleet-scale property checks — what a scenario must not violate.

Each check takes the finished :class:`~distlr_tpu.analysis.fleetsim.
models.SimFleet` (plus per-check bounds baked in by the scenario) and
returns a list of violation strings — empty means the property held.
Violations are counterexamples: the CLI prints the replay id, the
mutant suite pins the pre-fix behavior, and tier-1 asserts the fixed
policies keep every scenario clean.
"""

from __future__ import annotations

__all__ = [
    "all_rejoined",
    "no_flapping",
    "rank_seconds_bounded",
    "reshard_converged",
    "rpo_bounded",
    "rto_bounded",
    "slo_budget_held",
    "zero_failed_accepted",
]


def no_flapping(fleet, *, actuator: str, max_reversals: int) -> list[str]:
    """Bounded direction reversals per run: each reversal is a replica
    churn / a reshard, so a controller that oscillates at the cooldown
    cadence is broken even when every individual action is 'correct'
    (the autopilot_resonance counterexample)."""
    dirs = [a["action"]["direction"] for a in fleet.actions()
            if a["action"]["actuator"] == actuator]
    reversals = sum(1 for prev, cur in zip(dirs, dirs[1:]) if prev != cur)
    if reversals > max_reversals:
        return [f"no_flapping: {actuator} reversed direction {reversals}x "
                f"(bound {max_reversals}) — controller resonance"]
    return []


def zero_failed_accepted(fleet, *, allowed_until: float) -> list[str]:
    """No ACCEPTED request may fail outside a scripted fault window
    (+grace).  Sheds are explicit admission control and never count;
    errors after the fault cleared mean the routing tier turned a
    transient into an outage (the cascade_eject counterexample)."""
    late = [(t, n) for t, n in fleet.router.error_ticks
            if t > allowed_until]
    if late:
        total = sum(n for _t, n in late)
        return [f"zero_failed_accepted: {total:.1f} requests failed "
                f"after t={allowed_until:.1f}s (first at "
                f"t={late[0][0]:.1f}s) — outage outlived the fault"]
    return []


def slo_budget_held(fleet, *, min_budget: float = 0.0) -> list[str]:
    """The error budget survives the run (the slow_burn_slo
    counterexample: a frozen controller burns it to exhaustion)."""
    if not fleet.slo_summaries:
        return ["slo_budget_held: no SLO summaries were evaluated"]
    out = []
    for s in fleet.slo_summaries:
        budget = s.get("budget_remaining")
        if budget is None:
            out.append(f"slo_budget_held: {s['name']}: no budget computed")
        elif budget <= min_budget:
            out.append(f"slo_budget_held: {s['name']}: budget "
                       f"{budget:.3f} <= {min_budget} — exhausted")
    return out


def rank_seconds_bounded(fleet, *, slack: float = 1.0) -> list[str]:
    """Autopilot-scaled rank-seconds must not exceed static peak
    provisioning (times ``slack``) — an autoscaler that costs more
    than not having one is a bug, not a tuning problem."""
    static = fleet.peak_ranks * fleet.p.duration_s
    if fleet.rank_seconds > static * slack:
        return [f"rank_seconds: {fleet.rank_seconds:.0f} > "
                f"{slack:.2f} * static-peak {static:.0f}"]
    return []


def all_rejoined(fleet, *, deadline_s: float) -> list[str]:
    """Every partitioned worker is back and the feedback backlog has
    drained by the deadline (the 1000-worker heal scenario)."""
    out = []
    if fleet.workers.joined < fleet.workers.total:
        out.append(f"all_rejoined: {fleet.workers.joined}/"
                   f"{fleet.workers.total} workers joined by "
                   f"t={deadline_s:.0f}s")
    if fleet.shard_lag > fleet.p.shard_inflow_rate:
        out.append(f"all_rejoined: shard backlog {fleet.shard_lag:.1f} "
                   f"not drained by t={deadline_s:.0f}s")
    return out


def rto_bounded(fleet, *, max_rto_s: float) -> list[str]:
    """Recovery-Time Objective: the span from a whole-fleet power loss
    to the LAST rank back in service stays under the bound (the
    power_loss_durable scenario writes ``fleet.dr``).  A fleet that
    never fully recovers is the worst violation, not a vacuous pass."""
    dr = getattr(fleet, "dr", None)
    if not dr:
        return ["rto_bounded: the fleet has no DR record — the power "
                "loss never ran"]
    if dr["rto_s"] is None:
        down = [i for i, r in enumerate(dr["ranks"]) if not r["up"]]
        return [f"rto_bounded: the fleet never fully recovered "
                f"(ranks still down: {down})"]
    if dr["rto_s"] > max_rto_s:
        return [f"rto_bounded: RTO {dr['rto_s']:.1f}s > bound "
                f"{max_rto_s:.1f}s"]
    return []


def rpo_bounded(fleet) -> list[str]:
    """Recovery-Point Objective, per rank against its durability mode
    (the ISSUE-20 contract): a WAL rank loses ZERO applied pushes; a
    snapshot-only rank loses at most one snapshot interval's worth; a
    rank whose newest generation was torn by the cut falls back ONE
    generation — at most two intervals lost, never a refusal to start
    and never a silent restore of the corrupt file.  The scenario bakes
    each rank's bound (``rpo_bound``) from its mode and the live push
    rate at the moment of the cut."""
    dr = getattr(fleet, "dr", None)
    if not dr:
        return ["rpo_bounded: the fleet has no DR record — the power "
                "loss never ran"]
    out = []
    for i, r in enumerate(dr["ranks"]):
        if r["lost"] is None:
            out.append(f"rpo_bounded: rank {i} has no loss record — it "
                       "never lost power")
            continue
        if r["lost"] > r["rpo_bound"] + 1e-9:
            out.append(
                f"rpo_bounded: rank {i} ({r['mode']}) lost "
                f"{r['lost']:.1f} pushes > bound {r['rpo_bound']:.1f}")
    return out


def reshard_converged(plan, dim: int, old_ranges, *, sampler=None,
                      max_hot_share: float | None = None) -> list[str]:
    """The resize plan exactly tiles the new layout: every key of every
    new range is either resident (the reused prefix) or covered by
    exactly one move; nothing moves into a reused resident prefix; and
    under a Zipf-hot key distribution the hottest new rank's load share
    (closed-form, :meth:`~distlr_tpu.traffic.ZipfSampler.mass`) stays
    under ``max_hot_share``."""
    out = []
    by_target: dict[int, list[tuple[int, int]]] = {}
    for _o, lo, hi, nr in plan.moves:
        by_target.setdefault(nr, []).append((lo, hi))
    for nr, (lo, hi) in enumerate(plan.new_ranges):
        res_hi = lo
        if nr in plan.reuse:
            res_hi = min(old_ranges[plan.reuse[nr]][1], hi)
        spans = sorted(by_target.get(nr, []))
        cursor = res_hi
        for mlo, mhi in spans:
            if mlo < res_hi:
                out.append(f"reshard_converged: move [{mlo},{mhi}) into "
                           f"rank {nr} overlaps resident prefix "
                           f"[{lo},{res_hi})")
            if mlo != cursor:
                out.append(f"reshard_converged: rank {nr} gap/overlap at "
                           f"{cursor} (next move starts {mlo})")
            cursor = max(cursor, mhi)
        if cursor != hi:
            out.append(f"reshard_converged: rank {nr} covered to {cursor}, "
                       f"range ends {hi}")
    covered = sum(hi - lo for lo, hi in plan.new_ranges)
    if covered != dim:
        out.append(f"reshard_converged: new ranges cover {covered} keys "
                   f"of dim {dim}")
    if sampler is not None and max_hot_share is not None:
        hottest = max(sampler.mass(lo, hi) for lo, hi in plan.new_ranges)
        if hottest > max_hot_share:
            out.append(f"reshard_converged: hottest new rank carries "
                       f"{hottest:.3f} of the load "
                       f"(bound {max_hot_share})")
    return out
