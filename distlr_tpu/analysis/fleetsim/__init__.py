"""Fleetsim: a deterministic discrete-event fleet simulator (ISSUE 19).

Every control-plane policy this repo ships — the autopilot band
controller (PR 16), the membership resize planner (PR 12), the
router's eject/reinstate/least-in-flight logic (PR 4), reloader
polling, the joiner/spool window machinery, the SLO engine's burn-rate
math (PR 17) — has only ever been exercised at the ≤4-process shapes
tier-1 can spawn.  The dynamics that actually break such policies
(staleness growth with worker count, cascading ejections, controller
resonance with the diurnal curve) appear two orders of magnitude
beyond that.  Fleetsim points schedcheck's determinism discipline
outward: a seeded heap-based event loop drives thousand-rank fleet
scenarios in simulated time, composing the REAL policy classes against
MODELED processes.

What is REAL (imported, not reimplemented):

* :class:`~distlr_tpu.autopilot.daemon.AutopilotDaemon` +
  :class:`~distlr_tpu.autopilot.policy.PolicyEngine` — the daemon's
  own sensor reduction, rate windows, journal, and band arithmetic,
  fed a simulated ``fleet.json`` and a virtual clock;
* :mod:`distlr_tpu.serve.balance` — the router's selection/ejection/
  probe policy, applied to simulated replicas;
* :func:`distlr_tpu.ps.server.plan_reshard` — the membership
  planner's arithmetic, applied to thousand-rank layouts;
* :class:`~distlr_tpu.obs.tsdb.FleetTSDB` +
  :class:`~distlr_tpu.obs.slo.SLOEngine` — ingestion, rate/increase
  queries, and multi-window burn-rate alerting on the virtual clock;
* :class:`~distlr_tpu.feedback.spool.FeedbackSpool` +
  :class:`~distlr_tpu.feedback.join.LabelJoiner` — the delayed-label
  window machinery, driven with virtual timestamps;
* :mod:`distlr_tpu.traffic` — the same diurnal/Zipf/label-delay
  arithmetic ``benchmarks/loadgen.py`` drives real sockets with.

What is MODELED: engines (capacity/latency as fluid queues), workers
(join/leave/push rates), PS migration time, the standby pool.  Models
emit the same ``fleet.json`` field names obs-agg federates, so the
policy code cannot tell it is simulated.

Determinism contract: identical seed + scenario ⇒ byte-identical
event log (and therefore digest and property verdicts).  Replay ids
are ``fleetsim:<scenario>:<seed>``; counterexamples are pinned in
:mod:`~distlr_tpu.analysis.fleetsim.mutants` exactly like the
schedcheck/protocol mutant suites.

Run ``python -m distlr_tpu.analysis.fleetsim --list`` (or
``launch fleetsim``) to see scenarios; docs/ANALYSIS.md has the
chapter.
"""

from distlr_tpu.analysis.fleetsim.events import EventLoop
from distlr_tpu.analysis.fleetsim.scenarios import (
    SCENARIOS,
    run_scenario,
)

__all__ = ["EventLoop", "SCENARIOS", "run_scenario"]
