"""Mutant-verified policy-bug rediscovery (the schedcheck tradition,
pointed at the CONTROL PLANE).

Fleetsim found three real policy bugs in this repo's shipping code;
each got a fix, a unit-test row, and an entry here.  A mutant swaps
ONE fixed policy seam back to its verbatim pre-fix body (kept below as
the historical record), re-runs the scenario that found the bug, and
the property that motivated the fix must fail again — with the exact
violation class, at the pinned replay id, byte-identically on every
run.  With the fix in place the same scenario must stay clean AND
reproduce its pinned digest.  Failing either direction means
"fleetsim stopped encoding the fix" and fails the analysis pass.

The three pinned counterexamples:

* ``router_eject_unbounded`` — ``fleetsim:cascade_eject_canary:0``.
  Pre-fix the router had NO ejection floor: a fleet-wide brownout
  failed every replica's health streak, the eject path removed all of
  them, and after the fault cleared the empty rotation kept erroring
  until probe backoff (doubling toward 30s) let somebody back in —
  the outage outlived the fault.  Fix: :func:`distlr_tpu.serve.
  balance.may_eject` refuses to eject the last healthy member of any
  multi-replica pool (singleton pools stay ejectable — fast
  "no healthy replica" admission errors beat dial timeouts), counted
  by ``distlr_route_eject_suppressed_total``.
* ``autopilot_alert_freeze`` — ``fleetsim:slow_burn_slo:0``.
  Pre-fix rule 2 froze EVERY actuator whenever any bound alert fired,
  blamable or not.  A slow capacity loss fires the SLO burn alert
  forever, the frozen controller can never add the engine that would
  clear it, and the error budget drains to zero.  Fix:
  :meth:`~distlr_tpu.autopilot.policy.PolicyEngine._on_alert` only
  freezes when the youngest action is young enough to blame;
  otherwise the tick runs capacity-only (adds allowed, removals
  suppressed).
* ``autopilot_no_flap_damping`` — ``fleetsim:autopilot_resonance:0``.
  Pre-fix ``_act`` charged a constant cooldown, so an offered load
  sitting between the scale-down and scale-up thresholds of adjacent
  engine counts drove up/down/up/down at exactly the cooldown cadence
  — each cycle a replica churn.  Fix: direction reversals inside
  ``FLAP_WINDOW_COOLDOWNS`` escalate the cooldown ``2**streak`` up to
  ``2**FLAP_STREAK_MAX``, stretching the oscillation period until the
  diurnal curve moves off the resonant point.
"""

from __future__ import annotations

import contextlib
import dataclasses

from distlr_tpu.autopilot.policy import ACTUATORS, Action, PolicyEngine
from distlr_tpu.serve import balance
from distlr_tpu.analysis.fleetsim.scenarios import Result, run_scenario

#: scenario -> seed-0 clean-run digest; byte-identity is asserted by
#: the lint pass and tier-1 (``tests/test_fleetsim.py``), so a change
#: to any modeled or real policy path shows up as a reviewable diff
#: of this table, never as silent drift
EXPECTED_DIGESTS: dict[str, str] = {
    "partition_heal_1000": "92c4ae086027f82b",
    "reshard_64_to_96_zipf": "1d3a5ab457abe029",
    "cascade_eject_canary": "3d3b548dfe07ddaf",
    "autopilot_resonance": "8a27b240d189726b",
    "slow_burn_slo": "f433f00e7d368a8b",
    "standby_exhaustion": "27fa5c1582a81512",
    "power_loss_durable": "69dcd9fcc6a72fc1",
}


# ---------------------------------------------------------------------------
# the verbatim pre-fix bodies
# ---------------------------------------------------------------------------


def _prefix_may_eject(rep, pools) -> bool:
    """``balance.may_eject`` BEFORE the floor: the eject path asked no
    questions — any replica whose failure streak crossed the threshold
    left the rotation, including the last healthy member of a pool."""
    return True


def _prefix_on_alert(self, current, now):
    """Rule 2 BEFORE the capacity-only fix (verbatim from the PR-16
    ``PolicyEngine.tick`` body, reshaped to the ``_on_alert`` seam):
    every firing alert froze every actuator for a cooldown, whether or
    not any action could be blamed — the slow-burn deadlock."""
    c = self.cfg
    for a in ACTUATORS:
        self._cooldown_until[a] = now + c.cooldown_s
    self._breach.clear()
    last = self._last_action
    if (last is not None and not self._rolled_back
            and now - self._last_action_t <= c.rollback_window_s
            and current.get(last.actuator) is not None):
        lo, hi = c.bounds(last.actuator)
        target = max(lo, min(hi, last.from_count))
        cur = int(current[last.actuator])
        self._rolled_back = True
        if target != cur:
            return ("rollback_on_alert",
                    Action(last.actuator, "down" if target < cur else "up",
                           cur, target))
    return ("hold_on_alert", None)


def _prefix_act(self, actuator, direction, current, now):
    """``PolicyEngine._act`` BEFORE flap damping: constant cooldown,
    no reversal streak — the resonance oscillator."""
    lo, hi = self.cfg.bounds(actuator)
    target = max(lo, min(hi, current + (1 if direction == "up" else -1)))
    act = Action(actuator, direction, current, target)
    self._cooldown_until[actuator] = now + self.cfg.cooldown_s
    # the action changes the very state both counters measured
    self._breach[(actuator, "up")] = 0
    self._breach[(actuator, "down")] = 0
    self._last_action, self._last_action_t = act, now
    self._rolled_back = False
    return act


# ---------------------------------------------------------------------------
# registry + driver
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Mutant:
    name: str
    historical: str                 # which shipped fix this reverts
    #: (module-or-class object, attribute) the buggy body replaces
    target: tuple[object, str]
    buggy_fn: object
    scenario: str
    seed: int
    #: substring every-run violations must carry under the mutation —
    #: rediscovering a DIFFERENT bug is a failure too ("wrong bug")
    expect_in_violation: str

    @property
    def replay_id(self) -> str:
        return f"fleetsim:{self.scenario}:{self.seed}"

    @contextlib.contextmanager
    def applied(self):
        """Swap the fixed seam for the historical pre-fix body."""
        obj, attr = self.target
        orig = getattr(obj, attr)
        setattr(obj, attr, self.buggy_fn)
        try:
            yield
        finally:
            setattr(obj, attr, orig)

    def clean_run(self) -> Result:
        return run_scenario(self.scenario, self.seed)

    def rediscover(self) -> Result:
        """Re-run the pinned scenario with the fix REVERTED."""
        with self.applied():
            return run_scenario(self.scenario, self.seed)


MUTANTS: dict[str, Mutant] = {
    m.name: m for m in (
        Mutant(
            name="router_eject_unbounded",
            historical="serve.balance ejection floor",
            target=(balance, "may_eject"),
            buggy_fn=_prefix_may_eject,
            scenario="cascade_eject_canary",
            seed=0,
            expect_in_violation="zero_failed_accepted",
        ),
        Mutant(
            name="autopilot_alert_freeze",
            historical="autopilot capacity-only alert mode",
            target=(PolicyEngine, "_on_alert"),
            buggy_fn=_prefix_on_alert,
            scenario="slow_burn_slo",
            seed=0,
            expect_in_violation="slo_budget_held",
        ),
        Mutant(
            name="autopilot_no_flap_damping",
            historical="autopilot flap-reversal cooldown escalation",
            target=(PolicyEngine, "_act"),
            buggy_fn=_prefix_act,
            scenario="autopilot_resonance",
            seed=0,
            expect_in_violation="no_flapping",
        ),
    )
}


def verify_mutant(name: str) -> list[str]:
    """Full acceptance for one mutant; returns problem strings (empty
    = fixed code clean at the pinned digest, reverted code violates
    the expected property, and the counterexample replays
    byte-identically)."""
    m = MUTANTS[name]
    problems: list[str] = []
    clean = m.clean_run()
    if clean.violations:
        problems.append(
            f"{name}: {m.replay_id} violates WITH the fix in place: "
            f"{clean.violations[0]}")
        return problems
    want = EXPECTED_DIGESTS.get(m.scenario)
    if want is not None and clean.digest != want:
        problems.append(
            f"{name}: clean digest {clean.digest} != pinned {want} "
            f"({m.replay_id}) — the simulated fleet drifted; re-pin "
            "EXPECTED_DIGESTS deliberately if the change is intended")
    cex = m.rediscover()
    if not cex.violations:
        problems.append(
            f"{name}: reverting the {m.historical} was NOT rediscovered "
            f"at {m.replay_id} — fleetsim stopped encoding the fix")
        return problems
    if not any(m.expect_in_violation in v for v in cex.violations):
        problems.append(
            f"{name}: rediscovered a DIFFERENT failure "
            f"({cex.violations[0]!r}) — wrong bug")
    again = m.rediscover()
    if again.digest != cex.digest or again.violations != cex.violations:
        problems.append(
            f"{name}: counterexample at {m.replay_id} did not replay "
            "byte-identically")
    if cex.digest == clean.digest:
        problems.append(
            f"{name}: mutant digest equals clean digest — the mutation "
            "never executed")
    return problems
