"""The seeded discrete-event loop fleetsim runs on.

Schedcheck's virtual-clock discipline, pointed at fleet scale: a heap
of ``(time, seq, fn, args)`` entries, one ``random.Random(seed)`` for
every stochastic choice, and an append-only structured event log whose
SHA-256 digest IS the determinism contract — identical seed + scenario
⇒ byte-identical log, twice in a row, asserted in tier-1.

Rules that keep the digest honest (mirrors ``schedcheck.engine``):

* ties break on insertion order (``seq``), never on object identity;
* every logged float is formatted through :func:`EventLoop.log`'s
  ``json.dumps(..., sort_keys=True)`` — no ``repr`` of dicts or sets;
* nothing reads the wall clock, the pid, or a filesystem path into a
  logged line.  Wall-clock measurements (events/s for the bench row)
  happen OUTSIDE the loop, around :meth:`EventLoop.run`.
"""

from __future__ import annotations

import hashlib
import heapq
import json
import random

__all__ = ["EventLoop"]


class EventLoop:
    """One simulation: virtual clock, seeded RNG, event heap, log."""

    def __init__(self, seed: int = 0):
        self.seed = int(seed)
        self.rng = random.Random(self.seed)
        self.now = 0.0
        self._heap: list[tuple[float, int, object, tuple]] = []
        self._seq = 0
        self.events = 0
        self.lines: list[str] = []

    # -- scheduling --------------------------------------------------------
    def at(self, t: float, fn, *args) -> None:
        """Schedule ``fn(*args)`` at virtual time ``t`` (clamped to
        now — the past is immutable)."""
        heapq.heappush(self._heap,
                       (max(float(t), self.now), self._seq, fn, args))
        self._seq += 1

    def after(self, dt: float, fn, *args) -> None:
        self.at(self.now + dt, fn, *args)

    def every(self, interval: float, fn, *, until: float) -> None:
        """Schedule ``fn()`` at ``interval`` cadence through ``until``
        (fixed grid from now — a drifting cadence would make the log
        depend on handler durations, which do not exist here)."""
        t = self.now + interval
        while t <= until:
            self.at(t, fn)
            t += interval

    # -- the log -----------------------------------------------------------
    def log(self, kind: str, **fields) -> None:
        """Append one canonical event line:
        ``<t> <kind> {sorted-json-fields}``."""
        self.lines.append(f"{self.now:.6f} {kind} "
                          + json.dumps(fields, sort_keys=True))

    def digest(self) -> str:
        """SHA-256 over the full log — the byte-identity pin replay
        ids and the mutant suite assert against."""
        return hashlib.sha256(
            "\n".join(self.lines).encode("utf-8")).hexdigest()[:16]

    # -- execution ---------------------------------------------------------
    def run(self, until: float) -> None:
        """Drain the heap through virtual time ``until``."""
        until = float(until)
        while self._heap and self._heap[0][0] <= until:
            t, _seq, fn, args = heapq.heappop(self._heap)
            self.now = t
            self.events += 1
            fn(*args)
        self.now = until
