"""The scripted fleet-scale scenarios and the replay entry point.

Each scenario wires a :class:`~distlr_tpu.analysis.fleetsim.models.
SimFleet` (modeled processes, REAL policies), schedules its fault /
traffic script on the event loop, and names the properties that must
hold.  ``run_scenario(name, seed)`` executes one and returns a
:class:`Result` whose ``digest`` is byte-stable for a given
``(scenario, seed)`` — the replay id ``fleetsim:<scenario>:<seed>``
reproduces it exactly (``--replay``), which is how counterexamples
stay pinned after their policy fix lands (see ``mutants.py``).
"""

from __future__ import annotations

import dataclasses
import shutil
import tempfile

from distlr_tpu.analysis.fleetsim.events import EventLoop
from distlr_tpu.analysis.fleetsim.models import (
    FleetParams,
    SimFleet,
    _r,
)
from distlr_tpu.analysis.fleetsim import props
from distlr_tpu.autopilot.policy import PolicyConfig
from distlr_tpu.feedback.join import LabelJoiner
from distlr_tpu.feedback.spool import FeedbackSpool, SpoolRecord
from distlr_tpu.ps.server import plan_reshard
from distlr_tpu.traffic import LabelDelay, ZipfSampler

__all__ = ["SCENARIOS", "Result", "Scenario", "parse_replay_id",
           "run_scenario"]


# ---------------------------------------------------------------------------
# scenario builders — each returns (fleet, [prop thunks])
# ---------------------------------------------------------------------------

def _partition_heal_1000(loop: EventLoop):
    """1000 workers drop on a partition and rejoin with jittered
    backoff when it heals; the REAL spool/joiner machinery runs the
    label window underneath, and the autopilot rides the push-rate and
    shard-lag bands down and back up (rank-seconds must beat static
    peak provisioning)."""
    p = FleetParams(
        engines=4, workers=1000, ps=16, ps_dim=1 << 14,
        duration_s=240.0, base_qps=40.0, peak_qps=60.0,
        shard_inflow_rate=5.0,
        policy=PolicyConfig(ps_max=32, worker_max=8),
    )
    fleet = SimFleet(loop, p, "partition_heal_1000")
    fleet.workers.push_rate_per_worker = 2.5
    fleet.workers.staleness_k = 0.25
    # feedback drain capacity follows the joined training workers
    # (online trainers claim shards) plus the autopilot's drain pool
    fleet.claim_capacity = lambda t: (
        fleet.workers.joined * 0.05
        + fleet.drain_workers * p.claim_rate_per_worker)

    def partition():
        fleet.workers.joined = 0
        loop.log("partition", workers=fleet.workers.total)

    def heal():
        loop.log("heal", rejoining=fleet.workers.total)
        for _ in range(fleet.workers.total):
            loop.after(loop.rng.uniform(0.0, 20.0), rejoin)

    def rejoin():
        fleet.workers.joined += 1
        fleet.workers.rejoin_events += 1
        if fleet.workers.joined % 100 == 0:
            loop.log("rejoined", joined=fleet.workers.joined)

    loop.at(30.0, partition)
    loop.at(90.0, heal)

    # -- the REAL label window machinery, on virtual timestamps --
    spool_dir = tempfile.mkdtemp(prefix="fleetsim-spool-")
    out_dir = tempfile.mkdtemp(prefix="fleetsim-shards-")
    spool = FeedbackSpool(spool_dir, capacity=4096)
    joiner = LabelJoiner(spool, out_dir, window_s=60.0,
                         negative_rate=0.25, shard_records=64, seed=7)
    fleet.cleanups += [lambda: shutil.rmtree(spool_dir, ignore_errors=True),
                       lambda: shutil.rmtree(out_dir, ignore_errors=True)]
    delay = LabelDelay(2.0, 30.0)
    outcomes = {"joined": 0, "pending": 0, "duplicate": 0}

    def label(i: int):
        outcomes[joiner.label(f"r{i}", 1, ts=loop.now)] += 1

    def score(i: int):
        joiner.scored(SpoolRecord(rid=f"r{i}", ts=loop.now, line="1:1",
                                  score=0.5, version=1))
        loop.after(delay.sample(loop.rng), label, i)

    for i in range(480):
        loop.at(i * 0.5, score, i)
    loop.every(5.0, lambda: joiner.tick(now=loop.now), until=p.duration_s)
    loop.every(30.0, lambda: loop.log(
        "joiner", joined=joiner.joined, negatives=joiner.negatives,
        shards=joiner.shards_written, spooled=len(spool)),
        until=p.duration_s)
    fleet.cleanups.append(lambda: loop.log(
        "joiner_final", joined=joiner.joined, negatives=joiner.negatives,
        shards=joiner.shards_written, outcomes=outcomes))

    return fleet, [
        lambda f: props.all_rejoined(f, deadline_s=p.duration_s),
        lambda f: props.no_flapping(f, actuator="worker", max_reversals=4),
        lambda f: props.no_flapping(f, actuator="ps", max_reversals=4),
        lambda f: props.zero_failed_accepted(f, allowed_until=0.0),
        lambda f: props.rank_seconds_bounded(f, slack=0.9),
    ]


def _reshard_64_to_96_zipf(loop: EventLoop):
    """A 64 -> 96 membership resize planned by the REAL planner under
    Zipf-hot traffic: the plan must exactly tile the new layout and the
    hottest new rank must carry no more load than the hottest old one
    (the head of the Zipf curve splits, never concentrates)."""
    p = FleetParams(engines=4, workers=128, ps=64, ps_dim=1 << 16,
                    duration_s=60.0, autopilot=False, slo=False)
    fleet = SimFleet(loop, p, "reshard_64_to_96_zipf")
    fleet.ps.migrate_keys_per_s = 20_000.0
    sampler = ZipfSampler(p.ps_dim, alpha=1.05)
    old_ranges = list(fleet.ps.ranges)
    hot_before = max(sampler.mass(lo, hi) for lo, hi in old_ranges)
    state: dict = {}

    def resize():
        loop.log("zipf_hot", hottest_old=_r(hot_before))
        state["plan"] = fleet.ps.start_resize(96, loop)

    loop.at(20.0, resize)

    def check(_f):
        if "plan" not in state:
            return ["reshard: the resize never ran"]
        return props.reshard_converged(
            state["plan"], p.ps_dim, old_ranges,
            sampler=sampler, max_hot_share=hot_before)

    def committed(f):
        if f.ps.num != 96:
            return [f"reshard: expected 96 ranks at end, got {f.ps.num}"]
        return []

    return fleet, [check, committed]


def _cascade_eject_canary(loop: EventLoop):
    """A transient brownout degrades every engine mid-canary-ramp with
    the standby pool empty.  The pre-fix router ejected ALL of them and
    kept serving nothing for a full probe backoff after the fault
    cleared; the ejection floor (serve.balance.may_eject) must keep the
    last replica in rotation so recovery is immediate."""
    p = FleetParams(engines=4, workers=8, ps=2, duration_s=90.0,
                    base_qps=60.0, peak_qps=60.0, standby_engines=0,
                    slo=False)
    fleet = SimFleet(loop, p, "cascade_eject_canary")
    loop.at(20.0, lambda: fleet.add_engine())        # the canary ramp
    loop.at(25.0, lambda: fleet.add_engine())
    fault_end = 52.0
    loop.at(40.0, lambda: fleet.degrade_all(fault_end))
    return fleet, [
        # one tick of grace past the fault for in-flight accounting
        lambda f: props.zero_failed_accepted(
            f, allowed_until=fault_end + 2 * p.tick_s),
    ]


def _autopilot_resonance(loop: EventLoop):
    """Offered load parked between the scale-down and scale-up
    thresholds of adjacent engine counts, at a diurnal period resonant
    with the cooldown: the pre-fix controller flips up/down/up at the
    cooldown cadence forever; flap damping must stretch the oscillation
    instead."""
    p = FleetParams(
        engines=2, workers=8, ps=2, duration_s=200.0,
        base_qps=26.0, peak_qps=30.0, period_s=80.0, slo=False,
        policy=PolicyConfig(hysteresis_ticks=2, cooldown_s=6.0,
                            req_rate_low=15.0, engine_max=4),
    )
    fleet = SimFleet(loop, p, "autopilot_resonance")
    return fleet, [
        lambda f: props.no_flapping(f, actuator="engine", max_reversals=10),
        lambda f: props.zero_failed_accepted(f, allowed_until=0.0),
    ]


def _slow_burn_slo(loop: EventLoop):
    """A sudden deep capacity loss (factor 0.1) starts an SLO burn.
    The controller's adds land, then the long-window burn alert fires
    mid-recovery and blames the youngest one — the rollback makes
    things WORSE, and the pre-fix policy then froze every actuator
    while the alert kept firing, burning the error budget to
    exhaustion.  The capacity-only alert mode must re-add engines
    until the burn clears."""
    p = FleetParams(
        engines=3, workers=8, ps=2, duration_s=200.0,
        base_qps=55.0, peak_qps=55.0, standby_engines=5,
        slo_objective=0.9,
        policy=PolicyConfig(hysteresis_ticks=2, cooldown_s=6.0),
    )
    fleet = SimFleet(loop, p, "slow_burn_slo")

    def degrade():
        for rep in fleet.router.pool():
            rep.capacity_factor = 0.1
        loop.log("fault", fault="capacity_loss", factor=0.1)

    loop.at(40.0, degrade)

    def capacity_added(f):
        if len(f.router.pool()) < 4:
            return ["slow_burn: the controller never added capacity "
                    "while the burn alert fired"]
        return []

    return fleet, [
        lambda f: props.slo_budget_held(f),
        capacity_added,
        lambda f: props.zero_failed_accepted(f, allowed_until=0.0),
    ]


def _standby_exhaustion(loop: EventLoop):
    """The diurnal peak demands more engines than the standby pool
    holds: the actuator raises, the daemon journals ``error:``
    outcomes and HOLDS — no crash, no failed accepted requests, and
    the controller still breathes back down after the peak."""
    p = FleetParams(
        engines=2, workers=8, ps=2, duration_s=180.0,
        base_qps=40.0, peak_qps=120.0, period_s=120.0,
        standby_engines=1, slo=False,
        policy=PolicyConfig(hysteresis_ticks=2, cooldown_s=6.0,
                            req_rate_low=8.0),
    )
    fleet = SimFleet(loop, p, "standby_exhaustion")

    def exhausted_surfaced(f):
        errs = [d for d in f.decisions
                if d.outcome and d.outcome.startswith("error:")]
        if not errs:
            return ["standby: the pool never exhausted — the scenario "
                    "lost its point"]
        return []

    return fleet, [
        exhausted_surfaced,
        lambda f: props.zero_failed_accepted(f, allowed_until=0.0),
        lambda f: props.no_flapping(f, actuator="engine", max_reversals=6),
    ]


def _power_loss_durable(loop: EventLoop):
    """The whole PS group loses power mid-push (ISSUE 20's DR drill at
    fleet scale): every rank dies at the same instant, cold-restarts
    from its durable store, and resumes at its persisted push clock.
    Odd ranks run the push WAL (durable clock tracks the applied clock
    — RPO 0); even ranks are snapshot-only (loss bounded by one
    snapshot interval); and rank 0's NEWEST snapshot generation is torn
    by the cut mid-write, so its recovery must fall back one generation
    (the 2-generation design: loss bounded by TWO intervals, never a
    refusal to start, never a silent restore of the corrupt file).
    RTO is the span from the cut to the LAST rank back."""
    p = FleetParams(engines=2, workers=256, ps=8, ps_dim=1 << 14,
                    duration_s=120.0, base_qps=20.0, peak_qps=30.0,
                    autopilot=False, slo=False)
    fleet = SimFleet(loop, p, "power_loss_durable")
    interval_s = 5.0
    # mid-interval on purpose: a cut ON a snapshot boundary loses
    # nothing and proves nothing (the losses_realistic prop pins this)
    t_kill = 62.7
    ranks = [{
        "mode": "wal" if r % 2 else "snap",
        "applied": 0.0,            # the rank's push clock
        "snapshots": [0.0, 0.0],   # the 2 on-disk generations (clocks)
        "durable": 0.0,            # what a cold restart recovers to
        "up": True,
        "recovered_at": None,
        "lost": None,
        "rpo_bound": None,
    } for r in range(p.ps)]
    dr = {"t_kill": t_kill, "interval_s": interval_s, "ranks": ranks,
          "rto_s": None, "rate_per_rank": 0.0}
    fleet.dr = dr

    def push_tick():
        rate = fleet.workers.push_rate() / p.ps
        dr["rate_per_rank"] = rate
        for r in ranks:
            if r["up"]:
                r["applied"] += rate * p.tick_s
                if r["mode"] == "wal":
                    # group-commit fsync (default 0.1s) << tick: the
                    # WAL's durable clock tracks the applied clock
                    r["durable"] = r["applied"]

    def snapshot_tick():
        for r in ranks:
            if r["up"]:
                r["snapshots"] = [r["snapshots"][1], r["applied"]]
                if r["mode"] == "snap":
                    r["durable"] = r["applied"]
        loop.log("store_snapshot", clock=_r(ranks[0]["applied"]))

    loop.every(p.tick_s, push_tick, until=p.duration_s)
    loop.every(interval_s, snapshot_tick, until=p.duration_s)

    def recover(i: int):
        r = ranks[i]
        r["up"] = True
        r["applied"] = r["durable"]
        r["recovered_at"] = loop.now
        loop.log("rank_recovered", rank=i, mode=r["mode"],
                 clock=_r(r["applied"]), lost=_r(r["lost"]))
        if all(x["up"] for x in ranks):
            dr["rto_s"] = loop.now - t_kill
            fleet.workers.joined = fleet.workers.total  # clients resume
            loop.log("fleet_recovered", rto_s=_r(dr["rto_s"]))

    def power_loss():
        rate = dr["rate_per_rank"]
        loop.log("power_loss", ranks=p.ps, rate_per_rank=_r(rate))
        for i, r in enumerate(ranks):
            r["up"] = False
            generations = 1
            if i == 0:
                # the snapshot write in flight at the cut is torn: CRC
                # rejects the newest generation, recovery restores the
                # previous one
                r["snapshots"][1] = r["snapshots"][0]
                if r["mode"] == "snap":
                    r["durable"] = r["snapshots"][0]
                generations = 2
            r["lost"] = r["applied"] - r["durable"]
            r["rpo_bound"] = (0.0 if r["mode"] == "wal"
                              else rate * (interval_s * generations
                                           + p.tick_s))
            # staggered cold restart: respawn + snapshot load, plus WAL
            # replay time for the WAL ranks
            delay = 1.0 + loop.rng.uniform(0.0, 2.0) + (
                0.5 if r["mode"] == "wal" else 0.0)
            loop.after(delay, recover, i)
        fleet.workers.joined = 0  # every push stream broke at once

    loop.at(t_kill, power_loss)

    def losses_realistic(_f):
        # the scenario must actually exercise its point: snapshot ranks
        # lose real pushes, the torn rank loses MORE than an untorn one
        if not any(r["mode"] == "snap" and r["lost"] and i > 0
                   for i, r in enumerate(ranks)):
            return ["power_loss: no snapshot-only rank lost anything — "
                    "the cut landed on a snapshot boundary and proved "
                    "nothing"]
        untorn = max(r["lost"] for i, r in enumerate(ranks)
                     if r["mode"] == "snap" and i > 0)
        if ranks[0]["lost"] <= untorn:
            return ["power_loss: the torn-generation rank lost no more "
                    "than an untorn one — the fallback never engaged"]
        return []

    return fleet, [
        lambda f: props.rto_bounded(f, max_rto_s=5.0),
        props.rpo_bounded,
        losses_realistic,
        lambda f: props.all_rejoined(f, deadline_s=p.duration_s),
    ]


@dataclasses.dataclass(frozen=True)
class Scenario:
    name: str
    describe: str
    build: object  # (EventLoop) -> (SimFleet, [prop thunks])


SCENARIOS: dict[str, Scenario] = {
    s.name: s for s in (
        Scenario("partition_heal_1000",
                 "1000 workers rejoin after a partition heals "
                 "(joiner/spool + ps/worker bands + rank-seconds)",
                 _partition_heal_1000),
        Scenario("reshard_64_to_96_zipf",
                 "64 -> 96 membership resize under Zipf-hot traffic "
                 "(real planner, closed-form hot-share check)",
                 _reshard_64_to_96_zipf),
        Scenario("cascade_eject_canary",
                 "brownout mid-canary with no standby: the ejection "
                 "floor must keep the last replica in rotation",
                 _cascade_eject_canary),
        Scenario("autopilot_resonance",
                 "load parked between adjacent thresholds at a "
                 "resonant diurnal period: flap damping bounds "
                 "reversals",
                 _autopilot_resonance),
        Scenario("slow_burn_slo",
                 "deep capacity loss + burn alert: capacity-only "
                 "alert mode must keep adding engines",
                 _slow_burn_slo),
        Scenario("standby_exhaustion",
                 "diurnal peak outgrows the standby pool: loud error "
                 "outcomes, no crash, no failed requests",
                 _standby_exhaustion),
        Scenario("power_loss_durable",
                 "whole-fleet power loss mid-push: cold restart from "
                 "the durable store with RTO/RPO bounds (WAL ranks "
                 "lose 0, snapshot ranks <= 1 interval, torn "
                 "generation falls back to <= 2)",
                 _power_loss_durable),
    )
}


# ---------------------------------------------------------------------------
# execution + replay
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class Result:
    scenario: str
    seed: int
    digest: str
    events: int
    violations: list[str]
    summary: dict
    lines: list[str]
    history: list[dict]

    @property
    def replay_id(self) -> str:
        return f"fleetsim:{self.scenario}:{self.seed}"

    def to_doc(self) -> dict:
        return {"scenario": self.scenario, "seed": self.seed,
                "replay_id": self.replay_id, "digest": self.digest,
                "events": self.events, "violations": self.violations,
                "summary": self.summary}


def parse_replay_id(replay_id: str) -> tuple[str, int]:
    parts = replay_id.split(":")
    if len(parts) != 3 or parts[0] != "fleetsim" \
            or parts[1] not in SCENARIOS:
        raise ValueError(
            f"bad replay id {replay_id!r}: want fleetsim:<scenario>:<seed> "
            f"with scenario one of {sorted(SCENARIOS)}")
    try:
        seed = int(parts[2])
    except ValueError:
        raise ValueError(
            f"bad replay id {replay_id!r}: seed {parts[2]!r} is not an "
            "int") from None
    return parts[1], seed


def run_scenario(name: str, seed: int = 0) -> Result:
    """Execute one scenario to completion; deterministic per
    ``(name, seed)`` — the digest is the byte-identity pin."""
    if name not in SCENARIOS:
        raise ValueError(
            f"unknown scenario {name!r}; have {sorted(SCENARIOS)}")
    loop = EventLoop(seed)
    loop.log("scenario", name=name, seed=seed)
    fleet, prop_thunks = SCENARIOS[name].build(loop)
    fleet.schedule()
    try:
        loop.run(fleet.p.duration_s)
        violations = [v for thunk in prop_thunks for v in thunk(fleet)]
        loop.log("summary", **fleet.summary())
        loop.log("verdict", violations=violations)
    finally:
        for fn in fleet.cleanups:
            try:
                fn()
            except Exception:  # noqa: BLE001 — cleanup is best-effort
                pass
    return Result(scenario=name, seed=seed, digest=loop.digest(),
                  events=loop.events, violations=violations,
                  summary=fleet.summary(), lines=loop.lines,
                  history=fleet.history)
