"""CLI for fleetsim: ``python -m distlr_tpu.analysis.fleetsim``
(also reachable as ``launch fleetsim``).

    python -m distlr_tpu.analysis.fleetsim              # fast tier
    python -m distlr_tpu.analysis.fleetsim --full       # + fuzz seeds
    python -m distlr_tpu.analysis.fleetsim --scenario slow_burn_slo
    python -m distlr_tpu.analysis.fleetsim --seed 7
    python -m distlr_tpu.analysis.fleetsim --fuzz 25    # wider sweep
    python -m distlr_tpu.analysis.fleetsim --list
    python -m distlr_tpu.analysis.fleetsim \
        --replay 'fleetsim:cascade_eject_canary:0'
    python -m distlr_tpu.analysis.fleetsim --scenario slow_burn_slo \
        --history /tmp/burn.jsonl   # then: launch top --replay ...

``--replay`` re-executes one pinned replay id (as printed in a
violation) and prints the byte-stable verdict.  ``--history`` banks
the run's simulated ``fleet.json`` frames as a ``history.jsonl`` that
``launch top --replay`` scrubs on the virtual clock.  Exit codes: 0
clean, 1 violations/problems, 2 bad usage.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from distlr_tpu.analysis.fleetsim import lint, mutants, scenarios


def _emit(res: scenarios.Result, *, as_json: bool) -> None:
    if as_json:
        print(json.dumps(res.to_doc(), sort_keys=True))
        return
    verdict = "CLEAN" if not res.violations else "VIOLATED"
    print(f"{res.replay_id}: {verdict} ({res.events} events, "
          f"digest {res.digest})")
    for v in res.violations:
        print(f"  {v}")


def _write_history(res: scenarios.Result, path: str) -> None:
    with open(path, "w", encoding="utf-8") as f:
        for doc in res.history:
            f.write(json.dumps(doc, sort_keys=True) + "\n")
    print(f"banked {len(res.history)} frames to {path} "
          f"(scrub with `python -m distlr_tpu.launch top --replay {path}`)")


def _replay(replay_id: str, *, as_json: bool) -> int:
    try:
        name, seed = scenarios.parse_replay_id(replay_id)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    with lint.quiet_logs():
        res = scenarios.run_scenario(name, seed)
    _emit(res, as_json=as_json)
    return 1 if res.violations else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m distlr_tpu.analysis.fleetsim",
        description="deterministic discrete-event fleet scenarios "
                    "property-testing the real autopilot / router / "
                    "reshard / SLO policies at thousand-rank scale")
    ap.add_argument("--full", action="store_true",
                    help="deep tier: add the multi-seed fuzz sweep "
                    "(the make verify-fleetsim-full tier)")
    ap.add_argument("--scenario", action="append", metavar="NAME",
                    help="run only this scenario (repeatable)")
    ap.add_argument("--seed", type=int, default=0,
                    help="RNG seed for the runs (default 0, the pinned "
                    "digest seed)")
    ap.add_argument("--fuzz", type=int, default=0, metavar="N",
                    help="additionally run seeds 1..N per scenario")
    ap.add_argument("--replay", metavar="REPLAY_ID",
                    help="re-run one pinned fleetsim:<scenario>:<seed> "
                    "id and print its byte-stable verdict")
    ap.add_argument("--history", metavar="PATH",
                    help="bank the run's simulated fleet.json frames "
                    "as a history.jsonl for `launch top --replay` "
                    "(single scenario only)")
    ap.add_argument("--json", action="store_true",
                    help="print one JSON result doc per run instead of "
                    "prose")
    ap.add_argument("--list", action="store_true",
                    help="list scenarios and mutants, then exit")
    args = ap.parse_args(argv)

    if args.list:
        for s in scenarios.SCENARIOS.values():
            print(f"{s.name}: {s.describe}")
        for m in mutants.MUTANTS.values():
            print(f"mutant:{m.name}: reverts the {m.historical} "
                  f"(pinned at {m.replay_id})")
        return 0
    if args.replay:
        if args.history:
            print("error: --history needs a scenario run, not --replay "
                  "(use --scenario NAME --history PATH)", file=sys.stderr)
            return 2
        return _replay(args.replay, as_json=args.json)

    picked = list(scenarios.SCENARIOS)
    if args.scenario:
        unknown = sorted(set(args.scenario) - set(picked))
        if unknown:
            print(f"unknown scenario(s) {unknown} "
                  f"(have: {', '.join(picked)})", file=sys.stderr)
            return 2
        picked = list(args.scenario)
    if args.history and len(picked) != 1:
        print("error: --history banks ONE scenario's frames — pick it "
              "with --scenario NAME", file=sys.stderr)
        return 2

    rc = 0
    for name in picked:
        t0 = time.monotonic()
        with lint.quiet_logs():
            res = scenarios.run_scenario(name, args.seed)
        dt = time.monotonic() - t0
        _emit(res, as_json=args.json)
        if not args.json:
            print(f"  {res.events / max(dt, 1e-9):,.0f} events/s "
                  f"({dt:.2f}s wall)")
        if res.violations:
            rc = 1
        if args.history:
            _write_history(res, args.history)
        seeds = list(range(1, args.fuzz + 1))
        if args.full and not seeds:
            seeds = list(range(1, lint.DEEP_FUZZ_SEEDS + 1))
        for seed in seeds:
            with lint.quiet_logs():
                r = scenarios.run_scenario(name, seed)
            if r.violations:
                rc = 1
                _emit(r, as_json=args.json)
            elif args.json:
                _emit(r, as_json=True)
        if seeds and not args.json:
            print(f"  fuzz: {len(seeds)} extra seed(s)")

    if not args.scenario and args.seed == 0:
        for name in mutants.MUTANTS:
            with lint.quiet_logs():
                problems = mutants.verify_mutant(name)
            if problems:
                rc = 1
                for p in problems:
                    print(f"[fleetsim] {p}", file=sys.stderr)
            else:
                print(f"mutant:{name}: rediscovered and replayable at "
                      f"{mutants.MUTANTS[name].replay_id}")
    return rc


if __name__ == "__main__":
    sys.exit(main())
