"""Pass 8 of distlr-lint: the fleetsim sweep.

Runs every registered fleet scenario at the pinned seed and the three
policy-bug mutants, converting anything unexpected into
:class:`~distlr_tpu.analysis.report.Finding`s:

* a property violation — a REAL control-plane bug with its replay id
  (``fleetsim:<scenario>:<seed>``) in the message (fix the policy, or
  pin the counterexample as a mutant and fix in the same PR; there is
  deliberately no suppression mechanism for violations);
* digest drift — a scenario no longer reproduces its pinned
  ``EXPECTED_DIGESTS`` entry, meaning the simulated fleet's dynamics
  changed; re-pin deliberately (a reviewable one-line diff) if the
  change is intended;
* nondeterminism — the same seed + scenario produced two different
  logs, which breaks replay, the mutant suite, and tier-1 at once;
* a mutant problem — a reverted policy fix that is no longer
  rediscovered, rediscovered as the wrong bug, or whose
  counterexample fails byte-identical replay.

The deep tier (a multi-seed fuzz sweep per scenario) lives behind
``python -m distlr_tpu.analysis.fleetsim --fuzz N`` /
``make verify-fleetsim-full`` and the ``slow`` pytest marker.
"""

from __future__ import annotations

import contextlib
import logging

from distlr_tpu.analysis.report import Finding
from distlr_tpu.analysis.fleetsim import mutants, scenarios

#: fuzz seeds per scenario inside the DEEP lint tier (the CLI's
#: ``--fuzz`` runs arbitrary widths; this keeps `make
#: verify-fleetsim-full` bounded)
DEEP_FUZZ_SEEDS = 5


@contextlib.contextmanager
def quiet_logs():
    """The scenarios drive the REAL daemon/SLO classes, whose health
    logging (actuator outcomes, burn alerts) is meaningless noise
    across a sweep — silence it for the pass."""
    logging.disable(logging.WARNING)
    try:
        yield
    finally:
        logging.disable(logging.NOTSET)


def check_scenario(name: str, *, deep: bool = False) -> list[Finding]:
    with quiet_logs():
        return _check_scenario(name, deep=deep)


def _check_scenario(name: str, *, deep: bool) -> list[Finding]:
    out: list[Finding] = []
    res = scenarios.run_scenario(name, 0)
    for v in res.violations:
        out.append(Finding(
            "fleetsim", f"scenario-violation:{name}",
            f"{v} — replay with `python -m distlr_tpu.analysis.fleetsim "
            f"--replay '{res.replay_id}'`"))
    if res.violations:
        return out
    again = scenarios.run_scenario(name, 0)
    if again.digest != res.digest:
        out.append(Finding(
            "fleetsim", f"scenario-nondeterministic:{name}",
            f"same seed produced digests {res.digest} then "
            f"{again.digest} — something leaked wall clock, set order, "
            "or unseeded randomness into the event log"))
        return out
    want = mutants.EXPECTED_DIGESTS.get(name)
    if want is not None and res.digest != want:
        out.append(Finding(
            "fleetsim", f"scenario-drift:{name}",
            f"digest {res.digest} != pinned {want} — the simulated "
            "fleet's dynamics changed; re-pin EXPECTED_DIGESTS "
            "deliberately if intended"))
    if deep:
        for seed in range(1, DEEP_FUZZ_SEEDS + 1):
            r = scenarios.run_scenario(name, seed)
            for v in r.violations:
                out.append(Finding(
                    "fleetsim", f"scenario-fuzz-violation:{name}",
                    f"{v} — replay with `python -m "
                    f"distlr_tpu.analysis.fleetsim --replay "
                    f"'{r.replay_id}'`"))
    return out


def check(*, deep: bool = False) -> list[Finding]:
    findings: list[Finding] = []
    with quiet_logs():
        for name in scenarios.SCENARIOS:
            findings.extend(_check_scenario(name, deep=deep))
        for name in mutants.MUTANTS:
            for problem in mutants.verify_mutant(name):
                findings.append(
                    Finding("fleetsim", f"mutant:{name}", problem))
    return findings
