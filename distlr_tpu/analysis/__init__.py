"""distlr-lint — the repo's jax-free static-analysis subsystem.

One runner (``python -m distlr_tpu.analysis``, ``make lint``;
``--only <pass>`` runs one in isolation, ``--list-passes`` lists
them), six passes, each tier-1-enforced the way the PR-8 metrics-doc
lint made metric drift impossible:

* **wire parity** (:mod:`distlr_tpu.analysis.wire_parity`) — parse
  ``ps/native/kv_protocol.h`` (op codes, flag bits, capability bits,
  stats counts, quant block, frame sizes, magic) and cross-check every
  Python mirror site against it.  A constant that exists on one side
  only, disagrees in value, or is re-inlined as a raw literal instead
  of a :mod:`distlr_tpu.ps.wire` name fails the build with
  ``file:line`` on both sides.
* **concurrency** (:mod:`distlr_tpu.analysis.concurrency`) — an AST
  pass building a per-class shared-state registry (attributes written
  under a ``with self.<lock>`` in one method but touched lock-free in
  another, on classes whose instances cross threads) plus a
  cross-module lock-acquisition-order graph with cycle detection.
  Hogwild-INTENTIONAL races are named and justified in
  ``analysis/concurrency_baseline.toml``; anything unsuppressed fails.
* **config/CLI/docs parity** (:mod:`distlr_tpu.analysis.config_doc`) —
  every :class:`~distlr_tpu.config.Config` field has a ``launch`` flag
  and a docs mention and vice versa (``docs/CONFIG.md`` is generated,
  like ``docs/METRICS.md``).
* **metrics doc** — the PR-8 :mod:`distlr_tpu.obs.metrics_doc` drift
  lint, folded under this runner so ``make lint`` is the single entry
  point (``tests/test_metrics_doc.py`` stays as the tier-1 shim).
* **protocol model checking** (:mod:`distlr_tpu.analysis.protocol`) —
  the SEMANTIC pass: an executable small-step spec of the KV state
  machine, exhaustive interleaving search with invariant checks,
  mutant rediscovery of the named historical bugs, and trace
  conformance of real runs' journals.  Full-depth entry point:
  ``make verify-protocol``.
* **schedcheck** (:mod:`distlr_tpu.analysis.schedcheck`) — the
  IMPLEMENTATION pass: the real fleet classes (batcher, joiner,
  spool, router, reloader, membership coordinator, shadow mirror,
  chaos link) execute under a cooperative deterministic scheduler via
  the :mod:`distlr_tpu.sync` facade — preemption-bounded exhaustive
  DFS + seeded fuzzing per scenario, deadlock detection with wait-for
  cycles, and mutant rediscovery of the PR-6 joiner and PR-13
  ChaosLink teardown races as replayable ≤ 20-step schedules.
  Full-depth entry point: ``make verify-sched-full``.

The native half of the same story is the sanitizer matrix
(``make -C distlr_tpu/ps/native sanitizers``, ``DISTLR_NATIVE_VARIANT``
— see :mod:`distlr_tpu.ps.build` and ``docs/ANALYSIS.md``): TSan/ASan/
UBSan builds of the server AND the client library that the existing
chaos/elastic/compress e2e suites run against unchanged.

Everything here is deliberately jax-free and import-light: lint must
run in CI images (and pre-commit hooks) that never built jaxlib.
"""

from distlr_tpu.analysis.report import Finding  # noqa: F401
