"""Mutant mode: revert a named historical fix, rediscover the bug.

A spec that never finds anything might be modeling the wrong protocol.
The calibration is the repo's own bug history: each mutant here turns
OFF exactly one :class:`~distlr_tpu.analysis.protocol.spec.Spec` fix
flag, and the checker must rediscover the production bug that fix
closed — as a counterexample schedule, within the step budget the
ISSUE pins (<= 12).  If a refactor of the spec ever makes a mutant
pass clean, the spec stopped encoding the fix and the protocol pass
fails loudly ("mutant not rediscovered").
"""

from __future__ import annotations

import dataclasses

from distlr_tpu.analysis.protocol import checker, spec as S

#: the ISSUE-12 schedule-length budget for rediscovered bugs
MAX_SCHEDULE_STEPS = 12


@dataclasses.dataclass(frozen=True)
class Mutant:
    name: str
    #: which fix is reverted, and where it landed
    reverts: str
    protocol: S.Spec
    scenario: S.Scenario
    #: substring the violation message must carry (the right bug, not
    #: just any bug)
    expect: str


def _barrier_scenario() -> S.Scenario:
    return S.Scenario(
        name="mutant-barrier-double-vote",
        dim=4, num_servers=2,
        programs=(
            (("barrier", 0),),
            (("barrier", 0),),
        ),
        faults=("reset",),
        fault_budget=1,
    )


def _straddle_scenario() -> S.Scenario:
    return S.Scenario(
        name="mutant-reissue-straddling-push",
        dim=4, num_servers=2,
        programs=(
            (("push", (1, 3)),),
        ),
        resize=1,
        faults=(),
        fault_budget=0,
    )


MUTANTS = (
    Mutant(
        name="barrier-double-vote",
        reverts="PR 5: HandleBarrier dedups votes by client_id "
                "(kv_server.cc replaces the stale entry's fd)",
        protocol=S.Spec(barrier_dedup_by_client=False),
        scenario=_barrier_scenario(),
        expect="I2: barrier gen 0 released",
    ),
    Mutant(
        name="reissue-straddling-push",
        reverts="PR 12: a push straddling a membership flip is absorbed "
                "as push_outcome_unknown, never re-issued "
                "(ps/client.py membership layer)",
        protocol=S.Spec(absorb_fenced_push=False),
        scenario=_straddle_scenario(),
        expect="I1: push",
    ),
)


def rediscover(mutant: Mutant, *, max_states: int = 200_000
               ) -> checker.CheckResult:
    """Run the checker against one reverted fix; the result must carry
    the expected violation (callers assert)."""
    return checker.explore(mutant.scenario, mutant.protocol,
                           max_states=max_states,
                           max_depth=MAX_SCHEDULE_STEPS + 4)


def check_all(max_states: int = 200_000) -> list:
    """Every mutant must be rediscovered: returns a list of problem
    strings (empty = all bugs found, spec still encodes every fix)."""
    problems = []
    for m in MUTANTS:
        res = rediscover(m, max_states=max_states)
        if res.violation is None:
            if not res.complete:
                # the search was CUT, not exhausted: the bug may still
                # be reachable past the bound — name the real cause
                problems.append(
                    f"mutant {m.name!r} not rediscovered within the "
                    f"search bounds ({res.states} states, depth "
                    f"{res.depth}, max_states={max_states}) — the "
                    "minimal schedule grew past the budget; shrink the "
                    "scenario or raise the bounds deliberately")
            else:
                problems.append(
                    f"mutant {m.name!r} NOT rediscovered: reverting "
                    f"[{m.reverts}] violates no invariant anywhere in "
                    "the CLOSED state space — the spec stopped "
                    "encoding the fix")
            continue
        msg, sched = res.violation
        if m.expect not in msg:
            problems.append(
                f"mutant {m.name!r} found the WRONG bug: expected "
                f"{m.expect!r} in {msg!r}")
        if len(sched) > MAX_SCHEDULE_STEPS:
            problems.append(
                f"mutant {m.name!r} counterexample takes {len(sched)} "
                f"steps (> {MAX_SCHEDULE_STEPS}) — the minimal schedule "
                "regressed; the spec grew accidental steps")
    return problems
