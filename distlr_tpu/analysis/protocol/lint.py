"""The ``protocol`` pass of ``python -m distlr_tpu.analysis``.

Three sub-checks, all fast enough for tier-1 (a few seconds total):

* bounded exploration of the standard scenarios — any invariant
  violation is a finding carrying the counterexample schedule;
* mutant rediscovery — each reverted historical fix MUST produce a
  counterexample (a spec that cannot find known bugs is a finding);
* conformance replay of the checked-in fixture artifacts (a real
  2-server chaos run at full trace sampling) — every violation cites
  the journal ``file:line``.

``make verify-protocol`` (:mod:`distlr_tpu.analysis.protocol.__main__`)
runs the same checks to closure with schedules printed.
"""

from __future__ import annotations

from distlr_tpu.analysis.protocol import checker, conformance, mutants
from distlr_tpu.analysis.report import Finding, rel

#: bounded-mode budget: every standard scenario CLOSES well under this
#: (the largest needs ~24k states), so tier-1 still gets full proofs;
#: the cap only guards against a spec edit exploding the space
LINT_MAX_STATES = 80_000


def check(max_states: int = LINT_MAX_STATES) -> list[Finding]:
    findings: list[Finding] = []
    for fn in checker.STANDARD_SCENARIOS:
        sc = fn()
        res = checker.explore(sc, max_states=max_states)
        if res.violation is not None:
            msg, sched = res.violation
            findings.append(Finding(
                "protocol", f"invariant:{sc.name}",
                f"{msg} — schedule: " + " | ".join(sched)))
        elif not res.complete:
            findings.append(Finding(
                "protocol", f"state-space:{sc.name}",
                f"exploration no longer closes under {max_states} "
                f"states ({res.states} visited, depth {res.depth}) — "
                "the spec grew; re-tune LINT_MAX_STATES deliberately "
                "or shrink the scenario"))
    for problem in mutants.check_all(max_states=max_states):
        findings.append(Finding("protocol", "mutant", problem))
    for v in conformance.check_fixtures():
        findings.append(Finding(
            "protocol", "conformance-fixture",
            v.message, ((rel(v.file), v.line),)))
    return findings
