"""``make verify-protocol`` — the full protocol verification runner.

    python -m distlr_tpu.analysis.protocol            # standard suite
    python -m distlr_tpu.analysis.protocol --full     # + the combined
                                                      #   resize+fault
                                                      #   space (~400k
                                                      #   states)
    python -m distlr_tpu.analysis.protocol --mutants  # schedules only
    python -m distlr_tpu.analysis.protocol --run-dir DIR \\
        [--chaos-events LOG]                          # conformance
                                                      #   replay of a
                                                      #   real run

Exit codes: 0 all clean / mutants rediscovered; 1 an invariant
violation, a missed mutant, or a conformance violation.
"""

from __future__ import annotations

import argparse
import sys
import time

from distlr_tpu.analysis.protocol import (
    checker,
    conformance,
    mutants,
    spec as S,
)


def scenario_full() -> S.Scenario:
    """The combined space: one live resize AND one chaos fault over the
    2x2 configuration — the largest closure the suite proves (~400k
    states; this is what the ``slow`` marker buys)."""
    return S.Scenario(
        name="full-resize-plus-fault",
        dim=4, num_servers=2,
        programs=(
            (("push", (1, 3)), ("barrier", 0)),
            (("push", (0, 2)),),
        ),
        resize=1,
        faults=("reset", "delay"),
        fault_budget=1,
    )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m distlr_tpu.analysis.protocol",
        description="KV-protocol model checking: exhaustive "
                    "interleaving search + mutant rediscovery + trace "
                    "conformance")
    ap.add_argument("--full", action="store_true",
                    help="also close the combined resize+fault space")
    ap.add_argument("--mutants", action="store_true",
                    help="only print the mutant counterexample schedules")
    ap.add_argument("--max-states", type=int, default=2_000_000)
    ap.add_argument("--run-dir", default=None,
                    help="conformance-replay a real run's --obs-run-dir")
    ap.add_argument("--chaos-events", default=None,
                    help="canonical chaos event log to replay with it")
    ap.add_argument("--require-parents", action="store_true",
                    help="run was captured at --trace-sample 1.0: every "
                         "handler span must resolve its client op span")
    ap.add_argument("--regen-fixtures", action="store_true",
                    help="re-run the chaos witness against the live "
                         "native stack and bank its artifacts under "
                         "fixtures/ (see fixtures/README.md)")
    args = ap.parse_args(argv)
    rc = 0

    if args.regen_fixtures:
        from distlr_tpu.analysis.protocol import witness  # noqa: PLC0415
        for path in witness.regen_fixtures(conformance.fixtures_dir()):
            print(f"banked {path}")
        vs = conformance.check_fixtures()
        for v in vs:
            print(v.render(), file=sys.stderr)
        print("fixture conformance after regen: "
              + (f"{len(vs)} violation(s)" if vs else "clean"))
        return 1 if vs else 0

    if args.run_dir or args.chaos_events:
        vs = conformance.check_run(
            conformance.run_dir_journals(args.run_dir)
            if args.run_dir else (),
            args.chaos_events, require_parents=args.require_parents)
        for v in vs:
            print(v.render(), file=sys.stderr)
        print(f"conformance: {len(vs)} violation(s)"
              if vs else "conformance: clean")
        return 1 if vs else 0

    if not args.mutants:
        scenarios = [fn() for fn in checker.STANDARD_SCENARIOS]
        if args.full:
            scenarios.append(scenario_full())
        for sc in scenarios:
            t0 = time.time()
            res = checker.explore(sc, max_states=args.max_states,
                                  max_depth=80)
            print(f"{res.render()}  [{time.time() - t0:.1f}s]")
            if res.violation is not None:
                rc = 1

    print()
    for m in mutants.MUTANTS:
        res = mutants.rediscover(m, max_states=args.max_states)
        if res.violation is None:
            print(f"mutant {m.name}: NOT REDISCOVERED — the spec "
                  f"stopped encoding [{m.reverts}]", file=sys.stderr)
            rc = 1
            continue
        print(f"mutant {m.name} (reverts {m.reverts}):")
        print(res.render())
        print()
    # the fixture witness rides every invocation, like the lint pass
    vs = conformance.check_fixtures()
    for v in vs:
        print(v.render(), file=sys.stderr)
        rc = 1
    print("fixture conformance: "
          + (f"{len(vs)} violation(s)" if vs else "clean"))
    return rc


if __name__ == "__main__":
    sys.exit(main())
