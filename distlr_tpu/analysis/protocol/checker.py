"""Explicit-state model checker over the executable KV-protocol spec.

Breadth-first search over every interleaving of a
:class:`~distlr_tpu.analysis.protocol.spec.Scenario`'s enabled steps,
with state hashing (two interleavings that converge on the same world
are explored once) and invariant checks at every node.  BFS means the
first violation found has a SHORTEST schedule — the counterexamples
this prints are minimal, which is what makes them readable bug
reports rather than thousand-step soup.

The search is bounded two ways (``max_states``, ``max_depth``) and the
result says whether the exploration CLOSED (every reachable state
visited) or was cut — a bounded-clean result is evidence, a closed
clean result is proof (for the configuration searched).  Tier-1 runs
the bounded check; ``make verify-protocol`` runs to closure.
"""

from __future__ import annotations

import dataclasses
from collections import deque

from distlr_tpu.analysis.protocol import spec as S


@dataclasses.dataclass
class CheckResult:
    """Outcome of one exploration."""

    scenario: str
    states: int                  # distinct states visited
    transitions: int             # edges traversed
    depth: int                   # deepest level reached
    complete: bool               # True: state space closed under bounds
    #: None, or (message, schedule) — schedule is the step-label list
    violation: tuple | None = None

    @property
    def ok(self) -> bool:
        return self.violation is None

    def schedule(self) -> list:
        return list(self.violation[1]) if self.violation else []

    def render(self) -> str:
        head = (f"[{self.scenario}] {self.states} states, "
                f"{self.transitions} transitions, depth {self.depth}, "
                f"{'closed' if self.complete else 'BOUNDED'}")
        if self.violation is None:
            return head + " — no invariant violations"
        msg, sched = self.violation
        lines = [head + " — VIOLATION", "",
                 f"counterexample ({len(sched)} steps):"]
        lines += [f"  {i + 1:2d}. {step}" for i, step in enumerate(sched)]
        lines += ["", f"  invariant violated: {msg}"]
        return "\n".join(lines)


def explore(scenario: S.Scenario, protocol: S.Spec | None = None, *,
            max_states: int = 200_000, max_depth: int = 64) -> CheckResult:
    """Exhaustive BFS of ``scenario`` under ``protocol`` (the fixed
    spec by default).  Stops at the FIRST invariant violation and
    rebuilds its schedule from the predecessor chain."""
    protocol = protocol or S.Spec()
    w0 = S.initial_world(scenario)
    root = w0.freeze()
    # frozen state -> (parent frozen state, step label); roots map to None
    parent: dict = {root: None}
    live: dict = {root: w0}
    queue = deque([(root, 0)])
    states, transitions, depth_seen = 1, 0, 0

    def schedule_of(key) -> list:
        steps = []
        while parent[key] is not None:
            key, label = parent[key][0], parent[key][1]
            steps.append(label)
        return list(reversed(steps))

    while queue:
        key, depth = queue.popleft()
        w = live.pop(key)
        depth_seen = max(depth_seen, depth)
        if depth >= max_depth:
            continue
        for label, nw in S.successors(w, scenario, protocol):
            transitions += 1
            nkey = nw.freeze()
            if nkey in parent:
                continue
            parent[nkey] = (key, label)
            msg = S.world_invariant(nw, scenario)
            if msg is not None:
                return CheckResult(
                    scenario=scenario.name, states=states + 1,
                    transitions=transitions, depth=depth + 1,
                    complete=False,
                    violation=(msg, schedule_of(nkey) + []))
            states += 1
            if states >= max_states:
                return CheckResult(
                    scenario=scenario.name, states=states,
                    transitions=transitions, depth=depth_seen,
                    complete=False, violation=None)
            live[nkey] = nw
            queue.append((nkey, depth + 1))
    # queue drained: complete iff no state was cut at max_depth
    complete = depth_seen < max_depth
    return CheckResult(scenario=scenario.name, states=states,
                       transitions=transitions, depth=depth_seen,
                       complete=complete, violation=None)


# -- the standard configurations the lint pass explores ------------------


def scenario_base() -> S.Scenario:
    """The ISSUE-14 base configuration: 2 clients x 2 servers, each
    client pushing a range-straddling gradient then voting the exit
    barrier, with ONE injected fault from the full chaos alphabet."""
    return S.Scenario(
        name="base-2c2s-fault",
        dim=4, num_servers=2,
        programs=(
            (("push", (1, 3)), ("barrier", 0)),
            (("push", (0, 2)), ("barrier", 0)),
        ),
        faults=("reset", "reset_mid", "delay", "partition"),
        fault_budget=1,
    )


def scenario_resize() -> S.Scenario:
    """One live resize (2 -> 1, the drain direction that moves a
    resident slice) under a concurrent straddling push + barrier, no
    extra fault — the interleavings AROUND the epoch flip are the
    search target."""
    return S.Scenario(
        name="resize-2c2s",
        dim=4, num_servers=2,
        programs=(
            (("push", (1, 3)), ("barrier", 0)),
            (("push", (0, 2)),),
        ),
        resize=1,
        faults=(),
        fault_budget=0,
    )


def scenario_mixed_vintage() -> S.Scenario:
    """A mixed-vintage group: rank 1 predates codecs AND membership
    epochs (kHello answers empty).  Clients WANT int8 — negotiation
    must degrade the whole group to dense f32 and skip the epoch
    announce, never desynchronize (invariant I4)."""
    from distlr_tpu.ps import wire
    return S.Scenario(
        name="mixed-vintage-2c2s",
        dim=4, num_servers=2,
        programs=(
            (("push", (1, 3)), ("barrier", 0)),
            (("push", (0, 2)), ("barrier", 0)),
        ),
        codec=wire.CODEC_INT8,
        server_caps=((1, S.LEGACY_CAPS),),
        faults=("reset",),
        fault_budget=1,
    )


def scenario_ftrl_resize() -> S.Scenario:
    """FTRL group under a live shrink: the drain must carry the z/n
    accumulator multiset exactly (invariant I5) while a concurrent
    push straddles the flip."""
    return S.Scenario(
        name="ftrl-resize-2c2s",
        dim=4, num_servers=2,
        programs=(
            (("push", (1, 3)),),
            (("push", (0, 2)),),
        ),
        optimizer="ftrl",
        resize=1,
        faults=(),
        fault_budget=0,
    )


STANDARD_SCENARIOS = (scenario_base, scenario_resize,
                      scenario_mixed_vintage, scenario_ftrl_resize)
