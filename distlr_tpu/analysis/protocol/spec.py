"""Executable small-step spec of the KV protocol state machine.

This is the semantic twin of the prose in ``ps/native/kv_protocol.h``
and the retry/membership docstrings of :mod:`distlr_tpu.ps.client`:
the same rules, written as an enumerable transition system the
explicit-state checker (:mod:`~distlr_tpu.analysis.protocol.checker`)
can search exhaustively.  Wire-level identities (op codes, flag bits,
capability bits, the fence reply shape) come from
:mod:`distlr_tpu.ps.wire` — the ONE Python protocol mirror — so the
wire-parity lint covers this module like any other framing site, and a
drifted constant fails the build before it can mis-model the protocol.

Modeling choices (every abstraction is stated, none silent):

* **granularity** — one step is one atomic protocol event: a client
  issuing an op (its per-rank slice frames enter the per-connection
  FIFOs — TCP ordering per connection, full interleaving across
  connections), a server processing ONE frame, a client consuming ONE
  reply, a fault firing, or one coordinator stage.  Delay faults and
  cross-connection reordering are interleaving, which the checker
  explores exhaustively; an explicit ``delay`` fault additionally
  pins a stream stalled across other events.
* **values are not modeled** — a push is a unique id; servers record
  which push ids touched which coordinate.  "Applied <= issued, never
  double-applied" is then exact counting, and FTRL z/n migration is
  multiset preservation (z is a sum: order-insensitive, copy-count-
  sensitive — exactly what a drain must preserve).
* **delivery proof** — frames enqueue at issue time (bytes handed to
  the kernel: ``kv_op_delivery_began`` true from then on).  A slice
  aimed at an already-dead connection stays ``unsent`` (nothing left
  the client — the one case the real retry ladder may re-issue a push).
* **negotiation** — connect + kHello + epoch announce are one atomic
  step per client (the handshake is one blocking call in the real
  client); what is CHECKED is its outcome under every interleaving of
  resizes/faults around it: capability intersection, mixed-vintage
  downgrade, announce-only-if-every-rank-speaks-kEpoch.

The ``Spec`` flags name the historical fixes; reverting one
(:mod:`~distlr_tpu.analysis.protocol.mutants`) must make the checker
rediscover the corresponding production bug as a counterexample
schedule.
"""

from __future__ import annotations

import dataclasses
from collections import namedtuple

from distlr_tpu.ps import wire

# -- wire-derived identities (lint-checked against kv_protocol.h) --------
OP_NAMES = {
    wire.OP_PUSH: "push",
    wire.OP_PULL: "pull",
    wire.OP_BARRIER: "barrier",
    wire.OP_SHUTDOWN: "shutdown",
    wire.OP_HELLO: "hello",
    wire.OP_STATS: "stats",
    wire.OP_PUSH_PULL: "push_pull",
    wire.OP_EPOCH: "epoch",
}

CODEC_NAMES = {
    wire.CODEC_NONE: "none",
    wire.CODEC_INT8: "int8",
    wire.CODEC_SIGN: "sign",
}

#: capability bit a codec id needs before a client may set its flag bits
CODEC_CAP = {
    wire.CODEC_INT8: wire.CAP_CODEC_INT8,
    wire.CODEC_SIGN: wire.CAP_CODEC_SIGN,
}

#: every capability a current-vintage server advertises
FULL_CAPS = (wire.CAP_CODEC_INT8 | wire.CAP_CODEC_SIGN
             | wire.CAP_TRACE | wire.CAP_EPOCH)
#: a pre-codec / pre-epoch vintage (kHello answered empty)
LEGACY_CAPS = 0

#: the fence reply shape (kv_protocol.h kEpoch ANNOUNCE): op is kEpoch —
#: NOT the echoed data op — with the error+response flags; aux carries
#: the server's current epoch.  `classify_reply` below is the client's
#: side of the same contract.
FENCE_OP = wire.OP_EPOCH
FENCE_FLAGS = wire.FLAG_RESPONSE | wire.FLAG_ERROR


def classify_reply(op: int, flags: int) -> str:
    """The client's reply classification — the exact discrimination
    :meth:`distlr_tpu.ps.client.KVWorker._check` performs from wire
    bytes: a fence is ``op == kEpoch`` with the error flag (transient
    by design: re-fetch the layout and re-route); any OTHER errored op
    is a protocol rejection (deterministic caller error, never
    retried); everything else is a plain response."""
    if flags & wire.FLAG_ERROR:
        return "fence" if op == FENCE_OP else "reject"
    return "ok"


def frame_bytes(req: "Req") -> bytes:
    """A model frame rendered as REAL wire bytes (MsgHeader via the
    mirror's struct) — ties counterexample schedules to the byte layout
    and keeps this module an honest framing site for the lint."""
    flags = (req.codec << wire.CODEC_SHIFT) & wire.CODEC_MASK
    aux = req.aux & wire.AUX_MAX
    return wire.HEADER_STRUCT.pack(wire.MAGIC, req.op, flags, aux,
                                   req.client, 0, len(req.coords))


# -- frames --------------------------------------------------------------
#: client->server frame: one op slice on one connection.  ``push`` is
#: the op's unique id (None for barrier votes), ``coords`` the global
#: coordinates this slice covers, ``codec`` the negotiated codec id.
Req = namedtuple("Req", "op aux client push coords codec")
#: server->client reply.  ``intent`` is a model-only annotation of what
#: the server MEANT ("ok" | "fence" | "reject") — the client must
#: recover it from (op, flags) alone; invariant I3 fails if it cannot.
Resp = namedtuple("Resp", "op flags aux push intent")


@dataclasses.dataclass(frozen=True)
class Spec:
    """The protocol rules, with the named historical fixes revertible.

    Every flag defaults to the FIXED behavior; a mutant reverts exactly
    one and the checker must rediscover the production bug it caused.
    """

    #: PR 5 (chaos round): HandleBarrier dedups votes by client_id,
    #: replacing a stale entry's fd — False reverts to blind append,
    #: where a reconnecting worker's re-vote races the old connection's
    #: DropConnection rollback and double-counts.
    barrier_dedup_by_client: bool = True
    #: PR 12 (elastic round): a gradient push bounced by a membership
    #: fence (or dead against a retired rank) after delivery began is
    #: ABSORBED as push_outcome_unknown — False reverts to re-issuing
    #: it through the new layout, a silent double-apply on every rank
    #: that applied its slice before the flip.
    absorb_fenced_push: bool = True
    #: protocol design pin (kv_protocol.h kEpoch): fence replies carry
    #: op=kEpoch, never the echoed data op — False makes fences
    #: indistinguishable from kError config rejections (invariant I3).
    fence_uses_epoch_op: bool = True


@dataclasses.dataclass(frozen=True)
class Scenario:
    """One small configuration the checker explores exhaustively.

    ``programs`` maps client id -> a tuple of ops, each
    ``("push", coords)`` / ``("pull", coords)`` / ``("barrier", gen)``.
    ``server_caps`` overrides per-rank kHello capability masks (index ->
    mask) for mixed-vintage groups.  ``resize`` is a target server
    count (one live resize mid-run) or None.  ``faults`` is the allowed
    chaos alphabet subset and ``fault_budget`` how many may fire.
    """

    name: str
    dim: int = 4
    num_servers: int = 2
    programs: tuple = ()
    codec: int = wire.CODEC_NONE          # what clients WANT to push
    optimizer: str = "sgd"                # sgd | ftrl
    server_caps: tuple = ()               # ((rank, caps), ...) overrides
    resize: int | None = None
    faults: tuple = ("reset", "reset_mid", "delay", "partition")
    fault_budget: int = 1

    @property
    def num_workers(self) -> int:
        return len(self.programs)

    def caps_of(self, rank: int) -> int:
        for r, caps in self.server_caps:
            if r == rank:
                return caps
        return FULL_CAPS


def split_ranges(dim: int, n: int) -> tuple:
    """The ServerGroup range split: dim sliced into n near-equal
    contiguous ranges (lo, hi)."""
    base, rem = divmod(dim, n)
    out, lo = [], 0
    for r in range(n):
        hi = lo + base + (1 if r < rem else 0)
        out.append((lo, hi))
        lo = hi
    return tuple(out)


# -- mutable world (cloned per transition, frozen for hashing) -----------


class ServerS:
    __slots__ = ("sid", "lo", "hi", "epoch", "caps", "alive",
                 "partitioned", "barrier", "released", "zn")

    def __init__(self, sid, lo, hi, epoch, caps):
        self.sid = sid
        self.lo, self.hi = lo, hi
        self.epoch = epoch
        self.caps = caps
        self.alive = True
        self.partitioned = False
        #: gen -> tuple of (client_id, conn_id) votes, insertion order
        self.barrier: dict = {}
        self.released: frozenset = frozenset()
        #: coord -> tuple of applied push ids (the FTRL z/n proxy: a
        #: sum is order-insensitive but copy-count-sensitive)
        self.zn: dict = {}

    def clone(self):
        s = ServerS(self.sid, self.lo, self.hi, self.epoch, self.caps)
        s.alive, s.partitioned = self.alive, self.partitioned
        s.barrier = {g: v for g, v in self.barrier.items()}
        s.released = self.released
        s.zn = dict(self.zn)
        return s

    def freeze(self):
        return (self.sid, self.lo, self.hi, self.epoch, self.caps,
                self.alive, self.partitioned,
                tuple(sorted((g, v) for g, v in self.barrier.items())),
                tuple(sorted(self.released)),
                tuple(sorted((k, tuple(sorted(v)))
                             for k, v in self.zn.items())))


class ConnS:
    __slots__ = ("cid", "client", "server", "alive", "announced",
                 "delayed", "drop_done", "delivered", "req", "resp")

    def __init__(self, cid, client, server, announced):
        self.cid = cid
        self.client = client
        self.server = server
        self.alive = True
        self.announced = announced    # epoch announced on this conn (0 = none)
        self.delayed = False
        self.drop_done = False        # server processed the disconnect
        self.delivered = 0            # frames the server has dequeued
        self.req: tuple = ()          # FIFO of Req
        self.resp: tuple = ()         # FIFO of Resp

    def clone(self):
        c = ConnS(self.cid, self.client, self.server, self.announced)
        c.alive, c.delayed, c.drop_done, c.delivered = \
            self.alive, self.delayed, self.drop_done, self.delivered
        c.req, c.resp = self.req, self.resp
        return c

    def freeze(self):
        return (self.cid, self.client, self.server, self.alive,
                self.announced, self.delayed, self.drop_done,
                self.delivered, self.req, self.resp)


class ClientS:
    __slots__ = ("cid", "pc", "layout", "layout_epoch", "conns", "codec",
                 "op", "done", "absorbed")

    def __init__(self, cid):
        self.cid = cid
        self.pc = 0
        self.layout: tuple = ()       # ((sid, lo, hi), ...)
        self.layout_epoch = 0
        self.conns: dict = {}         # sid -> conn id
        self.codec = wire.CODEC_NONE
        #: in-flight op: (kind, push_id_or_gen, {sid: status}) where
        #: status in {"sent", "unsent", "ok", "unknown"} — or
        #: ("reroute", kind, push_id_or_gen) while waiting out a
        #: migration, or None
        self.op = None
        self.done = False
        self.absorbed: tuple = ()     # push ids absorbed as unknown-outcome

    def clone(self):
        c = ClientS(self.cid)
        c.pc = self.pc
        c.layout, c.layout_epoch = self.layout, self.layout_epoch
        c.conns = dict(self.conns)
        c.codec = self.codec
        if self.op is not None and isinstance(self.op[-1], dict):
            c.op = self.op[:-1] + (dict(self.op[-1]),)
        else:
            c.op = self.op
        c.done = self.done
        c.absorbed = self.absorbed
        return c

    def freeze(self):
        op = self.op
        if op is not None and isinstance(op[-1], dict):
            op = op[:-1] + (tuple(sorted(op[-1].items())),)
        return (self.cid, self.pc, self.layout, self.layout_epoch,
                tuple(sorted(self.conns.items())), self.codec, op,
                self.done, self.absorbed)


class CoordS:
    """The membership coordinator mid-resize (spawn -> fence -> drain ->
    commit -> activate), or idle."""

    __slots__ = ("phase", "epoch", "target", "new_ranges", "reuse",
                 "moves", "fenced", "drained", "pub_status")

    def __init__(self, epoch):
        self.phase = "idle"           # idle|begun|fenced|drained|done
        self.epoch = epoch            # published layout epoch
        self.target = None
        self.new_ranges: tuple = ()
        self.reuse: dict = {}         # new rank index -> old sid
        self.moves: tuple = ()        # ((old_sid, lo, hi, new_rank), ...)
        self.fenced: frozenset = frozenset()
        self.drained: frozenset = frozenset()
        self.pub_status = "active"    # what layout() reports to clients

    def clone(self):
        c = CoordS(self.epoch)
        for f in self.__slots__:
            setattr(c, f, getattr(self, f))
        return c

    def freeze(self):
        return (self.phase, self.epoch, self.target, self.new_ranges,
                tuple(sorted(self.reuse.items())), self.moves,
                tuple(sorted(self.fenced)), tuple(sorted(self.drained)),
                self.pub_status)


class World:
    """The whole model state.  ``violation`` is set (with a message) the
    step an invariant breaks — the checker stops there and rebuilds the
    schedule."""

    __slots__ = ("servers", "clients", "conns", "coord", "next_conn",
                 "issued", "applied", "faults_left", "violation")

    def __init__(self):
        self.servers: dict = {}
        self.clients: dict = {}
        self.conns: dict = {}
        self.coord: CoordS | None = None
        self.next_conn = 0
        self.issued: dict = {}        # push id -> coords tuple
        self.applied: dict = {}       # (push id, coord) -> apply count
        self.faults_left = 0
        self.violation: str | None = None

    def clone(self):
        w = World()
        w.servers = {k: v.clone() for k, v in self.servers.items()}
        w.clients = {k: v.clone() for k, v in self.clients.items()}
        w.conns = {k: v.clone() for k, v in self.conns.items()}
        w.coord = self.coord.clone() if self.coord else None
        w.next_conn = self.next_conn
        w.issued = dict(self.issued)
        w.applied = dict(self.applied)
        w.faults_left = self.faults_left
        w.violation = self.violation
        return w

    def freeze(self):
        return (tuple(s.freeze() for _, s in sorted(self.servers.items())),
                tuple(c.freeze() for _, c in sorted(self.clients.items())),
                tuple(c.freeze() for _, c in sorted(self.conns.items())),
                self.coord.freeze() if self.coord else None,
                self.next_conn,
                tuple(sorted(self.issued.items())),
                tuple(sorted(self.applied.items())),
                self.faults_left, self.violation)


def initial_world(sc: Scenario) -> World:
    w = World()
    for sid, (lo, hi) in enumerate(split_ranges(sc.dim, sc.num_servers)):
        w.servers[sid] = ServerS(sid, lo, hi, epoch=1, caps=sc.caps_of(sid))
    for cid in range(len(sc.programs)):
        w.clients[cid] = ClientS(cid)
    w.coord = CoordS(epoch=1)
    w.faults_left = sc.fault_budget if sc.faults else 0
    return w


# -- transition helpers --------------------------------------------------


def _owners(w: World, client: ClientS, coords) -> dict:
    """coords split by owning rank per the CLIENT's layout view (which
    may be stale mid-resize — exactly the straddle the fence catches)."""
    out: dict = {}
    for k in coords:
        for sid, lo, hi in client.layout:
            if lo <= k < hi:
                out.setdefault(sid, []).append(k)
                break
        else:
            raise AssertionError(f"coord {k} outside client layout")
    return {sid: tuple(ks) for sid, ks in out.items()}


def _connect(w: World, client: ClientS, sc: Scenario) -> bool:
    """Atomic connect + kHello + epoch announce against the client's
    current layout.  Returns False (connect refused) when any target
    rank is partitioned or dead — the caller leaves state untouched and
    the client retries under another interleaving (the real client's
    bounded poll).  Negotiation outcome per the protocol:

    * codec = wanted codec iff EVERY rank's capability mask advertises
      it (kv_negotiate_codec takes the group intersection), else dense;
    * epoch announced iff EVERY rank speaks kEpoch (kCapEpoch) — a
      kEpoch frame against a pre-epoch binary would never be answered.
    """
    for sid, _lo, _hi in client.layout:
        srv = w.servers[sid]
        if not srv.alive or srv.partitioned:
            return False
    caps = ~0
    for sid, _lo, _hi in client.layout:
        caps &= w.servers[sid].caps
    client.codec = (sc.codec if sc.codec == wire.CODEC_NONE
                    or caps & CODEC_CAP[sc.codec] else wire.CODEC_NONE)
    announce = client.layout_epoch if caps & wire.CAP_EPOCH else 0
    for sid, _lo, _hi in client.layout:
        # a still-open previous conn to this rank is closed client-side
        old = client.conns.get(sid)
        if old is not None and old in w.conns:
            w.conns[old].alive = False
        conn = ConnS(w.next_conn, client.cid, sid, announce)
        w.next_conn += 1
        w.conns[conn.cid] = conn
        client.conns[sid] = conn.cid
        # I4: the negotiation rules above make these unreachable; a
        # mutant (or future refactor) that breaks intersection/announce
        # gating trips them on the exact interleaving that desyncs
        if announce and not w.servers[sid].caps & wire.CAP_EPOCH:
            w.violation = (f"I4: client c{client.cid} announced epoch "
                           f"{announce} to pre-epoch rank s{sid} — the "
                           "frame would never be answered")
        if (client.codec != wire.CODEC_NONE
                and not w.servers[sid].caps & CODEC_CAP[client.codec]):
            w.violation = (f"I4: client c{client.cid} negotiated codec "
                           f"{CODEC_NAMES[client.codec]} but rank s{sid} "
                           "does not decode it — stream desync")
    return True


def _enqueue_slices(w: World, client: ClientS, kind: str, push, coords):
    """Issue one op: slice frames per owning rank, enqueued on live
    connections (delivery began); slices whose connection is already
    dead stay ``unsent`` (kv_op_delivery_began stays false for them)."""
    op = (wire.OP_PUSH if kind == "push"
          else wire.OP_PULL if kind == "pull" else wire.OP_BARRIER)
    slices = {}
    targets = (_owners(w, client, coords) if kind != "barrier"
               else {client.layout[0][0]: ()})
    for sid, ks in targets.items():
        conn = w.conns.get(client.conns.get(sid, -1))
        aux = push if kind == "barrier" else 0
        if conn is not None and conn.alive:
            conn.req = conn.req + (
                Req(op, aux, client.cid, push if kind != "barrier" else None,
                    ks, client.codec if kind == "push" else wire.CODEC_NONE),)
            slices[sid] = "sent"
        else:
            slices[sid] = "unsent"
    client.op = (kind, push, slices)


def _apply_push(w: World, srv: ServerS, req: Req):
    """Server-side gradient apply: exact per-coordinate counting.
    I1 ("applied <= issued and never double-applied") fails the moment
    any (push, coord) applies twice or a never-issued push applies."""
    if req.push not in w.issued:
        w.violation = f"I1: rank s{srv.sid} applied unissued push {req.push}"
        return
    for k in req.coords:
        n = w.applied.get((req.push, k), 0) + 1
        w.applied[(req.push, k)] = n
        if n > 1:
            w.violation = (f"I1: push {req.push} applied {n}x to coord "
                           f"{k} at rank s{srv.sid} — double-apply")
        srv.zn[k] = srv.zn.get(k, ()) + (req.push,)


def _release_barrier(w: World, srv: ServerS, gen: int, num_workers: int):
    votes = srv.barrier[gen]
    distinct = {c for c, _cid in votes}
    if len(distinct) < num_workers:
        w.violation = (
            f"I2: barrier gen {gen} released at rank s{srv.sid} with a "
            f"live unvoted client — votes {[c for c, _ in votes]} count "
            f"{len(votes)} but only {sorted(distinct)} distinct")
    del srv.barrier[gen]
    srv.released = srv.released | {gen}
    for _client, vcid in votes:
        conn = w.conns.get(vcid)
        if conn is not None and conn.alive:
            conn.resp = conn.resp + (
                Resp(wire.OP_BARRIER, wire.FLAG_RESPONSE, gen, None, "ok"),)


def _reply(w: World, srv: ServerS, conn: ConnS, req: Req, spec: Spec,
           num_workers: int):
    """Process ONE dequeued frame — the server dispatch loop's body."""
    name = OP_NAMES[req.op]
    # membership fence: every keyed data op on an epoch-announced
    # connection bounces when the server's epoch moved (payload already
    # fully read — the model dequeued the whole frame — so the stream
    # stays framed); barrier votes are not keyed and pass
    if (name in ("push", "pull", "push_pull") and conn.announced
            and conn.announced != srv.epoch):
        op = FENCE_OP if spec.fence_uses_epoch_op else req.op
        if conn.alive:
            conn.resp = conn.resp + (
                Resp(op, FENCE_FLAGS, srv.epoch, req.push, "fence"),)
        return
    if name == "push":
        if req.codec != wire.CODEC_NONE and not srv.caps & CODEC_CAP[req.codec]:
            w.violation = (f"I4: rank s{srv.sid} received codec "
                           f"{CODEC_NAMES[req.codec]} it cannot decode")
            return
        _apply_push(w, srv, req)
        if conn.alive:
            conn.resp = conn.resp + (
                Resp(wire.OP_PUSH, wire.FLAG_RESPONSE, 0, req.push, "ok"),)
    elif name == "pull":
        if conn.alive:
            conn.resp = conn.resp + (
                Resp(wire.OP_PULL, wire.FLAG_RESPONSE, 0, req.push, "ok"),)
    elif name == "barrier":
        gen = req.aux
        if gen in srv.released:
            if conn.alive:
                conn.resp = conn.resp + (
                    Resp(wire.OP_BARRIER, wire.FLAG_RESPONSE, gen, None,
                         "ok"),)
            return
        votes = srv.barrier.get(gen, ())
        if spec.barrier_dedup_by_client:
            # the PR-5 fix: one vote per CLIENT per generation — a
            # reconnecting worker's re-vote REPLACES the stale entry's
            # fd instead of appending a second live vote
            votes = tuple((c, conn.cid if c == req.client else vcid)
                          for c, vcid in votes)
            if not any(c == req.client for c, _ in votes):
                votes = votes + ((req.client, conn.cid),)
        else:
            votes = votes + ((req.client, conn.cid),)
        srv.barrier[gen] = votes
        if len(votes) >= num_workers:
            _release_barrier(w, srv, gen, num_workers)


def _client_consume(w: World, client: ClientS, sid: int, resp: Resp,
                    spec: Spec, sc: Scenario):
    """One reply consumed — classification + the retry/membership
    ladder's per-outcome rules."""
    cls = classify_reply(resp.op, resp.flags)
    if cls != resp.intent:
        w.violation = (
            f"I3: client c{client.cid} classified a reply (op="
            f"{OP_NAMES.get(resp.op, resp.op)}, flags={resp.flags:#x}) as "
            f"{cls!r} but the server meant {resp.intent!r} — fence/"
            "kError ambiguity")
        return
    if client.op is None:
        return  # late reply of an op the ladder already resolved
    kind, ident, slices = client.op[0], client.op[1], None
    if kind == "reroute":
        return  # already waiting out a migration; late replies ignored
    slices = client.op[2]
    if cls == "fence":
        # the membership layer: re-fetch layout, rebuild, and (pushes)
        # absorb-or-reissue per the PR-12 flag.  Modeled as entering a
        # reroute phase; `client_reroute` completes it when the
        # coordinator publishes an ACTIVE layout.
        client.op = ("reroute", kind, ident)
        return
    if cls == "reject":
        client.op = None  # deterministic caller error: op aborts
        return
    if kind == "barrier":
        client.op = None
        client.pc += 1
    else:
        if slices.get(sid) == "sent":
            slices[sid] = "ok"
        if all(st in ("ok", "unknown") for st in slices.values()):
            client.op = None
            client.pc += 1


def _finish_op_if_resolved(client: ClientS):
    _kind, _ident, slices = client.op
    if all(st in ("ok", "unknown") for st in slices.values()):
        client.op = None
        client.pc += 1


# -- enumerating enabled transitions -------------------------------------


def successors(w: World, sc: Scenario, spec: Spec):
    """Yield ``(label, next_world)`` for every enabled atomic step."""
    # --- clients ---
    for cid, cl in sorted(w.clients.items()):
        if cl.done:
            continue
        # initial connect — only against an ACTIVE published layout
        # (mid-migration the coordinator reports `status: migrating`
        # and the real client polls instead of connecting)
        if not cl.conns and cl.op is None:
            if w.coord.pub_status != "active":
                continue
            nw = w.clone()
            ncl = nw.clients[cid]
            ncl.layout = tuple(
                (s.sid, s.lo, s.hi)
                for _, s in sorted(nw.servers.items()) if s.alive)
            ncl.layout_epoch = nw.coord.epoch
            if _connect(nw, ncl, sc):
                yield (f"c{cid}: connect + hello "
                       f"(epoch {ncl.layout_epoch}, codec "
                       f"{CODEC_NAMES[ncl.codec]})", nw)
            continue
        # issue the next program op
        if cl.op is None:
            if cl.pc >= len(sc.programs[cid]):
                nw = w.clone()
                nw.clients[cid].done = True
                yield (f"c{cid}: done", nw)
                continue
            kind, arg = sc.programs[cid][cl.pc]
            nw = w.clone()
            ncl = nw.clients[cid]
            if kind == "barrier":
                _enqueue_slices(nw, ncl, kind, arg, ())
                yield (f"c{cid}: vote barrier gen {arg}", nw)
            else:
                push = f"{kind[0]}{cid}.{cl.pc}"
                nw.issued[push] = tuple(arg)
                _enqueue_slices(nw, ncl, kind, push, tuple(arg))
                tgt = ",".join(f"s{s}" for s in ncl.op[2])
                yield (f"c{cid}: issue {kind} {push} coords {arg} "
                       f"-> {tgt}", nw)
            continue
        if cl.op[0] == "reroute":
            # fence recovery: blocked until the coordinator publishes an
            # ACTIVE layout (the real ladder's bounded poll), then one
            # atomic re-fetch + rebuild + renegotiate + resolve
            if w.coord.pub_status == "active":
                nw = w.clone()
                yield (_client_reroute(nw, nw.clients[cid], sc, spec), nw)
            continue
        # consume a reply
        for sid, ccid in sorted(cl.conns.items()):
            conn = w.conns.get(ccid)
            if conn is None or not conn.resp or not conn.alive:
                continue
            nw = w.clone()
            nconn = nw.conns[ccid]
            resp = nconn.resp[0]
            nconn.resp = nconn.resp[1:]
            _client_consume(nw, nw.clients[cid], sid, resp, spec, sc)
            yield (f"c{cid}: recv {resp.intent} reply from s{sid} "
                   f"({OP_NAMES.get(resp.op, resp.op)})", nw)
        # timeout: only when no progress is possible on a slice's
        # connection — dead socket, retired rank, or (for a delivered
        # push, whose outcome is then unknown) a partitioned rank.  An
        # idempotent op under a pure partition just waits: the real
        # client's reconnect would be refused and burn backoff until
        # the window heals, observably equivalent to the late reply.
        kind, ident, slices = cl.op
        for sid, st in sorted(slices.items()):
            if st not in ("sent", "unsent"):
                continue
            conn = w.conns.get(cl.conns.get(sid, -1))
            dead = conn is None or not conn.alive
            stalled = (conn is not None and conn.server in w.servers
                       and w.servers[conn.server].partitioned)
            retired = sid not in w.servers or not w.servers[sid].alive
            if not (dead or retired
                    or (stalled and kind == "push" and st == "sent")):
                continue
            nw = w.clone()
            yield (_client_timeout(nw, nw.clients[cid], sid, sc, spec), nw)
            break  # one timeout action per state is enough (same ladder)
    # --- servers ---
    for sid, srv in sorted(w.servers.items()):
        if not srv.alive:
            continue
        for ccid, conn in sorted(w.conns.items()):
            if conn.server != sid:
                continue
            if (conn.req and not srv.partitioned and not conn.delayed):
                nw = w.clone()
                nsrv, nconn = nw.servers[sid], nw.conns[ccid]
                req = nconn.req[0]
                nconn.req = nconn.req[1:]
                nconn.delivered += 1
                _reply(nw, nsrv, nconn, req, spec, sc.num_workers)
                yield (f"s{sid}: process {OP_NAMES[req.op]}"
                       f"{f' {req.push}' if req.push else ''} "
                       f"(conn {ccid})", nw)
            if not conn.alive and not conn.drop_done:
                # DropConnection: roll back this connection's unreleased
                # barrier votes (the reader thread noticing EOF) — the
                # action whose RACE with a re-vote the PR-5 dedup closed
                nw = w.clone()
                nsrv, nconn = nw.servers[sid], nw.conns[ccid]
                nconn.drop_done = True
                for gen in list(nsrv.barrier):
                    nsrv.barrier[gen] = tuple(
                        (c, vc) for c, vc in nsrv.barrier[gen]
                        if vc != ccid)
                    if not nsrv.barrier[gen]:
                        del nsrv.barrier[gen]
                yield (f"s{sid}: drop conn {ccid} (roll back its "
                       "barrier votes)", nw)
    # --- faults (chaos alphabet, budgeted) ---
    if w.faults_left > 0:
        yield from _fault_actions(w, sc)
    for sid, srv in sorted(w.servers.items()):
        if srv.partitioned:
            nw = w.clone()
            nw.servers[sid].partitioned = False
            yield (f"fault: heal partition of s{sid}", nw)
    for ccid, conn in sorted(w.conns.items()):
        if conn.delayed:
            nw = w.clone()
            nw.conns[ccid].delayed = False
            yield (f"fault: release delayed conn {ccid}", nw)
    # --- coordinator (one scripted resize) ---
    if sc.resize is not None:
        yield from _coord_actions(w, sc, spec)


def _client_timeout(w: World, cl: ClientS, sid: int, sc: Scenario,
                    spec: Spec) -> str:
    """The retry ladder on a receive timeout / dead socket, per
    :meth:`distlr_tpu.ps.client.KVWorker._run_with_retry`:

    * idempotent ops (pull, barrier): reconnect in place and re-issue —
      the server rolls a dead connection's votes back, so a re-issue
      counts once;
    * a push slice whose delivery BEGAN: outcome unknown — absorbed
      (counted, never re-issued: a maybe-applied push re-issued is a
      silent double-apply).  If the rank is RETIRED (resharded away),
      recovery is the membership layer: enter reroute;
    * a push slice never delivered (``unsent``): safe to re-issue.
    """
    kind, ident, slices = cl.op
    retired = sid not in w.servers or not w.servers[sid].alive
    if retired and kind == "push" and slices.get(sid) == "sent":
        if spec.absorb_fenced_push:
            # delivered against a rank the layout retired: the PR-12
            # membership-layer absorption (outcome unknown)
            slices[sid] = "unknown"
            cl.absorbed = cl.absorbed + (ident,)
            if any(st == "unsent" for st in slices.values()):
                cl.op = ("reroute", kind, ident)
            else:
                _finish_op_if_resolved(cl)
            return (f"c{cl.cid}: timeout on retired s{sid} — push {ident} "
                    "absorbed as outcome-unknown")
        cl.op = ("reroute", kind, ident)
        return (f"c{cl.cid}: timeout on retired s{sid} — will re-route "
                f"and RE-ISSUE push {ident} (mutant)")
    if retired:
        cl.op = ("reroute", kind, ident)
        return (f"c{cl.cid}: timeout on retired s{sid} — re-route "
                f"{kind} {ident}")
    if kind == "push" and slices.get(sid) == "sent":
        # transport fault after delivery began: unknown-outcome, absorbed
        slices[sid] = "unknown"
        cl.absorbed = cl.absorbed + (ident,)
        _finish_op_if_resolved(cl)
        return (f"c{cl.cid}: timeout on s{sid} — push {ident} slice "
                "absorbed as outcome-unknown (delivery began)")
    # idempotent (or never-delivered push slice): reconnect + re-issue
    srv = w.servers[sid]
    old = cl.conns.get(sid)
    if old is not None and old in w.conns:
        w.conns[old].alive = False
    announce = cl.layout_epoch if srv.caps & wire.CAP_EPOCH else 0
    conn = ConnS(w.next_conn, cl.cid, sid, announce)
    w.next_conn += 1
    w.conns[conn.cid] = conn
    cl.conns[sid] = conn.cid
    if kind == "barrier":
        conn.req = conn.req + (
            Req(wire.OP_BARRIER, ident, cl.cid, None, (), wire.CODEC_NONE),)
        slices[sid] = "sent"
        return (f"c{cl.cid}: timeout — reconnect s{sid} (conn "
                f"{conn.cid}) and re-vote barrier gen {ident}")
    coords = _owners(w, cl, w.issued[ident]).get(sid, ())
    op = wire.OP_PUSH if kind == "push" else wire.OP_PULL
    conn.req = conn.req + (
        Req(op, 0, cl.cid, ident, coords, cl.codec if kind == "push"
            else wire.CODEC_NONE),)
    slices[sid] = "sent"
    return (f"c{cl.cid}: timeout — reconnect s{sid} (conn {conn.cid}) "
            f"and re-issue {kind} {ident} slice")


def _client_reroute(w: World, cl: ClientS, sc: Scenario,
                    spec: Spec) -> str:
    """Complete a fence/retirement recovery once the coordinator is
    ACTIVE: re-fetch the layout, rebuild + renegotiate every
    connection, then resolve the interrupted op — idempotent ops
    re-issue; pushes are absorbed as outcome-unknown (PR-12 fix) or
    re-issued (the reverted mutant, a double-apply)."""
    _phase, kind, ident = cl.op
    cl.layout = tuple((s.sid, s.lo, s.hi)
                      for _, s in sorted(w.servers.items()) if s.alive)
    cl.layout_epoch = w.coord.epoch
    cl.conns = {}
    if not _connect(w, cl, sc):
        return f"c{cl.cid}: re-route blocked (target partitioned)"
    if kind == "push":
        if spec.absorb_fenced_push:
            cl.absorbed = cl.absorbed + (ident,)
            cl.op = None
            cl.pc += 1
            return (f"c{cl.cid}: re-route to epoch {cl.layout_epoch} — "
                    f"push {ident} absorbed as outcome-unknown "
                    "(fence straddle)")
        _enqueue_slices(w, cl, kind, ident, w.issued[ident])
        return (f"c{cl.cid}: re-route to epoch {cl.layout_epoch} — "
                f"RE-ISSUED push {ident} (mutant)")
    if kind == "barrier":
        _enqueue_slices(w, cl, kind, ident, ())
        return (f"c{cl.cid}: re-route to epoch {cl.layout_epoch} — "
                f"re-vote barrier gen {ident}")
    _enqueue_slices(w, cl, kind, ident, w.issued.get(ident, ()))
    return (f"c{cl.cid}: re-route to epoch {cl.layout_epoch} — "
            f"re-issue {kind} {ident}")


def _fault_actions(w: World, sc: Scenario):
    """The chaos fault alphabet (:mod:`distlr_tpu.chaos.plan`), one
    budgeted injection: ``reset`` severs a connection AFTER a delivered
    frame (its reply is already unreachable — the push-outcome-unknown
    case), ``reset_mid`` cuts the tail frame mid-stream (RST: the
    server drops it, bytes DID leave the client), ``delay`` stalls a
    stream, ``partition`` stalls a whole rank."""
    for ccid, conn in sorted(w.conns.items()):
        if not conn.alive:
            continue
        if "reset" in sc.faults and (conn.req or conn.resp
                                     or conn.delivered):
            nw = w.clone()
            nc = nw.conns[ccid]
            nc.alive = False
            nc.resp = ()   # replies severed; delivered reqs stand
            nw.faults_left -= 1
            yield (f"fault: reset conn {ccid} after delivery "
                   "(replies severed)", nw)
        if "reset_mid" in sc.faults and conn.req:
            nw = w.clone()
            nc = nw.conns[ccid]
            dropped = nc.req[-1]
            nc.req = nc.req[:-1]   # mid-frame RST: server drops the cut frame
            nc.resp = ()
            nc.alive = False
            nw.faults_left -= 1
            yield (f"fault: reset conn {ccid} mid-frame (drops "
                   f"{OP_NAMES[dropped.op]})", nw)
        if "delay" in sc.faults and conn.req and not conn.delayed:
            nw = w.clone()
            nw.conns[ccid].delayed = True
            nw.faults_left -= 1
            yield f"fault: delay conn {ccid} (stream stalled)", nw
    if "partition" in sc.faults:
        for sid, srv in sorted(w.servers.items()):
            if srv.alive and not srv.partitioned:
                nw = w.clone()
                nw.servers[sid].partitioned = True
                nw.faults_left -= 1
                yield f"fault: partition s{sid}", nw


def _coord_actions(w: World, sc: Scenario, spec: Spec):
    """The one scripted live resize, staged exactly like
    :meth:`distlr_tpu.ps.membership.MembershipCoordinator.resize`:
    spawn (new ranks at the next epoch) -> fence (per rank — the
    interleavings AROUND the flip are the whole point) -> drain (per
    moved sub-range; copies the z/n multiset) -> commit+activate."""
    co = w.coord
    if co.phase == "idle":
        nw = w.clone()
        nco = nw.coord
        nco.phase = "begun"
        # the real resize() flips its published status to "migrating"
        # under the lock before anything else — clients poll from here
        nco.pub_status = "migrating"
        nco.target = sc.resize
        nco.new_ranges = split_ranges(sc.dim, sc.resize)
        old = {s.sid: (s.lo, s.hi) for s in nw.servers.values() if s.alive}
        nco.reuse = {nr: sid for nr, (lo, hi) in enumerate(nco.new_ranges)
                     for sid, (olo, _ohi) in old.items() if olo == lo}
        moves = []
        for sid, (olo, ohi) in sorted(old.items()):
            for nr, (nlo, nhi) in enumerate(nco.new_ranges):
                mlo, mhi = max(olo, nlo), min(ohi, nhi)
                if mhi <= mlo:
                    continue
                if nco.reuse.get(nr) == sid:
                    continue  # resident slice never crosses the wire
                moves.append((sid, mlo, mhi, nr))
        nco.moves = tuple(moves)
        # spawn: new ranks at the NEXT epoch (fresh sids above the max)
        next_sid = max(nw.servers) + 1
        for nr in range(sc.resize):
            if nr not in nco.reuse:
                lo, hi = nco.new_ranges[nr]
                srv = ServerS(next_sid, lo, hi, co.epoch + 1,
                              sc.caps_of(next_sid))
                nw.servers[next_sid] = srv
                nco.reuse[nr] = next_sid   # resolved rank -> sid mapping
                next_sid += 1
        yield (f"coord: begin resize -> {sc.resize} rank(s), spawn at "
               f"epoch {co.epoch + 1}; layout now MIGRATING", nw)
        return
    if co.phase == "begun":
        for sid, srv in sorted(w.servers.items()):
            # old ranks are the ones still at the published epoch
            # (spawned ranks start life at epoch+1, already "fenced")
            if srv.alive and sid not in co.fenced and srv.epoch == co.epoch:
                nw = w.clone()
                nw.servers[sid].epoch = co.epoch + 1
                nw.coord.fenced = nw.coord.fenced | {sid}
                if _all_old_fenced(nw.coord, nw.servers):
                    nw.coord.phase = "fenced"
                yield (f"coord: fence s{sid} at epoch {co.epoch + 1} "
                       "(admin kEpoch SET)", nw)
        return
    if co.phase == "fenced":
        for i, (sid, mlo, mhi, nr) in enumerate(co.moves):
            if i in co.drained:
                continue
            nw = w.clone()
            nco = nw.coord
            dst = nw.servers[nco.reuse[nr]]
            src = nw.servers[sid]
            for k in range(mlo, mhi):
                if k in src.zn:
                    dst.zn[k] = src.zn[k]
            nco.drained = nco.drained | {i}
            if len(nco.drained) == len(nco.moves):
                nco.phase = "drained"
            yield (f"coord: drain [{mlo},{mhi}) s{sid} -> "
                   f"s{nco.reuse[nr]} (keyed pull + forced init-push)",
                   nw)
        if not co.moves:
            nw = w.clone()
            nw.coord.phase = "drained"
            yield "coord: nothing to drain", nw
        return
    if co.phase == "drained":
        nw = w.clone()
        nco = nw.coord
        keep = set(nco.reuse.values())
        for nr, (lo, hi) in enumerate(nco.new_ranges):
            srv = nw.servers[nco.reuse[nr]]
            srv.lo, srv.hi = lo, hi
            srv.zn = {k: v for k, v in srv.zn.items() if lo <= k < hi}
            srv.epoch = nco.epoch + 1
        for sid, srv in nw.servers.items():
            if srv.alive and sid not in keep:
                srv.alive = False       # retired rank: process exits,
                for conn in nw.conns.values():  # its sockets die
                    if conn.server == sid:
                        conn.alive = False
        nco.epoch += 1
        nco.phase = "done"
        nco.pub_status = "active"
        _check_zn_preserved(nw, sc)
        yield (f"coord: commit + activate epoch {nco.epoch} "
               f"({len(keep)} rank(s))", nw)


def _all_old_fenced(co: CoordS, servers: dict) -> bool:
    for sid, srv in servers.items():
        if srv.alive and srv.epoch == co.epoch:
            return False
    return True


def _check_zn_preserved(w: World, sc: Scenario):
    """I5 (FTRL scenarios): after activate, every coordinate's z/n
    multiset at its NEW owner equals the multiset of pushes actually
    applied to it — a drain that lost, duplicated, or mis-ranged an
    accumulator shows up as a mismatch."""
    if sc.optimizer != "ftrl":
        return
    for srv in w.servers.values():
        if not srv.alive:
            continue
        for k in range(srv.lo, srv.hi):
            have = tuple(sorted(srv.zn.get(k, ())))
            want = tuple(sorted(
                p for (p, kk), n in w.applied.items()
                if kk == k for _ in range(n)))
            if have != want:
                w.violation = (
                    f"I5: FTRL z/n lost by migration at coord {k} of "
                    f"rank s{srv.sid}: accumulator holds {have} but "
                    f"applied history says {want}")
                return


def world_invariant(w: World, sc: Scenario) -> str | None:
    """State invariants re-checked by the checker at every node (the
    action-time checks set ``violation`` eagerly; this is the safety
    net for anything state-shaped): applied <= issued, per-coordinate."""
    if w.violation:
        return w.violation
    for (push, coord), n in w.applied.items():
        if n > 1:
            return f"I1: push {push} applied {n}x to coord {coord}"
        if push not in w.issued or coord not in w.issued[push]:
            return (f"I1: applied ({push}, {coord}) was never issued "
                    "for that coordinate")
    return None
