"""Conformance witnesses: real runs whose artifacts replay through the
model.

Two scripted runs, both at ``--trace-sample 1.0`` so every handler
span's client op span is journaled (``require_parents`` replay):

* :func:`chaos_witness` — a 2-server async group behind the chaos
  proxy (a per-op delay plus a one-shot reset-after-delivery), a
  retrying client pushing/pulling through the faults.  Artifacts: the
  client span journal, both native ``--trace_journal`` files, and the
  schema-pinned canonical event log.
* :func:`resize_witness` — a 2-server elastic group live-shrunk to 1
  under a route-following client (epoch fence + re-route mid-traffic).

``tests/test_protocol_model.py`` runs both against tmp dirs (every
chaos/elastic e2e doubling as a conformance witness is the point);
``python -m distlr_tpu.analysis.protocol --regen-fixtures`` banks the
chaos witness's artifacts under ``fixtures/`` so the default lint pass
can replay a REAL run on machines that never built the native server.

This module (unlike the rest of ``analysis/``) imports the live PS
stack — numpy, the ctypes client, spawned native servers.  It is only
imported by tests and the fixture regenerator, never by the lint pass.
"""

from __future__ import annotations

import json
import os
import shutil


def chaos_witness(out_dir: str) -> dict:
    """Run the traced 2-server chaos scenario; returns
    ``{"journals": [...], "chaos_events": path}``."""
    import numpy as np  # noqa: PLC0415

    from distlr_tpu.chaos import ChaosFabric, parse_plan  # noqa: PLC0415
    from distlr_tpu.obs import dtrace  # noqa: PLC0415
    from distlr_tpu.ps import KVWorker, RetryPolicy, ServerGroup  # noqa: PLC0415

    os.makedirs(out_dir, exist_ok=True)
    native_dir = os.path.join(out_dir, "native")
    dim = 8
    plan = parse_plan({
        "seed": 14,
        "faults": [
            {"kind": "delay", "delay_ms": 1, "links": [0]},
            # sever the reply of a DELIVERED frame mid-run: the
            # push-outcome-unknown path the model absorbs
            {"kind": "reset", "after_ops": 4, "links": [1]},
        ],
    })
    dtrace.reset_for_tests()
    dtrace.configure(out_dir, "worker", 0, sample=1.0)
    try:
        with ServerGroup(2, 1, dim=dim, sync=False,
                         trace_journal_dir=native_dir) as group:
            with ChaosFabric(group.hosts, plan) as fabric:
                kv = KVWorker(fabric.hosts, dim, client_id=1,
                              sync_group=False, timeout_ms=2000,
                              retry=RetryPolicy(attempts=4,
                                                backoff_ms=20.0,
                                                seed=14))
                try:
                    for step in range(7):
                        with dtrace.use(dtrace.new_trace()), \
                                dtrace.span("train.step",
                                            tags={"step": step}):
                            if step == 0:
                                kv.push_init(np.zeros(dim, np.float32))
                            else:
                                kv.push(np.full(dim, 0.5, np.float32))
                                kv.pull()
                finally:
                    kv.close()
                events_path = os.path.join(out_dir, "chaos_events.json")
                with open(events_path, "w") as f:
                    json.dump(fabric.events_doc(), f, indent=1)
        dtrace.flush()
    finally:
        dtrace.reset_for_tests()
    journals = [os.path.join(out_dir, "spans", "worker-0.jsonl")]
    for rank in range(2):
        p = os.path.join(native_dir, f"kvserver-{rank}.jsonl")
        if os.path.exists(p):
            journals.append(p)
    return {"journals": journals, "chaos_events": events_path}


def resize_witness(out_dir: str) -> dict:
    """Run the traced live-resize scenario (2 -> 1 under a
    route-following client); returns ``{"journals": [...]}``."""
    import numpy as np  # noqa: PLC0415

    from distlr_tpu.obs import dtrace  # noqa: PLC0415
    from distlr_tpu.ps import KVWorker, ServerGroup  # noqa: PLC0415
    from distlr_tpu.ps.membership import MembershipCoordinator  # noqa: PLC0415

    os.makedirs(out_dir, exist_ok=True)
    native_dir = os.path.join(out_dir, "native")
    dim = 8
    dtrace.reset_for_tests()
    dtrace.configure(out_dir, "worker", 0, sample=1.0)
    try:
        with ServerGroup(2, 1, dim=dim, sync=False,
                         trace_journal_dir=native_dir) as group:
            coord = MembershipCoordinator(group)
            kv = KVWorker(group.hosts, dim, client_id=1,
                          sync_group=False, timeout_ms=2000,
                          epoch=coord.epoch, route=coord.layout)
            try:
                with dtrace.use(dtrace.new_trace()), \
                        dtrace.span("train.step", tags={"step": 0}):
                    kv.push_init(np.zeros(dim, np.float32))
                    kv.push(np.ones(dim, np.float32))
                # the coordinator journals its reshard.resize /
                # reshard.migrate spans under its own root trace
                with dtrace.use(dtrace.new_trace()):
                    coord.resize(1)
                # the next op bounces off the fence / dead rank and
                # re-routes through the coordinator's new layout
                with dtrace.use(dtrace.new_trace()), \
                        dtrace.span("train.step", tags={"step": 1}):
                    kv.push(np.ones(dim, np.float32))
                    kv.pull()
            finally:
                kv.close()
        dtrace.flush()
    finally:
        dtrace.reset_for_tests()
    journals = [os.path.join(out_dir, "spans", "worker-0.jsonl")]
    for rank in range(3):
        p = os.path.join(native_dir, f"kvserver-{rank}.jsonl")
        if os.path.exists(p):
            journals.append(p)
    return {"journals": journals}


def regen_fixtures(fixtures_dir: str) -> list:
    """Re-bank the chaos witness's artifacts as the checked-in
    conformance fixture (provenance in ``fixtures/README.md``)."""
    import tempfile  # noqa: PLC0415

    with tempfile.TemporaryDirectory() as tmp:
        arts = chaos_witness(tmp)
        os.makedirs(fixtures_dir, exist_ok=True)
        out = []
        for j in arts["journals"]:
            dst = os.path.join(fixtures_dir, os.path.basename(j))
            shutil.copy(j, dst)
            out.append(dst)
        dst = os.path.join(fixtures_dir, "chaos_events.json")
        shutil.copy(arts["chaos_events"], dst)
        out.append(dst)
    return out
