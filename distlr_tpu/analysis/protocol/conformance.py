"""Trace conformance: replay a real run's artifacts through the model.

The executable spec (:mod:`~distlr_tpu.analysis.protocol.spec`) fixes
what a correct run may OBSERVABLY do; this module checks that a real
run's artifacts — dtrace span journals (Python clients and the native
server's ``--trace_journal``, one schema), and the chaos proxy's
canonical event log — stay inside those rules.  Observational
refinement, not re-execution: the artifacts are projected onto the
model's observable alphabet and every projected event must be
explicable by the spec.  Every violation cites ``file:line`` — the
exact journal line that cannot have come from a conforming run.

What is checked (each rule names the spec clause it projects):

* **schemas** — the chaos event log must carry the pinned ``schema: 1``
  header (an unknown or headerless log is REJECTED loudly: silently
  misparsing an old log would vacuously "conform"); span-journal lines
  must parse and carry the one shared span schema.
* **chaos log sanity** — event kinds within the fault alphabet the
  model injects; one-shot resets fire at most once per (link, fault);
  per-(link, fault) delay op offsets are UNIQUE (the proxy claims each
  op index exactly once under its link lock — a duplicate means the
  log did not come from one deterministic run; note the canonical log
  is value-sorted, so offsets need not appear in order).
* **per-handler protocol tags** — every native ``kv.*`` span's op /
  codec / optimizer tags must name protocol identities the spec knows,
  and a sign-coded push is only explicable under the signsgd optimizer
  (kHello advertises kCapCodecSign only there — spec invariant I4
  observed from the outside).
* **journal order** — spans land in a journal at COMPLETION, so per
  writer thread the end timestamps are non-decreasing (within a
  configurable slop); an out-of-order journal cannot have been written
  by the runtime and fails with the offending line cited.
* **span-tree refinement** — within one trace, a server handler span
  must be parented under a client op span of the compatible
  ``ps.*`` class (the kv_client stamps exactly one frame per op — spec
  delivery-proof rule), and a child must nest inside its same-file
  parent's window; ``ps.reroute`` instants must carry non-decreasing
  membership epochs bounded by the wire's u16 aux ceiling.
"""

from __future__ import annotations

import dataclasses
import json
import os

from distlr_tpu.analysis.protocol import spec as S
from distlr_tpu.ps import wire

#: the chaos canonical event log schema this replayer speaks (the
#: header is pinned by `ChaosFabric.events_doc` / `launch chaos`)
CHAOS_SCHEMA = 1

#: the fault alphabet the model injects — event kinds outside it are
#: not explicable (distlr_tpu/chaos/plan.py FAULT_KINDS twin, plus the
#: proxy's partition_refused sub-kind rides the partition counter only)
FAULT_KINDS = ("delay", "throttle", "reset", "partition")

#: which client op spans may parent a given native handler span
#: (ps/client.py stamps `ps.<op>`; kv_server.cc logs the handler name)
HANDLER_PARENTS = {
    "kv.push": ("ps.push", "ps.push_init", "ps.push_init_opt_state"),
    "kv.pull": ("ps.pull", "ps.pull_chunked", "ps.pull_rows",
                "ps.pull_opt_state"),
    "kv.push_pull": ("ps.push_pull",),
}

CODEC_TAGS = tuple(S.CODEC_NAMES.values())
OPTIMIZER_TAGS = ("sgd", "ftrl", "signsgd")

#: default tolerance for journal-order / nesting checks, microseconds.
#: Within one process a record's end time is start-wall + perf-counter
#: duration, so completion order tracks end timestamps to well under a
#: millisecond; 5ms absorbs NTP slew without masking real reorderings.
DEFAULT_SLOP_US = 5_000.0


@dataclasses.dataclass(frozen=True)
class Violation:
    """One non-conforming artifact line — ``file:line`` citable."""

    file: str
    line: int
    message: str

    def render(self) -> str:
        return f"{self.file}:{self.line}: {self.message}"


# ---------------------------------------------------------------------------
# chaos canonical event log
# ---------------------------------------------------------------------------


def load_chaos_events(path: str) -> tuple[list, list]:
    """Parse a ``launch chaos --events-path`` log.  Returns
    ``(events, violations)`` where events are ``(link, kind, detail)``
    triples.  Unknown or missing schema REJECTS the whole file — a
    conformance replay must never silently misparse an old log."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return [], [Violation(path, 1, f"unreadable chaos event log: {e}")]
    if not isinstance(doc, dict) or "schema" not in doc:
        return [], [Violation(
            path, 1,
            "chaos event log carries no schema header (pre-pinning "
            f"format?) — this replayer speaks schema {CHAOS_SCHEMA} "
            "only and refuses to guess at field meanings")]
    if doc.get("schema") != CHAOS_SCHEMA:
        return [], [Violation(
            path, 1,
            f"chaos event log schema {doc.get('schema')!r} != the "
            f"pinned {CHAOS_SCHEMA} — refusing to misparse")]
    events, out = [], []
    for i, ev in enumerate(doc.get("events", ())):
        if (not isinstance(ev, list) or len(ev) != 3
                or not isinstance(ev[2], dict)):
            out.append(Violation(
                path, 1, f"events[{i}] is not a [link, kind, detail] "
                f"triple: {ev!r}"))
            continue
        events.append((ev[0], ev[1], ev[2]))
    return events, out


def check_chaos_events(path: str) -> list[Violation]:
    """The event-log sanity rules (see module docstring)."""
    events, out = load_chaos_events(path)
    resets_seen: set = set()
    delay_ops_seen: set = set()
    for i, (link, kind, detail) in enumerate(events):
        where = f"events[{i}]"
        if kind not in FAULT_KINDS:
            out.append(Violation(
                path, 1, f"{where}: fault kind {kind!r} is outside the "
                f"model's alphabet {FAULT_KINDS}"))
            continue
        if kind == "reset":
            key = (link, detail.get("fault"))
            if key in resets_seen:
                out.append(Violation(
                    path, 1, f"{where}: reset fault {detail.get('fault')} "
                    f"on link {link} fired twice — resets are one-shot "
                    "per (link, fault) in the proxy"))
            resets_seen.add(key)
        if kind == "delay" and "op" in detail:
            # NB: uniqueness, not order — the canonical log is
            # value-sorted, so a jittered plan's varying `ms` field
            # legitimately reorders offsets within one (link, fault)
            key = (link, detail.get("fault"), detail["op"])
            if key in delay_ops_seen:
                out.append(Violation(
                    path, 1, f"{where}: delay op offset {detail['op']} "
                    f"on link {link} fault {detail.get('fault')} "
                    "appears twice — the proxy claims each op index "
                    "exactly once under the link lock"))
            delay_ops_seen.add(key)
        tid = detail.get("trace")
        if tid is not None:
            try:
                int(str(tid), 16)
            except ValueError:
                out.append(Violation(
                    path, 1, f"{where}: trace id {tid!r} is not hex"))
    return out


# ---------------------------------------------------------------------------
# span journals (Python dtrace + native --trace_journal, one schema)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class SpanRec:
    file: str
    line: int
    doc: dict

    @property
    def name(self) -> str:
        return self.doc.get("name", "")

    @property
    def end_us(self) -> float:
        return float(self.doc.get("ts", 0.0)) + float(self.doc.get("dur",
                                                                   0.0))


def load_span_journal(path: str) -> tuple[list, list]:
    """Every well-formed record of one journal as :class:`SpanRec`,
    plus violations for lines that cannot be span-schema records.  A
    torn FINAL line is tolerated (the batched-flush contract); a torn
    line mid-file is not."""
    recs, out = [], []
    try:
        with open(path) as f:
            lines = f.readlines()
    except OSError as e:
        return [], [Violation(path, 1, f"unreadable span journal: {e}")]
    for n, raw in enumerate(lines, start=1):
        raw = raw.strip()
        if not raw:
            continue
        try:
            doc = json.loads(raw)
        except ValueError:
            if n == len(lines):
                continue  # torn tail: the documented crash shape
            out.append(Violation(path, n, "unparseable journal line "
                                          "mid-file (not a torn tail)"))
            continue
        typ = doc.get("type")
        if typ == "meta":
            continue
        if typ == "clock":
            # the traced-kHello clock probe (symmetric-RTT offset per
            # server) — per-peer offsets, no ordering semantics
            if "peer" not in doc or "offset_s" not in doc:
                out.append(Violation(
                    path, n, "clock record missing peer/offset_s"))
            continue
        if typ not in ("span", "instant"):
            out.append(Violation(
                path, n, f"unknown journal record type {typ!r}"))
            continue
        if typ == "span":
            missing = [k for k in ("name", "trace", "span", "ts", "dur")
                       if k not in doc]
            if missing:
                out.append(Violation(
                    path, n, f"span record missing {missing}"))
                continue
            bad_num = [k for k in ("ts", "dur")
                       if not isinstance(doc[k], (int, float))
                       or isinstance(doc[k], bool)]
            if bad_num:
                # validated HERE so every downstream arithmetic check
                # can trust the fields — artifacts are untrusted input
                # and a crash would take the whole lint runner down
                out.append(Violation(
                    path, n, f"span fields {bad_num} are not numeric"))
                continue
            if float(doc["dur"]) < 0:
                out.append(Violation(
                    path, n, f"span {doc['name']!r} has negative dur "
                    f"{doc['dur']}"))
            for k in ("trace", "span", "parent"):
                v = doc.get(k)
                if v is None:
                    continue
                try:
                    int(str(v), 16)
                except ValueError:
                    out.append(Violation(
                        path, n, f"span field {k}={v!r} is not hex"))
        elif not isinstance(doc.get("ts"), (int, float)) \
                or isinstance(doc.get("ts"), bool):
            out.append(Violation(
                path, n, f"instant ts {doc.get('ts')!r} is not numeric"))
            continue
        recs.append(SpanRec(path, n, doc))
    return recs, out


def _check_handler_tags(rec: SpanRec) -> list[Violation]:
    """Protocol-identity tags of a native ``kv.*`` handler span."""
    out = []
    args = rec.doc.get("args", {})
    op = args.get("op")
    # the native TraceLog repeats the span name as the op tag
    # ("kv.push"); the bare op-name spelling is accepted too
    if op is not None and \
            (op[3:] if op.startswith("kv.") else op) \
            not in S.OP_NAMES.values():
        out.append(Violation(rec.file, rec.line,
                             f"kv handler op tag {op!r} is not a "
                             "protocol op"))
    codec = args.get("codec")
    if codec is not None and codec not in CODEC_TAGS:
        out.append(Violation(rec.file, rec.line,
                             f"codec tag {codec!r} is not a wire codec "
                             f"({CODEC_TAGS})"))
    optimizer = args.get("optimizer")
    if optimizer is not None and optimizer not in OPTIMIZER_TAGS:
        out.append(Violation(rec.file, rec.line,
                             f"optimizer tag {optimizer!r} unknown"))
    if codec == "sign" and optimizer not in (None, "signsgd"):
        out.append(Violation(
            rec.file, rec.line,
            "sign-coded push at a non-signsgd server: kHello advertises "
            "kCapCodecSign only under --optimizer=signsgd, so a "
            "conforming negotiation cannot produce this frame "
            "(spec invariant I4)"))
    if args.get("sync") not in (None, 0, 1):
        out.append(Violation(rec.file, rec.line,
                             f"sync tag {args.get('sync')!r} not 0/1"))
    return out


def _check_journal_order(recs: list, slop_us: float) -> list[Violation]:
    """Per writer thread, records land at completion: end timestamps
    are non-decreasing (within slop).  The native journal serializes
    all handler threads under one mutex, and its tid is the pid — the
    same per-tid rule covers both."""
    out = []
    last: dict = {}
    for rec in recs:
        tid = rec.doc.get("tid", 0)
        end = rec.end_us
        prev = last.get(tid)
        if prev is not None and end < prev - slop_us:
            out.append(Violation(
                rec.file, rec.line,
                f"journal out of order: record ends at {end:.1f}us but "
                f"an earlier line of tid {tid} ended at {prev:.1f}us "
                f"(> {slop_us:.0f}us slop) — spans land at completion, "
                "a conforming writer cannot produce this"))
        if prev is None or end > prev:
            last[tid] = end
    return out


def _check_trace_trees(by_file: dict, slop_us: float,
                       require_parents: bool) -> list[Violation]:
    out = []
    all_spans: dict = {}        # span id (int) -> SpanRec
    for recs in by_file.values():
        for rec in recs:
            if rec.doc.get("type") != "span":
                continue
            try:
                all_spans[int(str(rec.doc["span"]), 16)] = rec
            except (KeyError, ValueError):
                continue
    for recs in by_file.values():
        for rec in recs:
            doc = rec.doc
            if doc.get("type") == "instant" and doc.get(
                    "name") == "ps.reroute":
                epoch = doc.get("args", {}).get("epoch")
                try:
                    bad = epoch is not None and not (
                        0 <= int(epoch) <= wire.AUX_MAX)
                except (TypeError, ValueError):
                    bad = True
                if bad:
                    out.append(Violation(
                        rec.file, rec.line,
                        f"ps.reroute epoch {epoch!r} outside the u16 "
                        f"MsgHeader::aux range [0, {wire.AUX_MAX}]"))
                continue
            if doc.get("type") != "span":
                continue
            name = rec.name
            if name.startswith("kv."):
                out.extend(_check_handler_tags(rec))
            parent = doc.get("parent")
            if parent is None:
                if require_parents and name in HANDLER_PARENTS:
                    # a parentless handler span contradicts the
                    # one-stamp-per-op rule just as hard as a dangling
                    # parent id does
                    out.append(Violation(
                        rec.file, rec.line,
                        f"{name} span carries no parent at all — the "
                        "kv_client stamps each traced op exactly once, "
                        "so a handler span must parent under a client "
                        "op span"))
                continue
            try:
                pid = int(str(parent), 16)
            except ValueError:
                continue  # already reported by the loader
            prec = all_spans.get(pid)
            if prec is None:
                if require_parents and name in HANDLER_PARENTS:
                    out.append(Violation(
                        rec.file, rec.line,
                        f"{name} span has no parent span "
                        f"{parent} in any provided journal — the "
                        "kv_client stamps each traced op exactly once, "
                        "so a handler span's client op span must exist"))
                continue
            if name in HANDLER_PARENTS and \
                    prec.name not in HANDLER_PARENTS[name]:
                out.append(Violation(
                    rec.file, rec.line,
                    f"{name} span parented under {prec.name!r} "
                    f"({prec.file}:{prec.line}) — the spec only lets "
                    f"{HANDLER_PARENTS[name]} issue this handler"))
            # same-file nesting: no cross-host clock question there
            if prec.file == rec.file:
                p0 = float(prec.doc["ts"])
                p1 = prec.end_us
                if (float(doc["ts"]) < p0 - slop_us
                        or rec.end_us > p1 + slop_us):
                    out.append(Violation(
                        rec.file, rec.line,
                        f"{name} span [{doc['ts']}, {rec.end_us:.1f}]us "
                        f"escapes its parent {prec.name} window "
                        f"[{p0}, {p1:.1f}]us ({prec.file}:{prec.line}) "
                        "— a child span cannot outlive its parent in "
                        "one process"))
    # ps.reroute epochs non-decreasing per file
    for path, recs in by_file.items():
        last_epoch = None
        for rec in recs:
            if rec.doc.get("type") != "instant" or \
                    rec.name != "ps.reroute":
                continue
            epoch = rec.doc.get("args", {}).get("epoch")
            try:
                epoch = int(epoch)
            except (TypeError, ValueError):
                continue  # absent/malformed: reported by the aux check
            if last_epoch is not None and epoch < last_epoch:
                out.append(Violation(
                    rec.file, rec.line,
                    f"ps.reroute epoch went backwards ({last_epoch} -> "
                    f"{epoch}) — membership epochs only advance"))
            last_epoch = epoch
    return out


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------


def check_run(span_journals=(), chaos_events: str | None = None, *,
              require_parents: bool = False,
              slop_us: float = DEFAULT_SLOP_US) -> list[Violation]:
    """Conformance-check one run's artifacts.  ``span_journals`` is an
    iterable of journal paths (client and native mixed — one schema);
    ``chaos_events`` the canonical event log path, if the run rode a
    fault plan.  ``require_parents`` should be True for runs captured
    at ``--trace-sample 1.0`` (every handler span's client op span is
    then guaranteed journaled)."""
    out: list[Violation] = []
    by_file: dict = {}
    for path in span_journals:
        recs, vs = load_span_journal(path)
        out.extend(vs)
        by_file[path] = recs
        out.extend(_check_journal_order(recs, slop_us))
    out.extend(_check_trace_trees(by_file, slop_us, require_parents))
    if chaos_events is not None:
        out.extend(check_chaos_events(chaos_events))
    return out


def run_dir_journals(run_dir: str) -> list:
    """Every span journal of an ``--obs-run-dir`` tree.  The launch
    convention (``ServerGroup(trace_journal_dir=...)`` wired by
    ``launch ps-server --obs-run-dir``) puts native ``kvserver-*``
    journals in the SAME ``spans/`` directory as the Python ones, so
    one listing covers both; ``native/`` and ``trace_journal/``
    subdirectories are scanned too for runs (like the witnesses) that
    keep the native journals apart."""
    out = []
    for sub in ("spans", "native", "trace_journal"):
        d = os.path.join(run_dir, sub)
        if os.path.isdir(d):
            out += sorted(os.path.join(d, f) for f in os.listdir(d)
                          if f.endswith(".jsonl"))
    return out


def check_run_dir(run_dir: str, chaos_events: str | None = None, *,
                  require_parents: bool = False) -> list[Violation]:
    return check_run(run_dir_journals(run_dir), chaos_events,
                     require_parents=require_parents)


def fixtures_dir() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fixtures")


def check_fixtures() -> list[Violation]:
    """The checked-in witness: journals + chaos event log captured from
    a REAL 2-server chaos run at ``--trace-sample 1.0`` (see
    ``fixtures/README.md``).  The default protocol pass replays them so
    ``python -m distlr_tpu.analysis`` exercises the whole replay path
    even on machines that never built the native server."""
    d = fixtures_dir()
    journals = sorted(
        os.path.join(d, f) for f in os.listdir(d) if f.endswith(".jsonl"))
    chaos = os.path.join(d, "chaos_events.json")
    return check_run(journals,
                     chaos if os.path.exists(chaos) else None,
                     require_parents=True)
