"""Protocol model checking — the semantic half of distlr-lint.

PR 13 made the repo lint itself *syntactically* (wire-constant parity,
lock discipline, doc drift).  This package makes it verify itself
*semantically*: every serious bug in the repo's history — the barrier
double-vote early release (PR 5), the re-issued straddling push that
PR 12 had to absorb as ``push_outcome_unknown`` — was a protocol
INTERLEAVING bug that chaos testing stumbled onto rather than analysis
ruled out.  Three parts:

* **executable spec** (:mod:`~distlr_tpu.analysis.protocol.spec`) — a
  small-step state machine of the KV protocol: client handles with
  per-connection negotiation (kHello capability intersection, epoch
  announce), server tables + barrier vote sets with
  generation/connection rollback, the retry ladder with
  ``kv_op_delivery_began`` semantics, and membership resize
  (spawn -> fence -> drain -> commit -> activate).  Written against
  :mod:`distlr_tpu.ps.wire` — the ONE Python protocol mirror — so the
  wire-parity pass covers it for free.
* **explicit-state model checker**
  (:mod:`~distlr_tpu.analysis.protocol.checker`) — exhaustive BFS over
  interleavings of small configurations (2 clients x 2 servers, one
  resize, one injected fault from the chaos fault alphabet) with state
  hashing and invariant checks.  Counterexamples pretty-print as
  step-by-step schedules.  Mutant mode
  (:mod:`~distlr_tpu.analysis.protocol.mutants`) reverts the named
  historical fixes and must rediscover each as a counterexample — a
  spec that cannot find known bugs is not verifying anything.
* **trace conformance**
  (:mod:`~distlr_tpu.analysis.protocol.conformance`) — replay a real
  run's artifacts (dtrace span journals, the chaos proxy's canonical
  event log, ``distlr_kv_server --trace_journal`` spans) through the
  model's observable rules, so every existing chaos/elastic e2e
  doubles as a conformance witness.  Violations cite ``file:line``.

Entry points: the ``protocol`` pass of ``python -m distlr_tpu.analysis``
(bounded exploration + mutant rediscovery + fixture conformance, fast
enough for tier-1), ``make verify-protocol`` /
``python -m distlr_tpu.analysis.protocol`` (full-depth, prints
schedules), and ``make -C benchmarks protocol-smoke``.  Everything here
is jax-free and import-light, like the rest of ``analysis/``.
"""

from distlr_tpu.analysis.protocol.checker import CheckResult, explore  # noqa: F401
from distlr_tpu.analysis.protocol.spec import Scenario, Spec  # noqa: F401
