"""Pass 6 of distlr-lint: the schedcheck sweep.

Runs every registered scenario's fast-tier exploration (bounded
exhaustive DFS + a small seeded fuzz layer) and the two mutant
rediscoveries, converting anything unexpected into
:class:`~distlr_tpu.analysis.report.Finding`s:

* a scenario failure — a REAL interleaving bug with its replayable
  schedule id in the message (fix the bug, or pin the schedule and
  fix in the same PR; there is deliberately no suppression mechanism
  for schedule failures);
* a fast-tier DFS that no longer closes within its budget — the
  scenario grew past its exploration budget and the bound must be
  re-sized consciously, exactly like PR 14 treats a BOUNDED protocol
  space;
* a mutant problem — a reverted historical fix that is no longer
  rediscovered, rediscovered as the wrong bug, needs more than the
  pinned 20 steps, or fails byte-identical replay.

The deep tier (bigger preemption bound / run budgets) lives behind
``python -m distlr_tpu.analysis.schedcheck --full`` /
``make verify-sched-full`` and the ``slow`` pytest marker.
"""

from __future__ import annotations

import contextlib
import logging

from distlr_tpu.analysis.report import Finding
from distlr_tpu.analysis.schedcheck import explore, mutants, scenarios


@contextlib.contextmanager
def quiet_logs():
    """The scenarios run REAL production classes, whose health logging
    (ejections, degraded polls, resizes) is meaningless noise across
    thousands of exploration runs — silence it for the sweep."""
    logging.disable(logging.WARNING)
    try:
        yield
    finally:
        logging.disable(logging.NOTSET)

#: fuzz seeds per scenario inside the lint pass (the CLI and tests run
#: wider sweeps; this keeps `make lint` interactive)
LINT_FUZZ_SEEDS = 5


def _first_line(text: str) -> str:
    return text.splitlines()[0] if text else text


def check_scenario(s: scenarios.Scenario, *, deep: bool = False
                   ) -> list[Finding]:
    with quiet_logs():
        return _check_scenario(s, deep=deep)


def _check_scenario(s: scenarios.Scenario, *, deep: bool
                    ) -> list[Finding]:
    out: list[Finding] = []
    bound = s.deep_bound if deep else s.dfs_bound
    runs = s.deep_runs if deep else s.dfs_runs
    res = explore.dfs(s.name, s.fn, preemption_bound=bound,
                      max_runs=runs, max_steps=s.max_steps)
    if res.failure is not None:
        out.append(Finding(
            "sched", f"scenario-failure:{s.name}",
            f"{_first_line(res.failure.failure.message)} — replay with "
            f"`python -m distlr_tpu.analysis.schedcheck --replay "
            f"'{res.failure.schedule_id}'`"))
        return out
    if not res.closed and not deep:
        # the FAST tier is the closure proof (ISSUE 15: <60 s each);
        # the deep tier is budgeted extra depth — bound-2 exhaustion of
        # the largest scenarios (the router's ~10^5+ schedules) is
        # best-effort coverage, not a contract, so only a failure
        # found there is a finding
        out.append(Finding(
            "sched", f"scenario-unclosed:{s.name}",
            f"fast-tier DFS (preemption bound {bound}) no longer "
            f"closes within {runs} runs — the scenario outgrew its "
            "exploration budget; re-size it consciously"))
    fz = explore.fuzz(s.name, s.fn,
                      seeds=s.fuzz_seeds if deep else LINT_FUZZ_SEEDS,
                      max_steps=s.max_steps)
    if fz.failure is not None:
        out.append(Finding(
            "sched", f"scenario-fuzz-failure:{s.name}",
            f"{_first_line(fz.failure.failure.message)} — replay with "
            f"`python -m distlr_tpu.analysis.schedcheck --replay "
            f"'{fz.failure.schedule_id}'`"))
    return out


def check(*, deep: bool = False) -> list[Finding]:
    findings: list[Finding] = []
    with quiet_logs():
        for s in scenarios.SCENARIOS.values():
            findings.extend(_check_scenario(s, deep=deep))
        for name in mutants.MUTANTS:
            for problem in mutants.verify_mutant(name):
                findings.append(
                    Finding("sched", f"mutant:{name}", problem))
    return findings
