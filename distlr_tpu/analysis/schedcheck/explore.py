"""Schedule exploration: bounded-exhaustive DFS, seeded fuzzing,
and pinned replay.

Three drivers over :func:`~distlr_tpu.analysis.schedcheck.runtime.
run_controlled`, all stateless (every schedule re-runs the scenario
from scratch, so exploration needs no snapshot/restore of arbitrary
Python state):

* :func:`dfs` — CHESS-style iterative exploration with **preemption
  bounding**: the baseline schedule runs each task until it blocks
  (zero preemptions); alternatives preempt a runnable task at some
  decision, and only schedules with at most ``preemption_bound``
  preemptions are explored.  Empirically almost every concurrency bug
  needs very few preemptions (the CHESS result), which turns an
  exponential space into a small polynomial one — the SOUNDNESS
  CAVEAT being that a bug requiring more preemptions than the bound
  (or an interleaving inside uninstrumented code) is out of scope;
  ``closed=True`` means "no bug within the bound", not "no bug".
* :func:`fuzz` — seeded random schedules.  Cheap diversity beyond the
  bound; every failing run is reported by its explicit choice list,
  so a fuzz finding replays exactly like a DFS finding.
* :func:`replay` — re-run one pinned schedule id (regression tests,
  counterexample reproduction).  Reports are byte-stable: same
  schedule id, same failure text, every time.
"""

from __future__ import annotations

import dataclasses

from distlr_tpu.analysis.schedcheck.runtime import (
    Decision,
    Failure,
    RandomStrategy,
    ReplayStrategy,
    RunResult,
    run_controlled,
)


@dataclasses.dataclass
class ExploreResult:
    scenario: str
    runs: int
    #: every distinct failing run (first failure per distinct schedule)
    failures: list[RunResult]
    #: True when every schedule within the preemption bound was run
    #: (DFS only; fuzz always reports False — sampling never closes)
    closed: bool

    @property
    def failure(self) -> RunResult | None:
        return self.failures[0] if self.failures else None


def replay(scenario: str, fn, choices: list[int], *,
           max_steps: int = 4000) -> RunResult:
    res = run_controlled(scenario, fn, ReplayStrategy(choices),
                         max_steps=max_steps)
    if res.failure is None and len(res.decisions) < len(choices):
        # a pin longer than the run's branching means the code under
        # it changed shape — surface a stale pin, never a silent pass
        res = dataclasses.replace(res, failure=Failure(
            "divergence",
            f"schedule pins {len(choices)} choices but the run "
            f"branched only {len(res.decisions)} times — the pinned "
            "schedule no longer matches the code"))
    return res


def _alt_cost(decisions: list[Decision], upto: int, alt: int) -> int:
    """Preemptions in ``decisions[:upto]`` plus the preemption the
    alternative ``alt`` at decision ``upto`` would add."""
    cost = sum(1 for d in decisions[:upto] if d.preemptive)
    d = decisions[upto]
    cur_enabled = d.current is not None and d.current in d.enabled
    if cur_enabled and alt != d.current:
        cost += 1
    return cost


def dfs(scenario: str, fn, *, preemption_bound: int = 2,
        max_runs: int = 4000, max_steps: int = 4000,
        stop_at_first_failure: bool = True) -> ExploreResult:
    """Bounded-exhaustive exploration.  Every run follows a forced
    choice prefix and then the default policy (run the current task
    until it blocks); new prefixes branch off each run's decisions
    wherever an untried alternative stays within the preemption
    bound."""
    stack: list[list[int]] = [[]]
    failures: list[RunResult] = []
    runs = 0
    while stack:
        if runs >= max_runs:
            return ExploreResult(scenario, runs, failures, closed=False)
        prefix = stack.pop()
        res = run_controlled(scenario, fn, ReplayStrategy(prefix),
                             max_steps=max_steps)
        runs += 1
        if res.failure is not None:
            failures.append(res)
            if stop_at_first_failure:
                return ExploreResult(scenario, runs, failures,
                                     closed=False)
            if res.failure.kind == "divergence":
                # the prefix no longer matches the code — harness-level
                # problem, no point branching below it
                continue
        chosen = [d.chosen for d in res.decisions]
        # branch points strictly below this run's forced prefix are
        # already covered by the runs that produced the prefix
        for i in range(len(res.decisions) - 1, len(prefix) - 1, -1):
            d = res.decisions[i]
            for alt in d.enabled:
                if alt == d.chosen:
                    continue
                if _alt_cost(res.decisions, i, alt) > preemption_bound:
                    continue
                stack.append(chosen[:i] + [alt])
    return ExploreResult(scenario, runs, failures, closed=True)


def fuzz(scenario: str, fn, *, seeds: int = 50, seed_base: int = 0,
         max_steps: int = 4000,
         stop_at_first_failure: bool = True) -> ExploreResult:
    failures: list[RunResult] = []
    runs = 0
    for s in range(seed_base, seed_base + seeds):
        res = run_controlled(scenario, fn, RandomStrategy(s),
                             max_steps=max_steps)
        runs += 1
        if res.failure is not None:
            failures.append(res)
            if stop_at_first_failure:
                break
    return ExploreResult(scenario, runs, failures, closed=False)
