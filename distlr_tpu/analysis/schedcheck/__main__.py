"""CLI for schedcheck: ``python -m distlr_tpu.analysis.schedcheck``.

    python -m distlr_tpu.analysis.schedcheck              # fast tier
    python -m distlr_tpu.analysis.schedcheck --full       # deep DFS
    python -m distlr_tpu.analysis.schedcheck --scenario joiner_label_race
    python -m distlr_tpu.analysis.schedcheck --fuzz 200   # wider fuzz
    python -m distlr_tpu.analysis.schedcheck --list
    python -m distlr_tpu.analysis.schedcheck \
        --replay 'mutant:joiner_check_then_insert:1.1.0.0.0.0'

``--replay`` re-executes one pinned schedule id (as printed by a
failure report) and prints the byte-stable report; a ``mutant:``-
prefixed id replays with the historical bug re-applied.  Exit codes:
0 clean, 1 findings/failure.
"""

from __future__ import annotations

import argparse
import sys
import time

from distlr_tpu.analysis.schedcheck import explore, lint, mutants, scenarios
from distlr_tpu.analysis.schedcheck.runtime import parse_schedule_id


def _replay(sid: str) -> int:
    name, choices = parse_schedule_id(sid)
    if name.startswith("mutant:"):
        mname = name.split(":", 1)[1]
        if mname not in mutants.MUTANTS:
            print(f"unknown mutant {mname!r}", file=sys.stderr)
            return 1
        res = mutants.MUTANTS[mname].replay(choices)
    else:
        if name not in scenarios.SCENARIOS:
            print(f"unknown scenario {name!r} "
                  f"(have: {', '.join(scenarios.names())})",
                  file=sys.stderr)
            return 1
        s = scenarios.SCENARIOS[name]
        res = explore.replay(name, s.fn, choices, max_steps=s.max_steps)
    if res.failure is None:
        print(f"schedule {sid} replays CLEAN "
              f"({len(res.decisions)} decisions, {len(res.steps)} steps)")
        return 0
    print(res.render_failure())
    return 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m distlr_tpu.analysis.schedcheck",
        description="deterministic-interleaving execution of the real "
                    "Python fleet: scenario DFS + fuzz + mutant "
                    "rediscovery")
    ap.add_argument("--full", action="store_true",
                    help="deep tier: higher preemption bound and run "
                    "budgets (the make verify-sched-full tier)")
    ap.add_argument("--scenario", action="append", metavar="NAME",
                    help="run only this scenario (repeatable)")
    ap.add_argument("--fuzz", type=int, default=0, metavar="N",
                    help="additionally run N random schedules per "
                    "scenario")
    ap.add_argument("--replay", metavar="SCHEDULE_ID",
                    help="re-run one pinned schedule id and print its "
                    "byte-stable report")
    ap.add_argument("--list", action="store_true",
                    help="list scenarios and mutants, then exit")
    args = ap.parse_args(argv)

    if args.list:
        for s in scenarios.SCENARIOS.values():
            print(f"{s.name}: {', '.join(s.classes)}")
        for m in mutants.MUTANTS.values():
            print(f"mutant:{m.name}: reverts {m.target} "
                  f"({m.historical})")
        return 0
    if args.replay:
        return _replay(args.replay)

    picked = scenarios.SCENARIOS
    if args.scenario:
        unknown = sorted(set(args.scenario) - set(picked))
        if unknown:
            print(f"unknown scenario(s) {unknown} "
                  f"(have: {', '.join(scenarios.names())})",
                  file=sys.stderr)
            return 1
        picked = {n: picked[n] for n in args.scenario}

    rc = 0
    for s in picked.values():
        t0 = time.monotonic()
        findings = lint.check_scenario(s, deep=args.full)
        dt = time.monotonic() - t0
        if findings:
            rc = 1
            for f in findings:
                print(f.render(), file=sys.stderr)
        else:
            print(f"{s.name}: clean ({dt:.1f}s)")
        if args.fuzz:
            fz = explore.fuzz(s.name, s.fn, seeds=args.fuzz,
                              max_steps=s.max_steps)
            if fz.failure is not None:
                rc = 1
                print(fz.failure.render_failure(), file=sys.stderr)
            else:
                print(f"{s.name}: fuzz clean ({fz.runs} schedules)")
    if not args.scenario:
        for name in mutants.MUTANTS:
            with lint.quiet_logs():
                problems = mutants.verify_mutant(name)
            if problems:
                rc = 1
                for p in problems:
                    print(f"[sched] {p}", file=sys.stderr)
            else:
                print(f"mutant:{name}: rediscovered, bounded, replayable")
    return rc


if __name__ == "__main__":
    sys.exit(main())
