"""Mutant-verified race rediscovery (the PR-14 tradition, applied to
the IMPLEMENTATION instead of the spec).

A checker that has never caught anything proves nothing — so, like
the protocol pass's barrier/absorption mutants, schedcheck must
REDISCOVER the repo's named historical Python races when their fixes
are reverted.  Each mutant swaps ONE real method for its verbatim
pre-fix body (kept here as the historical record), runs the matching
scenario, and the explorer must produce a replayable counterexample
schedule of **at most 20 steps**; with the fix in place the same
scenario must stay clean.  Failing to rediscover means "schedcheck
stopped encoding the fix" and fails the lint.

The two pinned races:

* ``joiner_check_then_insert`` — PR 6's post-review fix: the joiner
  originally released its lock between the pending-label check and
  the spool insert, so a label arriving in that window parked in the
  pending buffer while its request aged out through negative
  sampling (``LabelJoiner.scored``, tests/test_feedback.py vintage).
* ``chaoslink_stop_snapshot`` — PR 13's first concurrency-lint
  finding: ``ChaosLink.stop()`` snapshotted ``_conns``/``_threads``
  lock-free BEFORE joining the accept loop, so a connection accepted
  concurrently with stop leaked its sockets and pump threads past
  stop() (``chaos/proxy.py``, tests/test_analysis.py regression).
"""

from __future__ import annotations

import contextlib
import dataclasses

from distlr_tpu import sync
from distlr_tpu.analysis.schedcheck import explore, scenarios
from distlr_tpu.analysis.schedcheck.runtime import RunResult

#: the acceptance bound: a rediscovered race must replay in this many
#: schedule steps or fewer (ISSUE 15)
MAX_SCHEDULE_STEPS = 20


# ---------------------------------------------------------------------------
# the verbatim pre-fix bodies
# ---------------------------------------------------------------------------


def _prefix_joiner_scored(self, rec) -> None:
    """``LabelJoiner.scored`` BEFORE the PR-6 post-review hardening:
    the pending-label check and the spool insert run under separate
    lock acquisitions — the check-then-insert window."""
    with self._lock:
        pend = self._pending.pop(rec.rid, None)
    if pend is not None:
        y, label_ts = pend
        with self._lock:
            self._join_locked(rec.rid, y, rec, now=label_ts)
        return
    self.spool.add(rec)


def _prefix_chaoslink_stop(self) -> None:
    """``ChaosLink.stop`` BEFORE the PR-13 fix: conns/threads
    snapshotted lock-free, and only THEN the accept loop joined — a
    connection registered between the snapshot and the join escapes
    the teardown entirely."""
    self._stop.set()
    try:
        self._lsock.close()
    except OSError:
        pass
    conns = list(self._conns)
    threads = list(self._threads)
    for down, up in conns:
        for s in (down, up):
            try:
                s.close()
            except OSError:
                pass
    for t in threads:
        t.join(timeout=2.0)
    self._accept_thread.join(timeout=6.0)


# ---------------------------------------------------------------------------
# lean race scenarios (shared by the fixed-code clean check and the
# mutant rediscovery — small on purpose: the counterexample schedule
# must stay human-readable and within MAX_SCHEDULE_STEPS)
# ---------------------------------------------------------------------------


def _scn_joiner_strand(rt) -> None:
    with scenarios._workdir() as wd:
        _spool, joiner = scenarios._mk_joiner(wd)
        base = sync.wall()

        def scorer():
            joiner.scored(scenarios._rec("r1", base))

        t = sync.Thread(target=scorer, name="scorer")
        t.start()
        out = joiner.label("r1", 1, ts=base + 1.0)   # main is the labeler
        t.join()
        joiner.tick(now=base + 1000.0)
        scenarios._check(
            joiner.joined == 1,
            f"label and request both in-window but joined="
            f"{joiner.joined} (outcome={out!r}, negatives="
            f"{joiner.negatives}, pending={len(joiner._pending)}) — "
            "the label stranded in the pending buffer")


def _scn_chaoslink_leak(rt) -> None:
    link, made = scenarios._scripted_link()
    down = scenarios._FakeSock()
    link._lsock.feed((down, ("127.0.0.1", 1)))
    link.stop()                                      # main is the stopper
    alive = sorted(task.name for task in rt.tasks
                   if task.name.startswith("chaos-")
                   and task.state not in (scenarios.NEW, scenarios.DONE))
    scenarios._check(
        not alive,
        f"pump/accept thread(s) {alive} still live after stop() "
        "returned — teardown lost a concurrently-accepted connection")
    unclosed = [i for i, s in enumerate([down] + made) if not s.closed]
    scenarios._check(
        not unclosed,
        f"socket(s) {unclosed} not closed after stop() — the snapshot "
        "missed a concurrently-registered connection")


# ---------------------------------------------------------------------------
# registry + driver
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Mutant:
    name: str
    historical: str                 # which PR's fix this reverts
    target: str                     # "module:Class.method"
    scenario_fn: object
    buggy_fn: object
    #: substring the counterexample's invariant message must carry —
    #: rediscovering a DIFFERENT bug is a failure too ("wrong bug")
    expect_in_message: str
    dfs_runs: int = 1500
    max_steps: int = 1500

    def _cls(self):
        module, _, rest = self.target.partition(":")
        clsname, _, meth = rest.partition(".")
        import importlib
        mod = importlib.import_module(module)
        return getattr(mod, clsname), meth

    @contextlib.contextmanager
    def applied(self):
        """Swap the real method for the historical pre-fix body."""
        cls, meth = self._cls()
        orig = getattr(cls, meth)
        setattr(cls, meth, self.buggy_fn)
        try:
            yield
        finally:
            setattr(cls, meth, orig)

    def clean_check(self) -> RunResult | None:
        """With the FIX in place the scenario must be schedule-proof;
        returns the offending RunResult if it is not."""
        res = explore.dfs(f"mutant:{self.name}", self.scenario_fn,
                          preemption_bound=2, max_runs=self.dfs_runs,
                          max_steps=self.max_steps)
        return res.failure

    def rediscover(self) -> RunResult | None:
        """With the fix REVERTED the explorer must find the historical
        race; returns the counterexample run (None = not found)."""
        with self.applied():
            res = explore.dfs(f"mutant:{self.name}", self.scenario_fn,
                              preemption_bound=2,
                              max_runs=self.dfs_runs,
                              max_steps=self.max_steps)
        return res.failure

    def replay(self, choices: list[int]) -> RunResult:
        """Re-run one pinned counterexample under the mutation."""
        with self.applied():
            return explore.replay(f"mutant:{self.name}",
                                  self.scenario_fn, choices,
                                  max_steps=self.max_steps)


MUTANTS: dict[str, Mutant] = {
    m.name: m for m in (
        Mutant(
            name="joiner_check_then_insert",
            historical="PR 6 post-review hardening",
            target="distlr_tpu.feedback.join:LabelJoiner.scored",
            scenario_fn=_scn_joiner_strand,
            buggy_fn=_prefix_joiner_scored,
            expect_in_message="the label stranded",
        ),
        Mutant(
            name="chaoslink_stop_snapshot",
            historical="PR 13 concurrency-lint fix",
            target="distlr_tpu.chaos.proxy:ChaosLink.stop",
            scenario_fn=_scn_chaoslink_leak,
            buggy_fn=_prefix_chaoslink_stop,
            expect_in_message="after stop()",
        ),
    )
}


def verify_mutant(name: str) -> list[str]:
    """Full acceptance for one mutant; returns problem strings (empty
    = the race is rediscovered, bounded, replayable, and the fixed
    code is clean)."""
    m = MUTANTS[name]
    problems: list[str] = []
    clean = m.clean_check()
    if clean is not None:
        problems.append(
            f"{name}: scenario fails WITH the fix in place "
            f"({clean.failure.kind}: {clean.failure.message.splitlines()[0]})")
        return problems
    cex = m.rediscover()
    if cex is None:
        problems.append(
            f"{name}: reverting the {m.historical} was NOT rediscovered "
            "— schedcheck stopped encoding the fix")
        return problems
    if m.expect_in_message not in cex.failure.message:
        problems.append(
            f"{name}: rediscovered a DIFFERENT failure "
            f"({cex.failure.message.splitlines()[0]!r}) — wrong bug")
    nsteps = len(cex.decisions)
    if nsteps > MAX_SCHEDULE_STEPS:
        problems.append(
            f"{name}: counterexample needs {nsteps} steps "
            f"(> {MAX_SCHEDULE_STEPS}) — schedule-length regression")
    rep = m.replay([d.chosen for d in cex.decisions])
    if rep.failure is None:
        problems.append(f"{name}: pinned counterexample did not replay")
    elif rep.render_failure() != cex.render_failure():
        problems.append(
            f"{name}: replay is not byte-identical to the original "
            "failure report")
    return problems
