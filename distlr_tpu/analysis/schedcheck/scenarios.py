"""Schedcheck scenarios: the highest-risk REAL classes under
controlled interleavings.

Each scenario builds real production objects (through the
:mod:`distlr_tpu.sync` facade, so their locks/threads are the
instrumented twins), races a handful of logical threads over them,
and checks interleaving-independent invariants — anything the
invariants reject under SOME schedule is a real concurrency bug with
a replayable counterexample.

Scenario scope is honest about the runtime's limits: classes whose
concurrency lives in pure-Python state (locks, lists, dicts, queues,
events) run verbatim; where a class touches the OS mid-race (the
chaos proxy's sockets, the router's probe dial) the scenario
substitutes a *scripted endpoint* behind the class's seam methods
while every line that actually races — lock ordering, list
registration, teardown joins — stays the real code.  Classes that
cannot run here at all (jax-holding ``ScoringEngine``,
process-spawning ``ServerGroup``) are declared ``schedcheck_scenario
= "-"`` in the concurrency baseline instead — the cross-reference the
lint enforces.

Every scenario also runs :func:`assert_facade`: the concurrency
lint's shared-state registry (``analysis/concurrency.py``) knows
which attributes of a class are its locks, and schedcheck asserts
those attributes resolved to instrumented twins — a module that
silently reverts from ``sync`` to raw ``threading`` fails its
scenario before it can un-instrument its own races.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import shutil
import socket
import tempfile
import threading as _real_threading

from distlr_tpu import sync
from distlr_tpu.analysis.schedcheck.runtime import (
    DONE,
    NEW,
    InvariantViolation,
    Runtime,
    TCondition,
    TLock,
    TRLock,
)

# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Scenario:
    name: str
    fn: object
    #: "path/module.py:Class" labels this scenario exercises — the
    #: concurrency baseline's ``schedcheck_scenario`` cross-reference
    #: is validated against these
    classes: tuple[str, ...]
    #: fast-tier exhaustive search (must close in seconds)
    dfs_bound: int = 1
    dfs_runs: int = 2500
    #: deep tier (`--full` / `make verify-sched-full`): higher bound,
    #: bigger run budget, and this many fuzz seeds (the fast lint pass
    #: uses lint.LINT_FUZZ_SEEDS instead)
    deep_bound: int = 2
    deep_runs: int = 60_000
    fuzz_seeds: int = 25
    max_steps: int = 4000


SCENARIOS: dict[str, Scenario] = {}


def scenario(name: str, classes: tuple[str, ...], **kw):
    def deco(fn):
        SCENARIOS[name] = Scenario(name=name, fn=fn, classes=classes, **kw)
        return fn
    return deco


def names() -> tuple[str, ...]:
    return tuple(SCENARIOS)


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

_LINT_CLASSES: dict[tuple[str, str], object] | None = None


def _lint_registry() -> dict[tuple[str, str], object]:
    global _LINT_CLASSES
    if _LINT_CLASSES is None:
        from distlr_tpu.analysis import concurrency
        _LINT_CLASSES = {(c.module, c.name): c
                         for c in concurrency.collect_classes()}
    return _LINT_CLASSES


def assert_facade(obj, label: str) -> None:
    """``label`` is ``"path/module.py:Class"``.  Every lock attribute
    the concurrency lint's shared-state registry records for that
    class must be an instrumented twin on ``obj`` — the facade-drift
    detector."""
    module, _, cls = label.partition(":")
    info = _lint_registry().get((module, cls))
    if info is None:
        raise InvariantViolation(
            f"{label} is not in the concurrency lint's class registry — "
            "scenario and lint disagree about what exists")
    for attr in sorted(info.lock_attrs):
        val = getattr(obj, attr, None)
        if not isinstance(val, (TLock, TRLock, TCondition)):
            raise InvariantViolation(
                f"{label}.{attr} is {type(val).__name__}, not an "
                "instrumented twin — the class no longer creates this "
                "lock through distlr_tpu.sync, so schedcheck cannot "
                "control (or verify) its interleavings")


@contextlib.contextmanager
def _workdir():
    d = tempfile.mkdtemp(prefix="schedcheck-")
    try:
        yield d
    finally:
        shutil.rmtree(d, ignore_errors=True)


def _check(cond: bool, msg: str) -> None:
    if not cond:
        raise InvariantViolation(msg)


# ---------------------------------------------------------------------------
# 1 + 2. MicroBatcher — coalesce/flush and the close race
# ---------------------------------------------------------------------------


def _mk_batcher(max_batch_size=4, max_wait_ms=10.0):
    import numpy as np
    from distlr_tpu.serve.batcher import MicroBatcher

    def score(merged):
        n = merged[0].shape[0]
        return (np.zeros(n, np.int32),
                merged[0].reshape(n, -1).sum(axis=1).astype(np.float32))

    return np, MicroBatcher(score, max_batch_size=max_batch_size,
                            max_wait_ms=max_wait_ms)


@scenario("batcher_coalesce",
          ("distlr_tpu/serve/batcher.py:MicroBatcher",),
          dfs_runs=4000)
def scn_batcher_coalesce(rt: Runtime) -> None:
    """Two submitters race the flush thread: every future must resolve
    with exactly its own rows' scores, whatever the coalescing."""
    np, b = _mk_batcher()
    assert_facade(b, "distlr_tpu/serve/batcher.py:MicroBatcher")
    futs: list[tuple[float, object]] = []

    def submit(v):
        futs.append((v, b.submit((np.full((1, 2), v, np.float32),))))

    t1 = sync.Thread(target=submit, args=(1.0,), name="submit-a")
    t2 = sync.Thread(target=submit, args=(2.0,), name="submit-b")
    t1.start()
    t2.start()
    t1.join()
    t2.join()
    rt.await_until(lambda: all(f.done() for _, f in futs), "futures done")
    b.close()
    for v, f in futs:
        _labels, scores = f.result(timeout=0)
        _check(float(scores[0]) == 2 * v,
               f"request {v:g} got score {float(scores[0]):g}, "
               f"want {2 * v:g} — cross-request slice corruption")
    _check(b.requests == 2 and b.rows == 2,
           f"accounting drift: requests={b.requests} rows={b.rows}, "
           "want 2/2")


@scenario("batcher_close_flush",
          ("distlr_tpu/serve/batcher.py:MicroBatcher",),
          dfs_runs=4000)
def scn_batcher_close_flush(rt: Runtime) -> None:
    """submit() racing close(): an ACCEPTED request must resolve (a
    closing batcher drains, it never strands a future); a request
    after close must be refused loudly."""
    np, b = _mk_batcher(max_batch_size=8, max_wait_ms=50.0)
    out: dict = {}

    def submit():
        try:
            out["fut"] = b.submit((np.ones((1, 2), np.float32),))
        except RuntimeError:
            out["refused"] = True

    t = sync.Thread(target=submit, name="submitter")
    t.start()
    b.close()
    t.join()
    if "fut" in out:
        rt.await_until(out["fut"].done, "accepted future done")
        _labels, scores = out["fut"].result(timeout=0)
        _check(float(scores[0]) == 2.0,
               "accepted-then-closed future resolved wrong")
    else:
        _check(out.get("refused", False),
               "submit neither accepted nor refused")
    _check(not b._thread.is_alive(), "flush thread alive after close()")


# ---------------------------------------------------------------------------
# 3. LabelJoiner — label vs request vs window expiry
# ---------------------------------------------------------------------------


def _mk_joiner(workdir, *, window_s=60.0, negative_rate=1.0):
    from distlr_tpu.feedback.join import LabelJoiner
    from distlr_tpu.feedback.spool import FeedbackSpool

    spool = FeedbackSpool(os.path.join(workdir, "spool"), capacity=16)
    joiner = LabelJoiner(spool, os.path.join(workdir, "shards"),
                         window_s=window_s, negative_rate=negative_rate,
                         shard_records=64, seed=0)
    return spool, joiner


def _rec(rid: str, ts: float):
    from distlr_tpu.feedback.spool import SpoolRecord
    return SpoolRecord(rid=rid, ts=ts, line="1:1", score=0.5, version=1)


@scenario("joiner_label_race",
          ("distlr_tpu/feedback/join.py:LabelJoiner",),
          dfs_runs=4000)
def scn_joiner_label_race(rt: Runtime) -> None:
    """The PR-6 guarantee: a request and its label that BOTH arrive
    inside the window must join, under every interleaving of the
    scorer, the labeler and the expiry ticker — a label may never
    strand in the pending buffer while its request negative-samples
    away."""
    with _workdir() as wd:
        spool, joiner = _mk_joiner(wd)
        assert_facade(joiner, "distlr_tpu/feedback/join.py:LabelJoiner")
        assert_facade(spool, "distlr_tpu/feedback/spool.py:FeedbackSpool")
        base = sync.wall()

        def scorer():
            joiner.scored(_rec("r1", base))
            joiner.scored(_rec("r2", base))     # never labeled

        def labeler():
            out = joiner.label("r1", 1, ts=base + 1.0)
            _check(out in ("joined", "pending"),
                   f"label outcome {out!r} for a first in-window label")

        def ticker():
            joiner.tick(now=base + 20.0)        # inside window: no-op

        tasks = [sync.Thread(target=scorer, name="scorer"),
                 sync.Thread(target=labeler, name="labeler"),
                 sync.Thread(target=ticker, name="ticker")]
        for t in tasks:
            t.start()
        for t in tasks:
            t.join()
        joiner.tick(now=base + 1000.0)          # everything resolves
        _check(joiner.joined == 1,
               f"label and request both in-window but joined="
               f"{joiner.joined} (negatives={joiner.negatives}, "
               f"pending={len(joiner._pending)}) — the label stranded")
        _check(joiner.negatives == 1,
               f"never-labeled r2 must negative-sample: negatives="
               f"{joiner.negatives}")
        _check(len(joiner._pending) == 0,
               f"{len(joiner._pending)} label(s) still pending after "
               "full expiry")


# ---------------------------------------------------------------------------
# 4. FeedbackSpool — capacity eviction vs expiry vs pop vs rotation
# ---------------------------------------------------------------------------


@scenario("spool_evict_rotation",
          ("distlr_tpu/feedback/spool.py:FeedbackSpool",),
          dfs_runs=4000)
def scn_spool_evict_rotation(rt: Runtime) -> None:
    """Record conservation under pressure: with capacity 2 and journal
    segments of 2, two adders race an expirer and a popper — every
    record must end up in exactly one of {evicted, expired, popped,
    resident}, and the on-disk segment count must hold its bound."""
    from distlr_tpu.feedback.spool import FeedbackSpool

    with _workdir() as wd:
        spool = FeedbackSpool(wd, capacity=2, segment_records=2,
                              max_segments=2, evict_scan=2)
        assert_facade(spool, "distlr_tpu/feedback/spool.py:FeedbackSpool")
        base = sync.wall()
        out = {"expired": 0, "popped": 0}

        def add_a():
            spool.add(_rec("r1", base + 1))
            spool.add(_rec("r2", base + 2))

        def add_b():
            spool.add(_rec("r3", base + 3))
            spool.add(_rec("r4", base + 4))

        def expirer():
            out["expired"] += len(spool.expire_before(base + 2.5))

        def popper():
            if spool.pop("r3") is not None:
                out["popped"] += 1

        tasks = [sync.Thread(target=add_a, name="add-a"),
                 sync.Thread(target=add_b, name="add-b"),
                 sync.Thread(target=expirer, name="expirer"),
                 sync.Thread(target=popper, name="popper")]
        for t in tasks:
            t.start()
        for t in tasks:
            t.join()
        left = len(spool)
        total = spool.evicted + out["expired"] + out["popped"] + left
        _check(spool.spooled == 4, f"spooled={spool.spooled}, want 4")
        _check(total == 4,
               f"conservation broke: evicted={spool.evicted} "
               f"expired={out['expired']} popped={out['popped']} "
               f"resident={left} (sum {total}, want 4)")
        _check(left <= 2, f"capacity bound broke: {left} resident > 2")
        segs = [n for n in os.listdir(wd) if n.startswith("spool-")]
        _check(len(segs) <= 2,
               f"journal rotation bound broke: {len(segs)} segments")
        spool.close()


# ---------------------------------------------------------------------------
# 5. ScoringRouter — eject / reinstate vs in-flight vs membership
# ---------------------------------------------------------------------------

_RESPONDER: tuple[str, object] | None = None


def _stats_responder() -> str:
    """One process-wide REAL (unmanaged) STATS responder the router's
    probe can dial.  It answers every line with ``{}`` — deterministic
    probe success.  Deliberately uses raw ``threading``: it must stay
    outside the scheduler (a managed task doing real socket IO against
    it completes without a baton handoff)."""
    global _RESPONDER
    if _RESPONDER is not None:
        return _RESPONDER[0]
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", 0))
    srv.listen(32)

    def serve():
        while True:
            try:
                conn, _ = srv.accept()
            except OSError:
                return
            try:
                f = conn.makefile("rwb")
                if f.readline():
                    f.write(b"{}\n")
                    f.flush()
            except OSError:
                pass
            finally:
                conn.close()

    t = _real_threading.Thread(target=serve, daemon=True,
                               name="schedcheck-stats-responder")
    t.start()
    addr = "127.0.0.1:%d" % srv.getsockname()[1]
    _RESPONDER = (addr, srv)
    return addr


@scenario("router_eject_inflight",
          ("distlr_tpu/serve/router.py:ScoringRouter",
           "distlr_tpu/serve/router.py:_Replica"),
          dfs_runs=6000, max_steps=6000)
def scn_router_eject_inflight(rt: Runtime) -> None:
    """Ejection/reinstatement racing in-flight accounting and elastic
    ADDREPLICA/DELREPLICA: in-flight budgets must balance, removal
    must never break a request already holding the replica, and
    healthy must stay consistent with the eject/reinstate history."""
    from distlr_tpu.serve.router import ScoringRouter

    live = _stats_responder()
    dead = "127.0.0.1:9"               # nothing listens: probe refused
    router = ScoringRouter([dead, live], max_inflight=1, eject_after=1,
                           seed=0)
    assert_facade(router, "distlr_tpu/serve/router.py:ScoringRouter")
    reps = {r.addr: r for r in router.replicas}
    assert_facade(reps[dead], "distlr_tpu/serve/router.py:_Replica")
    model = router.default_model

    def worker_fail():
        for _ in range(2):
            rep = router._acquire([])
            if rep is not None:
                router._note_failure(rep)
                router._release(rep)

    def worker_ok():
        rep = router._acquire([])
        if rep is not None:
            router._note_success(rep)
            router._release(rep)

    def admin():
        router.add_replica(model, "127.0.0.1:11")
        router.remove_replica(model, dead)

    def prober():
        router._probe(reps[live])

    tasks = [sync.Thread(target=worker_fail, name="worker-fail"),
             sync.Thread(target=worker_ok, name="worker-ok"),
             sync.Thread(target=admin, name="admin"),
             sync.Thread(target=prober, name="prober")]
    try:
        for t in tasks:
            t.start()
        for t in tasks:
            t.join()
        for rep in set(list(reps.values()) + router.replicas):
            _check(rep.inflight == 0,
                   f"replica {rep.addr}: inflight={rep.inflight} after "
                   "all requests released")
            _check(rep._sem._value == 1,
                   f"replica {rep.addr}: in-flight semaphore "
                   f"value={rep._sem._value}, want 1 — budget leak")
            _check(rep.healthy == (rep.ejections == rep.reinstates),
                   f"replica {rep.addr}: healthy={rep.healthy} but "
                   f"ejections={rep.ejections} reinstates="
                   f"{rep.reinstates} — eject/reinstate alternation "
                   "broke")
    finally:
        router.stop()


# ---------------------------------------------------------------------------
# 6. HotReloader — poll loop vs wait_for_weights vs stop
# ---------------------------------------------------------------------------


class _FakeEngine:
    def __init__(self):
        self.versions: list[int] = []
        self.has_weights = False

    def set_weights(self, w) -> None:
        self.versions.append(int(w))
        self.has_weights = True


class _FakeSource:
    """poll() fails once (degraded path) then publishes versions."""

    def __init__(self):
        self.calls = 0
        self.closed = False

    def poll(self):
        self.calls += 1
        if self.calls == 1:
            raise RuntimeError("transient source blip")
        return self.calls, self.calls

    def close(self) -> None:
        self.closed = True


@scenario("reloader_poll_swap",
          ("distlr_tpu/serve/reload.py:HotReloader",),
          dfs_runs=4000, max_steps=6000)
def scn_reloader_poll_swap(rt: Runtime) -> None:
    """The poll loop racing a foreground wait_for_weights and stop():
    versions swap monotonically, every swap is accounted, the one
    seeded source error lands in the degraded counter, and stop joins
    the loop."""
    from distlr_tpu.serve.reload import HotReloader

    eng, src = _FakeEngine(), _FakeSource()
    r = HotReloader(eng, src, interval_s=1.0, jitter=0.0, _seed=0)
    assert_facade(r, "distlr_tpu/serve/reload.py:HotReloader")
    out: dict = {}

    def waiter():
        try:
            r.wait_for_weights(timeout_s=30.0)
            out["waited"] = True
        except TimeoutError:
            out["waited"] = False

    r.start()
    t = sync.Thread(target=waiter, name="waiter")
    t.start()
    rt.await_until(lambda: r.reloads >= 2, "two reloads")
    t.join()
    r.stop()
    _check(out.get("waited") is True,
           "wait_for_weights timed out while the source was publishing")
    _check(eng.versions == sorted(eng.versions),
           f"weight versions went backwards: {eng.versions}")
    _check(len(eng.versions) == r.reloads,
           f"swap accounting drift: engine saw {len(eng.versions)} "
           f"swaps, reloader counted {r.reloads}")
    _check(r.errors == 1,
           f"seeded single source error counted {r.errors} times")
    _check(not r._thread.is_alive(), "poll loop alive after stop()")
    _check(src.closed, "source not closed by stop()")


# ---------------------------------------------------------------------------
# 7. MembershipCoordinator — resize vs client reroute reads
# ---------------------------------------------------------------------------


class _FakePlan:
    def __init__(self, new_n: int):
        self.new_n = new_n
        self.moves: list = []
        self.reuse: dict = {}
        self.spawn: list = []
        self.retire: list = []
        self.moved_keys = 0
        self.new_ranges: dict = {}


class _FakeGroup:
    """The ServerGroup surface resize() touches, minus processes and
    sockets (``ports`` empty, so fence/drain have nothing to dial) —
    the coordinator's own locking and publication order is what runs
    for real."""

    def __init__(self, num_servers=2, dim=8):
        self.num_servers = num_servers
        self.dim = dim
        self.epoch = 0
        self.has_ftrl = False
        self.ports: list[int] = []

    @property
    def hosts(self) -> str:
        return ",".join(f"127.0.0.1:{7000 + r}"
                        for r in range(self.num_servers))

    def plan_resize(self, n: int):
        if n <= 0:
            raise ValueError("bad target")
        return _FakePlan(n)

    def spawn_for_resize(self, plan, epoch) -> dict:
        return {}

    def commit_resize(self, plan, staged, epoch) -> None:
        self.num_servers = plan.new_n


@scenario("membership_resize_reroute",
          ("distlr_tpu/ps/membership.py:MembershipCoordinator",),
          dfs_runs=6000, max_steps=6000)
def scn_membership_resize_reroute(rt: Runtime) -> None:
    """resize() racing layout()/epoch/status() readers (the client
    reroute path) and a second resize: epochs observed by any reader
    are non-decreasing, an 'active' layout snapshot is always a
    CONSISTENT (epoch, num_servers) pair, and overlapping resizes are
    either serialized or refused loudly."""
    from distlr_tpu.ps.membership import (
        MembershipCoordinator,
        MembershipError,
    )

    group = _FakeGroup(num_servers=2)
    coord = MembershipCoordinator(group)
    assert_facade(coord,
                  "distlr_tpu/ps/membership.py:MembershipCoordinator")
    results: list[dict] = []
    refused = {"n": 0}
    snaps: list[list[dict]] = [[], []]

    def resizer(n):
        try:
            results.append(coord.resize(n))
        except MembershipError:
            refused["n"] += 1

    def reader(i):
        for _ in range(2):
            snaps[i].append(coord.layout())

    tasks = [sync.Thread(target=resizer, args=(4,), name="resize-4"),
             sync.Thread(target=resizer, args=(8,), name="resize-8"),
             sync.Thread(target=reader, args=(0,), name="reader-a"),
             sync.Thread(target=reader, args=(1,), name="reader-b")]
    for t in tasks:
        t.start()
    for t in tasks:
        t.join()
    _check(len(results) + refused["n"] == 2,
           "a resize neither completed nor raised")
    allowed = {(0, 2)} | {(r["epoch"], r["num_servers"]) for r in results}
    for i, seen in enumerate(snaps):
        epochs = [s["epoch"] for s in seen]
        _check(epochs == sorted(epochs),
               f"reader {i} observed epochs going backwards: {epochs}")
        for s in seen:
            if s["status"] == "active":
                pair = (s["epoch"],
                        len(s["hosts"].split(",")) if s["hosts"] else 0)
                _check(pair in allowed,
                       f"reader {i} saw TORN active layout {pair}; "
                       f"consistent pairs: {sorted(allowed)}")
    _check(coord.epoch == len(results),
           f"final epoch {coord.epoch} != {len(results)} completed "
           "resizes")


# ---------------------------------------------------------------------------
# 8. ShadowMirror — submit vs worker vs stop
# ---------------------------------------------------------------------------


@scenario("shadow_mirror_stop",
          ("distlr_tpu/serve/tenant.py:ShadowMirror",),
          dfs_runs=4000, max_steps=6000)
def scn_shadow_mirror_stop(rt: Runtime) -> None:
    """Two submitters race the mirror worker and stop(): every
    submitted mirror is processed, queued-at-stop, or was refused at
    submit — never silently lost twice-counted — and the worker thread
    never outlives stop()."""
    from distlr_tpu.serve.tenant import ShadowMirror

    sm = ShadowMirror(lambda model, line: '{"scores": [0.5]}',
                      queue_max=2, block=8)
    assert_facade(sm, "distlr_tpu/serve/tenant.py:ShadowMirror")
    accepted = {"n": 0, "refused": 0}

    def submitter():
        for _ in range(2):
            if sm.submit("v1", "v2", "1:1", [0.4]):
                accepted["n"] += 1
            else:
                accepted["refused"] += 1

    t1 = sync.Thread(target=submitter, name="submit-a")
    t2 = sync.Thread(target=submitter, name="submit-b")
    t1.start()
    t2.start()
    t1.join()
    t2.join()
    sm.stop()
    leftover = len(sm._queue)
    attempts = accepted["n"] + accepted["refused"]
    _check(sm.submitted == accepted["n"],
           f"submit() True {accepted['n']} times but submitted="
           f"{sm.submitted}")
    # FULL conservation: every attempted mirror is mirrored, errored,
    # still queued, or counted dropped (refused at submit OR shed by a
    # stop() landing mid-batch — the silent-shed accounting hole was
    # schedcheck's first real finding, fixed in serve/tenant.py)
    _check(sm.mirrored + sm.errors + leftover + sm.dropped == attempts,
           f"mirror accounting broke: mirrored={sm.mirrored} "
           f"errors={sm.errors} queued={leftover} dropped={sm.dropped} "
           f"attempts={attempts}")
    _check(sm.errors == 0, f"deterministic exchange errored {sm.errors}x")
    _check(not sm._thread.is_alive(), "mirror worker alive after stop()")


# ---------------------------------------------------------------------------
# 9. ChaosLink — stop() vs a concurrently-accepted connection
# ---------------------------------------------------------------------------


class _ScriptClosed:
    pass


class _ScriptedListener:
    """Stands in for the link's listener socket: accept() pops scripted
    connections from an instrumented queue (so the accept loop blocks
    through the scheduler), close() delivers the OSError the real
    closed listener would."""

    def __init__(self):
        self._q = sync.Queue()

    def feed(self, pair) -> None:
        self._q.put(pair)

    def accept(self):
        item = self._q.get()
        if isinstance(item, _ScriptClosed):
            self._q.put(item)      # stay closed for later accepts
            raise OSError("listener closed")
        return item

    def close(self) -> None:
        # kernel semantics: closing a listener RSTs backlog connections
        # the app never accept()ed — they die with the listener and are
        # nobody's teardown responsibility.  Only connections DELIVERED
        # through accept() become the link's to close.
        while True:
            try:
                item = self._q.get_nowait()
            except sync.Empty:
                break
            if not isinstance(item, _ScriptClosed):
                item[0].close()
        self._q.put(_ScriptClosed())

    def getsockname(self):
        return ("127.0.0.1", 0)

    def settimeout(self, t) -> None:
        pass


class _FakeSock:
    """EOF-on-read socket twin: pump threads spawned over it run their
    real teardown path immediately; close() is observable."""

    def __init__(self):
        self.closed = False

    def settimeout(self, t) -> None:
        pass

    def setsockopt(self, *a) -> None:
        pass

    def fileno(self) -> int:
        return -1 if self.closed else 99

    def recv(self, n) -> bytes:
        if self.closed:
            raise OSError("closed")
        return b""

    def sendall(self, data) -> None:
        pass

    def close(self) -> None:
        self.closed = True


class _FakeFabric:
    def now(self) -> float:
        return sync.monotonic()

    def record(self, *a, **k) -> None:
        pass


def _scripted_link():
    from distlr_tpu.chaos.plan import FaultPlan
    from distlr_tpu.chaos.proxy import ChaosLink

    made: list[_FakeSock] = []

    class _ScriptedLink(ChaosLink):
        # only the two ENDPOINT seams are substituted; the accept
        # loop, registration, pumps and stop() are the real code
        def _listen(self):
            return _ScriptedListener()

        def _connect_upstream(self):
            s = _FakeSock()
            made.append(s)
            return s

    link = _ScriptedLink(0, ("127.0.0.1", 9), FaultPlan(), _FakeFabric())
    return link, made


@scenario("chaoslink_stop_accept",
          ("distlr_tpu/chaos/proxy.py:ChaosLink",),
          dfs_runs=4000, max_steps=6000)
def scn_chaoslink_stop_accept(rt: Runtime) -> None:
    """stop() racing the accept loop mid-connection (the PR-13 fix):
    once stop() returns, no pump thread may still be live and every
    accepted socket pair must be closed — under EVERY interleaving of
    the accept processing and the teardown."""
    link, made = _scripted_link()
    assert_facade(link, "distlr_tpu/chaos/proxy.py:ChaosLink")
    down = _FakeSock()
    link._lsock.feed((down, ("127.0.0.1", 1)))

    def stopper():
        link.stop()

    t = sync.Thread(target=stopper, name="stopper")
    t.start()
    t.join()
    # the instant stop() has returned: teardown must be COMPLETE
    alive = sorted(task.name for task in rt.tasks
                   if task.name.startswith("chaos-")
                   and task.state not in (NEW, DONE))
    _check(not alive,
           f"pump/accept thread(s) {alive} still live after stop() "
           "returned — the teardown lost a concurrently-accepted "
           "connection")
    unclosed = [i for i, s in enumerate([down] + made) if not s.closed]
    _check(not unclosed,
           f"socket(s) {unclosed} not closed after stop() — the "
           "snapshot missed a concurrently-registered connection")


# ---------------------------------------------------------------------------
# 10. FleetTSDB — scrape-tick writer vs /query reader vs rule evaluator
# ---------------------------------------------------------------------------


def _tsdb_frame(i: int) -> dict:
    return {"updated": 10.0 * (i + 1),
            "ranks": [{"role": "route", "rank": 0,
                       "route_requests": 100.0 * (i + 1),
                       "route_shed": 0.0}],
            "totals": {"samples_per_s": 5.0}}


@scenario("tsdb_write_query_rollup",
          ("distlr_tpu/obs/tsdb.py:FleetTSDB",),
          dfs_runs=4000, max_steps=6000)
def scn_tsdb_write_query_rollup(rt: Runtime) -> None:
    """The scrape-tick writer racing a /query reader, the recording-
    rule evaluator, and lock-free stats() monitoring: ingest is atomic
    (a query sees a frame PREFIX, so every mid-race rate is a rate some
    serial history produces — here always 10/s once two frames exist),
    the rule's derived point lands under the store's lock, the
    monotonic stats counters never run backwards, and the final state
    is frame-count deterministic whatever the interleaving."""
    from distlr_tpu.obs.tsdb import FleetTSDB, RecordingRule

    db = FleetTSDB(raw_points=4, rollup_retention_s=1000.0)
    assert_facade(db, "distlr_tpu/obs/tsdb.py:FleetTSDB")
    rule = RecordingRule("fleet:req_rate", "rate(route_requests)", 100.0)
    queried: list = []

    def writer():
        for i in range(3):
            db.ingest(_tsdb_frame(i))

    def querier():
        for _ in range(2):
            queried.append(db.query("rate(route_requests)",
                                    window_s=100.0))

    def ruler():
        now = db.latest_time()
        if now is not None:
            rule.evaluate(db, now)

    def monitor():
        a = db.stats()
        b = db.stats()
        _check(b["points"] >= a["points"] and b["frames"] >= a["frames"],
               f"monotonic stats ran backwards: {a} -> {b}")

    tasks = [sync.Thread(target=writer, name="scrape-writer"),
             sync.Thread(target=querier, name="query-reader"),
             sync.Thread(target=ruler, name="rule-eval"),
             sync.Thread(target=monitor, name="monitor")]
    for t in tasks:
        t.start()
    for t in tasks:
        t.join()
    for q in queried:
        _check(q is None or q == 10.0,
               f"torn mid-race rate {q!r}: every frame prefix yields "
               "None (<2 frames) or exactly 10.0/s")
    _check(db.query("rate(route_requests)", window_s=100.0) == 10.0,
           "final rate drifted from the serial value")
    st = db.stats()
    # 3 frames x (2 rank fields + 1 total) + at most one rule point
    want = (9, 10)
    _check(st["frames"] == 3 and st["points"] in want,
           f"final accounting drifted: {st} (want frames=3, "
           f"points in {want})")
    _check(sum(st["dropped"].values()) == 0,
           f"bounded-tier eviction miscounted under no pressure: {st}")


# ---------------------------------------------------------------------------
# 12. AutopilotDaemon — tick loop vs stop() vs lock-free status reads
# ---------------------------------------------------------------------------


class _ScriptedFleet:
    """fetch() seam: fails once (the fail-safe hold path), then serves
    a fleet doc whose shard_lag keeps the worker band breached."""

    def __init__(self):
        self.calls = 0

    def __call__(self) -> dict:
        self.calls += 1
        if self.calls == 1:
            raise OSError("aggregator not up yet")
        return {"ranks": [{"role": "online", "rank": 0, "shard_lag": 10.0,
                           "pushes": 100.0 * self.calls,
                           "route_shed": 0.0, "route_requests": 0.0}]}


class _ScriptedWorkerActuator:
    """The worker actuator surface, minus subprocesses."""

    def __init__(self):
        self.n = 1
        self.scales: list[int] = []
        self.stopped = False

    def current(self) -> int:
        return self.n

    def scale(self, target: int) -> str:
        self.scales.append(int(target))
        self.n = int(target)
        return "ok"

    def stop_all(self) -> None:
        self.stopped = True


@scenario("autopilot_tick_stop",
          ("distlr_tpu/autopilot/daemon.py:AutopilotDaemon",),
          dfs_runs=4000, max_steps=6000)
def scn_autopilot_tick_stop(rt: Runtime) -> None:
    """The autopilot's tick loop racing concurrent lock-free status()
    reads and a stop(): the loop survives the seeded fetch failure
    (fail-safe hold, not a crash), the tick/action counters stay
    consistent with the last decision, stop() joins the loop and
    closes the actuators under EVERY interleaving."""
    from distlr_tpu.autopilot import (
        Actuators,
        AutopilotDaemon,
        PolicyConfig,
        PolicyEngine,
    )

    worker = _ScriptedWorkerActuator()
    policy = PolicyEngine(PolicyConfig(hysteresis_ticks=1, cooldown_s=0.0))
    d = AutopilotDaemon(policy, Actuators(worker=worker),
                        fetch=_ScriptedFleet(), interval_s=0.01)
    assert_facade(d, "distlr_tpu/autopilot/daemon.py:AutopilotDaemon")
    snaps: list[dict] = []

    def monitor():
        snaps.append(d.status())
        snaps.append(d.status())

    d.start()
    t = sync.Thread(target=monitor, name="monitor")
    t.start()
    rt.await_until(lambda: d.ticks >= 3, "three ticks")
    t.join()
    d.stop()
    _check(d._thread is None, "loop thread not joined by stop()")
    alive = sorted(task.name for task in rt.tasks
                   if task.name == "distlr-autopilot"
                   and task.state not in (NEW, DONE))
    _check(not alive, "autopilot loop still live after stop() returned")
    _check(worker.stopped, "actuators not closed by stop()")
    _check(d.ticks >= 3, f"tick counter lost updates: {d.ticks}")
    # the seeded fetch failure must surface as a held tick, not a crash
    _check(d.errors == 0,
           f"fail-safe hold misaccounted as actuator error: {d.errors}")
    _check(worker.scales and worker.scales[0] == 2,
           f"breached worker band never acted: {worker.scales}")
    _check(d.actions == len(worker.scales),
           f"action accounting drift: daemon {d.actions}, "
           f"actuator saw {len(worker.scales)}")
    for s in snaps:
        _check(0 <= s["actions"] <= s["ticks"] + 1,
               f"torn status() snapshot: {s}")


# ---------------------------------------------------------------------------
# 13. FleetLogger — emit writers vs flush vs incident collector vs stats
# ---------------------------------------------------------------------------


@scenario("log_ring_incident_assemble",
          ("distlr_tpu/obs/log.py:FleetLogger",),
          dfs_runs=5000, max_steps=6000)
def scn_log_ring_incident_assemble(rt: Runtime) -> None:
    """ISSUE 18: the structured-log sink's emit path (ring append +
    dedupe + journal) raced against an explicit flush, the incident
    engine's journal collector, and the deliberately lock-free stats()
    monitor.  Invariants: a WARN+ record is on disk the moment emit
    returns (the eager-flush contract the incident collector relies
    on), so every collector snapshot is a subset of the final record
    set with no torn or phantom records; the dedupe table collapses
    same-template duplicates to exactly one journaled record whatever
    the interleaving; the ring holds the newest records; and the
    monotonic stats counters never run backwards."""
    from distlr_tpu.obs import log as fleetlog
    from distlr_tpu.obs.log import FleetLogger

    with _workdir() as d:
        fl = FleetLogger(d, "serve", 0, level="info", ring=4,
                         dedupe_s=0.0)
        assert_facade(fl, "distlr_tpu/obs/log.py:FleetLogger")
        collected: list[list[dict]] = []

        def writer():
            for i in range(2):
                fl.emit("error", f"boom {i}", logger="scn")

        def flusher():
            fl.flush()

        def collector():
            collected.append(fleetlog.read_records(d, level="warning"))

        def monitor():
            a = fl.stats()
            b = fl.stats()
            _check(b["records"] >= a["records"]
                   and b["suppressed"] >= a["suppressed"],
                   f"monotonic stats ran backwards: {a} -> {b}")

        tasks = [sync.Thread(target=writer, name="emit-writer"),
                 sync.Thread(target=flusher, name="flusher"),
                 sync.Thread(target=collector, name="incident-collector"),
                 sync.Thread(target=monitor, name="monitor")]
        for t in tasks:
            t.start()
        for t in tasks:
            t.join()
        final = {r["msg"] for r in fleetlog.read_records(d)}
        _check(final == {"boom 0", "boom 1"},
               f"journal lost or tore records: {sorted(final)}")
        for snap in collected:
            msgs = [r["msg"] for r in snap]
            _check(set(msgs) <= final and len(msgs) == len(set(msgs)),
                   f"collector saw torn/phantom records: {msgs}")
        _check([r["msg"] for r in fl.tail(4)] == ["boom 0", "boom 1"],
               "ring order drifted from emit order")
        st = fl.stats()
        _check(st["records"] == 2 and st["suppressed"] == 0,
               f"accounting drifted: {st}")
        fl.close()

        # second act: the dedupe table under two racing writers with
        # the SAME template — exactly one journaled record carries the
        # window, the other three emits fold into suppressed counts,
        # first-writer-wins being schedule-dependent but the TOTALS not
        f2 = FleetLogger(d, "router", 0, level="info", ring=4,
                         dedupe_s=1000.0)

        def dup_writer():
            for _ in range(2):
                f2.emit("warning", "link flap", logger="scn")

        tasks = [sync.Thread(target=dup_writer, name="dup-a"),
                 sync.Thread(target=dup_writer, name="dup-b")]
        for t in tasks:
            t.start()
        for t in tasks:
            t.join()
        st = f2.stats()
        _check(st["records"] == 1 and st["suppressed"] == 3,
               f"dedupe accounting drifted under race: {st}")
        f2.close()
