"""The schedcheck runtime: a cooperative, deterministic scheduler for
REAL production classes.

Execution model (the CHESS one): every logical thread a scenario
spawns through the :mod:`distlr_tpu.sync` facade becomes a *task*
parked on a baton — exactly ONE task executes at any instant, and the
baton changes hands only at instrumented yield points (lock acquires,
condition waits/notifies, event sets, queue ops, thread start/join,
virtual sleeps).  The OS scheduler never chooses an interleaving;
the strategy object does, so every run is a replayable sequence of
choices and the whole interleaving space is enumerable.

Time is VIRTUAL: ``sync.monotonic()``/``sync.wall()`` read a clock
that advances only at quiescence (every task blocked, at least one
with a deadline) — a ``cv.wait(timeout)`` or ``Event.wait(timeout)``
can therefore time out deterministically, never racily, and a
scenario with a 30 s join finishes in microseconds.

Deadlock detection falls out of the model: all live tasks blocked
with no pending timer is a deadlock by construction; the failure
report prints the minimal wait-for cycle and the numbered schedule
that drove there.

This module is the checked twin of :mod:`distlr_tpu.sync` (see its
docstring): the facade's passthrough bindings are the production
build, the twins below are the verification build, and scenarios
assert via the concurrency lint's shared-state registry that the
classes under test actually created their primitives through the
facade.
"""

from __future__ import annotations

import dataclasses
import threading as _threading
import time as _time

from distlr_tpu import sync

#: real-seconds watchdog on every baton wait — a harness bug must fail
#: loudly, not hang CI
WATCHDOG_S = 60.0
#: virtual wall-clock base (sync.wall() = base + virtual monotonic)
WALL_BASE = 1_600_000_000.0

NEW, RUNNABLE, BLOCKED, DONE = "new", "runnable", "blocked", "done"


class InvariantViolation(AssertionError):
    """A scenario invariant failed under the current schedule."""


class ScheduleDivergence(RuntimeError):
    """A replayed schedule no longer matches the code (stale pin)."""


class _TaskAbort(BaseException):
    """Internal: unwind a task after the run already failed.

    Derives from BaseException so production ``except Exception``
    blocks cannot swallow the teardown.
    """


@dataclasses.dataclass
class Failure:
    kind: str      # deadlock | invariant | exception | step-budget | divergence
    message: str

    def render(self) -> str:
        return f"{self.kind}: {self.message}"


@dataclasses.dataclass
class Decision:
    """One branching point: >1 task was runnable and the strategy chose."""

    index: int
    enabled: tuple[int, ...]
    chosen: int
    current: int | None        # tid running before the choice (None: it blocked)
    #: True when a runnable current task was preempted (the CHESS cost)
    preemptive: bool


@dataclasses.dataclass
class Step:
    """One executed scheduling event (decision or forced continuation)."""

    decision: int | None       # index into decisions, None = forced
    task: str
    desc: str


@dataclasses.dataclass
class RunResult:
    scenario: str
    failure: Failure | None
    steps: list[Step]
    decisions: list[Decision]
    clock: float
    tasks: list[str]

    @property
    def schedule_id(self) -> str:
        return (self.scenario + ":"
                + ".".join(str(d.chosen) for d in self.decisions))

    def render_schedule(self) -> str:
        """The numbered schedule: one line per DECISION (the replayable
        choices — forced continuations print indented, unnumbered)."""
        out = []
        for st in self.steps:
            if st.decision is not None:
                out.append(f"{st.decision + 1:3d}. {st.task}: {st.desc}")
            else:
                out.append(f"     · {st.task}: {st.desc}")
        return "\n".join(out)

    def render_failure(self) -> str:
        """Byte-stable failure report (replay determinism is pinned on
        this string: no wall times, no object ids, no paths)."""
        assert self.failure is not None
        return (
            f"schedcheck FAILURE scenario={self.scenario}\n"
            f"schedule={self.schedule_id} "
            f"steps={len(self.decisions)} vclock={self.clock:.3f}\n"
            f"{self.failure.render()}\n"
            "schedule (numbered lines are the replayable choices):\n"
            + self.render_schedule() + "\n"
        )


def parse_schedule_id(sid: str) -> tuple[str, list[int]]:
    # split at the LAST colon: scenario names may carry a namespace
    # prefix of their own ("mutant:<name>:<choices>")
    name, _, rest = sid.rpartition(":")
    if not name:
        raise ValueError(f"bad schedule id {sid!r}")
    choices = [int(c) for c in rest.split(".") if c != ""]
    return name, choices


# ---------------------------------------------------------------------------
# strategies
# ---------------------------------------------------------------------------


class Strategy:
    """Chooses the next task at each branching point.

    ``choose`` sees the sorted enabled tids, the tid that was running
    (None when it just blocked/finished) and whether it is still
    enabled.  The DEFAULT policy — run the current task while it can
    run, else the lowest tid — is the zero-preemption baseline every
    explorer perturbs.
    """

    def choose(self, index: int, enabled: list[int],
               current: int | None, current_enabled: bool) -> int:
        if current is not None and current_enabled:
            return current
        return enabled[0]


class ReplayStrategy(Strategy):
    """Follow a recorded choice list, default policy past its end."""

    def __init__(self, choices: list[int]):
        self.choices = list(choices)

    def choose(self, index, enabled, current, current_enabled):
        if index < len(self.choices):
            want = self.choices[index]
            if want not in enabled:
                raise ScheduleDivergence(
                    f"decision {index}: schedule pins task {want} but "
                    f"enabled tasks are {enabled} — the pinned schedule "
                    "no longer matches the code")
            return want
        return super().choose(index, enabled, current, current_enabled)


class RandomStrategy(Strategy):
    """Seeded uniform choice — the fuzzing layer.  Fully replayable:
    the resulting RunResult's schedule_id pins the explicit choices."""

    def __init__(self, seed: int):
        import random
        self._rng = random.Random(seed)

    def choose(self, index, enabled, current, current_enabled):
        return self._rng.choice(enabled)


# ---------------------------------------------------------------------------
# tasks
# ---------------------------------------------------------------------------


class Task:
    __slots__ = ("tid", "name", "state", "gate", "thread", "pending",
                 "block_kind", "block_res", "deadline", "timed_out",
                 "wake_pred", "abort", "exc", "daemon")

    def __init__(self, tid: int, name: str):
        self.tid = tid
        self.name = name
        self.state = NEW
        self.gate = _threading.Event()
        self.thread: _threading.Thread | None = None
        self.pending = "start"
        self.block_kind: str | None = None   # lock|cv|event|sem|queue|join|sleep|pred
        self.block_res = None                # twin / Task / None
        self.deadline: float | None = None
        self.timed_out = False
        self.wake_pred = None
        self.abort = False
        self.exc: BaseException | None = None
        self.daemon = True

    def __repr__(self):
        return f"<task {self.tid} {self.name} {self.state}>"


# ---------------------------------------------------------------------------
# the runtime
# ---------------------------------------------------------------------------


class Runtime:
    """One controlled run.  Use :func:`run_controlled`, not this
    directly — the driver thread becomes task 0 ("main")."""

    def __init__(self, scenario: str, strategy: Strategy, *,
                 max_steps: int = 4000):
        self.scenario = scenario
        self.strategy = strategy
        self.max_steps = max_steps
        self.tasks: list[Task] = []
        self.steps: list[Step] = []
        self.decisions: list[Decision] = []
        self.failure: Failure | None = None
        self.clock = 0.0
        self.finished = False
        self._aborting = False
        self._cur: Task | None = None
        self._by_ident: dict[int, Task] = {}
        self._res_seq: dict[str, int] = {}

    # -- naming / identity -------------------------------------------------
    def _res_name(self, kind: str) -> str:
        n = self._res_seq.get(kind, 0) + 1
        self._res_seq[kind] = n
        return f"{kind}#{n}"

    def current_task(self) -> Task | None:
        return self._by_ident.get(_threading.get_ident())

    def _managed(self) -> bool:
        """True when the calling thread should go through the
        scheduler: the run is live, not unwinding, and the caller is a
        registered task."""
        return (not self.finished and not self._aborting
                and self.current_task() is not None)

    # -- virtual clock -----------------------------------------------------
    def vmonotonic(self) -> float:
        return self.clock

    def vwall(self) -> float:
        return WALL_BASE + self.clock

    # -- scheduling core ---------------------------------------------------
    def _record_step(self, task: Task, decision: int | None) -> None:
        self.steps.append(
            Step(decision, f"{task.name}({task.tid})", task.pending))
        if len(self.steps) > self.max_steps:
            self._fail(Failure(
                "step-budget",
                f"run exceeded {self.max_steps} scheduling events — "
                "livelock, or the scenario is too large for its budget"))

    def _refresh_preds(self) -> None:
        for t in self.tasks:
            if t.state != BLOCKED:
                continue
            if t.block_kind == "join":
                if t.block_res.state == DONE:
                    self._wake(t, timed_out=False)
            elif t.wake_pred is not None and t.wake_pred():
                self._wake(t, timed_out=False)

    def _wake(self, task: Task, *, timed_out: bool) -> None:
        task.state = RUNNABLE
        task.timed_out = timed_out
        task.wake_pred = None
        task.block_kind = None
        task.block_res = None
        task.deadline = None

    def _enabled(self) -> list[Task]:
        self._refresh_preds()
        return [t for t in self.tasks if t.state == RUNNABLE]

    def _advance_clock(self) -> bool:
        """At quiescence: fire the earliest deadline(s).  Returns True
        when at least one task woke."""
        due = [t for t in self.tasks
               if t.state == BLOCKED and t.deadline is not None]
        if not due:
            return False
        dmin = min(t.deadline for t in due)
        self.clock = max(self.clock, dmin)
        for t in due:
            if t.deadline <= self.clock:
                if t.block_kind == "cv":
                    # a timed-out cv waiter leaves the waiter list
                    t.block_res._waiters.remove(t)
                elif t.block_kind in ("lock", "sem", "queue"):
                    t.block_res._unwait(t)
                self._wake(t, timed_out=True)
                t.pending = f"{t.pending} [timeout @{self.clock:.3f}]"
        return True

    def _deadlock_failure(self) -> Failure:
        blocked = [t for t in self.tasks if t.state == BLOCKED]
        lines = []
        edges: dict[int, int] = {}
        for t in blocked:
            what = t.pending.removeprefix("blocked: ")
            if t.block_kind == "lock" and t.block_res._owner is not None:
                owner = t.block_res._owner
                edges[t.tid] = owner.tid
                lines.append(f"  {t.name} blocked: {what} "
                             f"(held by {owner.name})")
            elif t.block_kind == "join":
                edges[t.tid] = t.block_res.tid
                lines.append(f"  {t.name} blocked: {what}")
            else:
                lines.append(f"  {t.name} blocked: {what} "
                             "(no pending wakeup — lost notify?)")
        # minimal wait-for cycle: each task has <= 1 outgoing edge, so
        # a walk with a visited set finds the cycle if one exists
        cycle = None
        by_tid = {t.tid: t for t in self.tasks}
        for start in sorted(edges):
            seen: list[int] = []
            cur = start
            while cur in edges and cur not in seen:
                seen.append(cur)
                cur = edges[cur]
            if cur in seen:
                loop = seen[seen.index(cur):] + [cur]
                cycle = " -> ".join(by_tid[tid].name for tid in loop)
                break
        msg = "all live tasks blocked; no pending timer\n" + "\n".join(lines)
        if cycle:
            msg += f"\n  wait-for cycle: {cycle}"
        return Failure("deadlock", msg)

    def _pick_next(self, *, current_ok: bool) -> Task:
        """Choose who runs next.  Raises _TaskAbort via _fail when the
        system is deadlocked."""
        while True:
            enabled = self._enabled()
            if enabled:
                break
            if not self._advance_clock():
                self._fail(self._deadlock_failure())
        if len(enabled) == 1:
            chosen = enabled[0]
            self._record_step(chosen, None)
            return chosen
        cur = self._cur if current_ok else None
        cur_tid = cur.tid if cur is not None else None
        cur_enabled = cur is not None and cur.state == RUNNABLE
        tids = [t.tid for t in enabled]
        idx = len(self.decisions)
        try:
            tid = self.strategy.choose(idx, tids, cur_tid, cur_enabled)
        except ScheduleDivergence as e:
            self._fail(Failure("divergence", str(e)))
        if tid not in tids:
            self._fail(Failure(
                "divergence", f"strategy chose tid {tid} not in {tids}"))
        chosen = next(t for t in enabled if t.tid == tid)
        self.decisions.append(Decision(
            idx, tuple(tids), tid, cur_tid,
            preemptive=cur_enabled and tid != cur_tid))
        self._record_step(chosen, idx)
        return chosen

    def _handoff(self, cur: Task, nxt: Task) -> None:
        if nxt is cur:
            return
        self._cur = nxt
        cur.gate.clear()
        nxt.gate.set()
        self._wait_gate(cur)

    def _wait_gate(self, task: Task) -> None:
        if not task.gate.wait(WATCHDOG_S):
            # harness bug — fail loudly rather than hang the test run
            self.failure = self.failure or Failure(
                "step-budget", f"watchdog: {task.name} never rescheduled")
            raise _TaskAbort()
        if task.abort:
            raise _TaskAbort()

    def yield_point(self, desc: str) -> None:
        """The instrumented preemption point: the running task offers
        the scheduler a switch before performing ``desc``."""
        cur = self.current_task()
        if cur is None:
            return
        cur.pending = desc
        nxt = self._pick_next(current_ok=True)
        self._handoff(cur, nxt)

    def block(self, kind: str, res, desc: str, *,
              deadline: float | None = None, wake_pred=None) -> bool:
        """Park the current task.  Returns True when it was woken by a
        timeout (vs granted/notified)."""
        cur = self.current_task()
        assert cur is not None
        cur.state = BLOCKED
        cur.block_kind = kind
        cur.block_res = res
        cur.deadline = deadline
        cur.wake_pred = wake_pred
        cur.timed_out = False
        cur.pending = f"blocked: {desc}"
        nxt = self._pick_next(current_ok=False)
        self._handoff(cur, nxt)
        # if _pick_next woke US (timer/pred with no other runnable),
        # _handoff was a no-op and we continue directly
        return cur.timed_out

    def _fail(self, failure: Failure) -> None:
        if self.failure is None:
            self.failure = failure
        self._abort_all()
        raise _TaskAbort()

    def _abort_all(self) -> None:
        self._aborting = True
        for t in self.tasks:
            if t.state in (RUNNABLE, BLOCKED) and t is not self.current_task():
                t.abort = True
                t.gate.set()

    # -- task lifecycle ----------------------------------------------------
    def _register_main(self) -> Task:
        t = Task(0, "main")
        t.state = RUNNABLE
        t.thread = _threading.current_thread()
        t.gate.set()
        self.tasks.append(t)
        self._by_ident[_threading.get_ident()] = t
        self._cur = t
        return t

    def spawn_task(self, name: str, fn, args, kwargs) -> Task:
        task = Task(len(self.tasks), name)
        self.tasks.append(task)

        def body():
            self._by_ident[_threading.get_ident()] = task
            try:
                self._wait_gate(task)
                fn(*args, **kwargs)
            except _TaskAbort:
                pass
            except BaseException as e:  # noqa: BLE001 — a dying task IS the finding
                task.exc = e
                if self.failure is None and not self._aborting:
                    self.failure = Failure(
                        "exception",
                        f"task {name} died: {type(e).__name__}: {e}")
                    self._abort_all()
            finally:
                task.state = DONE
                task.pending = "done"
                if not self._aborting and not self.finished:
                    try:
                        nxt = self._pick_next(current_ok=False)
                        self._cur = nxt
                        nxt.gate.set()
                    except _TaskAbort:
                        pass

        task.thread = _threading.Thread(
            target=body, daemon=True, name=f"schedcheck-{name}")
        task.thread.start()
        return task

    def start_task(self, task: Task) -> None:
        cur = self.current_task()
        assert cur is not None
        # runnable FIRST: the spawn point itself is a branch where the
        # child may run before the spawner's next instruction
        task.state = RUNNABLE
        self.yield_point(f"thread.start {task.name}")

    # -- scenario helpers --------------------------------------------------
    def await_until(self, pred, desc: str = "condition",
                    timeout: float | None = None) -> bool:
        """Block the calling task until ``pred()`` holds (re-evaluated
        at every scheduling step; must be side-effect-free).  Returns
        False on (virtual) timeout."""
        self.yield_point(f"await {desc}")
        if pred():
            return True
        deadline = None if timeout is None else self.clock + timeout
        timed_out = self.block("pred", None, f"await {desc}",
                               deadline=deadline, wake_pred=pred)
        return not timed_out

    def fail_invariant(self, message: str) -> None:
        raise InvariantViolation(message)


# ---------------------------------------------------------------------------
# instrumented twins.  Three regimes per call:
#
# * LIVE — the run is active and the caller is a managed task: full
#   scheduler semantics.
# * UNWIND — the run failed and tasks are tearing down through
#   production ``finally`` blocks: permissive non-blocking no-ops, so
#   unwinding can never re-enter (or hang) the dead scheduler.
# * ESCAPED — the run is over but the twin leaked out (cached in a
#   global, returned from a scenario): degrade to REAL stdlib behavior
#   via a lazily-created fallback primitive — mutual exclusion and
#   blocking semantics are preserved for whatever outlives the run.
#
# Out of scope (documented, not supported): an UNMANAGED thread
# touching a twin while its run is still live — the scheduler cannot
# wake managed waiters from outside the baton, so scenarios must keep
# twins inside the managed task set (the factories already hand
# unmanaged callers real stdlib objects at creation time).
# ---------------------------------------------------------------------------


class _TwinBase:
    def __init__(self, rt: Runtime, kind: str):
        self._rt = rt
        self.name = rt._res_name(kind)
        self._fallback = None

    def _live(self) -> bool:
        return self._rt._managed()

    def _escaped(self) -> bool:
        return self._rt.finished

    def _real(self, ctor):
        if self._fallback is None:
            self._fallback = ctor()
        return self._fallback


class TLock(_TwinBase):
    _reentrant = False

    def __init__(self, rt: Runtime, kind: str | None = None):
        super().__init__(rt, kind or type(self).__name__.lstrip("T"))
        self._owner: Task | None = None
        self._count = 0
        self._waiters: list[Task] = []

    def _unwait(self, task: Task) -> None:
        if task in self._waiters:
            self._waiters.remove(task)

    def acquire(self, blocking: bool = True, timeout: float = -1):
        rt = self._rt
        if not self._live():
            if not self._escaped():
                return True      # mid-run unwind: permissive
            real = self._real(_threading.RLock if self._reentrant
                              else _threading.Lock)
            if timeout is not None and timeout >= 0:
                return real.acquire(blocking, timeout)
            return real.acquire(blocking)
        cur = rt.current_task()
        rt.yield_point(f"acquire {self.name}")
        if self._owner is None or (self._reentrant and self._owner is cur):
            self._owner = cur
            self._count += 1
            return True
        if not blocking:
            return False
        deadline = (None if timeout is None or timeout < 0
                    else rt.clock + timeout)
        self._waiters.append(cur)
        timed_out = rt.block("lock", self, f"acquire {self.name}",
                             deadline=deadline)
        if timed_out:
            return False
        # granted by release(): ownership was transferred to us there
        assert self._owner is cur
        return True

    def release(self) -> None:
        rt = self._rt
        if not self._live():
            if self._escaped():
                try:
                    self._real(_threading.RLock if self._reentrant
                               else _threading.Lock).release()
                except RuntimeError:
                    pass
            return
        cur = rt.current_task()
        if self._owner is not cur:
            raise RuntimeError(f"release of un-acquired {self.name}")
        self._count -= 1
        if self._count:
            return
        self._owner = None
        if self._waiters:
            nxt = self._waiters.pop(0)   # deterministic FIFO handoff
            self._owner = nxt
            self._count = 1
            rt._wake(nxt, timed_out=False)
            nxt.pending = f"acquire {self.name} (granted)"

    def locked(self) -> bool:
        return self._owner is not None

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class TRLock(TLock):
    _reentrant = True

    def __init__(self, rt: Runtime):
        super().__init__(rt, "RLock")


class TCondition(_TwinBase):
    def __init__(self, rt: Runtime, lock=None):
        super().__init__(rt, "Condition")
        self._lock = lock if lock is not None else TLock(rt, "Condition.Lock")
        self._waiters: list[Task] = []

    # escaped twins delegate the WHOLE interface to one real Condition
    # (lock included — pairing the twin lock's separate fallback with a
    # real condition's internal lock would never match ownership)
    def _esc(self):
        return self._real(_threading.Condition)

    # delegate the lock interface
    def acquire(self, *a, **k):
        if not self._live() and self._escaped():
            return self._esc().acquire(*a, **k)
        return self._lock.acquire(*a, **k)

    def release(self):
        if not self._live() and self._escaped():
            try:
                self._esc().release()
            except RuntimeError:
                pass
            return
        return self._lock.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def wait(self, timeout: float | None = None) -> bool:
        rt = self._rt
        if not self._live():
            if self._escaped():
                return self._esc().wait(timeout)
            return False         # mid-run unwind: spurious wakeup
        cur = rt.current_task()
        if self._lock._owner is not cur:
            raise RuntimeError(f"cv.wait on un-owned {self.name}")
        rt.yield_point(f"cv.wait {self.name}")
        # fully release (rlock-aware), remember the depth to restore
        saved, self._lock._count = self._lock._count, 1
        self._lock.release()
        self._waiters.append(cur)
        deadline = None if timeout is None else rt.clock + timeout
        timed_out = rt.block("cv", self, f"cv.wait {self.name}",
                             deadline=deadline)
        # re-acquire before returning, like the stdlib
        self._lock.acquire()
        self._lock._count = saved
        return not timed_out

    def wait_for(self, predicate, timeout: float | None = None):
        rt = self._rt
        endtime = None if timeout is None else rt.clock + timeout
        result = predicate()
        while not result:
            waittime = None
            if endtime is not None:
                waittime = endtime - rt.clock
                if waittime <= 0:
                    break
            self.wait(waittime)
            result = predicate()
        return result

    def notify(self, n: int = 1) -> None:
        rt = self._rt
        if not self._live():
            if self._escaped():
                self._esc().notify(n)
            return
        if self._lock._owner is not rt.current_task():
            raise RuntimeError(f"cv.notify on un-owned {self.name}")
        rt.yield_point(f"cv.notify {self.name}")
        for _ in range(min(n, len(self._waiters))):
            t = self._waiters.pop(0)
            rt._wake(t, timed_out=False)
            t.pending = f"cv.wait {self.name} (notified)"

    def notify_all(self) -> None:
        if not self._live() and self._escaped():
            self._esc().notify_all()
            return
        self.notify(len(self._waiters) or 1)


class TEvent(_TwinBase):
    def __init__(self, rt: Runtime):
        super().__init__(rt, "Event")
        self._flag = False
        self._waiters: list[Task] = []

    def _esc(self):
        ev = self._real(_threading.Event)
        if self._flag and not ev.is_set():
            ev.set()             # carry the run-time flag over
        return ev

    def is_set(self) -> bool:
        return self._flag

    def set(self) -> None:
        rt = self._rt
        if not self._live():
            self._flag = True
            if self._escaped():
                self._esc().set()
            return
        rt.yield_point(f"event.set {self.name}")
        self._flag = True
        waiters, self._waiters = self._waiters, []
        for t in waiters:
            rt._wake(t, timed_out=False)
            t.pending = f"event.wait {self.name} (set)"

    def clear(self) -> None:
        self._flag = False
        if self._fallback is not None:
            self._fallback.clear()

    def wait(self, timeout: float | None = None) -> bool:
        rt = self._rt
        if not self._live():
            if self._escaped():
                return self._esc().wait(timeout)
            return self._flag    # mid-run unwind: never block
        cur = rt.current_task()
        rt.yield_point(f"event.wait {self.name}")
        if self._flag:
            return True
        deadline = None if timeout is None else rt.clock + timeout
        self._waiters.append(cur)
        rt.block("event", self, f"event.wait {self.name}",
                 deadline=deadline)
        if cur in self._waiters:
            self._waiters.remove(cur)
        return self._flag


class TSemaphore(_TwinBase):
    _bounded = False

    def __init__(self, rt: Runtime, value: int = 1):
        super().__init__(
            rt, "BoundedSemaphore" if self._bounded else "Semaphore")
        if value < 0:
            raise ValueError("semaphore initial value must be >= 0")
        self._value = value
        self._initial = value
        self._waiters: list[Task] = []

    def _unwait(self, task: Task) -> None:
        if task in self._waiters:
            self._waiters.remove(task)

    def acquire(self, blocking: bool = True, timeout: float | None = None):
        rt = self._rt
        if not self._live():
            if not self._escaped():
                return True      # mid-run unwind: permissive
            real = self._real(
                lambda: _threading.Semaphore(max(self._value, 0)))
            return real.acquire(blocking, timeout)
        cur = rt.current_task()
        rt.yield_point(f"sem.acquire {self.name}")
        if self._value > 0:
            self._value -= 1
            return True
        if not blocking:
            return False
        deadline = None if timeout is None else rt.clock + timeout
        self._waiters.append(cur)
        timed_out = rt.block("sem", self, f"sem.acquire {self.name}",
                             deadline=deadline)
        return not timed_out

    def release(self, n: int = 1) -> None:
        rt = self._rt
        if not self._live():
            if self._escaped():
                # the real fallback (seeded in acquire) takes over; the
                # bounded over-release guard does not survive escape
                self._real(
                    lambda: _threading.Semaphore(max(self._value, 0))
                ).release(n)
            else:
                self._value += n
            return
        if self._bounded and self._value + n > self._initial:
            raise ValueError("Semaphore released too many times")
        rt.yield_point(f"sem.release {self.name}")
        for _ in range(n):
            if self._waiters:
                t = self._waiters.pop(0)   # direct handoff, no +1
                rt._wake(t, timed_out=False)
                t.pending = f"sem.acquire {self.name} (granted)"
            else:
                self._value += 1

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class TBoundedSemaphore(TSemaphore):
    _bounded = True


class TQueue(_TwinBase):
    def __init__(self, rt: Runtime, maxsize: int = 0):
        super().__init__(rt, "Queue")
        self.maxsize = maxsize
        self._items: list = []
        self._getters: list[Task] = []
        self._putters: list[Task] = []

    def _unwait(self, task: Task) -> None:
        for lst in (self._getters, self._putters):
            if task in lst:
                lst.remove(task)

    def _esc(self):
        """Escaped queue: migrate run-time items into a real Queue once
        and delegate from then on (blocking get/put stay blocking)."""
        import queue as _q
        q = self._fallback
        if q is None:
            q = self._fallback = _q.Queue(self.maxsize)
            for item in self._items:
                q.put_nowait(item)
            self._items = []
        return q

    def qsize(self) -> int:
        if self._fallback is not None:
            return self._fallback.qsize()
        return len(self._items)

    def empty(self) -> bool:
        return self.qsize() == 0

    def full(self) -> bool:
        if self._fallback is not None:
            return self._fallback.full()
        return 0 < self.maxsize <= len(self._items)

    def put(self, item, block: bool = True, timeout: float | None = None):
        rt = self._rt
        if not self._live():
            if self._escaped():
                self._esc().put(item, block, timeout)
            else:
                self._items.append(item)
            return
        cur = rt.current_task()
        rt.yield_point(f"queue.put {self.name}")
        while self.full():
            if not block:
                raise sync.Full
            deadline = None if timeout is None else rt.clock + timeout
            self._putters.append(cur)
            if rt.block("queue", self, f"queue.put {self.name}",
                        deadline=deadline):
                raise sync.Full
        self._items.append(item)
        if self._getters:
            t = self._getters.pop(0)
            rt._wake(t, timed_out=False)
            t.pending = f"queue.get {self.name} (item ready)"

    def put_nowait(self, item):
        self.put(item, block=False)

    def get(self, block: bool = True, timeout: float | None = None):
        rt = self._rt
        if not self._live():
            if self._escaped():
                return self._esc().get(block, timeout)
            if self._items:
                return self._items.pop(0)
            raise sync.Empty     # mid-run unwind: never block
        cur = rt.current_task()
        rt.yield_point(f"queue.get {self.name}")
        while not self._items:
            if not block:
                raise sync.Empty
            deadline = None if timeout is None else rt.clock + timeout
            self._getters.append(cur)
            if rt.block("queue", self, f"queue.get {self.name}",
                        deadline=deadline):
                raise sync.Empty
        item = self._items.pop(0)
        if self._putters:
            t = self._putters.pop(0)
            rt._wake(t, timed_out=False)
            t.pending = f"queue.put {self.name} (space ready)"
        return item

    def get_nowait(self):
        return self.get(block=False)


class TThread:
    """Twin of ``threading.Thread`` for scenario-spawned logical
    threads.  ``start`` registers a scheduler task; ``join`` blocks
    through the scheduler (virtual-time deadline)."""

    def __init__(self, rt: Runtime, group=None, target=None, name=None,
                 args=(), kwargs=None, *, daemon=None):
        self._rt = rt
        self._target = target
        self._args = args
        self._kwargs = kwargs or {}
        self.name = name or f"thread-{len(rt.tasks)}"
        self.daemon = bool(daemon)
        self._task: Task | None = None

    def start(self) -> None:
        rt = self._rt
        if self._task is not None:
            raise RuntimeError("threads can only be started once")
        if not rt._managed():
            # escape hatch: spawn a real thread (run over / unmanaged)
            t = _threading.Thread(target=self._target, name=self.name,
                                  args=self._args, kwargs=self._kwargs,
                                  daemon=self.daemon)
            self._task = t
            t.start()
            return
        task = rt.spawn_task(self.name, self._target, self._args,
                             self._kwargs)
        task.daemon = self.daemon
        self._task = task
        rt.start_task(task)

    def join(self, timeout: float | None = None) -> None:
        rt = self._rt
        task = self._task
        if task is None:
            raise RuntimeError("cannot join thread before it is started")
        if isinstance(task, _threading.Thread):
            task.join(timeout)
            return
        if not rt._managed():
            # escaped join: fall through to the underlying OS thread
            if rt.finished and task.thread is not None:
                task.thread.join(timeout)
            return
        rt.yield_point(f"join {task.name}")
        if task.state == DONE:
            return
        deadline = None if timeout is None else rt.clock + timeout
        rt.block("join", task, f"join {task.name}", deadline=deadline)

    def is_alive(self) -> bool:
        task = self._task
        if task is None:
            return False
        if isinstance(task, _threading.Thread):
            return task.is_alive()
        return task.state in (RUNNABLE, BLOCKED)

    @property
    def ident(self):
        return None if self._task is None else id(self._task)


# ---------------------------------------------------------------------------
# install / run
# ---------------------------------------------------------------------------


def _twin_factories(rt: Runtime) -> dict:
    """The sync.install map: managed callers get twins, everyone else
    keeps real stdlib objects (so an install is safe in a process with
    unrelated live threads)."""
    def gate(twin_ctor, real_ctor):
        def make(*a, **k):
            if rt._managed():
                return twin_ctor(rt, *a, **k)
            return real_ctor(*a, **k)
        return make

    import queue as _q

    def v_monotonic():
        return rt.vmonotonic() if rt._managed() else _time.monotonic()

    def v_wall():
        return rt.vwall() if rt._managed() else _time.time()

    def v_sleep(s):
        if float(s) < 0:
            # stdlib parity: time.sleep(negative) raises — the twin
            # must too, or schedcheck can never catch the
            # negative-sleep-kills-the-thread bug class
            raise ValueError("sleep length must be non-negative")
        if rt._managed():
            rt.yield_point(f"sleep {s:g}")
            rt.block("sleep", None, f"sleep {s:g}",
                     deadline=rt.clock + float(s))
        else:
            _time.sleep(s)

    def cond(lock=None):
        if rt._managed():
            if lock is None or isinstance(lock, TLock):
                return TCondition(rt, lock)
        return _threading.Condition(lock)

    return {
        "Lock": gate(TLock, _threading.Lock),
        "RLock": gate(TRLock, _threading.RLock),
        "Condition": cond,
        "Event": gate(TEvent, _threading.Event),
        "Semaphore": gate(TSemaphore, _threading.Semaphore),
        "BoundedSemaphore": gate(TBoundedSemaphore,
                                 _threading.BoundedSemaphore),
        "Thread": gate(TThread, _threading.Thread),
        "Queue": gate(TQueue, _q.Queue),
        "monotonic": v_monotonic,
        "wall": v_wall,
        "sleep": v_sleep,
    }


def run_controlled(scenario: str, scenario_fn, strategy: Strategy, *,
                   max_steps: int = 4000) -> RunResult:
    """Run ``scenario_fn(rt)`` as task 0 under ``strategy``; returns
    the RunResult (failure captured, never raised — explorers decide
    what a failure means)."""
    rt = Runtime(scenario, strategy, max_steps=max_steps)
    main = rt._register_main()
    sync.install(_twin_factories(rt), owner=rt)
    try:
        try:
            scenario_fn(rt)
            # drain any still-running started tasks so a run's side
            # effects are complete before invariants/teardown compare
            rt.await_until(
                lambda: all(t.state in (NEW, DONE)
                            for t in rt.tasks if t is not main),
                "all tasks done")
        except _TaskAbort:
            pass
        except InvariantViolation as e:
            if rt.failure is None:
                rt.failure = Failure("invariant", str(e))
        except ScheduleDivergence as e:
            if rt.failure is None:
                rt.failure = Failure("divergence", str(e))
        except Exception as e:  # noqa: BLE001 — scenario bug or real finding
            if rt.failure is None:
                rt.failure = Failure(
                    "exception", f"main died: {type(e).__name__}: {e}")
    finally:
        rt.finished = True
        rt._aborting = True
        for t in rt.tasks:
            if t is not main:
                t.abort = True
                t.gate.set()
        sync.uninstall(owner=rt)
        for t in rt.tasks:
            if t.thread is not None and t is not main:
                t.thread.join(timeout=5.0)
    return RunResult(scenario=scenario, failure=rt.failure,
                     steps=rt.steps, decisions=rt.decisions,
                     clock=rt.clock, tasks=[t.name for t in rt.tasks])
