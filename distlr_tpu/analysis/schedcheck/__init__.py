"""schedcheck — deterministic-interleaving execution of the real
Python fleet (distlr-lint pass 6).

PR 13's concurrency lint finds lock-discipline smells *syntactically*
and PR 14 model-checks the protocol *spec*; this package verifies the
*implementation*: the real ``MicroBatcher``/``LabelJoiner``/
``FeedbackSpool``/``ScoringRouter``/``HotReloader``/
``MembershipCoordinator``/``ShadowMirror``/``ChaosLink`` classes run
single-stream under a cooperative scheduler
(:mod:`~distlr_tpu.analysis.schedcheck.runtime`), with every
interleaving choice made by an explorer
(:mod:`~distlr_tpu.analysis.schedcheck.explore`) instead of the OS:

* bounded-exhaustive DFS with CHESS-style preemption bounding;
* seeded random-schedule fuzzing, every run replayable by id;
* a deadlock detector printing the minimal wait-for cycle;
* per-scenario invariants
  (:mod:`~distlr_tpu.analysis.schedcheck.scenarios`), cross-checked
  against the concurrency lint's shared-state registry;
* mutant mode (:mod:`~distlr_tpu.analysis.schedcheck.mutants`):
  reverting the PR-6 joiner check-then-insert fix and the PR-13
  ``ChaosLink.stop()`` snapshot fix must each be REDISCOVERED as a
  ≤ 20-step replayable counterexample schedule.

Production code opts in by creating its primitives through the
:mod:`distlr_tpu.sync` facade (zero-overhead stdlib passthrough in
normal runs).  Entry points: ``python -m
distlr_tpu.analysis.schedcheck`` / ``make verify-sched`` (fast) /
``make verify-sched-full`` (deep DFS), and pass 6 of ``python -m
distlr_tpu.analysis``.  Everything is jax-free.
"""

from distlr_tpu.analysis.schedcheck.runtime import (  # noqa: F401
    InvariantViolation,
    RunResult,
    parse_schedule_id,
)
