"""distlr_tpu — a TPU-native distributed linear-model training framework.

A from-scratch JAX/XLA/Pallas re-design of the capabilities of
``future-xy/dist-lr`` (a C++ parameter-server logistic-regression trainer,
see ``/root/reference``):

* **Data layer** (:mod:`distlr_tpu.data`) — libsvm parsing (native C++ fast
  path + pure-Python fallback), epoch iterators, seeded synthetic data,
  shard generation.  Replaces ``include/data_iter.h`` / ``examples/gen_data.py``.
* **Models** (:mod:`distlr_tpu.models`) — dense binary logistic regression,
  multinomial softmax regression, sparse one-hot LR — all pure-functional
  JAX.  Replaces ``src/lr.cc`` / ``include/lr.h``.
* **Parallel** (:mod:`distlr_tpu.parallel`) — device meshes, synchronous
  data parallelism via ``lax.psum`` over ICI, feature-axis (model) sharding
  for very wide models.  Replaces the worker/server BSP protocol of
  ``src/main.cc`` with a single compiled SPMD program.
* **PS** (:mod:`distlr_tpu.ps`) — an asynchronous parameter-server mode:
  a native C++ KV server with Push/Pull/Wait and deferred-response
  barriers, the TPU-native equivalent of the ps-lite runtime the reference
  links against.
* **Train** (:mod:`distlr_tpu.train`) — trainer loops (sync SPMD and async
  PS), metrics, checkpointing (orbax + reference-compatible text export).
* **Serve** (:mod:`distlr_tpu.serve`) — the online scoring tier the
  reference never had (its ``SaveModel`` output is write-only): bucketed
  jitted batched scoring, request microbatching, and hot weight reload
  from checkpoints or a LIVE KV server group while training runs.
* **Launch** (:mod:`distlr_tpu.launch`) — single-host / multi-process
  launcher replacing ``examples/local.sh``.

The sync fast path is *one* jitted SPMD step: per-shard gradients are
``lax.psum``-reduced over the mesh's ``data`` axis and the SGD update is
applied replicated — the reference's Push/accumulate/apply/Pull round-trip
(``src/main.cc:41-96``, ``src/lr.cc:116-132``) collapsed into a collective.
"""

__version__ = "0.1.0"

from distlr_tpu.config import Config  # noqa: F401
