"""Multi-tenant serving primitives — many models, one fleet.

The routing tier (:mod:`distlr_tpu.serve.router`) balances replicas of
ONE model; production traffic means many model versions live in the
fleet at once.  This module holds the jax-free pieces the router and
the front-end share to make model identity first-class:

* :func:`parse_model_spec` — the ``v1=host:p+host:p,v2=host:p`` replica
  registry grammar (backward compatible: a spec without ``=`` is the
  old single-model form under :data:`DEFAULT_MODEL`).
* :class:`TenantQuota` — a token-bucket admission budget per tenant,
  layered ON TOP of the router's bounded in-flight sheds: a tenant past
  its rate gets an explicit ``ERR SHED tenant`` (its own counter,
  distinct from capacity sheds — "this tenant is over budget" and "the
  tier is out of capacity" page different people).
* :class:`ShadowMirror` — fire-and-forget mirroring of a fraction of a
  tenant's traffic to a candidate model version, strictly OFF the reply
  path (a bounded queue + worker thread; a full queue drops the mirror,
  never delays the primary), comparing primary vs candidate score
  distributions with the same block-wise PSI the drift detector uses
  (``distlr_tenant_shadow_psi{tenant,candidate}``).

Tenant identity == model id: each hosted model version belongs to the
tenant that addressed it (``MODEL <id>`` scoped connections or a
per-request ``@<id>`` prefix — both additive protocol extensions, like
STATS and TRACE before them).
"""

from __future__ import annotations

import json

import numpy as np

from distlr_tpu import sync
from distlr_tpu.obs.registry import get_registry
from distlr_tpu.utils.logging import get_logger

log = get_logger(__name__)

#: model id of unaddressed (pre-tenant) traffic — a spec without ``=``
#: registers its replicas here, so old clients and old replica lists
#: keep working byte-identically
DEFAULT_MODEL = "default"

_reg = get_registry()
_TENANT_REQUESTS = _reg.counter(
    "distlr_tenant_requests_total",
    "request lines answered per tenant (model id) across the fleet",
    labelnames=("model",),
)
_TENANT_SHED = _reg.counter(
    "distlr_tenant_shed_total",
    "request lines shed by a tenant's token-bucket admission quota "
    "(distinct from distlr_route_shed_total capacity sheds: quota = "
    "'this tenant is over budget', capacity = 'scale the tier up')",
    labelnames=("model",),
)
_TENANT_MODELS = _reg.gauge(
    "distlr_tenant_models",
    "model versions currently registered in this routing tier",
)
_SHADOW_TOTAL = _reg.counter(
    "distlr_tenant_shadow_total",
    "requests mirrored to a candidate model version, by outcome "
    "(scored / error / dropped — dropped means the bounded mirror "
    "queue was full, the primary reply is NEVER delayed)",
    labelnames=("tenant", "candidate", "outcome"),
)
_SHADOW_PSI = _reg.gauge(
    "distlr_tenant_shadow_psi",
    "population stability index between a tenant's primary score "
    "distribution and its shadow candidate's, per completed comparison "
    "block (the promote/rollback evidence a canary ramp reads)",
    labelnames=("tenant", "candidate"),
)


def parse_model_spec(spec) -> dict[str, list[str]]:
    """Replica-registry grammar -> ordered ``{model_id: [host:port, ...]}``.

    ``"v1=h:1+h:2,v2=h:3"`` — models separated by commas, a model's
    replicas by ``+``.  ``"h:1,h:2"`` (no ``=`` anywhere) is the
    pre-tenant single-model form: all addresses under
    :data:`DEFAULT_MODEL`.  Also accepts an existing mapping or a plain
    address list (normalized copies are returned).
    """
    if isinstance(spec, dict):
        out = {str(m): list(a) for m, a in spec.items()}
    elif isinstance(spec, (list, tuple)):
        out = {DEFAULT_MODEL: [str(a).strip() for a in spec if str(a).strip()]}
    else:
        spec = str(spec)
        if "=" not in spec:
            out = {DEFAULT_MODEL: [a.strip() for a in spec.split(",")
                                   if a.strip()]}
        else:
            out = {}
            for part in spec.split(","):
                part = part.strip()
                if not part:
                    continue
                model, eq, addrs = part.partition("=")
                model = model.strip()
                if not eq or not model:
                    raise ValueError(
                        f"bad model spec entry {part!r} (want "
                        "model=host:port+host:port)")
                if model in out:
                    raise ValueError(f"duplicate model id {model!r} in spec")
                out[model] = [a.strip() for a in addrs.split("+") if a.strip()]
    for model, addrs in out.items():
        if not addrs:
            raise ValueError(f"model {model!r} has no replica addresses")
        if len(set(addrs)) != len(addrs):
            raise ValueError(
                f"duplicate replica addresses for model {model!r}: {addrs}")
        if any(c in model for c in " \t@=,+"):
            raise ValueError(f"bad model id {model!r} (no spaces or @=,+)")
    if not out:
        raise ValueError("model spec names no models")
    return out


def parse_quota_spec(spec) -> dict[str, "TenantQuota"]:
    """``"v1=100:200,v2=50"`` -> ``{model: TenantQuota(rate, burst)}``
    (``rate`` requests/s, optional ``:burst`` bucket depth, default
    ``2*rate``).  Also accepts a ready mapping."""
    if not spec:
        return {}
    if isinstance(spec, dict):
        return {str(m): q if isinstance(q, TenantQuota) else TenantQuota(*q)
                for m, q in spec.items()}
    out: dict[str, TenantQuota] = {}
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        model, eq, rest = part.partition("=")
        if not eq or not model.strip():
            raise ValueError(
                f"bad quota entry {part!r} (want model=rate[:burst])")
        if model.strip() in out:
            # same rule as parse_model_spec: a silent overwrite would
            # ship a typo'd quota as the effective one
            raise ValueError(f"duplicate quota for model {model.strip()!r}")
        rate, _, burst = rest.partition(":")
        try:
            rate_f = float(rate)
            burst_f = float(burst) if burst else 2.0 * rate_f
        except ValueError as e:
            raise ValueError(f"bad quota entry {part!r}: {e}") from None
        out[model.strip()] = TenantQuota(rate_f, burst_f)
    return out


class TenantQuota:
    """Token-bucket admission budget: ``rate`` tokens/s refill into a
    bucket of depth ``burst``; each admitted request spends one.
    Thread-safe; monotonic-clock driven (no background thread)."""

    def __init__(self, rate: float, burst: float | None = None):
        if rate <= 0:
            raise ValueError(f"quota rate must be positive, got {rate}")
        self.rate = float(rate)
        self.burst = float(burst) if burst is not None else 2.0 * self.rate
        if self.burst < 1.0:
            raise ValueError(
                f"quota burst must be >= 1 token, got {self.burst}")
        self._lock = sync.Lock()
        self._tokens = self.burst
        self._at = sync.monotonic()
        self.admitted = 0
        self.shed = 0

    def try_admit(self, n: float = 1.0, now: float | None = None) -> bool:
        now = sync.monotonic() if now is None else now
        with self._lock:
            # negative elapsed (a caller-supplied clock behind ours)
            # must never DRAIN the bucket
            self._tokens = min(
                self.burst,
                self._tokens + max(0.0, now - self._at) * self.rate)
            self._at = now
            if self._tokens >= n:
                self._tokens -= n
                self.admitted += 1
                return True
            self.shed += 1
            return False

    def stats(self) -> dict:
        with self._lock:
            return {"rate": self.rate, "burst": self.burst,
                    "admitted": self.admitted, "shed": self.shed,
                    "tokens": round(self._tokens, 3)}


def extract_scores(reply: str) -> list[float] | None:
    """Served score(s) out of a reply line: ``"<label> <score>"`` for
    line-mode requests, the ``"scores"`` list for JSON batch replies;
    None for ERR / unparseable replies (the mirror skips those)."""
    reply = reply.strip()
    if not reply or reply.startswith("ERR"):
        return None
    if reply.startswith("{"):
        try:
            doc = json.loads(reply)
            scores = doc.get("scores")
            return [float(s) for s in scores] if scores else None
        except (ValueError, TypeError):
            return None
    parts = reply.split()
    if len(parts) != 2:
        return None
    try:
        return [float(parts[1])]
    except ValueError:
        return None


class _ShadowPair:
    """Per-(tenant, candidate) paired score histograms + block PSI."""

    def __init__(self, tenant: str, candidate: str, *, block: int,
                 bins: int):
        self.block = block
        self.bins = bins
        self.primary = np.zeros(bins, np.int64)
        self.candidate = np.zeros(bins, np.int64)
        self.pairs = 0
        self.blocks = 0
        self.psi_last: float | None = None
        self._gauge = _SHADOW_PSI.labels(tenant=tenant, candidate=candidate)

    def observe(self, primary: list[float], cand: list[float]) -> None:
        from distlr_tpu.feedback.drift import psi  # noqa: PLC0415 (numpy-only)

        n = min(len(primary), len(cand))
        for hist, scores in ((self.primary, primary[:n]),
                             (self.candidate, cand[:n])):
            idx = np.clip((np.asarray(scores, np.float64) * self.bins)
                          .astype(np.int64), 0, self.bins - 1)
            hist += np.bincount(idx, minlength=self.bins)
        self.pairs += n
        if self.pairs >= self.block:
            self.psi_last = psi(self.primary, self.candidate)
            self._gauge.set(self.psi_last)
            self.blocks += 1
            self.primary[:] = 0
            self.candidate[:] = 0
            self.pairs = 0


class ShadowMirror:
    """Fire-and-forget shadow scorer: requests enqueue with their
    primary score, a worker thread replays them against the candidate
    model and feeds the per-(tenant, candidate) PSI comparison.

    ``exchange(model, line) -> reply`` is supplied by the router (it
    reuses the replica pools and in-flight budgets, so shadow traffic
    is admission-controlled like any other — but a refused or failed
    mirror is simply dropped).  The submit path never blocks: a full
    queue counts a drop and returns.
    """

    def __init__(self, exchange, *, queue_max: int = 256, block: int = 256,
                 bins: int = 10):
        if queue_max <= 0 or block <= 0 or bins <= 1:
            raise ValueError(
                f"need queue_max/block > 0 and bins > 1, got "
                f"{queue_max}/{block}/{bins}")
        self._exchange = exchange
        self._queue_max = int(queue_max)
        self.block = int(block)
        self.bins = int(bins)
        self._queue: list[tuple[str, str, str, list[float]]] = []
        self._lock = sync.Lock()
        self._wake = sync.Event()
        self._stop = sync.Event()
        self._pairs: dict[tuple[str, str], _ShadowPair] = {}
        self.submitted = 0
        self.mirrored = 0
        self.dropped = 0
        self.errors = 0
        self._thread = sync.Thread(
            target=self._run, daemon=True, name="distlr-shadow-mirror")
        self._thread.start()

    def submit(self, tenant: str, candidate: str, line: str,
               primary_scores: list[float]) -> bool:
        """Enqueue one mirror; False = dropped (queue full / stopping).
        Called AFTER the primary reply was written — nothing here can
        reach the reply path."""
        if self._stop.is_set():
            return False
        with self._lock:
            if len(self._queue) >= self._queue_max:
                self.dropped += 1
                _SHADOW_TOTAL.labels(tenant=tenant, candidate=candidate,
                                     outcome="dropped").inc()
                return False
            self._queue.append((tenant, candidate, line, primary_scores))
            self.submitted += 1
        self._wake.set()
        return True

    def _run(self) -> None:
        from distlr_tpu.serve.tenant import extract_scores as _scores
        while not self._stop.is_set():
            with self._lock:
                batch, self._queue = self._queue, []
            if not batch:
                self._wake.wait(0.05)
                self._wake.clear()
                continue
            for i, (tenant, candidate, line, primary) in enumerate(batch):
                if self._stop.is_set():
                    # stop() mid-batch: the remaining dequeued mirrors
                    # are shed, and shed work is COUNTED — the original
                    # bare return left them accounted nowhere
                    # (submitted could never reconcile with mirrored +
                    # errors + dropped + queued again), found by
                    # schedcheck's first run (analysis/schedcheck,
                    # schedule pinned in tests/test_schedcheck.py)
                    with self._lock:
                        self.dropped += len(batch) - i
                    for tnt, cand_id, _l, _p in batch[i:]:
                        _SHADOW_TOTAL.labels(tenant=tnt,
                                             candidate=cand_id,
                                             outcome="dropped").inc()
                    return
                try:
                    reply = self._exchange(candidate, line)
                except Exception:  # noqa: BLE001 — mirror must never raise
                    reply = None
                cand = _scores(reply) if reply is not None else None
                if cand is None:
                    self.errors += 1
                    _SHADOW_TOTAL.labels(tenant=tenant, candidate=candidate,
                                         outcome="error").inc()
                    continue
                self.mirrored += 1
                _SHADOW_TOTAL.labels(tenant=tenant, candidate=candidate,
                                     outcome="scored").inc()
                key = (tenant, candidate)
                # insertion under the lock: stats() iterates _pairs
                # under it, and a first-pair insert mid-iteration would
                # RuntimeError the STATS handler thread
                with self._lock:
                    pair = self._pairs.get(key)
                    if pair is None:
                        pair = self._pairs[key] = _ShadowPair(
                            tenant, candidate, block=self.block,
                            bins=self.bins)
                pair.observe(primary, cand)

    def drain(self, timeout_s: float = 5.0) -> None:
        """Block until every submitted mirror was processed (not just
        dequeued) — tests/benches."""
        deadline = sync.monotonic() + timeout_s
        while sync.monotonic() < deadline:
            with self._lock:
                done = (not self._queue
                        and self.mirrored + self.errors >= self.submitted)
            if done:
                return
            sync.sleep(0.01)

    def psi(self, tenant: str, candidate: str) -> float | None:
        with self._lock:
            pair = self._pairs.get((tenant, candidate))
        return pair.psi_last if pair is not None else None

    def stats(self) -> dict:
        with self._lock:
            pairs = {f"{t}->{c}": {"pairs": p.pairs, "blocks": p.blocks,
                                   "psi": p.psi_last}
                     for (t, c), p in self._pairs.items()}
            queued = len(self._queue)
        return {"mirrored": self.mirrored, "dropped": self.dropped,
                "errors": self.errors, "queued": queued, "pairs": pairs}

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=5.0)


def set_model_count(n: int) -> None:
    """Publish the routing tier's registered-model count."""
    _TENANT_MODELS.set(float(n))


def count_request(model: str) -> None:
    _TENANT_REQUESTS.labels(model=model).inc()


def count_tenant_shed(model: str) -> None:
    _TENANT_SHED.labels(model=model).inc()
