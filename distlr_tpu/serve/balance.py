"""The router's balancing and health POLICY, extracted pure.

:class:`~distlr_tpu.serve.router.ScoringRouter` grew its
least-in-flight selection, consecutive-error ejection, probe backoff
doubling, and reinstatement logic inline, where only a live socket
fleet could exercise them.  ISSUE 19 pulls the decision arithmetic out
here so the fleetsim discrete-event simulator property-tests the SAME
policy at thousand-rank scale that the production router runs at
replica scale — not a reimplementation that drifts.

Every function takes duck-typed replica objects carrying the health
fields of ``serve.router._Replica`` (``healthy``,
``consecutive_errors``, ``inflight``, ``errors``, ``requests``,
``ejections``, ``reinstates``, ``backoff_s``, ``next_probe_at``,
``last_ok``, ``last_probe``).  Nothing here touches sockets, locks,
metrics, or clocks — the router calls these under its health lock with
``sync.monotonic()``; fleetsim calls them on simulated replicas with
the virtual clock.  Side effects are confined to the replica fields
named in each docstring.

The last-healthy **ejection floor** (:func:`may_eject`) is ISSUE 19's
router-policy fix: fleetsim's ``cascade_eject_canary`` scenario showed
the unbounded policy ejecting every replica of a pool during a
transient brownout, then serving nothing for a full probe-backoff
after the fault cleared — turning a degraded tier into a total outage.
Envoy calls the same guard an outlier-detection panic budget: the last
healthy replica of any pool it serves stays in rotation no matter how
it misbehaves, because a bad answer beats no answer and its
``consecutive_errors`` keep counting — it ejects the moment a sibling
is reinstated.
"""

from __future__ import annotations

__all__ = [
    "eject",
    "eject_verdict",
    "may_eject",
    "note_failure",
    "note_success",
    "order_candidates",
    "probe_due",
    "probe_result",
]


def order_candidates(cands: list, rr: int) -> tuple[list, int]:
    """Least in-flight first with a rotating tie-break, exactly the
    ``_acquire`` ordering: advance the rotation counter, rotate, then
    STABLE-sort by in-flight (so rotation order breaks ties and serial
    traffic still spreads).  Returns ``(ordered, new_rr)``; an empty
    candidate list leaves the counter untouched."""
    if not cands:
        return [], rr
    rr = (rr + 1) % len(cands)
    rotated = cands[rr:] + cands[:rr]
    rotated.sort(key=lambda r: r.inflight)
    return rotated, rr


def note_success(rep, now: float) -> None:
    """A successful exchange: the consecutive-error streak resets."""
    rep.requests += 1
    rep.consecutive_errors = 0
    rep.last_ok = now


def note_failure(rep) -> None:
    """A transport failure: count it (the caller then consults
    :func:`eject_verdict`)."""
    rep.errors += 1
    rep.consecutive_errors += 1


def may_eject(rep, pools: list) -> bool:
    """The ejection floor: True only if EVERY multi-replica pool in
    ``pools`` (the replica lists of each model ``rep`` serves) keeps at
    least one OTHER healthy replica after ``rep`` leaves rotation.

    Singleton pools are exempt: the floor exists to preserve a
    fail-over destination, and a pool of one has none — ejecting its
    only member at least converts slow per-request dial timeouts into
    fast ``no healthy replica`` admission errors while backoff probes
    watch for recovery (the pinned single-replica outage semantics)."""
    for pool in pools:
        if len(pool) > 1 and not any(r.healthy
                                     for r in pool if r is not rep):
            return False
    return True


def eject_verdict(rep, pools: list, eject_after: int) -> str:
    """Arbitrate one failure streak: ``"keep"`` below the threshold,
    ``"eject"`` at/over it, ``"floor"`` when only the last-healthy
    budget blocks the ejection (callers surface that loudly — a
    suppressed ejection is a pool running on its last replica)."""
    if not rep.healthy or rep.consecutive_errors < eject_after:
        return "keep"
    return "eject" if may_eject(rep, pools) else "floor"


def eject(rep, now: float, probe_backoff_s: float) -> None:
    """Take ``rep`` out of rotation and arm the first backoff probe.
    Pure state transition — the router adds metrics/logging and drains
    the connection pool around it."""
    rep.healthy = False
    rep.ejections += 1
    rep.backoff_s = probe_backoff_s
    rep.next_probe_at = now + rep.backoff_s


def probe_result(rep, ok: bool, now: float, *, probe_backoff_s: float,
                 probe_backoff_max_s: float, eject_after: int,
                 pools: list) -> str:
    """Fold one active health-probe outcome into the replica's state.

    Returns what happened: ``"reinstated"`` (ejected replica back in
    rotation), ``"ok"`` (healthy confirmed), ``"counted"`` (failure
    toward ejection), ``"ejected"``, ``"floor"`` (threshold crossed
    but the last-healthy budget held it), or ``"backoff"`` (ejected
    replica still down — backoff doubled, capped)."""
    rep.last_probe = now
    if ok:
        rep.consecutive_errors = 0
        rep.last_ok = now
        rep.backoff_s = 0.0
        if not rep.healthy:
            rep.healthy = True
            rep.reinstates += 1
            return "reinstated"
        return "ok"
    if rep.healthy:
        note_failure(rep)
        verdict = eject_verdict(rep, pools, eject_after)
        if verdict == "eject":
            eject(rep, now, probe_backoff_s)
            return "ejected"
        return "floor" if verdict == "floor" else "counted"
    rep.backoff_s = min(max(rep.backoff_s * 2, probe_backoff_s),
                        probe_backoff_max_s)
    rep.next_probe_at = now + rep.backoff_s
    return "backoff"


def probe_due(rep, now: float, health_interval_s: float,
              probe_backoff_s: float) -> bool:
    """The health loop's due computation: healthy replicas probe when
    neither traffic nor a probe confirmed them for an interval; ejected
    replicas probe on their backoff schedule.  When an ejected
    replica's probe comes due the NEXT slot is pre-pushed, so a
    fast-failing probe cannot hot-loop inside one backoff window."""
    if rep.healthy:
        return now - max(rep.last_ok, rep.last_probe) >= health_interval_s
    due = now >= rep.next_probe_at
    if due:
        rep.next_probe_at = now + max(rep.backoff_s, probe_backoff_s)
    return due
